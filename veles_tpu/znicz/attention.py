"""Transformer / long-context units.

The reference framework predates attention (SURVEY §5: long-context
"ABSENT in reference" — 2013-15, no attention anywhere), but the TPU
build treats long sequences as first-class: these units extend the
znicz layer family with an embedding, a pre-LN transformer block
whose attention can run **ring sequence-parallel** over a mesh
``seq`` axis (``ops/attention.py``: streaming-softmax k/v rotation
via ``lax.ppermute`` — no device materializes full K/V), and a
language-model evaluator wired into the standard on-device epoch
accounting.  Everything composes with the existing machinery: the
fused StepCompiler differentiates through the ring, the generic
GradientDescentBase momentum rule updates every trainable, snapshots
and the distributed contract come from ForwardBase.
"""

import functools

import numpy

from ..config import root, get as config_get
from ..memory import Vector
from .nn_units import ForwardBase, GradientDescentBase
from .evaluator import EvaluatorBase


def remat_enabled(unit_flag):
    """Whether a transformer unit should rematerialize (jax.checkpoint)
    its block application: the unit kwarg wins when set, otherwise
    ``root.common.engine.remat`` (default off).  Remat trades ~1/3 more
    FLOPs (forward re-run in backward) for O(layers) → O(1) residual
    activation memory per block — THE long-context/deep-stack enabler:
    ring attention already gives O(S/N) attention memory, but without
    remat the backward still stores every block's full residual
    stream."""
    if unit_flag is not None:
        return bool(unit_flag)
    return bool(config_get(root.common.engine.remat, False))


def fused_qkv_enabled(unit_flag):
    """Whether a transformer unit computes q/k/v with ONE (E, 3E)
    matmul (the attention fast path's stage (a)): the unit kwarg wins
    when set, otherwise ``root.common.engine.fused_qkv`` (default
    off).  The fused weight's column layout is HEAD-MAJOR —
    ``[q_h | k_h | v_h]`` per head — so a Megatron column shard of
    the 3E dim holds whole heads' q/k/v together and the
    (B, S, H, 3, D) reshape splits q/k/v on a replicated axis (no
    resharding), which is what lets the fused projection compose
    with tensor parallelism."""
    if unit_flag is not None:
        return bool(unit_flag)
    return bool(config_get(root.common.engine.fused_qkv, False))


def fuse_qkv_arrays(wq, wk, wv, n_heads):
    """Fuses three projection arrays into the head-major (…, 3·O)
    layout.  Trailing-dim based, so it handles (E, O) weights, (O,)
    biases, and stage-stacked (L, E, O) weights alike."""
    wq, wk, wv = (numpy.asarray(w) for w in (wq, wk, wv))
    O = wq.shape[-1]
    D = O // n_heads
    parts = [w.reshape(w.shape[:-1] + (n_heads, 1, D))
             for w in (wq, wk, wv)]
    return numpy.ascontiguousarray(
        numpy.concatenate(parts, axis=-2).reshape(
            wq.shape[:-1] + (3 * O,)))


def split_qkv_arrays(wqkv, n_heads):
    """Inverse of :func:`fuse_qkv_arrays`: (…, 3·O) → three (…, O)
    arrays (wq, wk, wv)."""
    wqkv = numpy.asarray(wqkv)
    O = wqkv.shape[-1] // 3
    D = O // n_heads
    r = wqkv.reshape(wqkv.shape[:-1] + (n_heads, 3, D))
    return tuple(
        numpy.ascontiguousarray(
            r[..., t, :].reshape(wqkv.shape[:-1] + (O,)))
        for t in range(3))


#: The per-projection parameter names the fused layout replaces.
_QKV_NAMES = ("wq", "wk", "wv", "bq", "bk", "bv")


def qkv_param_names(names, fused):
    """Rewrites a canonical PARAM_NAMES tuple for the fused layout:
    wq/wk/wv → wqkv, bq/bk/bv → bqkv (order of first occurrence)."""
    if not fused:
        return tuple(names)
    out = []
    for n in names:
        if n in _QKV_NAMES:
            repl = "wqkv" if n.startswith("w") else "bqkv"
            if repl not in out:
                out.append(repl)
        else:
            out.append(n)
    return tuple(out)


def _layer_norm(x, gamma, beta, eps=1e-5):
    import jax.numpy as jnp
    xf = x.astype(jnp.float32)
    mu = xf.mean(axis=-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(axis=-1, keepdims=True)
    return ((xf - mu) * jnp.reciprocal(jnp.sqrt(var + eps)) * gamma +
            beta).astype(x.dtype)


def transformer_block_apply(params, x, n_heads, causal, cdt,
                            attend=None, mlp=None):
    """Pure pre-LN block: x + MHA(LN(x)), then + MLP(LN(·)).  Shared
    by TransformerBlock.tforward, the MoE block (which passes its
    expert FFN via ``mlp``), and the pipelined stack (the pipeline
    stages must be a pure (params, x) → y function).  ``mlp``
    receives the post-LN activations (B, S, E) and returns the FFN
    output to be residual-added; None → the dense w1/w2 MLP."""
    import jax.numpy as jnp
    from ..ops import attention as A
    B, S, E = x.shape

    def dot(a, w, b):
        return jnp.dot(a.astype(cdt), w.astype(cdt),
                       preferred_element_type=jnp.float32) + b

    h = _layer_norm(x, params["ln1_g"], params["ln1_b"])
    if "wqkv" in params:
        # Fast path stage (a): one (E, 3E) matmul; the head-major
        # column layout makes the q/k/v split a reshape + index on a
        # replicated axis (tensor-parallel-safe, see
        # fused_qkv_enabled).
        qkv = dot(h, params["wqkv"], params["bqkv"]).reshape(
            B, S, n_heads, 3, -1)
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
    else:
        q = dot(h, params["wq"], params["bq"]).reshape(
            B, S, n_heads, -1)
        k = dot(h, params["wk"], params["bk"]).reshape(
            B, S, n_heads, -1)
        v = dot(h, params["wv"], params["bv"]).reshape(
            B, S, n_heads, -1)
    if attend is None:
        attend = functools.partial(A.attention, causal=causal)
    attn = attend(q.astype(cdt), k.astype(cdt),
                  v.astype(cdt)).reshape(B, S, E)
    x = x + dot(attn, params["wo"], params["bo"])
    h = _layer_norm(x, params["ln2_g"], params["ln2_b"])
    if mlp is not None:
        x = x + mlp(h)
    else:
        h = jnp.maximum(dot(h, params["w1"], params["b1"]), 0.0)
        x = x + dot(h, params["w2"], params["b2"])
    return x.astype(jnp.float32)


def _block_param_shapes(embed, hidden, fused_qkv=False):
    """Parameter geometry of one dense pre-LN block — single source
    of truth for TransformerBlock and the pipelined stack (which
    prepends a stage dim).  ``fused_qkv`` swaps the three (E, E)
    projections for the single (E, 3E) fused weight.

    Dict ORDER is load-bearing: initialization draws from the seeded
    prng in iteration order, so the unfused layout must keep the
    historical ordering bit-for-bit (seeded trajectories — and the
    tests pinning them — depend on it)."""
    if fused_qkv:
        proj = {"wqkv": (embed, 3 * embed), "wo": (embed, embed),
                "bqkv": (3 * embed,), "bo": (embed,)}
    else:
        proj = {"wq": (embed, embed), "wk": (embed, embed),
                "wv": (embed, embed), "wo": (embed, embed),
                "bq": (embed,), "bk": (embed,), "bv": (embed,),
                "bo": (embed,)}
    shapes = {"ln1_g": (embed,), "ln1_b": (embed,)}
    shapes.update(proj)
    shapes.update({
        "ln2_g": (embed,), "ln2_b": (embed,),
        "w1": (embed, hidden), "b1": (hidden,),
        "w2": (hidden, embed), "b2": (embed,),
    })
    return shapes


class Embedding(ForwardBase):
    """Token + learned positional embedding: int32 tokens (B, S) →
    activations (B, S, E)."""

    MAPPING = "embedding"

    def __init__(self, workflow, **kwargs):
        super(Embedding, self).__init__(workflow, **kwargs)
        self.vocab_size = kwargs["vocab_size"]
        self.embed_dim = kwargs["embed_dim"]
        self.max_len = kwargs.get("max_len")
        self.include_bias = False
        self.pos = Vector()

    @property
    def trainables(self):
        t = {"weights": self.weights} if self.weights else {}
        if self.pos:
            t["pos"] = self.pos
        return t

    def initialize(self, device=None, **kwargs):
        super(Embedding, self).initialize(device=device, **kwargs)
        batch, seq = self.input.shape[:2]
        max_len = self.max_len or seq
        if not self.weights:
            stddev = self.weights_stddev or 0.02
            w = numpy.zeros((self.vocab_size, self.embed_dim),
                            dtype=numpy.float32)
            self.rand().fill_normal(w, stddev=stddev)
            self.weights.mem = w
            self.weights.initialize(self.device)
        if not self.pos:
            p = numpy.zeros((max_len, self.embed_dim),
                            dtype=numpy.float32)
            self.rand().fill_normal(p, stddev=0.02)
            self.pos.mem = p
            self.pos.initialize(self.device)
        self.output.mem = numpy.zeros(
            (batch, seq, self.embed_dim), dtype=numpy.float32)
        self.output.initialize(self.device)

    def tforward(self, read, write, params, ctx, state=None):
        tokens = read(self.input).astype("int32")
        w = params["weights"]
        seq = tokens.shape[1]
        out = w[tokens] + params["pos"][:seq]
        write(self.output, out.astype(self.compute_dtype))


class TransformerBlock(ForwardBase):
    """Pre-LN transformer block: x + MHA(LN(x)), then + MLP(LN(·)).

    kwargs: ``n_heads``; ``mlp_ratio`` (default 4); ``causal``
    (default True); ``seq_axis`` — when set AND the workflow's mesh
    carries that axis, attention runs ring sequence-parallel
    (``ops.attention.sequence_parallel_attention``); otherwise
    blockwise/full attention on-device.
    """

    MAPPING = "transformer_block"

    PARAM_NAMES = ("ln1_g", "ln1_b", "wq", "wk", "wv", "wo",
                   "bq", "bk", "bv", "bo",
                   "ln2_g", "ln2_b", "w1", "b1", "w2", "b2")

    def __init__(self, workflow, **kwargs):
        super(TransformerBlock, self).__init__(workflow, **kwargs)
        self.n_heads = kwargs.get("n_heads", 4)
        self.mlp_ratio = kwargs.get("mlp_ratio", 4)
        self.causal = kwargs.get("causal", True)
        self.seq_axis = kwargs.get("seq_axis")
        #: "ring" (ppermute k/v streaming, O(S/N) memory) or
        #: "ulysses" (two all-to-alls, dense local attention).
        self.sp_mode = kwargs.get("sp_mode", "ring")
        from ..ops.attention import SP_MODES
        if self.sp_mode not in SP_MODES:
            raise ValueError("unknown sp_mode %r — valid: %s" %
                             (self.sp_mode, list(SP_MODES)))
        self.batch_axis = kwargs.get("batch_axis", "data")
        #: When set (apply_dp_tp_sp_sharding), attention keeps the
        #: head dim sharded on this mesh axis inside the shard_map —
        #: the tp × sp composition.
        self.head_axis = kwargs.get("head_axis")
        #: Ring-kernel override for the sequence-parallel path:
        #: None → the ``sp_ring_kernel`` knob ("auto" default —
        #: ring-flash where the platform supports it); "xla" forces
        #: the lax streaming scan; "pallas" forces the flash body.
        self.sp_kernel = kwargs.get("sp_kernel")
        #: Forces the interpret-mode flash kernel inside the ring —
        #: the CPU parity/dryrun path (tests only; never on a chip).
        self.sp_interpret = kwargs.get("sp_interpret")
        #: None → follow root.common.engine.remat; True/False forces.
        self.remat = kwargs.get("remat")
        #: Resolved at construction (None → the engine knob) so the
        #: parameter LAYOUT is frozen into the unit — a snapshot
        #: trained fused restores fused whatever the config says.
        self.fused_qkv = fused_qkv_enabled(kwargs.get("fused_qkv"))
        self.params = {name: Vector()
                       for name in qkv_param_names(self.PARAM_NAMES,
                                                   self.fused_qkv)}

    @property
    def trainables(self):
        return {n: v for n, v in self.params.items() if v}

    def initialize(self, device=None, **kwargs):
        super(TransformerBlock, self).initialize(device=device,
                                                 **kwargs)
        batch, seq, embed = self.input.shape
        if embed % self.n_heads:
            raise ValueError("embed dim %d not divisible by %d heads"
                             % (embed, self.n_heads))
        hidden = embed * self.mlp_ratio
        stddev = self.weights_stddev or (1.0 / numpy.sqrt(embed))
        shapes = _block_param_shapes(embed, hidden,
                                     fused_qkv=self.fused_qkv)
        for name, shape in shapes.items():
            vec = self.params[name]
            if vec:
                continue
            arr = numpy.zeros(shape, dtype=numpy.float32)
            if name.startswith("w"):
                self.rand().fill_normal(arr, stddev=stddev)
            elif name.endswith("_g"):
                arr[...] = 1.0
            vec.mem = arr
            vec.initialize(self.device)
        self.output.mem = numpy.zeros((batch, seq, embed),
                                      dtype=numpy.float32)
        self.output.initialize(self.device)

    def _attend(self, q, k, v):
        from ..ops import attention as A
        mesh = getattr(self.workflow, "mesh", None)
        if self.seq_axis and mesh is not None and \
                self.seq_axis in mesh.axis_names:
            return A.sequence_parallel_attention(
                q, k, v, mesh, self.seq_axis, causal=self.causal,
                batch_axis=self.batch_axis, mode=self.sp_mode,
                head_axis=getattr(self, "head_axis", None),
                kernel=getattr(self, "sp_kernel", None),
                interpret=getattr(self, "sp_interpret", None))
        return A.attention(q, k, v, causal=self.causal)

    def tforward(self, read, write, params, ctx, state=None):
        x = read(self.input)

        def apply(p, h):
            return transformer_block_apply(
                p, h, self.n_heads, self.causal, self.compute_dtype,
                attend=lambda q, k, v: self._attend(q, k, v))

        if remat_enabled(getattr(self, "remat", None)):
            import jax
            apply = jax.checkpoint(apply)
        write(self.output, apply(params, x))


class MoETransformerBlock(TransformerBlock):
    """Transformer block whose MLP is a top-1 Mixture-of-Experts
    (ops/moe.py — GShard dispatch/combine einsums).  Expert parameters
    carry a leading ``n_experts`` dimension; under a mesh with an
    ``expert`` axis (apply_dp_ep_sharding) that dimension shards there
    and XLA lowers the dispatch einsums to all-to-alls over ICI.

    kwargs beyond TransformerBlock: ``n_experts``;
    ``capacity_factor`` (default 1.25); ``aux_weight`` — load-balance
    loss weight (default 0.01); ``top_k`` — experts per token
    (default: ``root.common.engine.moe_top_k`` or 1 — the Switch/
    GShard top-1 path; k ≥ 2 routes through ``ops.moe.topk_routing``
    with rank-major capacity priority); ``router_z_weight`` — ST-MoE
    router z-loss weight (default: ``root.common.engine.
    moe_router_z`` or 0); ``expert_axis`` — recorded so the sharding
    helper can find MoE blocks.

    Router health rides the epoch accounting: ``moe_acc`` is a
    (3 classes × 2 + n_experts) on-device accumulator —
    [aux_sum, ticks, load_0 … load_{E−1}] — added to inside the
    fused step and fetched by DecisionGD at epoch boundaries (the
    ``moe.aux_loss`` / ``moe.expert_load`` gauges; router collapse
    is visible live on the heartbeat perf section / web_status).
    """

    MAPPING = "moe_transformer_block"

    PARAM_NAMES = ("ln1_g", "ln1_b", "wq", "wk", "wv", "wo",
                   "bq", "bk", "bv", "bo",
                   "ln2_g", "ln2_b", "router",
                   "w1", "b1", "w2", "b2")

    def __init__(self, workflow, **kwargs):
        self.n_experts = kwargs.get("n_experts", 4)
        self.capacity_factor = kwargs.get("capacity_factor", 1.25)
        self.aux_weight = kwargs.get("aux_weight", 0.01)
        top_k = kwargs.get("top_k")
        if top_k is None:
            top_k = config_get(root.common.engine.moe_top_k, 1)
        self.top_k = int(top_k)
        if not 1 <= self.top_k <= self.n_experts:
            raise ValueError(
                "top_k=%d must satisfy 1 <= k <= n_experts=%d"
                % (self.top_k, self.n_experts))
        z_weight = kwargs.get("router_z_weight")
        if z_weight is None:
            z_weight = config_get(root.common.engine.moe_router_z,
                                  0.0)
        self.router_z_weight = float(z_weight)
        self.expert_axis = kwargs.get("expert_axis")
        #: Optional link to the loader's class vector — buckets the
        #: moe_acc rows per sample class (TRAIN row when unlinked).
        self.minibatch_class_vec = kwargs.get("minibatch_class_vec")
        #: Optional link to the loader's mask — gates padded block
        #: ticks (all-zero mask) out of the router-health row, the
        #: same validity treatment the evaluator accumulator applies.
        self.minibatch_mask = kwargs.get("minibatch_mask")
        self.moe_acc = Vector()
        super(MoETransformerBlock, self).__init__(workflow, **kwargs)

    @property
    def tstate(self):
        state = dict(super(MoETransformerBlock, self).tstate)
        acc = getattr(self, "moe_acc", None)
        if acc is None:  # block from a pre-top-k snapshot
            acc = self.moe_acc = Vector()
        if not acc:
            acc.mem = numpy.zeros((3, 2 + self.n_experts),
                                  dtype=numpy.float32)
        state["moe_acc"] = acc
        return state

    def read_moe_acc(self, cls):
        """Host fetch of one class's router row — [aux_sum, ticks,
        per-expert load] (rides the Decision's epoch-boundary sync
        like the evaluator accumulators)."""
        acc = self.tstate["moe_acc"]
        acc.map_read()
        return numpy.array(acc.mem[cls])

    def reset_moe_acc(self, cls):
        acc = self.tstate["moe_acc"]
        acc.map_write()
        acc.mem[cls] = 0.0

    def initialize(self, device=None, **kwargs):
        batch, seq, embed = self.input.shape
        hidden = embed * self.mlp_ratio
        stddev = self.weights_stddev or (1.0 / numpy.sqrt(embed))
        E = self.n_experts
        moe_shapes = {
            "router": (embed, E),
            "w1": (E, embed, hidden), "b1": (E, hidden),
            "w2": (E, hidden, embed), "b2": (E, embed),
        }
        for name, shape in moe_shapes.items():
            vec = self.params[name]
            if vec:
                continue
            arr = numpy.zeros(shape, dtype=numpy.float32)
            if name in ("router", "w1", "w2"):
                self.rand().fill_normal(arr, stddev=stddev)
            vec.mem = arr
            vec.initialize(self.device)
        acc = self.tstate["moe_acc"]  # allocates when absent
        acc.initialize(device)
        super(MoETransformerBlock, self).initialize(device=device,
                                                    **kwargs)

    @property
    def expert_params(self):
        """The expert-stacked Vectors (leading n_experts dim) — what
        apply_dp_ep_sharding shards."""
        return {n: self.params[n] for n in ("w1", "b1", "w2", "b2")}

    def tforward(self, read, write, params, ctx, state=None):
        import jax.numpy as jnp
        from ..ops.moe import moe_ffn_topk
        x = read(self.input)
        B, S, E = x.shape

        def apply(p, h0):
            """Pure (params, x) → (out, aux, z, load): the MoE side
            outputs RIDE the return value (not ctx closure mutation),
            so the whole block is checkpointable — a tracer born
            inside jax.checkpoint must not leak out through ctx."""
            box = {}

            def mlp(h):
                y, aux, z, load = moe_ffn_topk(
                    h.reshape(B * S, E), p["router"], p["w1"],
                    p["b1"], p["w2"], p["b2"],
                    capacity_factor=self.capacity_factor,
                    top_k=getattr(self, "top_k", 1))
                box["aux"], box["z"], box["load"] = aux, z, load
                return y.reshape(B, S, E)

            out = transformer_block_apply(
                p, h0, self.n_heads, self.causal,
                self.compute_dtype,
                attend=lambda q, k, v: self._attend(q, k, v),
                mlp=mlp)
            return out, box["aux"], box["z"], box["load"]

        if remat_enabled(getattr(self, "remat", None)):
            import jax
            apply = jax.checkpoint(apply)
        out, aux, z, load = apply(params, x)
        total_aux = self.aux_weight * aux
        z_weight = getattr(self, "router_z_weight", 0.0)
        if z_weight:
            # Static-zero skip keeps the pre-z traced graph (and its
            # seeded trajectories) bit-identical when disabled.
            total_aux = total_aux + z_weight * z
        ctx.add_aux_loss(total_aux)
        ctx.add_metric("%s_max_expert_load" % self.name,
                       load.max() / jnp.maximum(load.sum(), 1.0))
        write(self.output, out)
        if state is not None and "moe_acc" in state:
            # Router-health epoch row: aux + per-expert load bucketed
            # by the minibatch class (TRAIN when no loader link) —
            # fetched by DecisionGD with the epoch accumulators.
            # Padded block ticks (all-zero mask) are gated out whole,
            # like the evaluator's epoch row: filler dispatches must
            # not dilute the mean aux or skew the load shares.
            cvec = getattr(self, "minibatch_class_vec", None)
            cls = read(cvec).astype(jnp.int32) if cvec is not None \
                else jnp.int32(2)
            mvec = getattr(self, "minibatch_mask", None)
            valid = (read(mvec).sum() > 0).astype(jnp.float32) \
                if mvec is not None else jnp.float32(1.0)
            row = jnp.concatenate([
                jnp.stack([aux.astype(jnp.float32),
                           jnp.float32(1.0)]),
                load.astype(jnp.float32)]) * valid
            return {"moe_acc": state["moe_acc"].at[cls].add(row)}


class PipelinedTransformerStack(ForwardBase):
    """N homogeneous transformer blocks as ONE unit with stage-
    stacked parameters (leading ``n_blocks`` dim) — the pipeline-
    parallel formulation (ops/pipeline.py ``gpipe``): under a mesh
    with a ``stage`` axis the stack shards one block per device, the
    minibatch splits into ``n_microbatches``, and activations hand
    off stage-to-stage via ppermute.  Without the mesh axis the same
    stacked parameters run as a plain ``lax.scan`` — bit-identical
    math, so pipelined vs sequential parity is testable.
    """

    MAPPING = "pipelined_transformer_stack"

    def __init__(self, workflow, **kwargs):
        super(PipelinedTransformerStack, self).__init__(workflow,
                                                        **kwargs)
        self.n_blocks = kwargs.get("n_blocks", 4)
        self.n_heads = kwargs.get("n_heads", 4)
        self.mlp_ratio = kwargs.get("mlp_ratio", 4)
        self.causal = kwargs.get("causal", True)
        self.stage_axis = kwargs.get("stage_axis")
        self.n_microbatches = kwargs.get("n_microbatches", 4)
        #: Pipeline schedule (ops/pipeline.py SCHEDULES): "gpipe"
        #: fill-and-drain, "1f1b" PipeDream-flush memory class,
        #: "interleaved" Megatron virtual chunks.  None → the
        #: root.common.engine.pp_schedule knob (--pp-schedule).
        schedule = kwargs.get("schedule")
        if schedule is None:
            schedule = config_get(root.common.engine.pp_schedule,
                                  "gpipe")
        from ..ops.pipeline import SCHEDULES
        if schedule not in SCHEDULES:
            raise ValueError("unknown pipeline schedule %r — valid: "
                             "%s" % (schedule, list(SCHEDULES)))
        self.schedule = schedule
        #: Interleaved only: virtual chunks per stage (None → one
        #: chunk per local block; root.common.engine.pp_chunks /
        #: --pp-chunks overrides).
        n_chunks = kwargs.get("n_chunks")
        if n_chunks is None:
            n_chunks = config_get(root.common.engine.pp_chunks, None)
        self.n_chunks = n_chunks
        #: None → follow root.common.engine.remat; True/False forces.
        self.remat = kwargs.get("remat")
        #: Fused-QKV layout, frozen at construction like
        #: TransformerBlock's.
        self.fused_qkv = fused_qkv_enabled(kwargs.get("fused_qkv"))
        self.params = {name: Vector()
                       for name in qkv_param_names(
                           TransformerBlock.PARAM_NAMES,
                           self.fused_qkv)}

    @property
    def trainables(self):
        return {n: v for n, v in self.params.items() if v}

    def initialize(self, device=None, **kwargs):
        super(PipelinedTransformerStack, self).initialize(
            device=device, **kwargs)
        batch, seq, embed = self.input.shape
        if embed % self.n_heads:
            raise ValueError("embed dim %d not divisible by %d heads"
                             % (embed, self.n_heads))
        hidden = embed * self.mlp_ratio
        stddev = self.weights_stddev or (1.0 / numpy.sqrt(embed))
        shapes = _block_param_shapes(embed, hidden,
                                     fused_qkv=self.fused_qkv)
        for name, shape in shapes.items():
            vec = self.params[name]
            if vec:
                continue
            arr = numpy.zeros((self.n_blocks,) + shape,
                              dtype=numpy.float32)
            if name.startswith("w"):
                self.rand().fill_normal(arr, stddev=stddev)
            elif name.endswith("_g"):
                arr[...] = 1.0
            vec.mem = arr
            vec.initialize(self.device)
        self.output.mem = numpy.zeros((batch, seq, embed),
                                      dtype=numpy.float32)
        self.output.initialize(self.device)

    @property
    def stage_params(self):
        """The stage-stacked Vectors — what a pipeline sharding
        helper shards on the stage axis (leading dim)."""
        return dict(self.trainables)

    def tforward(self, read, write, params, ctx, state=None):
        from ..ops import pipeline as PL
        x = read(self.input)
        cdt = self.compute_dtype

        def block_fn(p, h):
            return transformer_block_apply(p, h, self.n_heads,
                                           self.causal, cdt)

        if remat_enabled(getattr(self, "remat", None)):
            # Per-BLOCK checkpointing: the pipeline (or the
            # sequential scan) re-runs each block's forward during
            # its backward instead of storing every block's
            # residuals — per-stage activation memory drops from
            # O(blocks/stage) to O(1) per microbatch in flight.
            import jax
            block_fn = jax.checkpoint(block_fn)

        mesh = getattr(self.workflow, "mesh", None)
        if self.stage_axis and mesh is not None and \
                self.stage_axis in mesh.axis_names and \
                self.n_blocks % mesh.shape[self.stage_axis] == 0:
            # Mirrors apply_dp_pp_sharding's divisibility contract:
            # an indivisible stack stays replicated and runs the
            # sequential scan instead of crashing inside shard_map.
            out = PL.pipeline(
                block_fn, params, x, mesh, self.stage_axis,
                self.n_microbatches,
                schedule=getattr(self, "schedule", "gpipe"),
                n_chunks=getattr(self, "n_chunks", None))
        else:
            out = PL.sequential_stack(block_fn, params, x)
        write(self.output, out)


class GDPipelinedStack(GradientDescentBase):
    MAPPING = "pipelined_transformer_stack"


class LMHead(ForwardBase):
    """Tied or free projection to vocabulary logits:
    (B, S, E) → (B, S, V)."""

    MAPPING = "lm_head"

    def __init__(self, workflow, **kwargs):
        super(LMHead, self).__init__(workflow, **kwargs)
        self.vocab_size = kwargs["vocab_size"]
        #: Weight tying to an Embedding unit (standard LM practice;
        #: gradients flow to the embedding through the read).
        self.tie_to = kwargs.get("tie_to")

    @property
    def trainables(self):
        if self.tie_to is not None:
            return {"bias": self.bias} if self.include_bias and \
                self.bias else {}
        return super(LMHead, self).trainables

    def initialize(self, device=None, **kwargs):
        if self.tie_to is not None and \
                not self.tie_to.is_initialized:
            raise AttributeError("%s: tied embedding %s not "
                                 "initialized yet" %
                                 (self.name, self.tie_to.name))
        super(LMHead, self).initialize(device=device, **kwargs)
        batch, seq, embed = self.input.shape
        if self.tie_to is None and not self.weights:
            stddev = self.weights_stddev or (1.0 / numpy.sqrt(embed))
            w = numpy.zeros((embed, self.vocab_size),
                            dtype=numpy.float32)
            self.rand().fill_normal(w, stddev=stddev)
            self.weights.mem = w
            self.weights.initialize(self.device)
        if self.include_bias and not self.bias:
            self.bias.mem = numpy.zeros(self.vocab_size,
                                        dtype=numpy.float32)
            self.bias.initialize(self.device)
        self.output.mem = numpy.zeros(
            (batch, seq, self.vocab_size), dtype=numpy.float32)
        self.output.initialize(self.device)

    def tforward(self, read, write, params, ctx, state=None):
        import jax.numpy as jnp
        x = read(self.input)
        cdt = self.compute_dtype
        if self.tie_to is not None:
            w = read(self.tie_to.weights).T
        else:
            w = params["weights"]
        y = jnp.dot(x.astype(cdt), w.astype(cdt),
                    preferred_element_type=jnp.float32)
        if self.include_bias:
            y = y + params["bias"]
        write(self.output, y)


class EvaluatorLM(EvaluatorBase):
    """Next-token cross-entropy over (B, S, V) logits vs (B, S)
    labels, with per-SAMPLE validity mask; rides the on-device epoch
    accumulator like every evaluator (n_err/n_valid count tokens)."""

    def __init__(self, workflow, **kwargs):
        super(EvaluatorLM, self).__init__(workflow, **kwargs)
        self.labels = None
        self.demand("labels", "mask", "minibatch_class_vec")

    def tforward(self, read, write, params, ctx, state=None):
        import jax
        import jax.numpy as jnp
        logits = read(self.input)
        labels = read(self.labels).astype(jnp.int32)
        mask = read(self.mask)
        tokens_per = labels.shape[1]
        tok_mask = mask[:, None] * jnp.ones((1, tokens_per),
                                            jnp.float32)
        n_valid = jnp.maximum(tok_mask.sum(), 1.0)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32),
                                  axis=-1)
        nll = -jnp.take_along_axis(
            logp, labels[..., None], axis=-1)[..., 0]
        loss = (nll * tok_mask).sum() / n_valid
        pred = jnp.argmax(logits, axis=-1)
        n_err = ((pred != labels) * tok_mask).sum()
        ctx.set_loss(loss)
        ctx.add_metric("n_err", n_err)
        ctx.add_metric("n_valid", tok_mask.sum())
        return self._accumulate(read, state, n_err, tok_mask.sum(),
                                loss)


class GDEmbedding(GradientDescentBase):
    MAPPING = "embedding"


class GDTransformerBlock(GradientDescentBase):
    MAPPING = "transformer_block"


class GDMoETransformerBlock(GradientDescentBase):
    MAPPING = "moe_transformer_block"


class GDLMHead(GradientDescentBase):
    MAPPING = "lm_head"
