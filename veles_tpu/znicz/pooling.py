"""Pooling layer units.

Reconstructed znicz capability surface (SURVEY §2.5: "Pooling" units):
max, average and stochastic pooling over NHWC inputs with kernel
``ky``×``kx`` and ``sliding`` stride.

TPU-era mapping: ``lax.reduce_window`` — XLA lowers it to a fused
windowed reduction, and the backward (argmax routing for max pooling)
is derived by autodiff instead of the reference's stored-offsets
kernel.  Stochastic pooling (Zeiler & Fergus 2013, the znicz
``StochasticPooling``) samples a window element with probability
proportional to its activation during training and uses the
probability-weighted average at inference.
"""

import numpy

from .nn_units import ForwardBase
from .conv import _norm_padding, _norm_sliding


def _typed_inf(dtype, sign):
    """±inf as a scalar of ``dtype`` — reduce_window only specializes
    to its differentiable max/min form when the init value is the
    dtype's own identity."""
    import numpy as np
    # lint-ok: VL101 host-side dtype-identity scalar for the
    # reduce_window init value — no device data involved.
    return np.asarray(sign * np.inf, dtype=dtype)[()]


class Pooling(ForwardBase):
    """Common geometry for pooling units."""

    hide_from_registry = True
    HAS_PARAMS = False

    def __init__(self, workflow, **kwargs):
        super(Pooling, self).__init__(workflow, **kwargs)
        self.kx = kwargs["kx"]
        self.ky = kwargs.get("ky", self.kx)
        self.sliding = _norm_sliding(kwargs.get("sliding", (self.kx,
                                                            self.ky)))
        self.padding = _norm_padding(kwargs.get("padding"))
        self.include_bias = False

    @property
    def trainables(self):
        return {}

    def output_spatial(self, in_h, in_w):
        (pt, pb), (pl, pr) = self.padding
        sh, sw = self.sliding
        # Ceil-mode window count (znicz pooled the ragged tail too).
        out_h = -(-(in_h + pt + pb - self.ky) // sh) + 1
        out_w = -(-(in_w + pl + pr - self.kx) // sw) + 1
        return out_h, out_w

    def _window_dims(self):
        return (1, self.ky, self.kx, 1)

    def _window_strides(self):
        return (1,) + self.sliding + (1,)

    def _window_padding(self, in_h, in_w):
        """SAME-style explicit padding that covers the ragged tail."""
        (pt, pb), (pl, pr) = self.padding
        sh, sw = self.sliding
        out_h, out_w = self.output_spatial(in_h, in_w)
        need_h = (out_h - 1) * sh + self.ky - (in_h + pt)
        need_w = (out_w - 1) * sw + self.kx - (in_w + pl)
        return ((0, 0), (pt, max(pb, need_h)), (pl, max(pr, need_w)),
                (0, 0))

    def initialize(self, device=None, **kwargs):
        super(Pooling, self).initialize(device=device, **kwargs)
        batch, in_h, in_w, ch = self.input.shape
        out_h, out_w = self.output_spatial(in_h, in_w)
        self.output.mem = numpy.zeros((batch, out_h, out_w, ch),
                                      dtype=numpy.float32)
        self.output.initialize(self.device)


class MaxPooling(Pooling):
    """Max over each window; znicz's ``MaxPooling`` (the
    ``MaxAbsPooling`` variant keeps the signed value of the max-|x|
    element)."""

    MAPPING = "max_pooling"
    ABS = False

    def tforward(self, read, write, params, ctx, state=None):
        import jax.numpy as jnp
        from jax import lax
        x = read(self.input)  # pooling keeps the activation dtype
        _, in_h, in_w, _ = x.shape
        pad = self._window_padding(in_h, in_w)
        if self.ABS:
            # Signed value of the max-absolute element: take the max
            # over |x| and recover the sign via paired reductions.
            hi = lax.reduce_window(
                x, _typed_inf(x.dtype, -1), lax.max,
                self._window_dims(), self._window_strides(), pad)
            lo = lax.reduce_window(
                x, _typed_inf(x.dtype, +1), lax.min,
                self._window_dims(), self._window_strides(), pad)
            y = jnp.where(-lo > hi, lo, hi)
        else:
            y = lax.reduce_window(
                x, _typed_inf(x.dtype, -1), lax.max,
                self._window_dims(), self._window_strides(), pad)
        write(self.output, y)


class MaxAbsPooling(MaxPooling):
    MAPPING = "maxabs_pooling"
    ABS = True


class AvgPooling(Pooling):
    """Mean over each window (znicz ``AvgPooling``)."""

    MAPPING = "avg_pooling"

    def tforward(self, read, write, params, ctx, state=None):
        import jax.numpy as jnp
        from jax import lax
        x = read(self.input)
        # Accumulate in f32 even on a bf16 activation stream — a
        # windowed bf16 sum rounds every partial to 8 mantissa bits.
        x32 = x.astype(jnp.float32)
        _, in_h, in_w, _ = x.shape
        pad = self._window_padding(in_h, in_w)
        ssum = lax.reduce_window(
            x32, 0.0, lax.add, self._window_dims(),
            self._window_strides(), pad)
        # Divide by the true (unpadded) window population.
        ones = jnp.ones_like(x32)
        count = lax.reduce_window(
            ones, 0.0, lax.add, self._window_dims(),
            self._window_strides(), pad)
        write(self.output, (ssum / count).astype(x.dtype))


class StochasticPooling(Pooling):
    """Stochastic pooling (znicz ``StochasticPooling``): training picks
    one window element with probability ∝ its (non-negative)
    activation; inference outputs the probability-weighted mean.
    Restricted to non-overlapping windows (sliding == kernel), the only
    configuration znicz's samples used."""

    MAPPING = "stochastic_pooling"
    ABS = False

    def __init__(self, workflow, **kwargs):
        super(StochasticPooling, self).__init__(workflow, **kwargs)
        # Geometry restrictions checked up front so output_spatial
        # and the traced patches view always agree.
        if self.sliding != (self.ky, self.kx):
            raise ValueError(
                "%s supports only sliding == kernel" % self)
        if self.padding != ((0, 0), (0, 0)):
            raise ValueError("%s does not support padding" % self)

    def _patches(self, x):
        """(B, OH, OW, ky·kx, C) view of non-overlapping windows,
        padding the ragged tail with zeros."""
        import jax.numpy as jnp
        b, h, w, c = x.shape
        oh = -(-h // self.ky)
        ow = -(-w // self.kx)
        x = jnp.pad(x, ((0, 0), (0, oh * self.ky - h),
                        (0, ow * self.kx - w), (0, 0)))
        x = x.reshape(b, oh, self.ky, ow, self.kx, c)
        x = x.transpose(0, 1, 3, 2, 4, 5)
        return x.reshape(b, oh, ow, self.ky * self.kx, c)

    def tforward(self, read, write, params, ctx, state=None):
        import jax
        import jax.numpy as jnp
        x = read(self.input).astype(jnp.float32)
        p = self._patches(jnp.abs(x) if self.ABS else x)
        v = self._patches(x)
        w = jnp.maximum(p, 0.0)
        tot = w.sum(axis=3, keepdims=True)
        # All-zero windows fall back to uniform.
        k = w.shape[3]
        probs = jnp.where(tot > 0, w / jnp.maximum(tot, 1e-30),
                          1.0 / k)
        from ..accelerated_units import select_by_training

        def train_branch():
            g = jax.random.gumbel(ctx.next_key(), probs.shape)
            pick = jnp.argmax(jnp.log(probs + 1e-30) + g, axis=3)
            return jnp.take_along_axis(
                v, pick[:, :, :, None, :], axis=3)[:, :, :, 0, :]

        def eval_branch():
            return (probs * v).sum(axis=3)

        write(self.output, select_by_training(
            ctx, train_branch, eval_branch))


class StochasticAbsPooling(StochasticPooling):
    MAPPING = "stochastic_abs_pooling"
    ABS = True
