"""RBM and tied-weight autoencoder pretraining units.

Reconstructed znicz capability surface (SURVEY §2.5 / BASELINE.json
parity config #4: "RBM/autoencoder pretraining with tied-weight deconv
units"; the reference's GPU RNG kernel ocl/random.cl existed largely to
drive the RBM's Bernoulli sampling).

TPU-era mapping of contrastive divergence: CD-k is NOT plain gradient
descent, but its update rule IS the gradient of the free-energy
difference

    L = FE(v0) − FE(vk),   FE(v) = −v·b − Σ softplus(c + vW)

with the negative phase ``vk`` treated as a constant
(``stop_gradient``).  So the :class:`RBM` unit computes the Gibbs
chain with the step's keyed PRNG and sets L as the step loss — the
fused-step compiler's ``jax.grad`` then yields exactly the CD-k
statistics ⟨v0ᵀh0⟩−⟨vkᵀhk⟩, and the ordinary per-layer GD units
(momentum, weight decay) apply them.  One jitted computation per tick,
no hand-written CD kernels.

:class:`All2AllDeconv` is the tied-weight decoder half for denoising-
autoencoder pretraining: y = act(x·Wᵀ + b) with W read from (and
trained through) the paired encoder.
"""

import numpy

from ..memory import Vector
from . import nn_units
from .evaluator import EvaluatorMSE
from .nn_units import ForwardBase, GradientDescentBase


class RBM(ForwardBase):
    """Bernoulli-Bernoulli RBM layer trained by CD-k
    (znicz RBM unit family).

    Outputs: ``output`` — hidden probabilities h0 (the features for
    stacking); ``reconstruction`` — vk probabilities (for evaluators).
    """

    MAPPING = "rbm"

    def __init__(self, workflow, **kwargs):
        super(RBM, self).__init__(workflow, **kwargs)
        self.output_sample_shape = kwargs.get("output_sample_shape",
                                              kwargs.get("output_shape"))
        if isinstance(self.output_sample_shape, int):
            self.output_sample_shape = (self.output_sample_shape,)
        self.cd_k = kwargs.get("cd_k", 1)
        self.mask = None  # linked: loader.minibatch_mask
        self.vbias = Vector()  # visible bias (b)
        self.reconstruction = Vector()

    @property
    def n_hidden(self):
        n = 1
        for d in self.output_sample_shape:
            n *= d
        return n

    @property
    def trainables(self):
        t = {"weights": self.weights, "vbias": self.vbias}
        if self.include_bias:
            t["bias"] = self.bias  # hidden bias (c)
        return t

    def initialize(self, device=None, **kwargs):
        super(RBM, self).initialize(device=device, **kwargs)
        batch = self.input.shape[0]
        n_vis = self.input.size // batch
        n_hid = self.n_hidden
        if not self.weights:
            stddev = self.weights_stddev or (1.0 / numpy.sqrt(n_vis))
            w = numpy.zeros((n_vis, n_hid), dtype=numpy.float32)
            self.rand().fill_normal(w, stddev=stddev)
            self.weights.mem = w
            self.weights.initialize(self.device)
        if self.include_bias and not self.bias:
            self.bias.mem = numpy.zeros(n_hid, dtype=numpy.float32)
            self.bias.initialize(self.device)
        if not self.vbias:
            self.vbias.mem = numpy.zeros(n_vis, dtype=numpy.float32)
            self.vbias.initialize(self.device)
        self.output.mem = numpy.zeros((batch,) +
                                      tuple(self.output_sample_shape),
                                      dtype=numpy.float32)
        self.output.initialize(self.device)
        self.reconstruction.mem = numpy.zeros(
            (batch, n_vis), dtype=numpy.float32)
        self.reconstruction.initialize(self.device)

    def step_persist_vectors(self):
        return [self.output, self.reconstruction]

    def _free_energy(self, v, w, b, c):
        import jax
        import jax.numpy as jnp
        return -(v @ b) - jax.nn.softplus(c + v @ w).sum(axis=-1)

    def tforward(self, read, write, params, ctx, state=None):
        import jax
        import jax.numpy as jnp
        v0 = read(self.input)
        v0 = v0.reshape(v0.shape[0], -1).astype(jnp.float32)
        w = params["weights"]
        b = params["vbias"]
        c = params["bias"] if self.include_bias else 0.0

        h = jax.nn.sigmoid(v0 @ w + c)
        write(self.output,
              h.reshape((v0.shape[0],) +
                        tuple(self.output_sample_shape)))
        vk = v0
        hk = h
        for _ in range(self.cd_k):
            hs = jax.random.bernoulli(
                ctx.next_key(), hk).astype(jnp.float32)
            vk = jax.nn.sigmoid(hs @ w.T + b)
            hk = jax.nn.sigmoid(vk @ w + c)
        vk = jax.lax.stop_gradient(vk)
        write(self.reconstruction, vk)
        # CD-k pseudo-loss: grad == positive − negative statistics.
        # Padded rows of partial minibatches carry no statistics —
        # mask them like every other loss-setting unit does.
        per_sample = (self._free_energy(v0, w, b, c) -
                      self._free_energy(vk, w, b, c))
        if self.mask is not None:
            m = read(self.mask)
            loss = (per_sample * m).sum() / jnp.maximum(m.sum(), 1.0)
        else:
            loss = per_sample.mean()
        ctx.set_loss(loss)


class GDRBM(GradientDescentBase):
    """Momentum/decay applier for the CD statistics."""
    MAPPING = "rbm"


class EvaluatorRBM(EvaluatorMSE):
    """Reconstruction-MSE metrics for RBM pretraining: identical to
    EvaluatorMSE except it does NOT claim the step loss — the RBM's
    CD pseudo-loss is the differentiated objective; this unit only
    feeds Decision's epoch accounting."""

    OWNS_LOSS = False


class All2AllDeconv(ForwardBase):
    """Tied-weight dense decoder: y = act(x·Wᵀ + b) with W shared
    from the paired encoder All2All (znicz tied-weight deconv for
    autoencoder pretraining).  Own trainable: the visible bias."""

    MAPPING = "all2all_deconv"

    def __init__(self, workflow, **kwargs):
        super(All2AllDeconv, self).__init__(workflow, **kwargs)
        self.encoder = kwargs["get_weights_from"]
        self.vbias = Vector()

    @property
    def trainables(self):
        return {"vbias": self.vbias} if self.include_bias else {}

    def activation(self, v):
        return v

    def initialize(self, device=None, **kwargs):
        if not self.encoder.is_initialized:
            raise AttributeError(
                "%s: tied encoder %s not initialized yet" %
                (self.name, self.encoder.name))
        super(All2AllDeconv, self).initialize(device=device, **kwargs)
        batch = self.input.shape[0]
        n_vis = self.encoder.weights.shape[0]
        if not self.vbias:
            self.vbias.mem = numpy.zeros(n_vis, dtype=numpy.float32)
            self.vbias.initialize(self.device)
        self.output.mem = numpy.zeros((batch, n_vis),
                                      dtype=numpy.float32)
        self.output.initialize(self.device)

    def tforward(self, read, write, params, ctx, state=None):
        import jax.numpy as jnp
        x = read(self.input)
        x = x.reshape(x.shape[0], -1).astype(jnp.float32)
        w = read(self.encoder.weights)  # tied: grads flow to encoder
        y = x @ w.T
        if self.include_bias:
            y = y + params["vbias"]
        write(self.output, self.activation(y))


class All2AllDeconvSigmoid(All2AllDeconv):
    MAPPING = "all2all_deconv_sigmoid"

    def activation(self, v):
        return nn_units.act_sigmoid(v)


class All2AllDeconvTanh(All2AllDeconv):
    MAPPING = "all2all_deconv_tanh"

    def activation(self, v):
        return nn_units.act_tanh(v)


class GDA2ADeconv(GradientDescentBase):
    MAPPING = "all2all_deconv"


class GDA2ADeconvSigmoid(GradientDescentBase):
    MAPPING = "all2all_deconv_sigmoid"


class GDA2ADeconvTanh(GradientDescentBase):
    MAPPING = "all2all_deconv_tanh"
