"""TinyLM — a causal transformer language-model workflow.

The reference has no attention models (SURVEY §5: long-context absent
in the 2013-15 framework); this sample exercises the TPU build's
long-context stack end-to-end: Embedding → N × TransformerBlock
(optionally ring sequence-parallel over a mesh ``seq`` axis) →
LMHead (tied weights) → EvaluatorLM → DecisionGD → per-unit GD, the
whole tick one fused XLA computation like every other workflow.

The bundled dataset is the **first-token recall** task: every label
equals the sequence's FIRST token, so the model cannot succeed
without attending across the whole (causal) context — a pure test of
the attention path that a bag-of-last-tokens model fails at chance
level (1/vocab).  Run::

    python -m veles_tpu veles_tpu/znicz/samples/tinylm.py
"""

import numpy

from ...config import root, get as config_get
from ...loader.fullbatch import FullBatchLoader
from ...plumbing import Repeater
from ...accelerated_units import AcceleratedWorkflow
from ..attention import (Embedding, EvaluatorLM, GDEmbedding,
                         GDLMHead, GDMoETransformerBlock,
                         GDPipelinedStack, GDTransformerBlock,
                         LMHead, MoETransformerBlock,
                         PipelinedTransformerStack,
                         TransformerBlock)
from ..decision import DecisionGD


class FirstTokenLoader(FullBatchLoader):
    """Synthetic sequences whose every label is the first token."""

    MAPPING = "first_token_loader"

    def __init__(self, workflow, **kwargs):
        super(FirstTokenLoader, self).__init__(workflow, **kwargs)
        self.vocab_size = kwargs.get("vocab_size", 16)
        self.seq_len = kwargs.get("seq_len", 32)
        self.n_train = kwargs.get("n_train", 512)
        self.n_valid = kwargs.get("n_valid", 128)

    def load_data(self):
        rng = numpy.random.RandomState(7)
        n = self.n_valid + self.n_train
        tokens = rng.randint(0, self.vocab_size,
                             (n, self.seq_len)).astype(numpy.int32)
        labels = numpy.repeat(tokens[:, :1], self.seq_len, axis=1)
        self.original_data.mem = tokens
        self.original_labels.mem = labels.astype(numpy.int32)
        self.class_lengths = [0, self.n_valid, self.n_train]


class TinyLMWorkflow(AcceleratedWorkflow):
    """The LM training workflow (long-context capability sample)."""

    def __init__(self, workflow, vocab_size=16, seq_len=32,
                 embed_dim=32, n_heads=4, n_blocks=1,
                 minibatch_size=64, learning_rate=0.01,
                 gradient_moment=0.9, max_epochs=8, seq_axis=None,
                 sp_mode="ring", sp_kernel=None, sp_interpret=None,
                 n_experts=0, expert_axis=None, top_k=None,
                 router_z_weight=None, pipelined=False,
                 stage_axis=None, n_microbatches=4, schedule=None,
                 n_chunks=None, fused_qkv=None,
                 loader_cls=FirstTokenLoader, loader_config=None,
                 **kwargs):
        super(TinyLMWorkflow, self).__init__(workflow, **kwargs)
        self.repeater = Repeater(self)
        self.repeater.link_from(self.start_point)

        self.loader = loader_cls(
            self, minibatch_size=minibatch_size,
            vocab_size=vocab_size, seq_len=seq_len,
            **(loader_config or {}))
        self.loader.link_from(self.repeater)

        self.embedding = Embedding(
            self, vocab_size=vocab_size, embed_dim=embed_dim,
            name="embedding")
        self.embedding.link_from(self.loader)
        self.embedding.input = self.loader.minibatch_data

        self.forwards = [self.embedding]
        prev = self.embedding
        if pipelined and n_experts:
            raise ValueError(
                "pipelined=True with n_experts>0 is not supported — "
                "the pipelined stack holds dense blocks only")
        if pipelined:
            stack = PipelinedTransformerStack(
                self, n_blocks=n_blocks, n_heads=n_heads,
                causal=True, stage_axis=stage_axis,
                n_microbatches=n_microbatches, schedule=schedule,
                n_chunks=n_chunks, fused_qkv=fused_qkv,
                name="stack")
            stack.link_from(prev)
            stack.input = prev.output
            self.forwards.append(stack)
            prev = stack
            n_blocks = 0
        for i in range(n_blocks):
            if n_experts:
                block = MoETransformerBlock(
                    self, n_heads=n_heads, causal=True,
                    seq_axis=seq_axis, sp_mode=sp_mode,
                    sp_kernel=sp_kernel, sp_interpret=sp_interpret,
                    n_experts=n_experts, top_k=top_k,
                    router_z_weight=router_z_weight,
                    fused_qkv=fused_qkv, expert_axis=expert_axis,
                    # Buckets the router-health accumulator rows by
                    # sample class and gates padded ticks
                    # (moe.aux_loss / moe.expert_load).
                    minibatch_class_vec=(
                        self.loader.minibatch_class_vec),
                    minibatch_mask=self.loader.minibatch_mask,
                    name="block%d" % i)
            else:
                block = TransformerBlock(
                    self, n_heads=n_heads, causal=True,
                    seq_axis=seq_axis, sp_mode=sp_mode,
                    sp_kernel=sp_kernel, sp_interpret=sp_interpret,
                    fused_qkv=fused_qkv, name="block%d" % i)
            block.link_from(prev)
            block.input = prev.output
            self.forwards.append(block)
            prev = block

        self.head = LMHead(self, vocab_size=vocab_size,
                           tie_to=self.embedding, name="head")
        self.head.link_from(prev)
        self.head.input = prev.output
        self.forwards.append(self.head)

        self.evaluator = EvaluatorLM(self)
        self.evaluator.link_from(self.head)
        self.evaluator.input = self.head.output
        self.evaluator.labels = self.loader.minibatch_labels
        self.evaluator.mask = self.loader.minibatch_mask
        self.evaluator.minibatch_class_vec = \
            self.loader.minibatch_class_vec

        self.decision = DecisionGD(self, max_epochs=max_epochs,
                                   evaluator=self.evaluator)
        self.decision.link_from(self.evaluator)
        self.decision.link_attrs(
            self.loader, "minibatch_class", "last_minibatch",
            "epoch_ended", "epoch_number")

        gd_kw = {"learning_rate": learning_rate,
                 "gradient_moment": gradient_moment}
        self.gds = []
        prev_gd = self.decision
        for unit in reversed(self.forwards):
            cls = {Embedding: GDEmbedding,
                   TransformerBlock: GDTransformerBlock,
                   MoETransformerBlock: GDMoETransformerBlock,
                   PipelinedTransformerStack: GDPipelinedStack,
                   LMHead: GDLMHead}[type(unit)]
            gd = cls(self, target=unit, **gd_kw)
            gd.link_from(prev_gd)
            self.gds.append(gd)
            prev_gd = gd

        self.repeater.link_from(prev_gd)
        self.repeater.gate_block = self.decision.complete
        self.end_point.link_from(prev_gd)
        self.end_point.gate_block = ~self.decision.complete


def run(load, main):
    cfg = root.tinylm
    load(TinyLMWorkflow,
         vocab_size=config_get(cfg.vocab_size, 16),
         seq_len=config_get(cfg.seq_len, 32),
         embed_dim=config_get(cfg.embed_dim, 32),
         n_heads=config_get(cfg.n_heads, 4),
         n_blocks=config_get(cfg.n_blocks, 1),
         n_experts=config_get(cfg.n_experts, 0),
         top_k=config_get(cfg.top_k, None),
         router_z_weight=config_get(cfg.router_z_weight, None),
         pipelined=config_get(cfg.pipelined, False),
         schedule=config_get(cfg.schedule, None),
         n_chunks=config_get(cfg.n_chunks, None),
         minibatch_size=config_get(cfg.minibatch_size, 64),
         learning_rate=config_get(cfg.learning_rate, 0.01),
         max_epochs=config_get(cfg.max_epochs, 8))
    main()
