"""ImageNet AlexNet workflow — parity config #3
(BASELINE.json: "znicz ImageNet AlexNet workflow (fullbatch loader +
mean_disp_normalizer)"; north-star perf config).

The reference pipeline kept preprocessed byte images device-resident
and normalized on device with the mean_disp_normalizer kernel
(reference: veles/mean_disp_normalizer.py, ocl/mean_disp_normalizer.cl,
veles/loader/fullbatch.py).  Here that is: uint8 originals in HBM,
in-step gather, and a traced (x−mean)·rdisp that XLA fuses into conv1 —
the float image never materializes in memory.

Graph: classic AlexNet — conv96@11×11s4 → LRN → maxpool3s2 →
conv256@5×5p2 → LRN → maxpool → conv384@3×3p1 → conv384 → conv256 →
maxpool → fc4096+dropout → fc4096+dropout → softmax1000 — the whole
tick one jitted XLA computation, convs in bf16 MXU passes.

Dataset: preprocessed numpy archives under
``root.common.dirs.datasets/imagenet`` (``{train,valid}_data.npy``
uint8 NHWC + ``{train,valid}_labels.npy`` int32) when present;
otherwise a synthetic uint8 fallback sized by kwargs (tests + perf
benches use it: the bench measures compute, not JPEG decode).
"""

import os

import numpy

from ...config import root, get as config_get
from ...loader.fullbatch import FullBatchLoader
from ...mean_disp_normalizer import MeanDispNormalizer
from ..standard_workflow import StandardWorkflow


class ImagenetLoader(FullBatchLoader):
    """Device-resident uint8 image loader with mean/rdisp analysis
    (the reference AlexNet path's loader contract)."""

    MAPPING = "imagenet_loader"

    def __init__(self, workflow, **kwargs):
        super(ImagenetLoader, self).__init__(workflow, **kwargs)
        from ...memory import Vector
        self.mean = Vector()
        self.rdisp = Vector()
        # Synthetic-fallback geometry.
        self.sim_image_size = kwargs.get("sim_image_size", 227)
        self.sim_classes = kwargs.get("sim_classes", 1000)
        self.sim_train = kwargs.get("sim_train", 2048)
        self.sim_valid = kwargs.get("sim_valid", 256)

    def load_data(self):
        d = os.path.join(config_get(root.common.dirs.datasets, "."),
                         "imagenet")
        names = ("train_data.npy", "train_labels.npy",
                 "valid_data.npy", "valid_labels.npy")
        paths = [os.path.join(d, n) for n in names]
        if all(map(os.path.isfile, paths)):
            self._load_npy(*paths)
        else:
            self._load_synthetic()
        self._analyze_mean_disp()

    def _load_npy(self, train_d, train_l, valid_d, valid_l):
        train = numpy.load(train_d)
        train_labels = numpy.load(train_l).astype(numpy.int32)
        valid = numpy.load(valid_d)
        valid_labels = numpy.load(valid_l).astype(numpy.int32)
        self.original_data.mem = numpy.concatenate([valid, train])
        self.original_labels.mem = numpy.concatenate(
            [valid_labels, train_labels])
        self.class_lengths = [0, len(valid), len(train)]
        self.info("loaded imagenet npy: %d train, %d validation",
                  len(train), len(valid))

    def _load_synthetic(self):
        s = self.sim_image_size
        n = self.sim_train + self.sim_valid
        rng = numpy.random.RandomState(0)
        labels = (numpy.arange(n) % self.sim_classes).astype(
            numpy.int32)
        rng.shuffle(labels)
        # Class-dependent spatial frequency/phase patterns + noise,
        # quantized to bytes: learnable by a conv stack, and the
        # uint8 → mean-disp path is identical to the real pipeline.
        yy, xx = numpy.mgrid[0:s, 0:s].astype(numpy.float32) / (s - 1)
        data = numpy.empty((n, s, s, 3), dtype=numpy.uint8)
        for i, lab in enumerate(labels):
            freq = 1.0 + (lab % 7)
            phase = (lab // 7) * 0.7
            pattern = numpy.sin(2 * numpy.pi * freq * xx + phase) * \
                numpy.cos(2 * numpy.pi * freq * yy + phase)
            img = pattern[:, :, None] * 80.0 + 128.0 + \
                rng.normal(0, 20.0, (s, s, 3))
            data[i] = numpy.clip(img, 0, 255).astype(numpy.uint8)
        self.original_data.mem = data
        self.original_labels.mem = labels
        self.class_lengths = [0, self.sim_valid, self.sim_train]
        self.info("imagenet files absent — synthetic fallback: "
                  "%d train, %d validation (%dpx, %d classes)",
                  self.sim_train, self.sim_valid, s, self.sim_classes)

    def _analyze_mean_disp(self):
        """Train-set per-pixel mean and reciprocal dispersion
        (the reference loader's dataset analysis feeding
        mean_disp_normalizer).  Two-pass chunked accumulation: the
        uint8 originals are never copied to float wholesale, so the
        real-ImageNet geometry (hundreds of GB) stays O(sample_shape)
        in extra host memory."""
        from ...loader.base import VALID
        train_start = self.class_end_offsets[VALID]
        train = self.original_data.mem[train_start:]
        n = len(train)
        s = numpy.zeros(train.shape[1:], dtype=numpy.float64)
        s2 = numpy.zeros(train.shape[1:], dtype=numpy.float64)
        chunk = max(1, (1 << 28) // max(
            1, int(numpy.prod(train.shape[1:])) * 8))
        for i in range(0, n, chunk):
            part = train[i:i + chunk].astype(numpy.float64)
            s += part.sum(axis=0)
            s2 += (part * part).sum(axis=0)
        mean = s / n
        disp = numpy.sqrt(numpy.maximum(s2 / n - mean * mean, 0.0))
        self.mean.mem = mean.astype(numpy.float32)
        self.rdisp.mem = (1.0 / numpy.maximum(disp, 1e-3)).astype(
            numpy.float32)


def alexnet_layers(n_classes=1000, lr=0.01, moment=0.9, decay=5e-4):
    gd = {"learning_rate": lr, "gradient_moment": moment,
          "weights_decay": decay}
    return [
        {"type": "conv_str",
         "->": {"n_kernels": 96, "kx": 11, "ky": 11,
                "sliding": (4, 4), "weights_stddev": 0.01,
                # space_to_depth=4 is available (conv.py) but
                # measured NEUTRAL-to-slower inside the fused step on
                # v5e — XLA's own conv lowering already handles the
                # C=3 stride-4 case well, and the fold's transposes
                # cost more than the MXU win.  Left off.
                "bias_stddev": 0}, "<-": dict(gd)},
        {"type": "norm", "->": {"alpha": 1e-4, "beta": 0.75, "n": 5,
                                "k": 2.0}},
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3,
                                       "sliding": (2, 2)}},
        {"type": "conv_str",
         "->": {"n_kernels": 256, "kx": 5, "ky": 5, "padding": 2,
                "weights_stddev": 0.01}, "<-": dict(gd)},
        {"type": "norm", "->": {"alpha": 1e-4, "beta": 0.75, "n": 5,
                                "k": 2.0}},
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3,
                                       "sliding": (2, 2)}},
        {"type": "conv_str",
         "->": {"n_kernels": 384, "kx": 3, "ky": 3, "padding": 1,
                "weights_stddev": 0.01}, "<-": dict(gd)},
        {"type": "conv_str",
         "->": {"n_kernels": 384, "kx": 3, "ky": 3, "padding": 1,
                "weights_stddev": 0.01}, "<-": dict(gd)},
        {"type": "conv_str",
         "->": {"n_kernels": 256, "kx": 3, "ky": 3, "padding": 1,
                "weights_stddev": 0.01}, "<-": dict(gd)},
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3,
                                       "sliding": (2, 2)}},
        {"type": "all2all_str",
         "->": {"output_sample_shape": (4096,),
                "weights_stddev": 0.005}, "<-": dict(gd)},
        {"type": "dropout", "->": {"dropout_ratio": 0.5}},
        {"type": "all2all_str",
         "->": {"output_sample_shape": (4096,),
                "weights_stddev": 0.005}, "<-": dict(gd)},
        {"type": "dropout", "->": {"dropout_ratio": 0.5}},
        {"type": "softmax",
         "->": {"output_sample_shape": (n_classes,),
                "weights_stddev": 0.01}, "<-": dict(gd)},
    ]


class AlexNetWorkflow(StandardWorkflow):
    """The AlexNet training workflow with in-step byte normalization."""

    def __init__(self, workflow, layers=None, minibatch_size=256,
                 learning_rate=0.01, gradient_moment=0.9,
                 weights_decay=5e-4, max_epochs=None,
                 fail_iterations=10, loader_cls=ImagenetLoader,
                 loader_config=None, n_classes=1000, **kwargs):
        cfg = {"minibatch_size": minibatch_size}
        cfg.update(loader_config or {})
        super(AlexNetWorkflow, self).__init__(
            workflow,
            layers=layers or alexnet_layers(
                n_classes, learning_rate, gradient_moment,
                weights_decay),
            loader_cls=loader_cls, loader_config=cfg,
            decision_config={"max_epochs": max_epochs,
                             "fail_iterations": fail_iterations},
            loss_function="softmax", **kwargs)

    def first_source(self):
        """Inserts the mean-disp normalizer between the loader's byte
        gather and conv1 (the reference AlexNet pipeline shape)."""
        self.normalizer = MeanDispNormalizer(self)
        self.normalizer.link_from(self.loader)
        self.normalizer.input = self.loader.minibatch_data
        self.normalizer.mean = self.loader.mean
        self.normalizer.rdisp = self.loader.rdisp
        return self.normalizer, self.normalizer.output


def run(load, main):
    load(AlexNetWorkflow,
         minibatch_size=config_get(root.imagenet.minibatch_size, 256),
         learning_rate=config_get(root.imagenet.learning_rate, 0.01),
         max_epochs=config_get(root.imagenet.max_epochs, 90))
    main()
