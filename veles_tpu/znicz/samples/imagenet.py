"""ImageNet AlexNet workflow — parity config #3
(BASELINE.json: "znicz ImageNet AlexNet workflow (fullbatch loader +
mean_disp_normalizer)"; north-star perf config).

The reference pipeline kept preprocessed byte images device-resident
and normalized on device with the mean_disp_normalizer kernel
(reference: veles/mean_disp_normalizer.py, ocl/mean_disp_normalizer.cl,
veles/loader/fullbatch.py).  Here that is: uint8 originals in HBM,
in-step gather, and a traced (x−mean)·rdisp that XLA fuses into conv1 —
the float image never materializes in memory.

Graph: classic AlexNet — conv96@11×11s4 → LRN → maxpool3s2 →
conv256@5×5p2 → LRN → maxpool → conv384@3×3p1 → conv384 → conv256 →
maxpool → fc4096+dropout → fc4096+dropout → softmax1000 — the whole
tick one jitted XLA computation, convs in bf16 MXU passes.

Dataset: preprocessed numpy archives under
``root.common.dirs.datasets/imagenet`` (``{train,valid}_data.npy``
uint8 NHWC + ``{train,valid}_labels.npy`` int32) when present;
otherwise a synthetic uint8 fallback sized by kwargs (tests + perf
benches use it: the bench measures compute, not JPEG decode).
"""

import os

import numpy

from ...config import root, get as config_get
from ...loader.base import VALID
from ...loader.fullbatch import FullBatchLoader
from ...loader.stream import StreamLoader
from ...mean_disp_normalizer import MeanDispNormalizer
from ..standard_workflow import StandardWorkflow


def fill_synthetic(out, labels, rng, s):
    """Class-dependent spatial frequency/phase patterns + noise,
    quantized to bytes: learnable by a conv stack, and the uint8 →
    mean-disp path is identical to the real pipeline.  ``out`` may be
    a plain array or a disk memmap (the streamed loader writes the
    dataset to disk once and never holds it whole in RAM)."""
    yy, xx = numpy.mgrid[0:s, 0:s].astype(numpy.float32) / (s - 1)
    for i, lab in enumerate(labels):
        freq = 1.0 + (lab % 7)
        phase = (lab // 7) * 0.7
        pattern = numpy.sin(2 * numpy.pi * freq * xx + phase) * \
            numpy.cos(2 * numpy.pi * freq * yy + phase)
        img = pattern[:, :, None] * 80.0 + 128.0 + \
            rng.normal(0, 20.0, (s, s, 3))
        out[i] = numpy.clip(img, 0, 255).astype(numpy.uint8)


def analyze_mean_disp(train, chunk_bytes=1 << 28):
    """Train-set per-pixel mean and reciprocal dispersion
    (the reference loader's dataset analysis feeding
    mean_disp_normalizer).  Two-pass chunked accumulation over any
    array-like (incl. disk memmaps): the originals are never copied
    to float wholesale, so the real-ImageNet geometry (hundreds of
    GB) stays O(sample_shape) in extra host memory."""
    n = len(train)
    s = numpy.zeros(train.shape[1:], dtype=numpy.float64)
    s2 = numpy.zeros(train.shape[1:], dtype=numpy.float64)
    chunk = max(1, chunk_bytes // max(
        1, int(numpy.prod(train.shape[1:])) * 8))
    for i in range(0, n, chunk):
        part = numpy.asarray(train[i:i + chunk],
                             dtype=numpy.float64)
        s += part.sum(axis=0)
        s2 += (part * part).sum(axis=0)
    mean = s / n
    disp = numpy.sqrt(numpy.maximum(s2 / n - mean * mean, 0.0))
    rdisp = 1.0 / numpy.maximum(disp, 1e-3)
    return mean.astype(numpy.float32), rdisp.astype(numpy.float32)


class ImagenetLoader(FullBatchLoader):
    """Device-resident uint8 image loader with mean/rdisp analysis
    (the reference AlexNet path's loader contract)."""

    MAPPING = "imagenet_loader"

    def __init__(self, workflow, **kwargs):
        super(ImagenetLoader, self).__init__(workflow, **kwargs)
        from ...memory import Vector
        self.mean = Vector()
        self.rdisp = Vector()
        # Synthetic-fallback geometry.
        self.sim_image_size = kwargs.get("sim_image_size", 227)
        self.sim_classes = kwargs.get("sim_classes", 1000)
        self.sim_train = kwargs.get("sim_train", 2048)
        self.sim_valid = kwargs.get("sim_valid", 256)

    def load_data(self):
        d = os.path.join(config_get(root.common.dirs.datasets, "."),
                         "imagenet")
        names = ("train_data.npy", "train_labels.npy",
                 "valid_data.npy", "valid_labels.npy")
        paths = [os.path.join(d, n) for n in names]
        if all(map(os.path.isfile, paths)):
            self._load_npy(*paths)
        else:
            self._load_synthetic()
        self._analyze_mean_disp()

    def _load_npy(self, train_d, train_l, valid_d, valid_l):
        train = numpy.load(train_d)
        train_labels = numpy.load(train_l).astype(numpy.int32)
        valid = numpy.load(valid_d)
        valid_labels = numpy.load(valid_l).astype(numpy.int32)
        self.original_data.mem = numpy.concatenate([valid, train])
        self.original_labels.mem = numpy.concatenate(
            [valid_labels, train_labels])
        self.class_lengths = [0, len(valid), len(train)]
        self.info("loaded imagenet npy: %d train, %d validation",
                  len(train), len(valid))

    def _load_synthetic(self):
        s = self.sim_image_size
        n = self.sim_train + self.sim_valid
        rng = numpy.random.RandomState(0)
        labels = (numpy.arange(n) % self.sim_classes).astype(
            numpy.int32)
        rng.shuffle(labels)
        data = numpy.empty((n, s, s, 3), dtype=numpy.uint8)
        fill_synthetic(data, labels, rng, s)
        self.original_data.mem = data
        self.original_labels.mem = labels
        self.class_lengths = [0, self.sim_valid, self.sim_train]
        self.info("imagenet files absent — synthetic fallback: "
                  "%d train, %d validation (%dpx, %d classes)",
                  self.sim_train, self.sim_valid, s, self.sim_classes)

    def _analyze_mean_disp(self):
        train_start = self.class_end_offsets[VALID]
        mean, rdisp = analyze_mean_disp(
            self.original_data.mem[train_start:])
        self.mean.mem = mean
        self.rdisp.mem = rdisp


class StreamedImagenetLoader(StreamLoader):
    """Streamed (non-HBM-resident) ImageNet loader — the reference's
    directory-scale path (reference: veles/loader/fullbatch_image.py:
    56-268): the dataset lives ON DISK as ``.npy`` files and is
    memmapped; each block of minibatches is read + staged by the host
    worker pool and double-buffer-uploaded while the previous block
    trains (see loader/stream.py).

    Sources, in order: ``{train,valid}_data.npy`` + labels under
    ``root.common.dirs.datasets/imagenet`` (same contract as
    :class:`ImagenetLoader`); otherwise a synthetic uint8 dataset is
    written to disk ONCE under ``cache_dir`` and memmapped from there —
    so even the fallback streams from real files, never from resident
    memory."""

    MAPPING = "imagenet_stream_loader"

    def __init__(self, workflow, **kwargs):
        super(StreamedImagenetLoader, self).__init__(workflow,
                                                     **kwargs)
        from ...memory import Vector
        self.mean = Vector()
        self.rdisp = Vector()
        self.sim_image_size = kwargs.get("sim_image_size", 227)
        self.sim_classes = kwargs.get("sim_classes", 1000)
        self.sim_train = kwargs.get("sim_train", 2048)
        self.sim_valid = kwargs.get("sim_valid", 256)
        self.cache_dir = kwargs.get("cache_dir")

    def init_unpickled(self):
        super(StreamedImagenetLoader, self).init_unpickled()
        self._sources_ = None  # [(memmap_data, memmap_labels), ...]

    def load_data(self):
        d = os.path.join(config_get(root.common.dirs.datasets, "."),
                         "imagenet")
        names = ("valid_data.npy", "valid_labels.npy",
                 "train_data.npy", "train_labels.npy")
        paths = [os.path.join(d, n) for n in names]
        if not all(map(os.path.isfile, paths)):
            paths = self._write_synthetic()
        valid = numpy.load(paths[0], mmap_mode="r")
        valid_l = numpy.load(paths[1], mmap_mode="r")
        train = numpy.load(paths[2], mmap_mode="r")
        train_l = numpy.load(paths[3], mmap_mode="r")
        self._sources_ = [(valid, valid_l), (train, train_l)]
        self.class_lengths = [0, len(valid), len(train)]
        self.sample_shape = tuple(train.shape[1:])
        self.sample_dtype = train.dtype
        mean, rdisp = self._cached_mean_disp(paths[2], train)
        self.mean.mem = mean
        self.rdisp.mem = rdisp
        self.info("streaming imagenet from disk: %d train + %d "
                  "validation (%s, %s)", len(train), len(valid),
                  "x".join(map(str, self.sample_shape)),
                  self.sample_dtype)

    def _cached_mean_disp(self, train_path, train):
        """mean/rdisp are a pure function of the (immutable) train
        file — cache them beside it so a restart/resume on the real
        hundreds-of-GB geometry costs O(sample_shape), not a full
        sequential disk pass."""
        cache = train_path + ".meandisp.npz"
        st = os.stat(train_path)
        key = numpy.array([st.st_size, int(st.st_mtime)],
                          dtype=numpy.int64)
        if os.path.isfile(cache):
            try:
                with numpy.load(cache) as z:
                    if numpy.array_equal(z["key"], key):
                        return z["mean"], z["rdisp"]
            except Exception as e:
                import logging
                logging.getLogger("imagenet").warning(
                    "corrupt normalization cache %s (%s) — "
                    "recomputing", cache, e)
        mean, rdisp = analyze_mean_disp(train)
        try:
            numpy.savez(cache + ".tmp.npz", key=key, mean=mean,
                        rdisp=rdisp)
            os.replace(cache + ".tmp.npz", cache)
        except OSError:
            self.warning("mean/disp cache not writable at %s", cache)
        return mean, rdisp

    def _write_synthetic(self):
        """Synthesizes the dataset to disk once (chunked through a
        memmap — host RAM stays O(chunk))."""
        import tempfile
        cache = self.cache_dir or os.path.join(
            tempfile.gettempdir(), "veles_tpu_imagenet_%dx%d_%d" % (
                self.sim_train, self.sim_image_size,
                self.sim_classes))
        os.makedirs(cache, exist_ok=True)
        s = self.sim_image_size
        sizes = {"valid": self.sim_valid, "train": self.sim_train}
        out = []
        rng = numpy.random.RandomState(0)
        for part in ("valid", "train"):
            dpath = os.path.join(cache, "%s_data.npy" % part)
            lpath = os.path.join(cache, "%s_labels.npy" % part)
            n = sizes[part]
            if not (os.path.isfile(dpath) and os.path.isfile(lpath)):
                labels = (numpy.arange(n) % self.sim_classes).astype(
                    numpy.int32)
                rng.shuffle(labels)
                mm = numpy.lib.format.open_memmap(
                    dpath + ".tmp", mode="w+", dtype=numpy.uint8,
                    shape=(n, s, s, 3))
                fill_synthetic(mm, labels, rng, s)
                mm.flush()
                del mm
                numpy.save(lpath, labels)
                os.replace(dpath + ".tmp", dpath)
                self.info("wrote synthetic %s set -> %s", part, dpath)
            out.extend([dpath, lpath])
        # Order: valid_data, valid_labels, train_data, train_labels.
        return out

    def dataset_labels(self):
        return [None, numpy.asarray(self._sources_[0][1]),
                numpy.asarray(self._sources_[1][1])]

    def fill_rows(self, indices, out_data, out_labels):
        """Vectorized memmap reads (the 'decode' of the npy source)."""
        n_valid = self.class_lengths[VALID]
        indices = numpy.asarray(indices)
        is_train = indices >= n_valid
        for src_id, sel in ((0, ~is_train), (1, is_train)):
            if not sel.any():
                continue
            data, labels = self._sources_[src_id]
            local = indices[sel] - (n_valid if src_id else 0)
            # memmap fancy indexing → one read per row, no wholesale
            # load.
            out_data[sel] = data[local]
            out_labels[sel] = labels[local]


def alexnet_layers(n_classes=1000, lr=0.01, moment=0.9, decay=5e-4):
    gd = {"learning_rate": lr, "gradient_moment": moment,
          "weights_decay": decay}
    return [
        {"type": "conv_str",
         "->": {"n_kernels": 96, "kx": 11, "ky": 11,
                "sliding": (4, 4), "weights_stddev": 0.01,
                # space_to_depth=4 is available (conv.py) but
                # measured NEUTRAL-to-slower inside the fused step on
                # v5e — XLA's own conv lowering already handles the
                # C=3 stride-4 case well, and the fold's transposes
                # cost more than the MXU win.  Left off.
                "bias_stddev": 0}, "<-": dict(gd)},
        {"type": "norm", "->": {"alpha": 1e-4, "beta": 0.75, "n": 5,
                                "k": 2.0}},
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3,
                                       "sliding": (2, 2)}},
        {"type": "conv_str",
         "->": {"n_kernels": 256, "kx": 5, "ky": 5, "padding": 2,
                "weights_stddev": 0.01}, "<-": dict(gd)},
        {"type": "norm", "->": {"alpha": 1e-4, "beta": 0.75, "n": 5,
                                "k": 2.0}},
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3,
                                       "sliding": (2, 2)}},
        {"type": "conv_str",
         "->": {"n_kernels": 384, "kx": 3, "ky": 3, "padding": 1,
                "weights_stddev": 0.01}, "<-": dict(gd)},
        {"type": "conv_str",
         "->": {"n_kernels": 384, "kx": 3, "ky": 3, "padding": 1,
                "weights_stddev": 0.01}, "<-": dict(gd)},
        {"type": "conv_str",
         "->": {"n_kernels": 256, "kx": 3, "ky": 3, "padding": 1,
                "weights_stddev": 0.01}, "<-": dict(gd)},
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3,
                                       "sliding": (2, 2)}},
        {"type": "all2all_str",
         "->": {"output_sample_shape": (4096,),
                "weights_stddev": 0.005}, "<-": dict(gd)},
        {"type": "dropout", "->": {"dropout_ratio": 0.5}},
        {"type": "all2all_str",
         "->": {"output_sample_shape": (4096,),
                "weights_stddev": 0.005}, "<-": dict(gd)},
        {"type": "dropout", "->": {"dropout_ratio": 0.5}},
        {"type": "softmax",
         "->": {"output_sample_shape": (n_classes,),
                "weights_stddev": 0.01}, "<-": dict(gd)},
    ]


class AlexNetWorkflow(StandardWorkflow):
    """The AlexNet training workflow with in-step byte normalization."""

    def __init__(self, workflow, layers=None, minibatch_size=256,
                 learning_rate=0.01, gradient_moment=0.9,
                 weights_decay=5e-4, max_epochs=None,
                 fail_iterations=10, loader_cls=ImagenetLoader,
                 loader_config=None, n_classes=1000, **kwargs):
        cfg = {"minibatch_size": minibatch_size}
        cfg.update(loader_config or {})
        super(AlexNetWorkflow, self).__init__(
            workflow,
            layers=layers or alexnet_layers(
                n_classes, learning_rate, gradient_moment,
                weights_decay),
            loader_cls=loader_cls, loader_config=cfg,
            decision_config={"max_epochs": max_epochs,
                             "fail_iterations": fail_iterations},
            loss_function="softmax", **kwargs)

    def first_source(self):
        """Inserts the mean-disp normalizer between the loader's byte
        gather and conv1 (the reference AlexNet pipeline shape)."""
        self.normalizer = MeanDispNormalizer(self)
        self.normalizer.link_from(self.loader)
        self.normalizer.input = self.loader.minibatch_data
        self.normalizer.mean = self.loader.mean
        self.normalizer.rdisp = self.loader.rdisp
        return self.normalizer, self.normalizer.output


def run(load, main):
    load(AlexNetWorkflow,
         minibatch_size=config_get(root.imagenet.minibatch_size, 256),
         learning_rate=config_get(root.imagenet.learning_rate, 0.01),
         max_epochs=config_get(root.imagenet.max_epochs, 90))
    main()
