"""Unsupervised pretraining workflows — parity config #4
(BASELINE.json: "RBM/autoencoder pretraining").

Two graphs over the MNIST784 loader:

  * :class:`MnistRBMWorkflow` — Bernoulli RBM trained by CD-k (the
    CD statistics come from autodiff of the free-energy difference,
    rbm.py); Decision tracks reconstruction MSE per epoch.
  * :class:`MnistAEWorkflow` — tied-weight denoising autoencoder:
    All2AllSigmoid encoder + All2AllDeconvSigmoid decoder sharing the
    encoder's weights, MSE against the clean input.

Both produce pretrained weights a supervised workflow can adopt by
Vector assignment (znicz's pretraining → fine-tune flow).
"""

from ...accelerated_units import AcceleratedWorkflow
from ...plumbing import Repeater
from ..decision import DecisionGD
from ..evaluator import EvaluatorMSE
from ..gd import GDSigmoid
from ..rbm import (RBM, GDRBM, EvaluatorRBM, All2AllDeconvSigmoid,
                   GDA2ADeconvSigmoid)
from ..all2all import All2AllSigmoid
from .mnist import MnistLoader


class MnistRBMWorkflow(AcceleratedWorkflow):
    def __init__(self, workflow, n_hidden=128, minibatch_size=100,
                 learning_rate=0.05, gradient_moment=0.5, cd_k=1,
                 max_epochs=5, loader_cls=MnistLoader, **kwargs):
        super(MnistRBMWorkflow, self).__init__(workflow, **kwargs)
        self.repeater = Repeater(self)
        self.repeater.link_from(self.start_point)

        self.loader = loader_cls(self, minibatch_size=minibatch_size)
        self.loader.link_from(self.repeater)

        self.rbm = RBM(self, output_sample_shape=(n_hidden,),
                       cd_k=cd_k, weights_stddev=0.01)
        self.rbm.link_from(self.loader)
        self.rbm.input = self.loader.minibatch_data
        self.rbm.mask = self.loader.minibatch_mask

        self.evaluator = EvaluatorRBM(self)
        self.evaluator.link_from(self.rbm)
        self.evaluator.input = self.rbm.reconstruction
        self.evaluator.target = self.loader.minibatch_data
        self.evaluator.mask = self.loader.minibatch_mask
        self.evaluator.minibatch_class_vec = \
            self.loader.minibatch_class_vec

        self.decision = DecisionGD(self, max_epochs=max_epochs,
                                   evaluator=self.evaluator)
        self.decision.link_from(self.evaluator)
        self.decision.link_attrs(
            self.loader, "minibatch_class", "last_minibatch",
            "epoch_ended", "epoch_number")

        self.gd = GDRBM(self, target=self.rbm,
                        learning_rate=learning_rate,
                        gradient_moment=gradient_moment)
        self.gd.link_from(self.decision)

        self.repeater.link_from(self.gd)
        self.repeater.gate_block = self.decision.complete
        self.end_point.link_from(self.gd)
        self.end_point.gate_block = ~self.decision.complete
        self.forwards = [self.rbm]


class MnistAEWorkflow(AcceleratedWorkflow):
    def __init__(self, workflow, n_hidden=128, minibatch_size=100,
                 learning_rate=0.1, gradient_moment=0.9,
                 max_epochs=5, loader_cls=MnistLoader, **kwargs):
        super(MnistAEWorkflow, self).__init__(workflow, **kwargs)
        self.repeater = Repeater(self)
        self.repeater.link_from(self.start_point)

        self.loader = loader_cls(self, minibatch_size=minibatch_size)
        self.loader.link_from(self.repeater)

        self.encoder = All2AllSigmoid(
            self, output_sample_shape=(n_hidden,),
            weights_stddev=0.05, name="encoder")
        self.encoder.link_from(self.loader)
        self.encoder.input = self.loader.minibatch_data

        self.decoder = All2AllDeconvSigmoid(
            self, get_weights_from=self.encoder, name="decoder")
        self.decoder.link_from(self.encoder)
        self.decoder.input = self.encoder.output

        self.evaluator = EvaluatorMSE(self, root=True)
        self.evaluator.link_from(self.decoder)
        self.evaluator.input = self.decoder.output
        self.evaluator.target = self.loader.minibatch_data
        self.evaluator.mask = self.loader.minibatch_mask
        self.evaluator.minibatch_class_vec = \
            self.loader.minibatch_class_vec

        self.decision = DecisionGD(self, max_epochs=max_epochs,
                                   evaluator=self.evaluator)
        self.decision.link_from(self.evaluator)
        self.decision.link_attrs(
            self.loader, "minibatch_class", "last_minibatch",
            "epoch_ended", "epoch_number")

        gd_kw = {"learning_rate": learning_rate,
                 "gradient_moment": gradient_moment}
        self.gd_decoder = GDA2ADeconvSigmoid(
            self, target=self.decoder, **gd_kw)
        self.gd_decoder.link_from(self.decision)
        self.gd_encoder = GDSigmoid(
            self, target=self.encoder, **gd_kw)
        self.gd_encoder.link_from(self.gd_decoder)

        self.repeater.link_from(self.gd_encoder)
        self.repeater.gate_block = self.decision.complete
        self.end_point.link_from(self.gd_encoder)
        self.end_point.gate_block = ~self.decision.complete
        self.forwards = [self.encoder, self.decoder]
