"""CIFAR-10 convolutional workflow — parity config #2
(BASELINE.json: "znicz CIFAR-10 conv workflow").

Graph: the classic znicz/caffe "cifar-quick" shape expressed as a
declarative StandardWorkflow layer list — conv(32,5×5,pad2) →
maxpool(3×3,s2) → conv(32,5×5,pad2) → avgpool(3×3,s2) →
conv(64,5×5,pad2) → avgpool(3×3,s2) → fc(64) → softmax(10) — the whole
tick (gather + convs + CE + backward + momentum updates) is ONE jitted
XLA computation; convs run on the MXU in bf16 with f32 accumulation.

Dataset: the real CIFAR-10 python batches under
``root.common.dirs.datasets/cifar-10-batches-py`` when present;
otherwise a structured synthetic fallback (class-dependent color/
frequency patterns + noise) so the workflow trains offline — tests gate
on the fallback.
"""

import os
import pickle

import numpy

from ...config import root, get as config_get
from ...loader.fullbatch import FullBatchLoader
from ..standard_workflow import StandardWorkflow


class CifarLoader(FullBatchLoader):
    """60k-sample CIFAR-10 (50k train / 10k validation) or the
    synthetic offline fallback."""

    MAPPING = "cifar_loader"

    #: Fallback geometry (kept small so CPU tests stay fast).
    FALLBACK_TRAIN = 1000
    FALLBACK_VALID = 300

    def load_data(self):
        cifar_dir = os.path.join(
            config_get(root.common.dirs.datasets, "."),
            "cifar-10-batches-py")
        train_files = [os.path.join(cifar_dir, "data_batch_%d" % i)
                       for i in range(1, 6)]
        test_file = os.path.join(cifar_dir, "test_batch")
        if all(map(os.path.isfile, train_files)) and \
                os.path.isfile(test_file):
            self._load_real(train_files, test_file)
        else:
            self._load_synthetic_fallback()

    @staticmethod
    def _read_batch(path):
        with open(path, "rb") as fin:
            d = pickle.load(fin, encoding="bytes")
        data = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        labels = numpy.asarray(d[b"labels"], dtype=numpy.int32)
        return data.astype(numpy.float32), labels

    def _load_real(self, train_files, test_file):
        train_x, train_y = [], []
        for path in train_files:
            x, y = self._read_batch(path)
            train_x.append(x)
            train_y.append(y)
        train_x = numpy.concatenate(train_x)
        train_y = numpy.concatenate(train_y)
        test_x, test_y = self._read_batch(test_file)
        # znicz normalized CIFAR linearly to [-1, 1].
        data = numpy.concatenate([test_x, train_x]) / 127.5 - 1.0
        labels = numpy.concatenate([test_y, train_y])
        self.original_data.mem = data.astype(numpy.float32)
        self.original_labels.mem = labels
        self.class_lengths = [0, len(test_x), len(train_x)]
        self.info("loaded real CIFAR-10: %d train, %d validation",
                  len(train_x), len(test_x))

    def _load_synthetic_fallback(self):
        n_train, n_valid = self.FALLBACK_TRAIN, self.FALLBACK_VALID
        n = n_train + n_valid
        rng = numpy.random.RandomState(0)
        labels = (numpy.arange(n) % 10).astype(numpy.int32)
        rng.shuffle(labels)
        yy, xx = numpy.mgrid[0:32, 0:32].astype(numpy.float32) / 31.0
        data = numpy.empty((n, 32, 32, 3), dtype=numpy.float32)
        for i, lab in enumerate(labels):
            freq = 1.0 + (lab % 5)
            phase = (lab // 5) * numpy.pi / 2
            pattern = numpy.sin(2 * numpy.pi * freq * xx + phase) * \
                numpy.cos(2 * numpy.pi * freq * yy)
            color = numpy.array([(lab % 3) - 1.0,
                                 ((lab // 3) % 3) - 1.0,
                                 ((lab // 9) % 3) - 1.0]) * 0.5
            img = pattern[:, :, None] * 0.5 + color[None, None, :]
            data[i] = img + rng.normal(0, 0.15, img.shape)
        self.original_data.mem = numpy.clip(data, -1, 1)
        self.original_labels.mem = labels
        self.class_lengths = [0, n_valid, n_train]
        self.info("CIFAR files absent — synthetic fallback: %d train, "
                  "%d validation", n_train, n_valid)


def cifar_layers(lr=0.001, moment=0.9, decay=0.004):
    gd = {"learning_rate": lr, "gradient_moment": moment,
          "weights_decay": decay}
    return [
        {"type": "conv_str",
         "->": {"n_kernels": 32, "kx": 5, "ky": 5, "padding": 2,
                "weights_stddev": 1e-4}, "<-": dict(gd)},
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3,
                                       "sliding": (2, 2)}},
        {"type": "conv_str",
         "->": {"n_kernels": 32, "kx": 5, "ky": 5, "padding": 2,
                "weights_stddev": 0.01}, "<-": dict(gd)},
        {"type": "avg_pooling", "->": {"kx": 3, "ky": 3,
                                       "sliding": (2, 2)}},
        {"type": "conv_str",
         "->": {"n_kernels": 64, "kx": 5, "ky": 5, "padding": 2,
                "weights_stddev": 0.01}, "<-": dict(gd)},
        {"type": "avg_pooling", "->": {"kx": 3, "ky": 3,
                                       "sliding": (2, 2)}},
        {"type": "all2all_tanh",
         "->": {"output_sample_shape": (64,),
                "weights_stddev": 0.1}, "<-": dict(gd)},
        {"type": "softmax",
         "->": {"output_sample_shape": (10,),
                "weights_stddev": 0.1}, "<-": dict(gd)},
    ]


class CifarWorkflow(StandardWorkflow):
    """The CIFAR-10 conv training workflow."""

    def __init__(self, workflow, minibatch_size=100,
                 learning_rate=0.001, gradient_moment=0.9,
                 weights_decay=0.004, max_epochs=None,
                 fail_iterations=50, layers=None,
                 loader_cls=CifarLoader, **kwargs):
        super(CifarWorkflow, self).__init__(
            workflow,
            layers=layers or cifar_layers(
                learning_rate, gradient_moment, weights_decay),
            loader_cls=loader_cls,
            loader_config={"minibatch_size": minibatch_size},
            decision_config={"max_epochs": max_epochs,
                             "fail_iterations": fail_iterations},
            loss_function="softmax", **kwargs)


def run(load, main):
    load(CifarWorkflow,
         minibatch_size=config_get(root.cifar.minibatch_size, 100),
         learning_rate=config_get(root.cifar.learning_rate, 0.001),
         max_epochs=config_get(root.cifar.max_epochs, 50))
    main()
