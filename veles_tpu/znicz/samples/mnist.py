"""MNIST784 fully-connected workflow — parity config #1
(BASELINE.json: "znicz MNIST784 fully-connected workflow (All2All + GD)").

Graph shape mirrors the classic znicz MNIST sample: Repeater →
FullBatchLoader → All2AllTanh(100) → All2AllSoftmax(10) →
EvaluatorSoftmax → DecisionGD → GD chain → loop; the whole tick
(gather + forward + CE loss + backward + momentum updates) compiles to
ONE jitted XLA computation.

Dataset: real MNIST IDX files under ``root.common.dirs.datasets/mnist``
when present; otherwise falls back to scikit-learn's bundled 8×8 digits
upsampled to 28×28 (same 784-feature shape) so the workflow runs
offline — accuracy gates in tests use the fallback.
"""

import gzip
import os
import struct

import numpy

from ...accelerated_units import AcceleratedWorkflow
from ...config import root, get as config_get
from ...loader.fullbatch import FullBatchLoader
from ...plumbing import Repeater
from ..all2all import All2AllTanh, All2AllSoftmax
from ..evaluator import EvaluatorSoftmax
from ..decision import DecisionGD
from ..gd import GDTanh, GDSoftmax


def _read_idx(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as fin:
        magic, = struct.unpack(">I", fin.read(4))
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, fin.read(4 * ndim))
        data = numpy.frombuffer(fin.read(), dtype=numpy.uint8)
    return data.reshape(dims)


class MnistLoader(FullBatchLoader):
    """70k-sample MNIST (60k train / 10k validation) or the offline
    digits fallback (~1.4k train / ~0.4k validation)."""

    MAPPING = "mnist_loader"

    def load_data(self):
        mnist_dir = os.path.join(
            config_get(root.common.dirs.datasets, "."), "mnist")
        candidates = {
            "train_images": ("train-images-idx3-ubyte",
                             "train-images-idx3-ubyte.gz"),
            "train_labels": ("train-labels-idx1-ubyte",
                             "train-labels-idx1-ubyte.gz"),
            "test_images": ("t10k-images-idx3-ubyte",
                            "t10k-images-idx3-ubyte.gz"),
            "test_labels": ("t10k-labels-idx1-ubyte",
                            "t10k-labels-idx1-ubyte.gz"),
        }
        paths = {}
        for key, names in candidates.items():
            for name in names:
                p = os.path.join(mnist_dir, name)
                if os.path.isfile(p):
                    paths[key] = p
                    break
        if len(paths) == 4:
            self._load_idx(paths)
        else:
            self._load_digits_fallback()

    def _load_idx(self, paths):
        train = _read_idx(paths["train_images"]).astype(
            numpy.float32) / 255.0
        train_l = _read_idx(paths["train_labels"]).astype(numpy.int32)
        test = _read_idx(paths["test_images"]).astype(
            numpy.float32) / 255.0
        test_l = _read_idx(paths["test_labels"]).astype(numpy.int32)
        n_train, n_valid = len(train), len(test)
        data = numpy.concatenate(
            [test.reshape(n_valid, -1), train.reshape(n_train, -1)])
        labels = numpy.concatenate([test_l, train_l])
        self.original_data.mem = data
        self.original_labels.mem = labels
        self.class_lengths = [0, n_valid, n_train]
        self.info("loaded real MNIST: %d train, %d validation",
                  n_train, n_valid)

    def _load_digits_fallback(self):
        from sklearn.datasets import load_digits
        digits = load_digits()
        images = digits.images.astype(numpy.float32) / 16.0
        labels = digits.target.astype(numpy.int32)
        # Nearest-neighbour 8×8 → 28×28 so the feature shape matches
        # MNIST784.
        idx = (numpy.arange(28) * 8) // 28
        images = images[:, idx][:, :, idx]
        n = len(images)
        n_valid = n // 5
        # validation first (class order TEST, VALID, TRAIN).
        self.original_data.mem = images.reshape(n, -1)
        self.original_labels.mem = labels
        self.class_lengths = [0, n_valid, n - n_valid]
        self.info("MNIST files absent — digits fallback: %d train, "
                  "%d validation", n - n_valid, n_valid)


class MnistWorkflow(AcceleratedWorkflow):
    """The MNIST784 training workflow."""

    def __init__(self, workflow, layers=(100, 10), minibatch_size=100,
                 learning_rate=0.03, gradient_moment=0.9,
                 weights_decay=0.0005, max_epochs=None,
                 fail_iterations=25, loader_cls=MnistLoader,
                 loader_config=None, **kwargs):
        super(MnistWorkflow, self).__init__(workflow, **kwargs)

        self.repeater = Repeater(self)
        self.repeater.link_from(self.start_point)

        self.loader = loader_cls(self, minibatch_size=minibatch_size,
                                 **(loader_config or {}))
        self.loader.link_from(self.repeater)

        # Forward stack: tanh hiddens + softmax output.
        self.forwards = []
        prev, prev_vec = self.loader, self.loader.minibatch_data
        for i, width in enumerate(layers):
            last = i == len(layers) - 1
            cls = All2AllSoftmax if last else All2AllTanh
            layer = cls(self, output_sample_shape=(width,),
                        name="fc%d" % i)
            layer.link_from(prev)
            layer.input = prev_vec
            self.forwards.append(layer)
            prev, prev_vec = layer, layer.output

        self.evaluator = EvaluatorSoftmax(self)
        self.evaluator.link_from(prev)
        self.evaluator.input = self.forwards[-1].logits
        self.evaluator.labels = self.loader.minibatch_labels
        self.evaluator.mask = self.loader.minibatch_mask
        self.evaluator.minibatch_class_vec = \
            self.loader.minibatch_class_vec

        self.decision = DecisionGD(
            self, max_epochs=max_epochs,
            fail_iterations=fail_iterations,
            evaluator=self.evaluator)
        self.decision.link_from(self.evaluator)
        self.decision.link_attrs(
            self.loader, "minibatch_class", "last_minibatch",
            "epoch_ended", "epoch_number")

        # GD chain (output layer first, like znicz backprop order).
        self.gds = []
        prev_gd = self.decision
        for layer in reversed(self.forwards):
            gd_cls = GDSoftmax if isinstance(layer, All2AllSoftmax) \
                else GDTanh
            gd = gd_cls(self, target=layer,
                        learning_rate=learning_rate,
                        gradient_moment=gradient_moment,
                        weights_decay=weights_decay,
                        name="gd_" + layer.name)
            gd.link_from(prev_gd)
            self.gds.append(gd)
            prev_gd = gd

        self.repeater.link_from(prev_gd)
        self.repeater.gate_block = self.decision.complete
        self.end_point.link_from(prev_gd)
        self.end_point.gate_block = ~self.decision.complete


def run(load, main):
    """velescli entry (reference convention: module-level run(load,
    main))."""
    load(MnistWorkflow,
         layers=tuple(config_get(root.mnist.layers, (100, 10))),
         minibatch_size=config_get(root.mnist.minibatch_size, 100),
         learning_rate=config_get(root.mnist.learning_rate, 0.03),
         max_epochs=config_get(root.mnist.max_epochs, 25))
    main()
