"""Sample workflows — the parity configs from BASELINE.json."""
