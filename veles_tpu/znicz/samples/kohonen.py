"""Kohonen self-organizing map workflow — znicz's unsupervised SOM
family as a launchable sample (SURVEY §2.5 "KohonenForward etc.";
znicz shipped Kohonen samples with grid plotters).

Graph: Repeater → loader → KohonenForward (BMU winner-take-all) →
KohonenTrainer (neighborhood pseudo-loss, decaying σ) → Decision →
GDKohonen → loop; the whole tick is one fused XLA computation like
every other workflow.

Dataset: any FullBatchLoader via ``loader_cls``; the default
synthetic fallback draws clustered 2-D blobs so the sample runs
offline and the map's organization is visually checkable
(MatrixPlotter on ``umatrix()``).
"""

import numpy

from ...accelerated_units import AcceleratedWorkflow
from ...config import root, get as config_get
from ...loader.fullbatch import FullBatchLoader
from ...plumbing import Repeater
from ..decision import DecisionBase
from ..kohonen import GDKohonen, KohonenForward, KohonenTrainer


class BlobLoader(FullBatchLoader):
    """Clustered 2-D points (synthetic fallback)."""

    MAPPING = "som_blob_loader"

    def __init__(self, workflow, **kwargs):
        super(BlobLoader, self).__init__(workflow, **kwargs)
        self.n_clusters = kwargs.get("n_clusters", 4)
        self.n_points = kwargs.get("n_points", 100)
        self.spread = kwargs.get("spread", 0.02)

    def load_data(self):
        rng = numpy.random.RandomState(0)
        centers = rng.rand(self.n_clusters, 2).astype(numpy.float32)
        pts = numpy.concatenate([
            c + rng.normal(0, self.spread, (self.n_points, 2))
            for c in centers])
        self.original_data.mem = pts.astype(numpy.float32)
        self.original_labels.mem = numpy.repeat(
            numpy.arange(self.n_clusters, dtype=numpy.int32),
            self.n_points)
        self.class_lengths = [0, 0, len(pts)]


class KohonenWorkflow(AcceleratedWorkflow):
    """The SOM training workflow (parity: znicz Kohonen samples)."""

    def __init__(self, workflow, shape=(8, 8), minibatch_size=50,
                 learning_rate=0.4, sigma_decay=0.95,
                 max_epochs=None, loader_cls=BlobLoader,
                 loader_config=None, **kwargs):
        super(KohonenWorkflow, self).__init__(workflow, **kwargs)
        self.repeater = Repeater(self)
        self.repeater.link_from(self.start_point)

        self.loader = loader_cls(
            self, minibatch_size=minibatch_size,
            **(loader_config or {}))
        self.loader.link_from(self.repeater)

        self.som = KohonenForward(self, shape=shape,
                                  weights_stddev=0.3)
        self.som.link_from(self.loader)
        self.som.input = self.loader.minibatch_data
        self.forwards = [self.som]

        self.trainer = KohonenTrainer(self, forward=self.som,
                                      sigma_decay=sigma_decay)
        self.trainer.link_from(self.som)
        self.trainer.input = self.loader.minibatch_data
        self.trainer.mask = self.loader.minibatch_mask

        self.decision = DecisionBase(self, max_epochs=max_epochs)
        self.decision.link_from(self.trainer)
        self.decision.link_attrs(
            self.loader, "minibatch_class", "last_minibatch",
            "epoch_ended", "epoch_number")

        self.gd = GDKohonen(self, target=self.som,
                            learning_rate=learning_rate)
        self.gd.link_from(self.decision)
        self.gds = [self.gd]
        self.repeater.link_from(self.gd)
        self.repeater.gate_block = self.decision.complete
        self.end_point.link_from(self.gd)
        self.end_point.gate_block = ~self.decision.complete

    def umatrix(self):
        """The U-matrix (mean distance of each node to its grid
        neighbors) — the classic SOM organization view; feed it to a
        MatrixPlotter."""
        self.som.weights.map_read()
        gy, gx = self.som.shape
        w = numpy.array(self.som.weights.mem).reshape(gy, gx, -1)
        u = numpy.zeros((gy, gx), dtype=numpy.float64)
        for y in range(gy):
            for x in range(gx):
                dists = []
                for dy, dx in ((-1, 0), (1, 0), (0, -1), (0, 1)):
                    ny, nx = y + dy, x + dx
                    if 0 <= ny < gy and 0 <= nx < gx:
                        dists.append(numpy.linalg.norm(
                            w[y, x] - w[ny, nx]))
                u[y, x] = numpy.mean(dists)
        return u

    def quantization_error(self):
        """Mean distance of every sample to its best-matching unit."""
        self.som.weights.map_read()
        self.loader.original_data.map_read()
        w = numpy.array(self.som.weights.mem)
        x = numpy.array(self.loader.original_data.mem).reshape(
            len(self.loader.original_data.mem), -1)
        d = ((x[:, None, :] - w[None, :, :]) ** 2).sum(-1)
        return float(numpy.sqrt(d.min(axis=1)).mean())


def run(load, main):
    load(KohonenWorkflow,
         shape=tuple(config_get(root.kohonen.shape, (8, 8))),
         minibatch_size=config_get(root.kohonen.minibatch_size, 50),
         learning_rate=config_get(root.kohonen.learning_rate, 0.4),
         max_epochs=config_get(root.kohonen.max_epochs, 20))
    main()
