"""Config-driven model workflow builder.

Reconstructed znicz capability surface (znicz ``standard_workflow.
StandardWorkflow``): a training workflow assembled from a declarative
``layers`` list — each entry a dict with the layer's registry ``type``
string, forward kwargs under ``"->"`` and trainer kwargs under ``"<-"``
— plus a loader (by registry name or class), an evaluator chosen by
``loss_function`` and a DecisionGD.  All the reference's sample configs
(MNIST784, CIFAR-10, AlexNet) are instances of this shape.

The assembled graph is the standard training loop::

    start → repeater → loader → forwards… → evaluator → decision
          → gd chain (output-first) → repeater   (until decision.complete)

and the whole tick compiles into one jitted XLA step
(accelerated_units.StepCompiler).
"""

from ..accelerated_units import AcceleratedWorkflow
from ..guardian import HealthGuardian
from ..loader.base import UserLoaderRegistry
from ..plumbing import Repeater
from .decision import DecisionGD
from .evaluator import EvaluatorSoftmax, EvaluatorMSE
from .nn_units import ForwardUnitRegistry, gd_for


class StandardWorkflow(AcceleratedWorkflow):
    """Declarative layers → full training workflow."""

    def __init__(self, workflow, layers=None, loader_name=None,
                 loader_cls=None, loader_config=None,
                 decision_config=None, guardian_config=None,
                 loss_function="softmax", **kwargs):
        super(StandardWorkflow, self).__init__(workflow, **kwargs)
        self.layer_configs = list(layers or [])
        self.loss_function = loss_function

        self.repeater = Repeater(self)
        self.repeater.link_from(self.start_point)

        if loader_cls is None:
            loader_cls = UserLoaderRegistry.get_factory(loader_name)
        self.loader = loader_cls(self, **dict(loader_config or {}))
        self.loader.link_from(self.repeater)

        self.forwards = []
        self.link_forwards()

        self.evaluator = self.link_evaluator()
        self.decision = self.link_decision(
            **dict(decision_config or {}))
        self.guardian = self.link_guardian(
            **dict(guardian_config or {}))
        self.gds = self.link_gds()

        last_gd = self.gds[-1] if self.gds else \
            (self.guardian or self.decision)
        self.repeater.link_from(last_gd)
        self.repeater.gate_block = self.decision.complete
        self.end_point.link_from(last_gd)
        self.end_point.gate_block = ~self.decision.complete

    # -- builders (overridable, znicz ergonomics) --------------------------

    def first_source(self):
        """(unit, vector) feeding the first layer — overridable for
        pipelines inserting preprocessing units (e.g. AlexNet's
        mean-disp normalizer)."""
        return self.loader, self.loader.minibatch_data

    def link_forwards(self):
        prev, prev_vec = self.first_source()
        for i, cfg in enumerate(self.layer_configs):
            cfg = dict(cfg)
            type_name = cfg.pop("type")
            fwd_kwargs = dict(cfg.get("->", cfg.get("forward", {})))
            cls = ForwardUnitRegistry.get_factory(type_name)
            unit = cls(self, name="%s%d" % (type_name, i),
                       **fwd_kwargs)
            unit.link_from(prev)
            unit.input = prev_vec
            self.forwards.append(unit)
            prev, prev_vec = unit, unit.output
        return self.forwards

    def link_evaluator(self):
        last = self.forwards[-1]
        if self.loss_function == "softmax":
            ev = EvaluatorSoftmax(self)
            # Prefer pre-activation logits when the layer has them
            # (Vector identity is what matters; it is allocated later).
            ev.input = last.logits if hasattr(last, "logits") \
                else last.output
            ev.labels = self.loader.minibatch_labels
        elif self.loss_function == "mse":
            ev = EvaluatorMSE(self)
            ev.input = last.output
            ev.target = self.loader.minibatch_targets
            ev.fallback_target = self.loader.minibatch_data
        elif self.loss_function == "lm":
            # Next-token cross-entropy over (B, S, V) logits — the
            # declarative path to transformer LMs ({"type":
            # "embedding"} / {"type": "transformer_block"} /
            # {"type": "lm_head"} layer configs).
            from .attention import EvaluatorLM
            ev = EvaluatorLM(self)
            ev.input = last.output
            ev.labels = self.loader.minibatch_labels
        else:
            raise ValueError("unknown loss_function %r" %
                             self.loss_function)
        ev.link_from(last)
        ev.mask = self.loader.minibatch_mask
        ev.minibatch_class_vec = self.loader.minibatch_class_vec
        return ev

    def link_decision(self, **decision_config):
        decision = DecisionGD(self, evaluator=self.evaluator,
                              **decision_config)
        decision.link_from(self.evaluator)
        decision.link_attrs(
            self.loader, "minibatch_class", "last_minibatch",
            "epoch_ended", "epoch_number")
        return decision

    def link_guardian(self, **guardian_config):
        """Health guardian between decision and the GD chain (it
        reads the metrics the decision just fetched, and a rollback
        must happen before the next update applies).  Returns None
        when the policy is "off" — pass ``guardian_config=
        {"policy": "off"}`` (or set root.common.guardian.policy) to
        train unguarded."""
        from ..config import root as _root, get as _config_get
        policy = guardian_config.get("policy", _config_get(
            _root.common.guardian.policy, "skip"))
        if policy == "off":
            return None
        guardian = HealthGuardian(self, decision=self.decision,
                                  **guardian_config)
        guardian.link_from(self.decision)
        guardian.link_attrs(
            self.loader, "minibatch_class", "last_minibatch",
            "epoch_number")
        return guardian

    def link_gds(self):
        """One trainer per trainable layer, output-first (znicz
        backprop order)."""
        gds = []
        prev = self.guardian or self.decision
        for i in reversed(range(len(self.layer_configs))):
            layer = self.forwards[i]
            if not type(layer).HAS_PARAMS:
                continue
            cfg = dict(self.layer_configs[i])
            gd_kwargs = dict(cfg.get("<-", cfg.get("gd", {})))
            gd_cls = gd_for(type(layer))
            gd = gd_cls(self, target=layer,
                        name="gd_" + layer.name, **gd_kwargs)
            gd.link_from(prev)
            gds.append(gd)
            prev = gd
        return gds
