"""Convolutional layer units.

Reconstructed znicz capability surface (SURVEY §2.5: "Conv" units;
BASELINE.json CIFAR-10 / AlexNet parity configs).  A Conv layer slides
``n_kernels`` filters of size ``ky``×``kx`` over an NHWC input with
``sliding`` stride and ``padding``, then applies the activation.

TPU-era mapping: one ``lax.conv_general_dilated`` in NHWC/HWIO layout —
XLA tiles it onto the MXU directly (bf16 operands, f32 accumulation via
``preferred_element_type``); the activation and bias fuse into the same
kernel.  No im2col, no hand-written backward: gradients come from
autodiff of the fused step (see accelerated_units.StepCompiler).

Geometry ergonomics follow the znicz units: ``padding`` is either a
single int, an (x, y) pair, or a 4-tuple (left, top, right, bottom);
``sliding`` is an (x, y) pair.  Weight init: normal with stddev
``weights_stddev`` (default 1/sqrt(fan_in), fan_in = kx·ky·channels).
"""

import numpy

from . import nn_units
from .nn_units import ForwardBase


def _norm_padding(padding):
    """→ ((top, bottom), (left, right))."""
    if padding is None:
        return ((0, 0), (0, 0))
    if isinstance(padding, int):
        return ((padding, padding), (padding, padding))
    padding = tuple(padding)
    if len(padding) == 2:
        px, py = padding
        return ((py, py), (px, px))
    if len(padding) == 4:
        left, top, right, bottom = padding
        return ((top, bottom), (left, right))
    raise ValueError("bad padding %r" % (padding,))


def _norm_sliding(sliding):
    if sliding is None:
        return (1, 1)
    if isinstance(sliding, int):
        return (sliding, sliding)
    sx, sy = tuple(sliding)
    return (sy, sx)  # row-major (y, x) strides for NHWC


class Conv(ForwardBase):
    """2-D convolution, identity activation (znicz ``Conv``)."""

    MAPPING = "conv"

    def __init__(self, workflow, **kwargs):
        super(Conv, self).__init__(workflow, **kwargs)
        self.n_kernels = kwargs["n_kernels"]
        self.kx = kwargs["kx"]
        self.ky = kwargs.get("ky", self.kx)
        self.padding = _norm_padding(kwargs.get("padding"))
        self.sliding = _norm_sliding(kwargs.get("sliding"))
        # MXU layout lever: a stride-f conv over few input channels
        # (an image's 3) wastes the 128-lane contraction; folding f×f
        # spatial blocks into channels (space-to-depth) makes conv1 a
        # stride-1 k/f conv over C·f² channels — mathematically
        # identical (the kernel zero-pads to a multiple of f), ~3×
        # faster on v5e for AlexNet conv1.  Enabled when
        # space_to_depth == both strides.
        self.space_to_depth = int(kwargs.get("space_to_depth", 0))
        if self.space_to_depth:
            sh, sw = self.sliding
            if not (self.space_to_depth == sh == sw):
                raise ValueError(
                    "space_to_depth (%d) must equal both strides %r"
                    % (self.space_to_depth, self.sliding))

    def output_spatial(self, in_h, in_w):
        (pt, pb), (pl, pr) = self.padding
        sh, sw = self.sliding
        out_h = (in_h + pt + pb - self.ky) // sh + 1
        out_w = (in_w + pl + pr - self.kx) // sw + 1
        return out_h, out_w

    def initialize(self, device=None, **kwargs):
        super(Conv, self).initialize(device=device, **kwargs)
        batch, in_h, in_w, in_ch = self.input.shape
        fan_in = self.kx * self.ky * in_ch
        if not self.weights:
            stddev = self.weights_stddev or (1.0 / numpy.sqrt(fan_in))
            w = numpy.zeros((self.ky, self.kx, in_ch, self.n_kernels),
                            dtype=numpy.float32)
            self.rand().fill_normal(w, stddev=stddev)
            self.weights.mem = w
            self.weights.initialize(self.device)
        if self.include_bias and not self.bias:
            b = numpy.zeros(self.n_kernels, dtype=numpy.float32)
            if self.bias_stddev:
                self.rand().fill_normal(b, stddev=self.bias_stddev)
            self.bias.mem = b
            self.bias.initialize(self.device)
        out_h, out_w = self.output_spatial(in_h, in_w)
        self.output.mem = numpy.zeros(
            (batch, out_h, out_w, self.n_kernels), dtype=numpy.float32)
        self.output.initialize(self.device)

    def activation(self, v):
        return v

    def _space_to_depth_conv(self, x, w):
        """The folded form: x (B,H,W,C) → (B,H/f,W/f,C·f²), kernel
        zero-padded to a multiple of f and regrouped to match —
        output is bit-identical conv math at stride 1 (derivation:
        window offsets o·f+d decompose as d = p + f·q, so the f-phase
        p folds into channels and q becomes the new kernel tap)."""
        import jax.numpy as jnp
        from jax import lax
        f = self.space_to_depth
        (pt, pb), (pl, pr) = self.padding
        if pt or pb or pl or pr:
            x = jnp.pad(x, ((0, 0), (pt, pb), (pl, pr), (0, 0)))
        b, h, wd, c = x.shape
        # Right/bottom-pad the image to f multiples (never read by
        # real windows — the padded kernel taps there are zero).
        ph = (-h) % f
        pw = (-wd) % f
        if ph or pw:
            x = jnp.pad(x, ((0, 0), (0, ph), (0, pw), (0, 0)))
        h2, w2 = (h + ph) // f, (wd + pw) // f
        ky2, kx2 = -(-self.ky // f), -(-self.kx // f)
        if (h2 - ky2 + 1, w2 - kx2 + 1) != \
                ((h - self.ky) // f + 1, (wd - self.kx) // f + 1):
            # The fold would emit an extra ragged-tail window the
            # strided conv does not have — geometry must tile.
            raise ValueError(
                "space_to_depth=%d does not tile input %dx%d with "
                "kernel %dx%d" % (f, h, wd, self.ky, self.kx))
        x2 = x.reshape(b, h2, f, w2, f, c).transpose(
            0, 1, 3, 2, 4, 5).reshape(b, h2, w2, f * f * c)
        wp = jnp.pad(w, ((0, ky2 * f - self.ky),
                         (0, kx2 * f - self.kx), (0, 0), (0, 0)))
        w2k = wp.reshape(ky2, f, kx2, f, c, self.n_kernels) \
            .transpose(0, 2, 1, 3, 4, 5) \
            .reshape(ky2, kx2, f * f * c, self.n_kernels)
        return lax.conv_general_dilated(
            x2, w2k, window_strides=(1, 1), padding=((0, 0), (0, 0)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def tforward(self, read, write, params, ctx, state=None):
        from jax import lax
        cdt = self.compute_dtype
        # Params live in f32 (the optimizer updates them there); the
        # conv itself runs with bf16 operands by default so the
        # activation stream between layers stays narrow — HBM
        # bandwidth, not MXU FLOPs, bounds the conv stack on v5e.
        # Matching operand dtypes keep the conv transpose rule happy
        # under autodiff.
        x = read(self.input).astype(cdt)
        w = params["weights"].astype(cdt)
        if self.space_to_depth:
            y = self._space_to_depth_conv(x, w)
        else:
            y = lax.conv_general_dilated(
                x, w,
                window_strides=self.sliding,
                padding=self.padding,
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.include_bias:
            y = y + params["bias"].astype(cdt)
        write(self.output, self.activation(y))


class ConvTanh(Conv):
    """Scaled tanh (znicz 1.7159·tanh(0.6666·x))."""

    MAPPING = "conv_tanh"

    def activation(self, v):
        return nn_units.act_tanh(v)


class ConvRelu(Conv):
    """Softplus log(1+e^x) — znicz's smooth "RELU" conv."""

    MAPPING = "conv_relu"

    def activation(self, v):
        return nn_units.act_softplus(v)


class ConvStrictRelu(Conv):
    """max(0, x) (znicz ``ConvStrictRELU``) — the AlexNet activation."""

    MAPPING = "conv_str"

    def activation(self, v):
        return nn_units.act_strict_relu(v)


class ConvSigmoid(Conv):
    MAPPING = "conv_sigmoid"

    def activation(self, v):
        return nn_units.act_sigmoid(v)


class Deconv(ForwardBase):
    """Transposed convolution with weights TIED to a forward Conv
    (znicz ``Deconv`` — the decoder half of conv autoencoder
    pretraining; ``get_weights_from`` names the conv whose filters are
    reused, never trained through this unit's own slot)."""

    MAPPING = "deconv"
    HAS_PARAMS = False

    def __init__(self, workflow, **kwargs):
        super(Deconv, self).__init__(workflow, **kwargs)
        self.conv = kwargs["get_weights_from"]
        self.include_bias = False

    @property
    def trainables(self):
        return {}  # tied weights belong to (and are trained via) conv

    def initialize(self, device=None, **kwargs):
        if not self.conv.is_initialized:
            raise AttributeError(
                "%s: tied conv %s not initialized yet" %
                (self.name, self.conv.name))
        super(Deconv, self).initialize(device=device, **kwargs)
        batch = self.input.shape[0]
        out_shape = self.conv.input.shape[1:]
        self.output.mem = numpy.zeros((batch,) + tuple(out_shape),
                                      dtype=numpy.float32)
        self.output.initialize(self.device)

    def tforward(self, read, write, params, ctx, state=None):
        import jax
        import jax.numpy as jnp
        from jax import lax
        cdt = self.compute_dtype
        x = read(self.input).astype(cdt)
        w = read(self.conv.weights).astype(cdt)
        conv = self.conv
        in_shape = (x.shape[0],) + tuple(conv.input.shape[1:])

        def paired_conv(inp):
            return lax.conv_general_dilated(
                inp, w, window_strides=conv.sliding,
                padding=conv.padding,
                dimension_numbers=("NHWC", "HWIO", "NHWC"))

        # Exact gradient-of-conv geometry: the VJP of the paired conv
        # (conv is linear in its input, so the zeros primal is free
        # and the cotangent pullback IS the transposed conv —
        # guaranteed to produce conv.input's spatial dims for ANY
        # stride/padding combination).
        _, vjp = jax.vjp(paired_conv, jnp.zeros(in_shape, x.dtype))
        write(self.output, vjp(x)[0])
