"""Dropout unit.

Reconstructed znicz capability surface (znicz ``dropout.DropoutForward``
with ``dropout_ratio``; its GD unit routed gradients through the same
mask).  Inverted dropout: training scales kept activations by
1/(1-ratio) so inference is the identity — no separate rescale pass.

TPU note: the mask comes from the step's keyed PRNG (``ctx.next_key``),
so a block-mode scan gives every tick an independent mask while staying
reproducible; autodiff routes gradients through the same mask
automatically (the reference needed a paired GD unit for that).
"""

import numpy

from ..accelerated_units import select_by_training
from .nn_units import ForwardBase


class DropoutForward(ForwardBase):
    MAPPING = "dropout"
    HAS_PARAMS = False

    def __init__(self, workflow, **kwargs):
        super(DropoutForward, self).__init__(workflow, **kwargs)
        self.dropout_ratio = kwargs.get("dropout_ratio", 0.5)

    @property
    def trainables(self):
        return {}

    def initialize(self, device=None, **kwargs):
        super(DropoutForward, self).initialize(device=device, **kwargs)
        self.output.mem = numpy.zeros(self.input.shape,
                                      dtype=numpy.float32)
        self.output.initialize(self.device)

    def tforward(self, read, write, params, ctx, state=None):
        import jax
        x = read(self.input)  # keeps the activation dtype
        keep = 1.0 - self.dropout_ratio

        def train_branch():
            mask = jax.random.bernoulli(ctx.next_key(), keep, x.shape)
            return x * mask.astype(x.dtype) * (1.0 / keep)

        write(self.output, select_by_training(ctx, train_branch,
                                              lambda: x))
