"""Local response normalization (cross-channel).

Reconstructed znicz capability surface (znicz ``normalization.
LRNormalizerForward`` used by the AlexNet-era conv samples):

    y = x / (k + alpha/n · Σ_{j∈window} x_j²)^beta

with the sum over ``n`` adjacent channels (AlexNet: k=2, n=5,
alpha=1e-4, beta=0.75; znicz defaults matched).

TPU note: expressed as a windowed reduction over the channel axis
(``lax.reduce_window``) that XLA fuses with the surrounding elementwise
math; backward is autodiff (the reference had a dedicated GD unit)."""

import numpy

from .nn_units import ForwardBase


class LRNormalizerForward(ForwardBase):
    MAPPING = "norm"
    HAS_PARAMS = False

    def __init__(self, workflow, **kwargs):
        super(LRNormalizerForward, self).__init__(workflow, **kwargs)
        self.alpha = kwargs.get("alpha", 1e-4)
        self.beta = kwargs.get("beta", 0.75)
        self.k = kwargs.get("k", 2.0)
        self.n = kwargs.get("n", 5)

    @property
    def trainables(self):
        return {}

    def initialize(self, device=None, **kwargs):
        super(LRNormalizerForward, self).initialize(device=device,
                                                    **kwargs)
        self.output.mem = numpy.zeros(self.input.shape,
                                      dtype=numpy.float32)
        self.output.initialize(self.device)

    def tforward(self, read, write, params, ctx, state=None):
        import jax.numpy as jnp
        from jax import lax
        x = read(self.input).astype(jnp.float32)
        half = self.n // 2
        sq = x * x
        window = (1,) * (x.ndim - 1) + (self.n,)
        strides = (1,) * x.ndim
        pad = tuple((0, 0) for _ in range(x.ndim - 1)) + \
            ((half, self.n - 1 - half),)
        ssum = lax.reduce_window(sq, 0.0, lax.add, window, strides,
                                 pad)
        denom = (self.k + (self.alpha / self.n) * ssum) ** self.beta
        write(self.output, x / denom)
