"""Local response normalization (cross-channel).

Reconstructed znicz capability surface (znicz ``normalization.
LRNormalizerForward`` used by the AlexNet-era conv samples):

    y = x / (k + alpha/n · Σ_{j∈window} x_j²)^beta

with the sum over ``n`` adjacent channels (AlexNet: k=2, n=5,
alpha=1e-4, beta=0.75; znicz defaults matched).

TPU note: the windowed channel sum is expressed as a banded 0/1
matmul ``x² @ B`` (B[i,j] = 1 iff i−j ∈ [−(n−1−n//2), n//2]) so it
rides the MXU
and fuses with the surrounding elementwise math — measured ~2×
faster (fwd+bwd) than the shifted slice-add formulation on v5e,
which itself beat ``lax.reduce_window`` by ~30%; the matmul's
autodiff transpose is the same symmetric band, so backward is
equally cheap (the reference had a dedicated GD unit)."""

import numpy

from .nn_units import ForwardBase


class LRNormalizerForward(ForwardBase):
    MAPPING = "norm"
    HAS_PARAMS = False

    def __init__(self, workflow, **kwargs):
        super(LRNormalizerForward, self).__init__(workflow, **kwargs)
        self.alpha = kwargs.get("alpha", 1e-4)
        self.beta = kwargs.get("beta", 0.75)
        self.k = kwargs.get("k", 2.0)
        self.n = kwargs.get("n", 5)

    @property
    def trainables(self):
        return {}

    def initialize(self, device=None, **kwargs):
        super(LRNormalizerForward, self).initialize(device=device,
                                                    **kwargs)
        self.output.mem = numpy.zeros(self.input.shape,
                                      dtype=numpy.float32)
        self.output.initialize(self.device)

    def tforward(self, read, write, params, ctx, state=None):
        import jax.numpy as jnp
        x = read(self.input)
        c = x.shape[-1]
        half = self.n // 2
        i = jnp.arange(c)
        # Window for output channel j covers input channels
        # [j-half, j+(n-1-half)] — asymmetric when n is even,
        # matching the padded slice-add formulation it replaces.
        d = i[:, None] - i[None, :]  # input minus output channel
        band = ((d >= -half) &
                (d <= self.n - 1 - half)).astype(jnp.float32)
        # The squares stay in the activation dtype: the banded matmul
        # rounds its operands to bf16 on the MXU anyway, so an f32
        # square would buy 0 extra bits in the sum while DOUBLING the
        # HBM traffic of the largest intermediate in the net (the
        # conv1 activation square) — this op is bandwidth-bound, not
        # FLOP-bound.  Accumulation is f32 via preferred_element_type,
        # the denominator math runs in f32.
        sq = x * x
        ssum = jnp.einsum("...c,cd->...d", sq,
                          band.astype(x.dtype),
                          preferred_element_type=jnp.float32)
        denom = (self.k + (self.alpha / self.n) * ssum) ** self.beta
        write(self.output,
              (x.astype(jnp.float32) / denom).astype(x.dtype))
