"""Local response normalization (cross-channel).

Reconstructed znicz capability surface (znicz ``normalization.
LRNormalizerForward`` used by the AlexNet-era conv samples):

    y = x / (k + alpha/n · Σ_{j∈window} x_j²)^beta

with the sum over ``n`` adjacent channels (AlexNet: k=2, n=5,
alpha=1e-4, beta=0.75; znicz defaults matched).

TPU note: the windowed channel sum is expressed as ``n`` shifted
slice-adds over a zero-padded copy — pure elementwise ops that XLA
fuses with the surrounding math (measurably faster than a
``lax.reduce_window`` formulation on v5e); backward is autodiff (the
reference had a dedicated GD unit)."""

import numpy

from .nn_units import ForwardBase


class LRNormalizerForward(ForwardBase):
    MAPPING = "norm"
    HAS_PARAMS = False

    def __init__(self, workflow, **kwargs):
        super(LRNormalizerForward, self).__init__(workflow, **kwargs)
        self.alpha = kwargs.get("alpha", 1e-4)
        self.beta = kwargs.get("beta", 0.75)
        self.k = kwargs.get("k", 2.0)
        self.n = kwargs.get("n", 5)

    @property
    def trainables(self):
        return {}

    def initialize(self, device=None, **kwargs):
        super(LRNormalizerForward, self).initialize(device=device,
                                                    **kwargs)
        self.output.mem = numpy.zeros(self.input.shape,
                                      dtype=numpy.float32)
        self.output.initialize(self.device)

    def tforward(self, read, write, params, ctx, state=None):
        import jax.numpy as jnp
        x = read(self.input).astype(jnp.float32)
        half = self.n // 2
        sq = x * x
        # Windowed channel sum as n shifted slice-adds over a padded
        # copy: pure elementwise adds that XLA fuses into the
        # surrounding math (and whose backward is equally cheap) —
        # measured ~30% whole-model AlexNet speedup over the
        # reduce_window formulation on TPU v5e.
        pad_spec = [(0, 0)] * (x.ndim - 1) + \
            [(half, self.n - 1 - half)]
        padded = jnp.pad(sq, pad_spec)
        c = x.shape[-1]
        ssum = padded[..., 0:c]
        for i in range(1, self.n):
            ssum = ssum + padded[..., i:i + c]
        denom = (self.k + (self.alpha / self.n) * ssum) ** self.beta
        write(self.output, x / denom)
