"""Local response normalization (cross-channel).

Reconstructed znicz capability surface (znicz ``normalization.
LRNormalizerForward`` used by the AlexNet-era conv samples):

    y = x / (k + alpha/n · Σ_{j∈window} x_j²)^beta

with the sum over ``n`` adjacent channels (AlexNet: k=2, n=5,
alpha=1e-4, beta=0.75; znicz defaults matched).

TPU note: the windowed channel sum is expressed as a banded 0/1
matmul ``x² @ B`` (B[i,j] = 1 iff i−j ∈ [−(n−1−n//2), n//2]) so it
rides the MXU
and fuses with the surrounding elementwise math — measured ~2×
faster (fwd+bwd) than the shifted slice-add formulation on v5e,
which itself beat ``lax.reduce_window`` by ~30%; the matmul's
autodiff transpose is the same symmetric band, so backward is
equally cheap (the reference had a dedicated GD unit)."""

import numpy

from .nn_units import ForwardBase


class LRNormalizerForward(ForwardBase):
    MAPPING = "norm"
    HAS_PARAMS = False

    def __init__(self, workflow, **kwargs):
        super(LRNormalizerForward, self).__init__(workflow, **kwargs)
        self.alpha = kwargs.get("alpha", 1e-4)
        self.beta = kwargs.get("beta", 0.75)
        self.k = kwargs.get("k", 2.0)
        self.n = kwargs.get("n", 5)

    @property
    def trainables(self):
        return {}

    def initialize(self, device=None, **kwargs):
        super(LRNormalizerForward, self).initialize(device=device,
                                                    **kwargs)
        self.output.mem = numpy.zeros(self.input.shape,
                                      dtype=numpy.float32)
        self.output.initialize(self.device)

    def tforward(self, read, write, params, ctx, state=None):
        import jax.numpy as jnp
        x = read(self.input)
        c = x.shape[-1]
        half = self.n // 2
        i = jnp.arange(c)
        # Window for output channel j covers input channels
        # [j-half, j+(n-1-half)] — asymmetric when n is even,
        # matching the padded slice-add formulation it replaces.
        d = i[:, None] - i[None, :]  # input minus output channel
        band = ((d >= -half) &
                (d <= self.n - 1 - half)).astype(jnp.float32)
        # Squaring happens after an exact upcast to f32 (bf16→f32 is
        # lossless, while a bf16 multiply would round every square);
        # the banded matmul itself runs at DEFAULT precision — the
        # MXU's bf16 passes round sq to 8 mantissa bits, which is
        # ample for a 5-term window sum entering k + α/n·Σ — and the
        # output returns to the input dtype so the activation stream
        # stays narrow.
        x32 = x.astype(jnp.float32)
        ssum = jnp.einsum("...c,cd->...d", x32 * x32, band)
        denom = (self.k + (self.alpha / self.n) * ssum) ** self.beta
        write(self.output, (x32 / denom).astype(x.dtype))
