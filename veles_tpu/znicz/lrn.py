"""Local response normalization (cross-channel).

Reconstructed znicz capability surface (znicz ``normalization.
LRNormalizerForward`` used by the AlexNet-era conv samples):

    y = x / (k + alpha/n · Σ_{j∈window} x_j²)^beta

with the sum over ``n`` adjacent channels (AlexNet: k=2, n=5,
alpha=1e-4, beta=0.75; znicz defaults matched).

TPU note: the windowed channel sum is expressed as a banded 0/1
matmul ``x² @ B`` (B[i,j] = 1 iff i−j ∈ [−(n−1−n//2), n//2]) so it
rides the MXU
and fuses with the surrounding elementwise math — measured ~2×
faster (fwd+bwd) than the shifted slice-add formulation on v5e,
which itself beat ``lax.reduce_window`` by ~30%; the matmul's
autodiff transpose is the same symmetric band, so backward is
equally cheap (the reference had a dedicated GD unit)."""

import numpy

from .nn_units import ForwardBase


class LRNormalizerForward(ForwardBase):
    MAPPING = "norm"
    HAS_PARAMS = False

    def __init__(self, workflow, **kwargs):
        super(LRNormalizerForward, self).__init__(workflow, **kwargs)
        self.alpha = kwargs.get("alpha", 1e-4)
        self.beta = kwargs.get("beta", 0.75)
        self.k = kwargs.get("k", 2.0)
        self.n = kwargs.get("n", 5)

    @property
    def trainables(self):
        return {}

    def initialize(self, device=None, **kwargs):
        super(LRNormalizerForward, self).initialize(device=device,
                                                    **kwargs)
        self.output.mem = numpy.zeros(self.input.shape,
                                      dtype=numpy.float32)
        self.output.initialize(self.device)

    def tforward(self, read, write, params, ctx, state=None):
        """Banded-matmul LRN by default; a Pallas one-pass kernel
        exists (ops/pallas_lrn.py) but measured SLOWER inside the
        fused step on v5e (see BENCHNOTES.md) — XLA's fusion already
        holds this op near the layout-limited bandwidth roofline —
        so the Pallas path is opt-in via
        ``root.common.engine.pallas_lrn = True``."""
        from ..config import root, get as config_get
        from ..ops.pallas_lrn import lrn, lrn_reference
        x = read(self.input)
        if config_get(root.common.engine.pallas_lrn, False):
            y = lrn(x, self.n, self.alpha, self.beta, self.k)
        else:
            y = lrn_reference(x, self.n, self.alpha, self.beta,
                              self.k)
        write(self.output, y)
