"""Command-line argument aggregation.

Capability parity with the reference CLI base (reference:
veles/cmdline.py — ``CommandLineArgumentsRegistry:61``,
``CommandLineBase:86``): any class built with the
:class:`CommandLineArgumentsRegistry` metaclass may declare a static
``init_parser(parser)`` hook; :func:`init_argparser` folds every
registered hook into one argparse tree, so subsystems (launcher,
loaders, genetics, graphics, …) contribute their own flags without the
entry point knowing about them.

TPU-era notes: no Twisted/manhole/daemon flags; backend selection is
cpu/tpu/auto (XLA platforms) instead of OpenCL/CUDA device indices.
"""

import argparse


class CommandLineArgumentsRegistry(type):
    """Metaclass accumulating per-class ``init_parser`` hooks
    (reference: cmdline.py:61)."""

    classes = []

    def __init__(cls, name, bases, clsdict):
        super(CommandLineArgumentsRegistry, cls).__init__(
            name, bases, clsdict)
        init_parser = clsdict.get("init_parser")
        if init_parser is None:
            return
        if not isinstance(init_parser, staticmethod):
            raise TypeError(
                "%s.init_parser must be a staticmethod (it is collected "
                "by CommandLineArgumentsRegistry before instantiation)"
                % name)
        CommandLineArgumentsRegistry.classes.append(cls)


class SortedHelpFormatter(argparse.RawDescriptionHelpFormatter):
    """Alphabetical option listing (reference: cmdline.py:118-122)."""

    def add_arguments(self, actions):
        super(SortedHelpFormatter, self).add_arguments(
            sorted(actions, key=lambda a: a.dest))


class CommandLineBase(object):
    """Holds the base velescli option set (reference: cmdline.py:86).

    Subsystem flags arrive via the registry; these are the core ones
    every run understands.
    """

    DRY_RUN_CHOICES = ("load", "init", "exec", "no")
    LOG_LEVELS = ("debug", "info", "warning", "error")

    @staticmethod
    def init_parser(parser):
        parser.add_argument(
            "workflow", nargs="?", default="",
            help="path to the workflow module (a .py file defining "
                 "run(load, main)) or a dotted module name")
        parser.add_argument(
            "config", nargs="*", default=[],
            help="config file(s) executed with `root` in scope, and/or "
                 "root.path=value override assignments")
        parser.add_argument(
            "-c", "--config-list", nargs="*", default=[], metavar="FILE",
            help="additional config files (explicit form)")
        parser.add_argument(
            "-s", "--snapshot", default="",
            help="resume from a snapshot file (or a _current.lnk "
                 "pointer)")
        parser.add_argument(
            "--chaos", default="", metavar="PLAN",
            help="deterministic fault-injection plan, e.g. "
                 "'net.drop@job:7,worker.kill@job:12,seed:42' — "
                 "replaces --slave-death-probability with a seeded, "
                 "replayable failure schedule (docs/resilience.md)")
        parser.add_argument(
            "-l", "--listen-address", default="", metavar="HOST:PORT",
            help="run as the distributed coordinator (master), "
                 "listening on HOST:PORT")
        parser.add_argument(
            "--blacklist-cooldown", type=float, default=None,
            metavar="SEC",
            help="blacklist parole: a worker machine blacklisted by "
                 "the adaptive job-timeout watchdog is re-admitted "
                 "on PROBATION (one in-flight job until it completes "
                 "clean) after this many seconds instead of being "
                 "ejected for good (default 60; 0 = immediate "
                 "probation)")
        parser.add_argument(
            "-m", "--master-address", default="", metavar="HOST:PORT",
            help="run as a worker (slave) of the coordinator at "
                 "HOST:PORT")
        parser.add_argument(
            "--nodes", default="", metavar="HOST[,HOST...]",
            help="with -l: spawn workers on these hosts over ssh "
                 "('local' spawns subprocesses on this machine); "
                 "dropped workers respawn the same way")
        parser.add_argument(
            "--jax-coordinator", default="", metavar="HOST:PORT",
            help="multi-controller SPMD: jax.distributed coordinator "
                 "address (every process runs the same program over "
                 "the combined device mesh)")
        parser.add_argument(
            "--jax-num-processes", type=int, default=0, metavar="N",
            help="multi-controller SPMD: total process count")
        parser.add_argument(
            "--jax-process-id", type=int, default=0, metavar="I",
            help="multi-controller SPMD: this process's index")
        parser.add_argument(
            "-r", "--random-seed", default="", metavar="SPEC",
            help="seed spec: an integer, or file:count:dtype "
                 "(e.g. /dev/urandom:16:uint32)")
        parser.add_argument(
            "-a", "--backend", default="",
            help="accelerator backend: tpu, cpu or auto")
        parser.add_argument(
            "--result-file", default="", metavar="FILE",
            help="write run metrics JSON here "
                 "(IResultProvider aggregation)")
        parser.add_argument(
            "--dry-run", default="no",
            choices=CommandLineBase.DRY_RUN_CHOICES,
            help="stop after the given stage: load = construct only, "
                 "init = initialize only, exec = run but skip "
                 "result/report output")
        parser.add_argument(
            "-v", "--verbosity", default="info",
            choices=CommandLineBase.LOG_LEVELS, help="log level")
        parser.add_argument(
            "--workflow-graph", default="", metavar="FILE",
            help="write the control-flow graph (Graphviz DOT) here")
        parser.add_argument(
            "--dump-config", action="store_true",
            help="print the effective config tree before running")
        parser.add_argument(
            "--max-epochs", default="", metavar="N",
            help="override the workflow's stop epoch "
                 "(root.common.max_epochs)")
        parser.add_argument(
            "--optimize", default="", metavar="SIZE[:GENERATIONS]",
            help="genetic hyperparameter search over Tune() config "
                 "leaves with the given population size")
        parser.add_argument(
            "--ensemble-train", default="", metavar="N[:RATIO]",
            help="train an ensemble of N instances, each on RATIO of "
                 "the train set (default 1.0)")
        parser.add_argument(
            "--ensemble-test", default="", metavar="FILE",
            help="evaluate the ensemble described by FILE (written by "
                 "--ensemble-train)")
        parser.add_argument(
            "--profile", default="", metavar="DIR",
            help="capture a jax.profiler trace of the run into DIR")
        parser.add_argument(
            "--frontend", nargs="?", const="frontend.html",
            default="", metavar="FILE",
            help="generate the HTML launch wizard (unit registry + "
                 "full flag tree) and exit")
        return parser


#: Modules that contribute flags via a module-level
#: ``init_parser(parser)`` — imported on demand so subsystems stay
#: lazily loadable yet their flags always appear (the reference's
#: per-class aggregation relied on import side effects instead,
#: cmdline.py:61).
CONTRIBUTING_MODULES = (
    "veles_tpu.client",
    "veles_tpu.guardian",
    "veles_tpu.loader.base",
    "veles_tpu.network_common",
    "veles_tpu.observability",
    "veles_tpu.ops.attention",
    "veles_tpu.ops.moe",
    "veles_tpu.ops.pipeline",
    "veles_tpu.population",
    "veles_tpu.restful",
    "veles_tpu.snapshotter",
    "veles_tpu.znicz.optimizers",
)


def init_argparser(**kwargs):
    """Builds the aggregated parser: base options + every registered
    class's ``init_parser`` + the contributing modules' hooks
    (reference: cmdline.py's per-class argparse merge)."""
    import importlib
    kwargs.setdefault("formatter_class", SortedHelpFormatter)
    kwargs.setdefault(
        "description",
        "veles_tpu — TPU-native distributed dataflow ML platform")
    parser = argparse.ArgumentParser(**kwargs)
    CommandLineBase.init_parser(parser)
    for name in CONTRIBUTING_MODULES:
        module = importlib.import_module(name)
        hook = getattr(module, "init_parser", None)
        if hook is not None:
            try:
                hook(parser)
            except argparse.ArgumentError:
                pass
    seen = {CommandLineBase}
    for cls in CommandLineArgumentsRegistry.classes:
        if cls in seen:
            continue
        seen.add(cls)
        try:
            cls.init_parser(parser)
        except argparse.ArgumentError:
            # Two subsystems claiming the same flag is a bug, but the
            # CLI should stay usable: first registration wins.
            pass
    return parser
