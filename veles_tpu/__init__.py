"""veles_tpu — a TPU-native distributed deep-learning workflow framework.

A ground-up JAX/XLA/Pallas re-design with the capability surface of the
Samsung VELES platform (reference: Lyubava/veles): declarative workflow
graphs of units, a minibatch loader hierarchy, checkpoint/resume,
distributed training, hyperparameter genetics, ensembles, observability
services, and a native inference runtime — with the compute path
expressed as jitted XLA computations over `jax.sharding` meshes instead
of per-unit OpenCL/CUDA kernels and pickled job shipping.
"""

__version__ = "0.1.0"

from .config import root, Config, Tune, get  # noqa: F401
from .mutable import Bool, LinkableAttribute  # noqa: F401
from .units import Unit, IUnit, TrivialUnit, Container  # noqa: F401
from .workflow import Workflow  # noqa: F401
from .plumbing import Repeater, StartPoint, EndPoint, FireStarter  # noqa: F401
from .memory import Vector, Array  # noqa: F401
from .launcher import Launcher  # noqa: F401
from .result_provider import IResultProvider  # noqa: F401
from .input_joiner import InputJoiner  # noqa: F401
from .avatar import Avatar  # noqa: F401
from .downloader import Downloader  # noqa: F401
from .mean_disp_normalizer import MeanDispNormalizer  # noqa: F401
from .normalization import (NormalizerRegistry,  # noqa: F401
                            normalizer_factory)
from .snapshotter import (SnapshotterBase, SnapshotterToFile,  # noqa: F401
                          SnapshotterRegistry)
from . import prng  # noqa: F401
