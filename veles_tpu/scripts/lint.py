"""Console entry for veles-lint (parity with generate_docs.py).

``python -m veles_tpu.scripts.lint [PATHS] [--baseline FILE]
[--write-baseline] [--list-rules] [--quiet]`` — a thin wrapper over
:mod:`veles_tpu.analysis.__main__` so the linter sits beside the
other operator scripts.  Findings print as ``path:line: RULE-ID
message`` (greppable); exit 1 when any remain.
"""

import sys

from ..analysis.__main__ import main


if __name__ == "__main__":
    sys.exit(main())
