"""Snapshot comparison + integrity verification tool.

Capability parity with the reference script (reference:
veles/scripts/compare_snapshots.py — diff two pickled workflow
snapshots): loads two snapshots (file or ``odbc://`` database specs),
walks their units, and reports per-tensor weight drift (L2 / max-abs
difference), structural mismatches, and result-metric deltas.

Run: ``python -m veles_tpu.scripts.compare_snapshots A B``.

``--verify`` mode checks checkpoint INTEGRITY instead of drift: every
snapshot generation in a directory (or one blob) is validated against
its sidecar manifest (SHA-256 + size), ``_current.lnk`` pointers are
resolved, and the exit status is non-zero when anything is corrupt,
dangling, or unmanifested — so CI and operators can gate on
checkpoint health from the command line::

    python -m veles_tpu.scripts.compare_snapshots --verify snapshots/
"""

import argparse
import os

import numpy


def _load(spec):
    # verify=False: compare mode is a read-only diagnostic — diffing
    # a poisoned/corrupt snapshot against the last good one is the
    # forensics workflow the verify errors point users at.
    if spec.startswith(("odbc://", "sqlite://", "db://")):
        from ..snapshotter import SnapshotterToDB
        return SnapshotterToDB.import_(spec, verify=False)
    from ..snapshotter import SnapshotterToFile
    return SnapshotterToFile.import_(spec, verify=False)


def _tensors(workflow):
    """{unit_name/attr: ndarray} for every allocated trainable (and
    evaluator state) in the workflow."""
    from ..memory import Vector
    out = {}
    for unit in workflow.units:
        vecs = dict(getattr(unit, "trainables", None) or {})
        tstate = getattr(unit, "tstate", None)
        if isinstance(tstate, dict):
            vecs.update(tstate)
        for attr, vec in vecs.items():
            if isinstance(vec, Vector) and vec:
                vec.map_read()
                out["%s/%s" % (unit.name, attr)] = numpy.asarray(
                    vec.mem)
    return out


def compare(spec_a, spec_b):
    """Returns the comparison report dict (also usable as a
    library)."""
    wf_a, wf_b = _load(spec_a), _load(spec_b)
    ta, tb = _tensors(wf_a), _tensors(wf_b)
    rows = []
    for name in sorted(set(ta) | set(tb)):
        if name not in ta:
            rows.append({"tensor": name, "status": "only in B"})
            continue
        if name not in tb:
            rows.append({"tensor": name, "status": "only in A"})
            continue
        a, b = ta[name], tb[name]
        if a.shape != b.shape:
            rows.append({"tensor": name,
                         "status": "shape %s vs %s" % (a.shape,
                                                       b.shape)})
            continue
        diff = (a.astype(numpy.float64) -
                b.astype(numpy.float64))
        rows.append({
            "tensor": name, "status": "ok",
            "l2": float(numpy.linalg.norm(diff)),
            "max_abs": float(numpy.abs(diff).max())
            if diff.size else 0.0,
            "rel": float(numpy.linalg.norm(diff) /
                         (numpy.linalg.norm(a) + 1e-30)),
        })
    report = {
        "a": {"workflow": type(wf_a).__name__,
              "results": wf_a.gather_results()},
        "b": {"workflow": type(wf_b).__name__,
              "results": wf_b.gather_results()},
        "tensors": rows,
        "identical": all(r.get("max_abs", 1.0) == 0.0
                         for r in rows if r["status"] == "ok") and
        all(r["status"] == "ok" for r in rows),
    }
    return report


def verify(spec, prefix=None):
    """Integrity report for a snapshot directory (every generation +
    every ``_current.lnk`` pointer) or a single blob.  Returns
    ``{"rows": [...], "ok": bool}`` — ``ok`` only when every row
    verified; a blob without a manifest counts as a failure (it
    cannot be proven good)."""
    import glob
    from ..snapshotter import (SnapshotterToFile, read_manifest,
                               MANIFEST_SUFFIX)
    rows = []
    if os.path.isdir(spec):
        blobs = sorted(
            p for p in glob.glob(os.path.join(spec, "*.pickle*"))
            if not p.endswith((MANIFEST_SUFFIX, ".part")))
        for link in sorted(glob.glob(
                os.path.join(spec, "*_current.lnk"))):
            if prefix and not os.path.basename(link)[
                    :-len("_current.lnk")].startswith(prefix):
                continue  # --prefix scopes pointers too
            try:
                target = SnapshotterToFile.resolve(link)
                rows.append({"path": link, "status": "ok",
                             "target": target})
            except FileNotFoundError as e:
                rows.append({"path": link, "status": "dangling",
                             "error": str(e)})
    else:
        blobs = [spec]
    if prefix:
        blobs = [p for p in blobs
                 if os.path.basename(p).startswith(prefix)]
    from ..snapshotter import SnapshotUnhealthyError
    for path in blobs:
        manifest = read_manifest(path)
        if manifest is None:
            rows.append({"path": path, "status": "no-manifest"})
            continue
        try:
            SnapshotterToFile.verify(path)
        except SnapshotUnhealthyError as e:
            rows.append({"path": path, "status": "unhealthy",
                         "error": str(e)})
            continue
        except Exception as e:
            rows.append({"path": path, "status": "corrupt",
                         "error": str(e)})
            continue
        rows.append({"path": path, "status": "ok",
                     "sha256": manifest.get("sha256"),
                     "epoch": manifest.get("epoch"),
                     "validation_error":
                         manifest.get("validation_error")})
    return {"rows": rows,
            "ok": bool(rows) and
            all(r["status"] == "ok" for r in rows)}


def verify_main(args):
    report = verify(args.snapshot_a, prefix=args.prefix)
    if args.json:
        from ..json_encoders import dumps_json
        print(dumps_json(report, indent=2))
    else:
        for row in report["rows"]:
            print("%-12s %s%s" % (
                row["status"], row["path"],
                "  (%s)" % row["error"] if "error" in row else ""))
        print("VERIFIED" if report["ok"] else "FAILED")
    return 0 if report["ok"] else 1


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="veles_tpu.scripts.compare_snapshots")
    parser.add_argument("snapshot_a")
    parser.add_argument("snapshot_b", nargs="?", default=None)
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    parser.add_argument(
        "--verify", action="store_true",
        help="validate snapshot integrity (manifest checksums, "
             "pointer resolution) of snapshot_a — a directory or a "
             "single blob; exits non-zero on any failure")
    parser.add_argument(
        "--prefix", default=None,
        help="with --verify on a directory: check only this "
             "snapshot family")
    args = parser.parse_args(argv)
    if args.verify:
        return verify_main(args)
    if args.snapshot_b is None:
        parser.error("snapshot_b is required unless --verify is "
                     "given")
    report = compare(args.snapshot_a, args.snapshot_b)
    if args.json:
        from ..json_encoders import dumps_json
        print(dumps_json(report, indent=2))
        return 0
    print("A: %s  %s" % (report["a"]["workflow"],
                         report["a"]["results"]))
    print("B: %s  %s" % (report["b"]["workflow"],
                         report["b"]["results"]))
    print("%-40s %-12s %12s %12s" % ("tensor", "status", "L2",
                                     "max|diff|"))
    for row in report["tensors"]:
        print("%-40s %-12s %12s %12s" % (
            row["tensor"], row["status"],
            "%.4g" % row["l2"] if "l2" in row else "",
            "%.4g" % row["max_abs"] if "max_abs" in row else ""))
    print("identical" if report["identical"] else "DIFFER")
    return 0 if report["identical"] else 1


if __name__ == "__main__":
    import sys
    sys.exit(main())
