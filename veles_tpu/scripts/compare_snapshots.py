"""Snapshot comparison tool.

Capability parity with the reference script (reference:
veles/scripts/compare_snapshots.py — diff two pickled workflow
snapshots): loads two snapshots (file or ``odbc://`` database specs),
walks their units, and reports per-tensor weight drift (L2 / max-abs
difference), structural mismatches, and result-metric deltas.

Run: ``python -m veles_tpu.scripts.compare_snapshots A B``.
"""

import argparse

import numpy


def _load(spec):
    if spec.startswith(("odbc://", "sqlite://", "db://")):
        from ..snapshotter import SnapshotterToDB
        return SnapshotterToDB.import_(spec)
    from ..snapshotter import SnapshotterToFile
    return SnapshotterToFile.import_(spec)


def _tensors(workflow):
    """{unit_name/attr: ndarray} for every allocated trainable (and
    evaluator state) in the workflow."""
    from ..memory import Vector
    out = {}
    for unit in workflow.units:
        vecs = dict(getattr(unit, "trainables", None) or {})
        tstate = getattr(unit, "tstate", None)
        if isinstance(tstate, dict):
            vecs.update(tstate)
        for attr, vec in vecs.items():
            if isinstance(vec, Vector) and vec:
                vec.map_read()
                out["%s/%s" % (unit.name, attr)] = numpy.asarray(
                    vec.mem)
    return out


def compare(spec_a, spec_b):
    """Returns the comparison report dict (also usable as a
    library)."""
    wf_a, wf_b = _load(spec_a), _load(spec_b)
    ta, tb = _tensors(wf_a), _tensors(wf_b)
    rows = []
    for name in sorted(set(ta) | set(tb)):
        if name not in ta:
            rows.append({"tensor": name, "status": "only in B"})
            continue
        if name not in tb:
            rows.append({"tensor": name, "status": "only in A"})
            continue
        a, b = ta[name], tb[name]
        if a.shape != b.shape:
            rows.append({"tensor": name,
                         "status": "shape %s vs %s" % (a.shape,
                                                       b.shape)})
            continue
        diff = (a.astype(numpy.float64) -
                b.astype(numpy.float64))
        rows.append({
            "tensor": name, "status": "ok",
            "l2": float(numpy.linalg.norm(diff)),
            "max_abs": float(numpy.abs(diff).max())
            if diff.size else 0.0,
            "rel": float(numpy.linalg.norm(diff) /
                         (numpy.linalg.norm(a) + 1e-30)),
        })
    report = {
        "a": {"workflow": type(wf_a).__name__,
              "results": wf_a.gather_results()},
        "b": {"workflow": type(wf_b).__name__,
              "results": wf_b.gather_results()},
        "tensors": rows,
        "identical": all(r.get("max_abs", 1.0) == 0.0
                         for r in rows if r["status"] == "ok") and
        all(r["status"] == "ok" for r in rows),
    }
    return report


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="veles_tpu.scripts.compare_snapshots")
    parser.add_argument("snapshot_a")
    parser.add_argument("snapshot_b")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    args = parser.parse_args(argv)
    report = compare(args.snapshot_a, args.snapshot_b)
    if args.json:
        from ..json_encoders import dumps_json
        print(dumps_json(report, indent=2))
        return 0
    print("A: %s  %s" % (report["a"]["workflow"],
                         report["a"]["results"]))
    print("B: %s  %s" % (report["b"]["workflow"],
                         report["b"]["results"]))
    print("%-40s %-12s %12s %12s" % ("tensor", "status", "L2",
                                     "max|diff|"))
    for row in report["tensors"]:
        print("%-40s %-12s %12s %12s" % (
            row["tensor"], row["status"],
            "%.4g" % row["l2"] if "l2" in row else "",
            "%.4g" % row["max_abs"] if "max_abs" in row else ""))
    print("identical" if report["identical"] else "DIFFER")
    return 0 if report["identical"] else 1


if __name__ == "__main__":
    import sys
    sys.exit(main())
