"""Batch audio feature extraction over directory trees (reference:
veles/scripts/music_features.py — walks folders of audio files with
include/exclude regexes and extracts a configurable feature set via
libSoundFeatureExtraction, writing a report file).

TPU-era rebuild: the feature backend is the framework's own audio
stack (``loader/audio.py`` — libsndfile via ctypes with a stdlib .wav
fallback, vectorized log-STFT).  Per file the extractor emits

* ``duration_s``, ``samplerate``, ``channels``,
* ``rms``, ``peak``, ``zero_crossing_rate``,
* ``spectral_centroid``, ``spectral_rolloff``, ``spectral_flatness``
  (means over STFT frames),
* ``log_spectrogram`` summary (frame count, band count, mean, std).

Results go to a JSON report (the reference wrote XML for its native
library; JSON is this framework's report lingua franca).

Usage::

    python -m veles_tpu.scripts.music_features -o report.json \
        [-i RE] [-e RE] [--fft 512] [--hop 256] PATH...
"""

import argparse
import fnmatch
import json
import logging
import os
import re
import sys

import numpy

from ..loader.audio import decode_audio
from ..logger import Logger

AUDIO_PATTERNS = ("*.wav", "*.flac", "*.ogg", "*.aiff", "*.au")


def find_audio_files(paths, include=None, exclude=None,
                     recurse=True):
    """Walks ``paths``; exclude wins over include (reference
    semantics)."""
    inc = re.compile(include) if include else None
    exc = re.compile(exclude) if exclude else None
    out = []
    for base in paths:
        if os.path.isfile(base):
            candidates = [base]
        elif recurse:
            candidates = [
                os.path.join(dirpath, name)
                for dirpath, _dirs, names in sorted(os.walk(base))
                for name in sorted(names)]
        else:
            candidates = [os.path.join(base, n)
                          for n in sorted(os.listdir(base))]
        for path in candidates:
            if not any(fnmatch.fnmatch(path.lower(), pat)
                       for pat in AUDIO_PATTERNS):
                continue
            if exc is not None and exc.search(path):
                continue
            if inc is not None and not inc.search(path):
                continue
            out.append(path)
    return out


def extract_features(path, fft_size=512, hop=256):
    """Feature dict for one audio file."""
    data, rate = decode_audio(path)
    mono = data.mean(axis=1) if data.ndim > 1 else data
    n = len(mono)
    feats = {
        "file": path,
        "samplerate": int(rate),
        "channels": int(data.shape[1]) if data.ndim > 1 else 1,
        "duration_s": float(n / float(rate)) if rate else 0.0,
        "rms": float(numpy.sqrt(numpy.mean(mono ** 2))) if n else 0.0,
        "peak": float(numpy.max(numpy.abs(mono))) if n else 0.0,
        "zero_crossing_rate": float(
            numpy.mean(numpy.abs(numpy.diff(numpy.signbit(
                mono).astype(numpy.int8))))) if n > 1 else 0.0,
    }
    if n >= fft_size:
        frames = numpy.lib.stride_tricks.sliding_window_view(
            mono, fft_size)[::hop] * numpy.hanning(fft_size)
        mag = numpy.abs(numpy.fft.rfft(frames, axis=-1))
        power = mag ** 2
        freqs = numpy.fft.rfftfreq(fft_size, d=1.0 / rate)
        psum = numpy.maximum(power.sum(axis=-1), 1e-12)
        centroid = (power * freqs).sum(axis=-1) / psum
        cumul = numpy.cumsum(power, axis=-1) / psum[:, None]
        rolloff = freqs[numpy.argmax(cumul >= 0.85, axis=-1)]
        flatness = numpy.exp(numpy.mean(
            numpy.log(numpy.maximum(mag, 1e-12)), axis=-1)) / \
            numpy.maximum(mag.mean(axis=-1), 1e-12)
        log_spec = numpy.log(numpy.maximum(mag, 1e-12))
        feats.update({
            "spectral_centroid": float(centroid.mean()),
            "spectral_rolloff": float(rolloff.mean()),
            "spectral_flatness": float(flatness.mean()),
            "log_spectrogram": {
                "frames": int(log_spec.shape[0]),
                "bands": int(log_spec.shape[1]),
                "mean": float(log_spec.mean()),
                "std": float(log_spec.std()),
            },
        })
    return feats


class MusicFeatures(Logger):
    def run(self, paths, output, include=None, exclude=None,
            recurse=True, fft_size=512, hop=256):
        files = find_audio_files(paths, include=include,
                                 exclude=exclude, recurse=recurse)
        self.info("extracting features from %d file(s)", len(files))
        report, failed = [], 0
        for path in files:
            try:
                report.append(extract_features(path, fft_size, hop))
            except Exception as e:
                self.warning("failed on %s: %s", path, e)
                failed += 1
        with open(output, "w") as fout:
            json.dump({"features": report, "failed": failed}, fout,
                      indent=2)
        self.info("report -> %s (%d ok, %d failed)", output,
                  len(report), failed)
        return len(report)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="veles_tpu.scripts.music_features")
    parser.add_argument("-o", "--output", required=True)
    parser.add_argument("-i", "--include", default=None,
                        help="only paths matching this regex")
    parser.add_argument("-e", "--exclude", default=None,
                        help="skip paths matching this regex "
                             "(wins over include)")
    parser.add_argument("--no-recurse", action="store_true")
    parser.add_argument("--fft", type=int, default=512)
    parser.add_argument("--hop", type=int, default=256)
    parser.add_argument("paths", nargs="+")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    MusicFeatures().run(
        args.paths, args.output, include=args.include,
        exclude=args.exclude, recurse=not args.no_recurse,
        fft_size=args.fft, hop=args.hop)
    return 0


if __name__ == "__main__":
    sys.exit(main())
