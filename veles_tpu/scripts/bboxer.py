"""Bounding-box image labeling GUI (reference:
veles/scripts/bboxer.py — a Tornado web app serving an image
directory with a browser labeling UI; selections persist as a
``<image>.json`` next to each image; thumbnails generated on demand).

TPU-era rebuild on the framework's stdlib HTTP stack
(``http_common.JsonHttpServer`` — the same machinery behind the
web-status dashboard and the forge server): a single-page canvas UI,
an image listing with labeled/unlabeled state, path-traversal-guarded
image serving, and the same ``file + ".json"`` selection format so
labels are plain artifacts next to the data.

Usage::

    python -m veles_tpu.scripts.bboxer --root /data/images [--port N]
"""

import argparse
import json
import logging
import mimetypes
import os
import sys
import urllib.parse

from ..http_common import JsonHttpServer, JsonRequestHandler

IMAGE_EXTENSIONS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")

_PAGE = """<!DOCTYPE html>
<html><head><title>veles_tpu bboxer</title><style>
body { font-family: sans-serif; margin: 0; display: flex; }
#list { width: 260px; height: 100vh; overflow-y: auto;
        border-right: 1px solid #ccc; padding: 8px; }
#list a { display: block; padding: 2px 4px; text-decoration: none;
          color: #333; }
#list a.labeled { color: #080; font-weight: bold; }
#main { flex: 1; padding: 8px; }
#wrap { position: relative; display: inline-block; }
canvas { position: absolute; left: 0; top: 0; cursor: crosshair; }
#bar { margin: 6px 0; }
</style></head><body>
<div id="list"></div>
<div id="main">
  <div id="bar">
    label: <input id="label" value="object">
    <button onclick="save()">save</button>
    <button onclick="clearBoxes()">clear</button>
    <span id="status"></span>
  </div>
  <div id="wrap"><img id="img"><canvas id="cv"></canvas></div>
</div>
<script>
let current = null, boxes = [], drag = null;
const img = document.getElementById("img"),
      cv = document.getElementById("cv"),
      ctx = cv.getContext("2d");
async function refresh() {
  const files = await (await fetch("api/images")).json();
  const list = document.getElementById("list");
  list.innerHTML = "";
  for (const f of files) {
    const a = document.createElement("a");
    a.textContent = (f.labeled ? "\\u2713 " : "") + f.file;
    a.href = "#"; a.className = f.labeled ? "labeled" : "";
    a.onclick = () => { open_(f.file); return false; };
    list.appendChild(a);
  }
}
async function open_(f) {
  current = f;
  img.src = "image/" + encodeURIComponent(f);
  await img.decode();
  cv.width = img.width; cv.height = img.height;
  boxes = await (await fetch(
    "api/selections?file=" + encodeURIComponent(f))).json();
  draw();
}
function draw() {
  ctx.clearRect(0, 0, cv.width, cv.height);
  ctx.lineWidth = 2; ctx.strokeStyle = "#f00";
  ctx.font = "13px sans-serif"; ctx.fillStyle = "#f00";
  for (const b of boxes) {
    ctx.strokeRect(b.x, b.y, b.w, b.h);
    ctx.fillText(b.label || "", b.x + 2, b.y + 14);
  }
  if (drag) ctx.strokeRect(drag.x, drag.y, drag.w, drag.h);
}
cv.onmousedown = e => {
  drag = {x: e.offsetX, y: e.offsetY, w: 0, h: 0};
};
cv.onmousemove = e => {
  if (!drag) return;
  drag.w = e.offsetX - drag.x; drag.h = e.offsetY - drag.y; draw();
};
cv.onmouseup = e => {
  if (drag && Math.abs(drag.w) > 3 && Math.abs(drag.h) > 3) {
    const b = {x: Math.min(drag.x, drag.x + drag.w),
               y: Math.min(drag.y, drag.y + drag.h),
               w: Math.abs(drag.w), h: Math.abs(drag.h),
               label: document.getElementById("label").value};
    boxes.push(b);
  }
  drag = null; draw();
};
function clearBoxes() { boxes = []; draw(); }
async function save() {
  const r = await fetch("api/selections", {method: "POST",
    headers: {"Content-Type": "application/json"},
    body: JSON.stringify({file: current, selections: boxes})});
  document.getElementById("status").textContent =
    r.ok ? "saved" : "save failed";
  refresh();
}
refresh();
</script></body></html>"""


def json_file(path):
    """Selection sidecar path (reference: bboxer.py ``json_file``)."""
    return path + ".json"


class BBoxerServer(JsonHttpServer):
    """Labeling backend over one image directory."""

    def __init__(self, root_dir, host="127.0.0.1", port=8083):
        self.root_dir = os.path.realpath(root_dir)
        if not os.path.isdir(self.root_dir):
            raise NotADirectoryError(self.root_dir)

        class Handler(JsonRequestHandler):
            def do_GET(self):
                outer = self.outer
                parsed = urllib.parse.urlparse(self.path)
                if parsed.path in ("/", "/index.html"):
                    self.reply(200, _PAGE, "text/html")
                elif parsed.path == "/api/images":
                    self.reply(200, outer.list_images())
                elif parsed.path == "/api/selections":
                    params = urllib.parse.parse_qs(parsed.query)
                    name = (params.get("file") or [""])[0]
                    try:
                        self.reply(200, outer.get_selections(name))
                    except (KeyError, OSError):
                        self.reply(404, {"error": "unknown image"})
                elif parsed.path.startswith("/image/"):
                    name = urllib.parse.unquote(
                        parsed.path[len("/image/"):])
                    try:
                        blob, ctype = outer.read_image(name)
                    except (KeyError, OSError):
                        self.reply(404, {"error": "unknown image"})
                        return
                    self.reply(200, blob, ctype)
                else:
                    self.reply(404, {"error": "not found"})

            def do_POST(self):
                outer = self.outer
                if self.path != "/api/selections":
                    self.reply(404, {"error": "not found"})
                    return
                try:
                    payload = self.read_json()
                    name = payload["file"]
                    boxes = payload["selections"]
                except (ValueError, KeyError, TypeError):
                    self.reply(400, {"error": "bad selection payload"})
                    return
                try:
                    outer.save_selections(name, boxes)
                except (ValueError, TypeError):
                    self.reply(400, {"error": "bad selection payload"})
                except KeyError:
                    # Same status/shape as the GET handlers for an
                    # unknown or non-image name.
                    self.reply(404, {"error": "unknown image"})
                except OSError:
                    # Server-side disk failure (ENOSPC, EACCES) is not
                    # the client's fault.
                    self.reply(500, {"error": "cannot write sidecar"})
                else:
                    self.reply(200, {"status": "saved"})

        super(BBoxerServer, self).__init__(
            Handler, host=host, port=port, thread_name="veles-bboxer")

    # -- backend ops -------------------------------------------------------

    def _resolve(self, name):
        """Path inside the root, or KeyError (traversal guard)."""
        path = os.path.realpath(os.path.join(self.root_dir, name))
        if not path.startswith(self.root_dir + os.sep):
            raise KeyError(name)
        return path

    def list_images(self):
        out = []
        for dirpath, _dirs, names in sorted(os.walk(self.root_dir)):
            for fname in sorted(names):
                if not fname.lower().endswith(IMAGE_EXTENSIONS):
                    continue
                full = os.path.join(dirpath, fname)
                rel = os.path.relpath(full, self.root_dir)
                out.append({
                    "file": rel,
                    "labeled": os.path.isfile(json_file(full))})
        return out

    def read_image(self, name):
        path = self._resolve(name)
        if not path.lower().endswith(IMAGE_EXTENSIONS):
            raise KeyError(name)
        ctype = mimetypes.guess_type(path)[0] or \
            "application/octet-stream"
        with open(path, "rb") as fin:
            return fin.read(), ctype

    def get_selections(self, name):
        sidecar = json_file(self._resolve(name))
        if not os.path.isfile(sidecar):
            return []
        with open(sidecar) as fin:
            return json.load(fin)

    def save_selections(self, name, selections):
        path = self._resolve(name)
        # Sidecars only for actual images in the tree — a request for
        # a nonexistent or non-image name must not create stray .json
        # files (and would 500 on a missing subdirectory otherwise).
        if not path.lower().endswith(IMAGE_EXTENSIONS) or \
                not os.path.isfile(path):
            raise KeyError(name)
        clean = []
        for b in selections:
            clean.append({
                "x": float(b["x"]), "y": float(b["y"]),
                "w": float(b["w"]), "h": float(b["h"]),
                "label": str(b.get("label", ""))[:128]})
        with open(json_file(path), "w") as fout:
            json.dump(clean, fout, indent=2)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="veles_tpu.scripts.bboxer")
    parser.add_argument("--root", required=True,
                        help="image directory to label")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8083)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    server = BBoxerServer(args.root, host=args.host, port=args.port)
    print("bboxer on http://%s:%d/ labeling %s" %
          (args.host, server.port, args.root))
    try:
        server.serve()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
