"""Frontend generator: an HTML wizard composing a CLI command.

Capability parity with the reference generator (reference:
veles/scripts/generate_frontend.py — introspects the unit registry +
aggregated argparse tree and emits the web wizard served by
``velescli --frontend``, __main__.py:251-325): walks
:func:`veles_tpu.cmdline.init_argparser`'s actions and the
:class:`~veles_tpu.registry.UnitRegistry` catalogue, and writes a
self-contained ``frontend.html`` — form fields per option, a unit
reference table, and live command-line composition in JavaScript.

Run: ``python -m veles_tpu.scripts.generate_frontend [-o FILE]``.
"""

import argparse
import html
import json


def collect_options():
    """[(flag, help, choices, default, is_positional)] from the
    aggregated parser."""
    from ..cmdline import init_argparser
    parser = init_argparser(prog="veles_tpu")
    options = []
    for action in parser._actions:
        if isinstance(action, argparse._HelpAction):
            continue
        flag = (max(action.option_strings, key=len)
                if action.option_strings else action.dest)
        options.append({
            "flag": flag,
            "positional": not action.option_strings,
            "help": action.help or "",
            "choices": list(action.choices) if action.choices
            else None,
            "default": action.default
            if action.default not in (None, "") else None,
            "is_bool": isinstance(
                action, (argparse._StoreTrueAction,
                         argparse._StoreFalseAction)),
        })
    return options


def collect_units():
    """[(class name, doc first line, view group)] from the unit
    registry — import the model/loader packages first so the
    catalogue is complete."""
    from .. import plotting_units, snapshotter  # noqa: F401
    from ..loader import audio, fullbatch, image  # noqa: F401
    from ..znicz import (all2all, conv, decision, dropout,  # noqa
                         evaluator, kohonen, lrn, pooling, rbm)
    from ..registry import UnitRegistry
    units = []
    for cls in sorted(UnitRegistry.units, key=lambda c: c.__name__):
        doc = (cls.__doc__ or "").strip().splitlines()
        units.append({
            "name": cls.__name__,
            "module": cls.__module__,
            "doc": doc[0] if doc else "",
            "mapping": getattr(cls, "MAPPING", None),
        })
    return units


_TEMPLATE = """<!DOCTYPE html>
<html><head><title>veles_tpu launcher wizard</title>
<style>
body {{ font-family: sans-serif; margin: 2em; max-width: 70em; }}
fieldset {{ margin-bottom: 1em; }}
label {{ display: inline-block; min-width: 16em; }}
#cmd {{ background: #222; color: #9e9; padding: 1em;
       font-family: monospace; white-space: pre-wrap; }}
table {{ border-collapse: collapse; font-size: 90%; }}
td, th {{ border: 1px solid #aaa; padding: 3px 8px; }}
</style></head><body>
<h1>veles_tpu launcher wizard</h1>
<p>Fill the fields; the command line composes itself below
(reference capability: the velescli web frontend).</p>
<form id="form" oninput="compose()">{fields}</form>
<h2>Command</h2><div id="cmd">python -m veles_tpu</div>
<h2>Unit reference</h2>
<table><tr><th>unit</th><th>mapping</th><th>module</th>
<th>summary</th></tr>{units}</table>
<script>
const OPTIONS = {options_json};
function compose() {{
  let parts = ["python -m veles_tpu"];
  for (const opt of OPTIONS) {{
    const el = document.getElementById(opt.flag);
    if (!el) continue;
    if (opt.is_bool) {{
      if (el.checked) parts.push(opt.flag);
    }} else if (el.value) {{
      if (opt.positional) parts.push(el.value);
      else parts.push(opt.flag + " " + el.value);
    }}
  }}
  document.getElementById("cmd").textContent = parts.join(" ");
}}
compose();
</script></body></html>
"""


def _field(opt):
    flag = html.escape(opt["flag"])
    label = "<label for='%s'>%s</label>" % (flag, flag)
    title = html.escape(opt["help"])
    if opt["is_bool"]:
        control = ("<input type='checkbox' id='%s' title='%s'/>"
                   % (flag, title))
    elif opt["choices"]:
        opts = "".join(
            "<option%s>%s</option>" %
            (" selected" if c == opt["default"] else "",
             html.escape(str(c)))
            for c in [""] + list(opt["choices"]))
        control = ("<select id='%s' title='%s'>%s</select>"
                   % (flag, title, opts))
    else:
        value = html.escape(str(opt["default"])) \
            if opt["default"] is not None else ""
        control = ("<input id='%s' title='%s' value='%s' "
                   "size='40'/>" % (flag, title, value))
    return ("<div>%s %s <small>%s</small></div>"
            % (label, control, title))


def generate(output="frontend.html"):
    options = collect_options()
    units = collect_units()
    fields = "\n".join(_field(o) for o in options)
    unit_rows = "\n".join(
        "<tr><td>%s</td><td>%s</td><td>%s</td><td>%s</td></tr>" %
        (html.escape(u["name"]),
         html.escape(str(u["mapping"] or "")),
         html.escape(u["module"]), html.escape(u["doc"]))
        for u in units)
    page = _TEMPLATE.format(fields=fields, units=unit_rows,
                            options_json=json.dumps(options))
    with open(output, "w") as fout:
        fout.write(page)
    return output


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="veles_tpu.scripts.generate_frontend")
    parser.add_argument("-o", "--output", default="frontend.html")
    args = parser.parse_args(argv)
    path = generate(args.output)
    print("frontend -> %s" % path)
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
