"""Utility scripts (reference: veles/scripts/)."""
