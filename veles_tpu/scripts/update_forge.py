"""Bulk forge refresh: re-upload every workflow package that carries a
manifest (reference: veles/scripts/update_forge.py — scans the sample
workflows and ``velescli forge upload``s each one that has a forge
manifest; server from the FORGE_SERVER environment variable).

Usage::

    python -m veles_tpu.scripts.update_forge [--server URL]
        [--token T] [DIR ...]

With no directories, the bundled sample workflows are scanned.
"""

import argparse
import logging
import os
import sys

from ..forge import MANIFEST_NAME
from ..forge.client import ForgeClient
from ..logger import Logger


def scan_packages(dirs):
    """Yields every subdirectory (or the directory itself) holding a
    forge manifest."""
    for base in dirs:
        if os.path.isfile(os.path.join(base, MANIFEST_NAME)):
            yield base
            continue
        for name in sorted(os.listdir(base)):
            sub = os.path.join(base, name)
            if os.path.isdir(sub) and \
                    os.path.isfile(os.path.join(sub, MANIFEST_NAME)):
                yield sub


def default_scan_dirs():
    samples = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "znicz", "samples")
    return [samples] if os.path.isdir(samples) else []


class UpdateForge(Logger):
    def run(self, server, dirs, token=None):
        if not server:
            raise ValueError(
                "no forge server: pass --server or set the "
                "FORGE_SERVER environment variable")
        client = ForgeClient(server, token=token)
        uploaded = skipped = 0
        for package_dir in scan_packages(dirs):
            try:
                reply = client.upload(package_dir)
                self.info("updated %s -> %s", package_dir, reply)
                uploaded += 1
            except Exception as e:
                self.warning("failed to upload %s: %s", package_dir, e)
                skipped += 1
        self.info("%d package(s) updated, %d failed", uploaded,
                  skipped)
        return uploaded


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="veles_tpu.scripts.update_forge")
    parser.add_argument("--server",
                        default=os.getenv("FORGE_SERVER"))
    parser.add_argument("--token",
                        default=os.getenv("FORGE_TOKEN"))
    parser.add_argument("dirs", nargs="*",
                        help="package directories (or parents of "
                             "them); default: bundled samples")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    dirs = args.dirs or default_scan_dirs()
    UpdateForge().run(args.server, dirs, token=args.token)
    return 0


if __name__ == "__main__":
    sys.exit(main())
