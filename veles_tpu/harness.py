"""Shared in-process run harness for meta-workflows.

Genetics and ensembles both need to run a workflow module's
``run(load, main)`` hooks to completion inside the current process
(reference ran a subprocess per evaluation, optimization_workflow.py:260;
in-process is the TPU-era default — the fused-step compiler caches
across runs).  One implementation here so launcher-driving stays in
sync with ``Main.main``.
"""

import zlib

from . import prng
from .launcher import Launcher

#: Metric key meta-workflows optimize on (provided by Decision units).
FITNESS_KEY = "EvaluationFitness"


def seed_to_int(spec, default=1234):
    """``--random-seed`` values must also serve as integer seed BASES
    for meta-workflows (instance i = base + i·prime).  Accepts an int
    string or the documented ``file:count:dtype`` form (hashed
    deterministically)."""
    if spec is None or spec == "":
        return default
    try:
        return int(spec)
    except (TypeError, ValueError):
        return zlib.crc32(str(spec).encode("utf-8")) & 0x7FFFFFFF


def run_workflow_module(module, seed=None, **main_kwargs):
    """Runs ``module.run(load, main)`` to completion; returns the
    finished workflow.  ``seed`` (int) reseeds generator 0 first so
    every evaluation starts from identical randomness."""
    if seed is not None:
        prng.reset()
        prng.get(0).seed(seed)
    state = {}

    def load(WorkflowClass, **kwargs):
        launcher = Launcher()
        wf = WorkflowClass(launcher, **kwargs)
        state["launcher"], state["wf"] = launcher, wf
        return wf, False

    def main(**kwargs):
        kwargs.update(main_kwargs)
        state["launcher"].initialize(**kwargs)
        state["launcher"].run()

    module.run(load, main)
    return state["wf"]
