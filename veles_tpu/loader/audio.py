"""Audio dataset loaders.

Capability parity with the reference audio stack (reference:
veles/loader/libsndfile.py — ctypes binding to libsndfile;
veles/loader/libsndfile_loader.py — decode audio files into sample
arrays): :func:`decode_audio` binds libsndfile via ctypes when the
system library exists (full format zoo: flac/ogg/aiff/...), and falls
back to the stdlib ``wave`` module for PCM WAV so the loader works on
hosts without libsndfile (this image has none).

:class:`AudioFileLoader` slices decoded streams into fixed-length
windows — each window is one sample of the device-resident fullbatch,
so the fused-step gather/normalize path is identical to images.
"""

import ctypes
import ctypes.util
import os

import numpy

from ..error import BadFormatError
from .fullbatch import FullBatchLoader
from .image import FileImageLoader

AUDIO_EXTS = (".wav", ".flac", ".ogg", ".aiff", ".aif", ".au",
              ".snd", ".voc")

_sndfile = None
_sndfile_checked = False


class _SFInfo(ctypes.Structure):
    # sf_info layout (libsndfile sndfile.h)
    _fields_ = [("frames", ctypes.c_int64),
                ("samplerate", ctypes.c_int),
                ("channels", ctypes.c_int),
                ("format", ctypes.c_int),
                ("sections", ctypes.c_int),
                ("seekable", ctypes.c_int)]


def _load_sndfile():
    """Binds libsndfile once; None when the library is absent."""
    global _sndfile, _sndfile_checked
    if _sndfile_checked:
        return _sndfile
    _sndfile_checked = True
    name = ctypes.util.find_library("sndfile")
    if not name:
        return None
    try:
        lib = ctypes.CDLL(name)
        lib.sf_open.restype = ctypes.c_void_p
        lib.sf_open.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                ctypes.POINTER(_SFInfo)]
        lib.sf_readf_float.restype = ctypes.c_int64
        lib.sf_readf_float.argtypes = [ctypes.c_void_p,
                                       ctypes.POINTER(ctypes.c_float),
                                       ctypes.c_int64]
        lib.sf_close.argtypes = [ctypes.c_void_p]
        _sndfile = lib
    except OSError:
        _sndfile = None
    return _sndfile


def _decode_sndfile(lib, path):
    SFM_READ = 0x10
    info = _SFInfo()
    handle = lib.sf_open(os.fsencode(path), SFM_READ,
                         ctypes.byref(info))
    if not handle:
        raise BadFormatError("libsndfile cannot open %s" % path)
    try:
        data = numpy.zeros(info.frames * info.channels,
                           dtype=numpy.float32)
        got = lib.sf_readf_float(
            handle, data.ctypes.data_as(
                ctypes.POINTER(ctypes.c_float)), info.frames)
        data = data[:got * info.channels]
        return (data.reshape(-1, info.channels), info.samplerate)
    finally:
        lib.sf_close(handle)


def _decode_wave(path):
    """stdlib fallback: PCM WAV only."""
    import wave
    with wave.open(path, "rb") as w:
        channels = w.getnchannels()
        width = w.getsampwidth()
        rate = w.getframerate()
        raw = w.readframes(w.getnframes())
    if width == 2:
        data = numpy.frombuffer(raw, dtype="<i2").astype(
            numpy.float32) / 32768.0
    elif width == 4:
        data = numpy.frombuffer(raw, dtype="<i4").astype(
            numpy.float32) / 2147483648.0
    elif width == 1:
        data = (numpy.frombuffer(raw, dtype=numpy.uint8).astype(
            numpy.float32) - 128.0) / 128.0
    else:
        raise BadFormatError("unsupported WAV sample width %d in %s"
                             % (width, path))
    return data.reshape(-1, channels), rate


def decode_audio(path):
    """→ (float32 (frames, channels) in [-1, 1], samplerate)."""
    lib = _load_sndfile()
    if lib is not None:
        return _decode_sndfile(lib, path)
    if not path.lower().endswith(".wav"):
        raise BadFormatError(
            "libsndfile is not installed — only PCM .wav decodable "
            "via the stdlib fallback (got %s)" % path)
    return _decode_wave(path)


class AudioFileLoader(FullBatchLoader):
    """Fixed-window audio fullbatch loader (reference:
    libsndfile_loader.py).

    kwargs: ``test_paths``/``validation_paths``/``train_paths`` —
    audio files, directories, or (path, label) pairs; ``window_size``
    — samples per training window; ``window_step`` — hop (defaults to
    window_size, i.e. non-overlapping); ``mono`` — average channels
    (default True).  Labels default to the parent directory name,
    like the image loaders.
    """

    MAPPING = "audio_file"

    def __init__(self, workflow, **kwargs):
        super(AudioFileLoader, self).__init__(workflow, **kwargs)
        self.window_size = int(kwargs.get("window_size", 4096))
        self.window_step = int(kwargs.get("window_step",
                                          self.window_size))
        self.mono = kwargs.get("mono", True)
        self.paths = {0: kwargs.get("test_paths") or [],
                      1: kwargs.get("validation_paths") or [],
                      2: kwargs.get("train_paths") or []}
        self._label_map = {}
        self.samplerate = None

    get_label_from_path = FileImageLoader.get_label_from_path

    def _expand(self, entries):
        out = []
        for e in entries:
            if isinstance(e, tuple):
                out.append(e)
            elif os.path.isdir(e):
                for root_, _dirs, files in sorted(os.walk(e)):
                    for f in sorted(files):
                        if f.lower().endswith(AUDIO_EXTS):
                            out.append((os.path.join(root_, f),
                                        None))
            else:
                out.append((e, None))
        return out

    def _windows(self, stream):
        """Windows are (window_size,) mono or (window_size, ch) —
        one consistent shape per dataset so the fullbatch stacks."""
        if self.mono and stream.shape[1] > 1:
            stream = stream.mean(axis=1, keepdims=True)
        mono = stream.shape[1] == 1
        flat = stream[:, 0] if mono else stream
        n = (len(flat) - self.window_size) // self.window_step + 1
        if n <= 0:
            # Short file: one zero-padded window (same rank as the
            # full-length case, multichannel included).
            shape = (self.window_size,) if mono else \
                (self.window_size, stream.shape[1])
            padded = numpy.zeros(shape, dtype=numpy.float32)
            padded[:len(flat)] = flat[:self.window_size]
            return [padded]
        return [flat[i * self.window_step:
                     i * self.window_step + self.window_size]
                for i in range(n)]

    def load_data(self):
        datas, labels = [], []
        lengths = [0, 0, 0]
        for cls in (0, 1, 2):
            count = 0
            for path, label in self._expand(self.paths[cls]):
                stream, rate = decode_audio(path)
                if self.samplerate is None:
                    self.samplerate = rate
                elif rate != self.samplerate:
                    raise BadFormatError(
                        "%s: samplerate %d != dataset rate %d"
                        % (path, rate, self.samplerate))
                lab = self.get_label_from_path(path) \
                    if label is None else label
                for window in self._windows(stream):
                    datas.append(window)
                    labels.append(lab)
                    count += 1
            lengths[cls] = count
        if not datas:
            raise BadFormatError("%s: no audio found" % self)
        self.original_data.mem = numpy.stack(datas).astype(
            numpy.float32)
        self.original_labels.mem = numpy.asarray(
            labels, dtype=numpy.int32)
        self.class_lengths = lengths


class SpectrogramLoader(AudioFileLoader):
    """Windowed log-spectrogram features (the reference's audio
    feature-extraction role, veles/scripts music_features +
    libsndfile_loader): each window becomes a (frames, bins)
    log-magnitude STFT computed once at load time — features are
    static per dataset, so paying the FFT once beats recomputing it
    every epoch on device.

    kwargs on top of AudioFileLoader: ``fft_size`` (per-frame FFT,
    default 256), ``hop`` (frame hop, default fft_size//2),
    ``log_floor`` (dB-ish clamp, default 1e-6).
    """

    MAPPING = "audio_spectrogram"

    def __init__(self, workflow, **kwargs):
        super(SpectrogramLoader, self).__init__(workflow, **kwargs)
        self.fft_size = int(kwargs.get("fft_size", 256))
        self.hop = int(kwargs.get("hop", self.fft_size // 2))
        self.log_floor = float(kwargs.get("log_floor", 1e-6))
        if self.hop <= 0:
            raise BadFormatError("hop must be positive (got %d)"
                                 % self.hop)
        if self.window_size < self.fft_size:
            raise BadFormatError(
                "window_size (%d) must be >= fft_size (%d) — no "
                "frame fits" % (self.window_size, self.fft_size))
        self._hann = numpy.hanning(self.fft_size).astype(
            numpy.float32)

    def _spectrogram(self, window):
        # One vectorized rfft over all frames (per-frame Python FFTs
        # would cost millions of tiny calls on large datasets).
        frames = numpy.lib.stride_tricks.sliding_window_view(
            window, self.fft_size)[::self.hop] * self._hann
        mag = numpy.abs(numpy.fft.rfft(frames, axis=-1))
        return numpy.log(numpy.maximum(
            mag, self.log_floor)).astype(numpy.float32)

    def load_data(self):
        super(SpectrogramLoader, self).load_data()
        raw = self.original_data.mem
        if raw.ndim != 2:
            raise BadFormatError(
                "SpectrogramLoader needs mono windows (got shape %s)"
                % (raw.shape,))
        self.original_data.mem = numpy.stack(
            [self._spectrogram(w) for w in raw])
