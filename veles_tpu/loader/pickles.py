"""Pickled-array dataset loader.

Capability parity with the reference (reference: veles/loader/
pickles.py — ``PicklesLoader:55``): one pickle file per sample class,
each holding the samples (and optionally labels/targets).
"""

import pickle

import numpy

from ..error import BadFormatError
from .base import TEST, VALID, TRAIN
from .fullbatch import FullBatchLoader


class PicklesLoader(FullBatchLoader):
    """kwargs ``test_path``/``validation_path``/``train_path`` name
    pickle files containing either an array, an (data, labels) tuple,
    or a dict with "data"/"labels"/"targets" keys."""

    MAPPING = "pickles"

    def __init__(self, workflow, **kwargs):
        super(PicklesLoader, self).__init__(workflow, **kwargs)
        self.paths = {TEST: kwargs.get("test_path"),
                      VALID: kwargs.get("validation_path"),
                      TRAIN: kwargs.get("train_path")}

    @staticmethod
    def _unpack(obj):
        if isinstance(obj, dict):
            return (obj["data"], obj.get("labels"),
                    obj.get("targets"))
        if isinstance(obj, tuple) and len(obj) >= 2:
            return obj[0], obj[1], (obj[2] if len(obj) > 2 else None)
        return obj, None, None

    def load_data(self):
        datas, labels, targets = [], [], []
        lengths = [0, 0, 0]
        have_labels = have_targets = False
        for cls in (TEST, VALID, TRAIN):
            path = self.paths[cls]
            if not path:
                continue
            with open(path, "rb") as fin:
                data, labs, tgts = self._unpack(pickle.load(fin))
            data = numpy.asarray(data)
            lengths[cls] = len(data)
            datas.append(data)
            if labs is not None:
                have_labels = True
                labels.append(numpy.asarray(labs, dtype=numpy.int32))
            if tgts is not None:
                have_targets = True
                targets.append(numpy.asarray(tgts))
        if not datas:
            raise BadFormatError("%s: no pickle paths given" % self)
        self.original_data.mem = numpy.concatenate(datas)
        if have_labels:
            self.original_labels.mem = numpy.concatenate(labels)
        if have_targets:
            self.original_targets.mem = numpy.concatenate(targets)
        self.class_lengths = lengths
