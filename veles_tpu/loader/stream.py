"""Streamed (non-HBM-resident) minibatch loader.

Capability parity with the reference's directory-scale image streaming
(reference: veles/loader/fullbatch_image.py:56-268 +
veles/loader/image.py:106 — datasets far larger than device memory are
decoded minibatch-by-minibatch on the host), redesigned for the fused
TPU step:

* the dataset stays on disk / in host memory — nothing resident in
  HBM beyond the in-flight blocks;
* a host-side worker pool (:class:`concurrent.futures.ThreadPoolExecutor`)
  materializes (decodes / augments / normalizes) each block of K
  minibatch ticks into staging numpy buffers;
* blocks ride ``jax.device_put`` which is **asynchronous**: the upload
  of block K+1 overlaps the device compute of block K, and because the
  fused dispatch itself is asynchronous, the host decode of block K+1
  also overlaps device compute of block K — double buffering with one
  block of lookahead and no extra threads in the control path.

The epoch walk therefore runs one block AHEAD of what the rest of the
graph observes.  Flag publication is split: the inherited serve
machinery advances the *walk* (private), and :meth:`run` publishes the
flags describing the block it actually DISPATCHED, so the decision
unit, heartbeats, and snapshots see truthful epoch accounting.

Distributed parity: the coordinator still serves only indices
(reference: loader/base.py:629-661); a streamed worker materializes
its assigned indices locally in :meth:`apply_data_from_master`.
"""

import os

import numpy

from ..accelerated_units import TracedUnit
from ..error import BadFormatError
from ..memory import Vector
from .base import Loader, TRAIN, VALID, TEST  # noqa: F401


class StreamLoader(Loader, TracedUnit):
    """Serves minibatch *data* from host each tick (contrast
    :class:`..fullbatch.FullBatchLoader`, which keeps originals in HBM
    and gathers in-step).

    Subclasses implement :meth:`materialize` (one sample) or override
    :meth:`fill_rows` (a batch of samples — vectorize when the source
    allows it), and ``load_data`` must set :attr:`sample_shape` /
    :attr:`sample_dtype` in addition to ``class_lengths``.

    kwargs: ``decode_workers`` — host decode pool size (default:
    ``os.cpu_count()``); ``prefetch`` — one-block lookahead on
    (default True; turn off for strictly synchronous debugging).
    """

    hide_from_registry = True

    #: Published epoch_number of the dispatched block (class-level
    #: default so the property works before/without publication).
    _pub_ = None
    _serving_ = False

    def __init__(self, workflow, **kwargs):
        super(StreamLoader, self).__init__(workflow, **kwargs)
        self.minibatch_data = Vector()
        self.minibatch_labels = Vector()
        self.decode_workers = int(kwargs.get(
            "decode_workers", os.cpu_count() or 4))
        self.prefetch = bool(kwargs.get("prefetch", True))
        self.sample_shape = None
        self.sample_dtype = numpy.float32

    def init_unpickled(self):
        super(StreamLoader, self).init_unpickled()
        self._staged_ = None
        self._pool_ = None
        self._pub_ = None
        self._serving_ = False

    # -- walk/published epoch split ----------------------------------------
    # serve_* both reads and writes epoch_number (the ``+= 1`` at epoch
    # end, the shuffle-limit check), so the walk's value must stay
    # private while the published value describes the dispatched
    # block.  The other flags are write-before-read per serve and are
    # simply re-assigned at publication time.

    @property
    def epoch_number(self):
        if not self._serving_ and self._pub_ is not None:
            return self._pub_["epoch_number"]
        return self._w_epoch_number

    @epoch_number.setter
    def epoch_number(self, value):
        self._w_epoch_number = value

    # -- ILoader ------------------------------------------------------------

    def create_minibatch_data(self):
        if self.sample_shape is None:
            raise BadFormatError(
                "%s.load_data must set sample_shape" % self)
        mb = self.max_minibatch_size
        self.minibatch_data.mem = numpy.zeros(
            (mb,) + tuple(self.sample_shape), dtype=self.sample_dtype)
        self.minibatch_labels.mem = numpy.zeros(mb, dtype=numpy.int32)

    def fill_minibatch(self):
        self._fill_current()

    # -- materialization hooks ----------------------------------------------

    def materialize(self, index):
        """Returns (sample_array, label) for one global index."""
        raise NotImplementedError()

    def fill_rows(self, indices, out_data, out_labels):
        """Materializes samples for 1-D global ``indices`` into
        ``out_data[i]`` / ``out_labels[i]``.  Default loops over
        :meth:`materialize`; override to vectorize (memmap fancy
        indexing, batched decode, ...)."""
        for i, gi in enumerate(indices):
            arr, lab = self.materialize(int(gi))
            out_data[i] = arr
            out_labels[i] = lab

    @property
    def pool(self):
        if self._pool_ is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool_ = ThreadPoolExecutor(
                max_workers=self.decode_workers,
                thread_name_prefix="veles-decode")
        return self._pool_

    def _fill_block(self, idxs, masks):
        """(K, mb) indices+masks → (K, mb, *sample) staging arrays,
        decode parallelized across the worker pool."""
        K, mb = idxs.shape
        data = numpy.zeros((K, mb) + tuple(self.sample_shape),
                           dtype=self.sample_dtype)
        labels = numpy.zeros((K, mb), dtype=numpy.int32)
        jobs = []
        for t in range(K):
            n = int(masks[t].sum())
            if n == 0:
                continue
            if K == 1 and self.decode_workers > 1:
                # Single-tick block: split the rows instead so the
                # pool still parallelizes the decode.
                step = max(1, -(-n // self.decode_workers))
                for lo in range(0, n, step):
                    hi = min(n, lo + step)
                    jobs.append((idxs[t][lo:hi], data[t][lo:hi],
                                 labels[t][lo:hi]))
            else:
                jobs.append((idxs[t][:n], data[t][:n], labels[t][:n]))
        if len(jobs) == 1:
            self.fill_rows(*jobs[0])
        elif jobs:
            futures = [self.pool.submit(self.fill_rows, *j)
                       for j in jobs]
            for f in futures:
                f.result()
        return data, labels

    def _fill_current(self):
        """Synchronous fill of the single current minibatch (eager
        path + worker-side materialization)."""
        if self.minibatch_size:
            data, labels = self._fill_block(
                self.minibatch_indices.mem[None, :],
                self.minibatch_mask.mem[None, :])
            data, labels = data[0], labels[0]
        else:
            mb = self.max_minibatch_size
            data = numpy.zeros((mb,) + tuple(self.sample_shape),
                               dtype=self.sample_dtype)
            labels = numpy.zeros(mb, dtype=numpy.int32)
        self.minibatch_data.mem = data
        self.minibatch_labels.mem = labels

    # -- fused-step contract -----------------------------------------------

    def step_batch_vectors(self):
        """The DATA is the per-tick host→device feed (contrast
        fullbatch: indices only)."""
        return [self.minibatch_data, self.minibatch_labels,
                self.minibatch_mask, self.minibatch_class_vec]

    def tforward(self, read, write, params, ctx, state=None):
        """Nothing traced: the minibatch tensors enter the step as
        batch inputs; downstream units read them from the bag."""

    # -- the tick ----------------------------------------------------------

    def _produce_block(self, ticks):
        """Advances the private walk by one block and stages its
        materialized tensors on device (async upload)."""
        import jax
        self._serving_ = True
        try:
            served = self.serve_block(ticks)
            flags = {
                "minibatch_class": self.minibatch_class,
                "minibatch_size": self.minibatch_size,
                "last_minibatch": self.last_minibatch,
                "epoch_ended": self.epoch_ended,
                "epoch_number": self._w_epoch_number,
            }
        finally:
            self._serving_ = False
        idxs = served[str(id(self.minibatch_indices))]
        masks = served[str(id(self.minibatch_mask))]
        cls_arr = served[str(id(self.minibatch_class_vec))]
        data, labels = self._fill_block(idxs, masks)
        blocks = {
            str(id(self.minibatch_data)): jax.device_put(data),
            str(id(self.minibatch_labels)): jax.device_put(labels),
            str(id(self.minibatch_mask)): jax.device_put(masks),
            str(id(self.minibatch_class_vec)): jax.device_put(cls_arr),
        }
        return {"blocks": blocks, "flags": flags,
                "in_flight": list(self._in_flight_)}

    def _apply_flags(self, flags):
        self.minibatch_class = flags["minibatch_class"]
        self.minibatch_size = flags["minibatch_size"]
        self.last_minibatch = flags["last_minibatch"]
        self.epoch_ended = flags["epoch_ended"]
        self._pub_ = flags

    def run(self):
        wf = self.workflow
        if getattr(wf, "fused", False):
            ticks = max(1, getattr(wf, "ticks_per_dispatch", 1))
            entry = self._staged_
            self._staged_ = None
            if entry is None:
                entry = self._produce_block(ticks)
            # Publish BEFORE dispatch: wf.training consults
            # minibatch_is_training for this block.
            self._apply_flags(entry["flags"])
            wf.begin_tick()
            wf.execute_block(entry["blocks"])
            if self.prefetch:
                # Stage the next block while the device crunches this
                # one; its serve tramples the flag attrs, so re-publish
                # the dispatched block's flags for the decision.
                self._staged_ = self._produce_block(ticks)
                self._apply_flags(entry["flags"])
                self._in_flight_ = (entry["in_flight"] +
                                    self._staged_["in_flight"])
            else:
                self._in_flight_ = entry["in_flight"]
            return
        # Eager fallback (debug / non-fused graphs).
        self.serve_next_minibatch()
        self._fill_current()
        if hasattr(wf, "begin_tick"):
            wf.begin_tick()
        TracedUnit.run(self)

    def invalidate_staged(self):
        """Drops the prefetched block (elastic rebuild: its device
        arrays live on the old device set and its indices were
        requeued from ``_in_flight_``)."""
        self._staged_ = None

    # -- distributed: worker materializes its assigned indices --------------

    def apply_data_from_master(self, data):
        super(StreamLoader, self).apply_data_from_master(data)
        self.minibatch_class_vec.mem = numpy.array(
            self.minibatch_class, dtype=numpy.int32)
        self._fill_current()

    # -- pickling: the staged (undispatched) block is requeued --------------

    def __getstate__(self):
        state = super(StreamLoader, self).__getstate__()
        staged = self._staged_
        if staged is not None:
            state["failed_minibatches"] = (
                list(state["failed_minibatches"]) +
                [(idx, cls) for idx, cls in staged["in_flight"]])
        return state
