"""Image dataset loaders.

Capability parity with the reference image loaders (reference:
veles/loader/image.py — ``ImageLoader:106`` with scale/crop/mirror/
color-space handling, veles/loader/file_image.py — file/directory
loaders with auto-labeling from paths, veles/loader/fullbatch_image.py
— device-resident variants).

TPU-era mapping: decoding/scaling/color conversion happen on host with
PIL at ``load_data`` time into a device-resident fullbatch (the gather
+ any normalization then ride the fused step); the reference's
on-the-fly minibatch decode exists as :class:`veles_tpu.loader.saver.
MinibatchesLoader` streaming instead.
"""

import os

import numpy

from ..error import BadFormatError
from ..normalization import normalizer_factory
from .fullbatch import FullBatchLoader
from .stream import StreamLoader

IMAGE_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".tif",
              ".tiff", ".ppm", ".webp")


class ImageDecoderMixin(object):
    """The image preprocessing pipeline shared by the resident
    (fullbatch) and streamed image loaders (reference: image.py:106 —
    scale / center-crop / color-space / aspect-pad)."""

    def init_image_kwargs(self, kwargs):
        self.size = tuple(kwargs.get("size", (32, 32)))
        self.color_space = kwargs.get("color_space", "RGB")
        self.crop = kwargs.get("crop")
        self.mirror = kwargs.get("mirror", False)
        # Aspect-preserving scale + padding (reference: image.py's
        # background/padding handling): when True the image is scaled
        # to FIT the target and the remainder filled with
        # ``background_color``; when False it is stretched.
        self.keep_aspect_ratio = kwargs.get("keep_aspect_ratio",
                                            False)
        self.background_color = kwargs.get("background_color", 0)
        # Rotation augmentation (reference: image.py:294-312
        # ``rotations`` — a tuple of radians; the TRAIN set holds one
        # variant PER LISTED ANGLE, so include 0.0 to keep the
        # unrotated originals — (0.0, a) doubles the set, (a,) alone
        # REPLACES it with rotated copies).
        rotations = kwargs.get("rotations", (0.0,))
        if not isinstance(rotations, tuple):
            raise TypeError("rotations must be a tuple (got %r)" %
                            (rotations,))
        for i, rot in enumerate(rotations):
            if not isinstance(rot, (int, float)):
                raise TypeError("rotations[%d] = %r is not a number" %
                                (i, rot))
            if abs(rot) > 2 * numpy.pi:
                raise ValueError(
                    "rotations[%d] = %s exceeds 2π radians" %
                    (i, rot))
        self.rotations = tuple(sorted(rotations))
        if self.rotations and 0.0 not in self.rotations:
            import logging
            logging.getLogger(type(self).__name__).warning(
                "rotations %s does not include 0.0 — the TRAIN set "
                "will contain ONLY rotated variants (one per listed "
                "angle), not the originals", self.rotations)
        ntype = kwargs.get("normalization_type", "none")
        self.normalizer = normalizer_factory(
            ntype, **kwargs.get("normalization_parameters", {}))

    @property
    def decoded_shape(self):
        """(h, w, c) a decoded sample comes out as."""
        w, h = self.crop if self.crop else self.size
        c = 1 if self.color_space == "L" else 3
        return (h, w, c)

    def _background(self, shape):
        bg = numpy.asarray(self.background_color,
                           dtype=numpy.float32)
        out = numpy.empty(shape, dtype=numpy.float32)
        out[...] = bg
        return out

    def rotate_image(self, arr, angle):
        """Rotates a decoded (h, w, c) array by ``angle`` radians
        around its center, background-filled.  Quarter turns are
        exact (numpy.rot90); arbitrary angles interpolate."""
        if not angle:
            return arr
        quarter = angle / (numpy.pi / 2.0)
        if abs(quarter - round(quarter)) < 1e-9:
            k = int(round(quarter)) % 4
            # The exact fast path must preserve (h, w, c): odd
            # quarter turns transpose the spatial dims, so non-square
            # images take the shape-preserving interpolated path.
            if k % 2 == 0 or arr.shape[0] == arr.shape[1]:
                return numpy.ascontiguousarray(
                    numpy.rot90(arr, k=k, axes=(0, 1)))
        from scipy import ndimage
        bg = float(numpy.mean(self.background_color))
        return ndimage.rotate(
            arr, numpy.degrees(angle), axes=(1, 0), reshape=False,
            mode="constant", cval=bg).astype(numpy.float32)

    def decode_image(self, path):
        from PIL import Image
        with Image.open(path) as img:
            img = img.convert(self.color_space)
            if self.keep_aspect_ratio:
                tw, th = self.size
                scale = min(tw / img.width, th / img.height)
                nw = max(1, int(round(img.width * scale)))
                nh = max(1, int(round(img.height * scale)))
                img = img.resize((nw, nh))
                arr = numpy.asarray(img, dtype=numpy.float32)
                if arr.ndim == 2:
                    arr = arr[:, :, None]
                canvas = self._background((th, tw, arr.shape[2]))
                top = (th - nh) // 2
                left = (tw - nw) // 2
                canvas[top:top + nh, left:left + nw] = arr
                arr = canvas
            else:
                img = img.resize(self.size)
                arr = numpy.asarray(img, dtype=numpy.float32)
                if arr.ndim == 2:
                    arr = arr[:, :, None]
        if self.crop:
            cw, ch = self.crop
            h, w = arr.shape[:2]
            if ch > h or cw > w:
                # Crop larger than the image: pad with background
                # (reference padding behavior) instead of failing.
                canvas = self._background((max(ch, h), max(cw, w),
                                           arr.shape[2]))
                canvas[(max(ch, h) - h) // 2:
                       (max(ch, h) - h) // 2 + h,
                       (max(cw, w) - w) // 2:
                       (max(cw, w) - w) // 2 + w] = arr
                arr = canvas
                h, w = arr.shape[:2]
            top, left = (h - ch) // 2, (w - cw) // 2
            arr = arr[top:top + ch, left:left + cw]
        return arr


class ImageLoaderBase(FullBatchLoader, ImageDecoderMixin):
    """Device-resident image loader base (reference: image.py:106).

    kwargs: ``size`` (w, h) target scale; ``color_space`` "RGB"/"L";
    ``crop`` optional (w, h) center crop after scale; ``mirror`` adds
    horizontally-flipped copies of TRAIN samples;
    ``normalization_type`` + ``normalization_parameters`` choose a
    host normalizer from the registry.
    """

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super(ImageLoaderBase, self).__init__(workflow, **kwargs)
        self.init_image_kwargs(kwargs)

    def _finalize(self, per_class):
        """per_class: {TEST/VALID/TRAIN: (list of arrays, list of
        labels)} → fullbatch originals in class order."""
        datas, labels = [], []
        lengths = [0, 0, 0]
        for cls in (0, 1, 2):
            arrs, labs = per_class.get(cls, ([], []))
            if cls == 2 and arrs and self.rotations != (0.0,):
                arrs = [self.rotate_image(a, rot)
                        for rot in self.rotations for a in arrs]
                labs = list(labs) * len(self.rotations)
            if cls == 2 and self.mirror and arrs:
                arrs = list(arrs) + [a[:, ::-1] for a in arrs]
                labs = list(labs) + list(labs)
            lengths[cls] = len(arrs)
            datas.extend(arrs)
            labels.extend(labs)
        if not datas:
            raise BadFormatError("%s: no images found" % self)
        data = numpy.stack(datas)
        self.normalizer.analyze(data[lengths[0] + lengths[1]:])
        data = self.normalizer.normalize(data)
        self.original_data.mem = data.astype(numpy.float32)
        self.original_labels.mem = numpy.asarray(labels,
                                                 dtype=numpy.int32)
        self.class_lengths = lengths


class FileListMixin(object):
    """Per-class path lists + auto-labeling, shared by the resident
    and streamed file loaders (reference: file_image.py:53 path
    handling)."""

    def init_path_kwargs(self, kwargs):
        self.paths = {0: kwargs.get("test_paths") or [],
                      1: kwargs.get("validation_paths") or [],
                      2: kwargs.get("train_paths") or []}
        self._label_map = {}

    def get_label_from_path(self, path):
        """Default auto-label: the parent directory name, interned to
        a dense int id (reference auto-labeling from paths)."""
        key = os.path.basename(os.path.dirname(path))
        return self._label_map.setdefault(key, len(self._label_map))

    def _expand(self, entries):
        out = []
        for e in entries:
            if isinstance(e, tuple):
                out.append(e)
            elif os.path.isdir(e):
                for root_, _dirs, files in sorted(os.walk(e)):
                    for f in sorted(files):
                        if f.lower().endswith(IMAGE_EXTS):
                            p = os.path.join(root_, f)
                            out.append((p, None))
            else:
                out.append((e, None))
        return out


class FileImageLoader(ImageLoaderBase, FileListMixin):
    """Explicit file lists per class (reference: file_image.py:53).

    kwargs ``test_paths``/``validation_paths``/``train_paths``: lists
    whose entries are image paths or (path, label) pairs; plain paths
    get label from ``get_label_from_path`` (filename prefix by
    default)."""

    MAPPING = "file_image"

    def __init__(self, workflow, **kwargs):
        super(FileImageLoader, self).__init__(workflow, **kwargs)
        self.init_path_kwargs(kwargs)

    def load_data(self):
        per_class = {}
        for cls, entries in self.paths.items():
            arrs, labs = [], []
            for path, label in self._expand(entries):
                arrs.append(self.decode_image(path))
                labs.append(self.get_label_from_path(path)
                            if label is None else label)
            per_class[cls] = (arrs, labs)
        self._finalize(per_class)

    @property
    def n_classes(self):
        return len(self._label_map) or \
            int(self.original_labels.mem.max()) + 1


class AutoLabelFileImageLoader(FileImageLoader):
    """Directory-per-label datasets (reference: file_image.py:150):
    pass class directories; labels are the subdirectory names."""

    MAPPING = "auto_label_file_image"


class FileImageMSELoader(FileImageLoader):
    """Image→image regression datasets (reference: image_mse.py —
    MSE-target variants): each input image is paired with a TARGET
    image served through ``minibatch_targets`` for EvaluatorMSE
    (denoising, super-resolution, autoencoder ground truths).

    kwargs: ``target_paths`` — a directory (targets matched to inputs
    by filename) or a callable ``path -> target_path``;
    ``target_size`` — target scale, defaulting to ``size``.
    """

    MAPPING = "file_image_mse"

    def __init__(self, workflow, **kwargs):
        super(FileImageMSELoader, self).__init__(workflow, **kwargs)
        self.target_paths = kwargs.get("target_paths")
        self.target_size = tuple(kwargs.get("target_size",
                                            self.size))
        if self.target_paths is None:
            raise BadFormatError(
                "%s requires target_paths (a directory or a "
                "path->path callable)" % self)
        if self.mirror or self.rotations != (0.0,):
            # Fail before any decode work: the target would need the
            # same augmentation, which this loader does not do.
            raise BadFormatError(
                "mirror/rotation augmentation is not supported with "
                "MSE targets")

    def target_path_for(self, path):
        if callable(self.target_paths):
            return self.target_paths(path)
        candidate = os.path.join(self.target_paths,
                                 os.path.basename(path))
        if not os.path.isfile(candidate):
            raise BadFormatError("no target image for %s (looked at "
                                 "%s)" % (path, candidate))
        return candidate

    def decode_target(self, path):
        size, self.size = self.size, self.target_size
        try:
            return self.decode_image(self.target_path_for(path))
        finally:
            self.size = size

    def load_data(self):
        per_class = {}
        targets = []
        for cls in (0, 1, 2):
            arrs, labs = [], []
            for path, label in self._expand(self.paths[cls]):
                arrs.append(self.decode_image(path))
                targets.append(self.decode_target(path))
                labs.append(self.get_label_from_path(path)
                            if label is None else label)
            per_class[cls] = (arrs, labs)
        self._finalize(per_class)
        # Targets ride the SAME normalizer transform as the inputs —
        # a regression target left at raw scale while inputs are
        # normalized would silently shift the learning objective.
        self.original_targets.mem = self.normalizer.normalize(
            numpy.stack(targets)).astype(numpy.float32)


class StreamedFileImageLoader(StreamLoader, ImageDecoderMixin,
                              FileListMixin):
    """Directory-scale image streaming (reference:
    fullbatch_image.py:56-268 + file_image.py — datasets larger than
    memory): only the file LIST is scanned at ``load_data``; images
    are decoded minibatch-by-minibatch by the host worker pool and
    double-buffer-uploaded while the previous block trains (see
    loader/stream.py).

    Same kwargs as :class:`FileImageLoader` (``test_paths`` /
    ``validation_paths`` / ``train_paths``, entries are paths,
    directories, or (path, label) pairs) plus the streaming knobs
    (``decode_workers``, ``prefetch``).  Normalizer state is analyzed
    over up to ``analysis_samples`` (default 256) train images at
    load time — a bounded pass, matching the reference's approach of
    analyzing before streaming.  ``mirror`` is unsupported (augment
    downstream instead of doubling the index space)."""

    MAPPING = "streamed_file_image"

    def __init__(self, workflow, **kwargs):
        super(StreamedFileImageLoader, self).__init__(workflow,
                                                      **kwargs)
        self.init_image_kwargs(kwargs)
        if self.mirror or self.rotations != (0.0,):
            raise BadFormatError(
                "mirror/rotation augmentation is not supported by "
                "the streamed loader")
        self.init_path_kwargs(kwargs)
        self.analysis_samples = int(kwargs.get("analysis_samples",
                                               256))
        self.files = []   # global index -> (path, label)

    def load_data(self):
        self.files = []
        lengths = [0, 0, 0]
        for cls in (0, 1, 2):
            entries = self._expand(self.paths[cls])
            for path, label in entries:
                self.files.append(
                    (path, self.get_label_from_path(path)
                     if label is None else label))
            lengths[cls] = len(entries)
        if not self.files:
            raise BadFormatError("%s: no images found" % self)
        self.class_lengths = lengths
        self.sample_shape = self.decoded_shape
        self.sample_dtype = numpy.float32
        # Bounded normalizer analysis, ALWAYS at load time (the lazy
        # analyze-on-first-normalize path is not thread-safe under the
        # decode pool).  Train split preferred; an inference-only
        # dataset analyzes over whatever split it has.
        if type(self.normalizer).__name__ != "NoneNormalizer":
            for cls in (2, 1, 0):
                if lengths[cls] == 0:
                    continue
                start = sum(lengths[:cls])
                take = min(self.analysis_samples, lengths[cls])
                sample = numpy.stack([
                    self.decode_image(self.files[start + i][0])
                    for i in range(take)])
                self.normalizer.analyze(sample)
                break
        self.info("streaming %d images (%d/%d/%d test/val/train), "
                  "%d classes", len(self.files), *lengths,
                  self.n_classes)

    @property
    def n_classes(self):
        # Explicit (path, label) entries may carry ids beyond the
        # auto-label map — count from the materialized labels.
        return 1 + max(lab for _p, lab in self.files)

    def dataset_labels(self):
        return self.slice_labels_by_class(numpy.array(
            [lab for _p, lab in self.files], dtype=numpy.int32))

    def materialize(self, index):
        path, label = self.files[index]
        arr = self.decode_image(path)
        arr = self.normalizer.normalize(arr[None])[0]
        return arr.astype(numpy.float32), label
