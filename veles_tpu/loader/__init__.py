from .base import (CLASS_NAME, TEST, VALID, TRAIN, Loader, ILoader,
                   UserLoaderRegistry)  # noqa: F401
from .fullbatch import FullBatchLoader  # noqa: F401
