from .base import (CLASS_NAME, TEST, VALID, TRAIN, Loader, ILoader,
                   UserLoaderRegistry)  # noqa: F401
from .fullbatch import FullBatchLoader  # noqa: F401
from .image import (ImageLoaderBase, FileImageLoader,  # noqa: F401
                    AutoLabelFileImageLoader)
from .pickles import PicklesLoader  # noqa: F401
from .hdf5 import HDF5Loader  # noqa: F401
from .saver import (MinibatchesSaver, MinibatchesLoader,  # noqa: F401
                    read_minibatch_stream)
from .interactive import (QueueLoader, InteractiveLoader,  # noqa: F401
                          RestfulLoader)
