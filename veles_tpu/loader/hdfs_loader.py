"""HDFS text streaming loader.

Capability parity with the reference HDFS loader (reference:
veles/loader/hdfs_loader.py:48 ``HDFSTextLoader`` — streams a text
file from HDFS in fixed line chunks through a unit ``output`` until
``finished`` flips): here the transport is the WebHDFS REST API via
stdlib urllib — no hdfs client package dependency, works against any
namenode with webhdfs enabled (dfs.webhdfs.enabled).
"""

import json
import urllib.parse
import urllib.request

from ..error import BadFormatError
from ..mutable import Bool
from ..units import Unit
from .base import UserLoaderRegistry


class WebHDFSClient(object):
    """Minimal WebHDFS REST client (OPEN / GETFILESTATUS /
    LISTSTATUS)."""

    def __init__(self, address, user=None, timeout=30.0):
        if not address.startswith("http"):
            address = "http://" + address
        self.base = address.rstrip("/")
        self.user = user
        self.timeout = timeout

    def _url(self, path, op, **params):
        if not path.startswith("/"):
            path = "/" + path
        params["op"] = op
        if self.user:
            params["user.name"] = self.user
        return "%s/webhdfs/v1%s?%s" % (
            self.base, urllib.parse.quote(path),
            urllib.parse.urlencode(params))

    def open(self, path):
        """Returns the file's bytes (urllib follows the namenode →
        datanode redirect WebHDFS issues)."""
        with urllib.request.urlopen(self._url(path, "OPEN"),
                                    timeout=self.timeout) as resp:
            return resp.read()

    def iter_chunks(self, path, chunk_bytes=1 << 20):
        """Streams the file in ``chunk_bytes`` pieces using WebHDFS
        OPEN's offset/length params — multi-GB files never land in
        memory whole."""
        offset = 0
        while True:
            url = self._url(path, "OPEN", offset=offset,
                            length=chunk_bytes)
            with urllib.request.urlopen(
                    url, timeout=self.timeout) as resp:
                blob = resp.read()
            if not blob:
                return
            yield blob
            if len(blob) < chunk_bytes:
                return
            offset += len(blob)

    def stat(self, path):
        with urllib.request.urlopen(
                self._url(path, "GETFILESTATUS"),
                timeout=self.timeout) as resp:
            return json.loads(resp.read())["FileStatus"]

    def list(self, path):
        with urllib.request.urlopen(
                self._url(path, "LISTSTATUS"),
                timeout=self.timeout) as resp:
            statuses = json.loads(resp.read())
        return [s["pathSuffix"] for s in
                statuses["FileStatuses"]["FileStatus"]]


class HDFSTextLoader(Unit, metaclass=UserLoaderRegistry):
    """Streams an HDFS text file in line chunks (reference:
    hdfs_loader.py:48).

    kwargs: ``file`` — HDFS path; ``address`` — namenode
    ``host:port`` (WebHDFS); ``chunk`` — lines per run; ``user`` —
    optional user.name.  Each ``run()`` refills ``output`` with the
    next chunk; ``finished`` flips at EOF (gate downstream units on
    it, as the reference did).
    """

    MAPPING = "hdfs_text"

    def __init__(self, workflow, **kwargs):
        super(HDFSTextLoader, self).__init__(workflow, **kwargs)
        if "file" not in kwargs or "address" not in kwargs:
            raise BadFormatError(
                "HDFSTextLoader requires file= and address= kwargs")
        self.file_name = kwargs["file"]
        self.chunk_lines_number = int(kwargs.get("chunk", 1000))
        self.hdfs_client = WebHDFSClient(
            kwargs["address"], user=kwargs.get("user"),
            timeout=kwargs.get("timeout", 30.0))
        self.output = [""] * self.chunk_lines_number
        self.finished = Bool(False)
        self._lines_ = None

    def initialize(self, **kwargs):
        super(HDFSTextLoader, self).initialize(**kwargs)
        self.debug("opening hdfs://%s (%s)", self.file_name,
                   self.hdfs_client.stat(self.file_name))
        self._lines_ = self._iter_lines()

    def _iter_lines(self):
        """Streaming line iterator over chunked OPEN reads — the
        whole file never materializes (multi-GB is HDFS's normal
        case)."""
        tail = b""
        for blob in self.hdfs_client.iter_chunks(self.file_name):
            blob = tail + blob
            lines = blob.split(b"\n")
            tail = lines.pop()
            for line in lines:
                yield line.decode("utf-8", errors="replace")
        if tail:
            yield tail.decode("utf-8", errors="replace")

    def run(self):
        if bool(self.finished):
            return
        count = 0
        for i in range(self.chunk_lines_number):
            try:
                self.output[i] = next(self._lines_)
                count += 1
            except StopIteration:
                self.output[i:] = [""] * (
                    self.chunk_lines_number - i)
                self.finished <<= True
                break
        self.debug("served %d lines", count)
