"""Device-resident full-batch loader.

Capability parity with the reference fullbatch loader (reference:
veles/loader/fullbatch.py — ``FullBatchLoader:79``, on-device originals
``_gpu_init:197``, on-device index gather ``fill_indices:292`` backed by
the ocl/fullbatch_loader.cl / cuda/fullbatch_loader.cu kernels):
the ENTIRE dataset lives in device memory and each minibatch is
assembled on-device by gathering rows for the served indices.

TPU-era mapping: the originals are jax.Arrays in HBM (sharding-aware —
on a mesh they can be replicated or sharded along the data axis) and
the gather is ``jnp.take`` traced INTO the fused step, so XLA fuses
minibatch assembly with the first layer's compute; no custom gather
kernel and no host round-trip.  The indices + mask are the only
per-tick host→device traffic (a few hundred bytes).
"""

import numpy

from ..accelerated_units import TracedUnit
from ..memory import Vector
from .base import Loader, TRAIN, VALID, TEST  # noqa: F401


class FullBatchLoader(Loader, TracedUnit):
    """Keeps originals on device; gathers minibatches in-step
    (reference: fullbatch.py:79)."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super(FullBatchLoader, self).__init__(workflow, **kwargs)
        self.original_data = Vector()
        self.original_labels = Vector()
        self.original_targets = Vector()
        self.minibatch_data = Vector()
        self.minibatch_labels = Vector()
        self.minibatch_targets = Vector()
        self.normalizer = kwargs.get("normalizer")
        self.validation_ratio = kwargs.get("validation_ratio", 0.0)

    # -- ILoader -----------------------------------------------------------

    def create_minibatch_data(self):
        """Allocates minibatch output shells (shapes drive downstream
        layer initialization; contents are produced in-step)."""
        mb = self.max_minibatch_size
        sample_shape = self.original_data.shape[1:]
        self.minibatch_data.mem = numpy.zeros(
            (mb,) + tuple(sample_shape),
            dtype=self.original_data.dtype)
        if self.original_labels:
            self.minibatch_labels.mem = numpy.zeros(
                mb, dtype=numpy.int32)
        if self.original_targets:
            self.minibatch_targets.mem = numpy.zeros(
                (mb,) + tuple(self.original_targets.shape[1:]),
                dtype=self.original_targets.dtype)

    def dataset_labels(self):
        """Class-sliced views of the resident labels (originals are
        stored [test, validation, train] concatenated)."""
        if not self.original_labels:
            return None
        return self.slice_labels_by_class(self.original_labels.mem)

    def resplit_validation(self):
        """Moves a ratio of train samples into the validation class
        (reference: fullbatch.py:349 ``validation_ratio`` resplit)."""
        if not self.validation_ratio:
            return
        take = int(self.class_lengths[TRAIN] * self.validation_ratio)
        self.class_lengths[VALID] += take
        self.class_lengths[TRAIN] -= take

    def initialize(self, **kwargs):
        super(FullBatchLoader, self).initialize(**kwargs)
        # Upload originals once (lazy: first devmem access).
        for vec in (self.original_data, self.original_labels,
                    self.original_targets):
            if vec:
                vec.initialize(self.device)

    # -- fused-step contract -----------------------------------------------

    def step_batch_vectors(self):
        """Per-tick host→device inputs."""
        return [self.minibatch_indices, self.minibatch_mask,
                self.minibatch_class_vec]

    def step_const_vectors(self):
        """Large device-resident constants passed (not donated) to the
        step."""
        consts = [self.original_data]
        if self.original_labels:
            consts.append(self.original_labels)
        if self.original_targets:
            consts.append(self.original_targets)
        return consts

    def tforward(self, read, write, params, ctx, state=None):
        """On-device minibatch gather (replaces
        ocl/fullbatch_loader.cl)."""
        import jax.numpy as jnp
        idx = read(self.minibatch_indices)
        data = jnp.take(read(self.original_data), idx, axis=0)
        write(self.minibatch_data, data)
        if self.original_labels:
            write(self.minibatch_labels,
                  jnp.take(read(self.original_labels), idx, axis=0))
        if self.original_targets:
            write(self.minibatch_targets,
                  jnp.take(read(self.original_targets), idx, axis=0))

    def run(self):
        """Host part of the tick: serve indices, then trigger the fused
        step (which performs the gather + everything downstream).  In
        block mode, serves a whole same-class block of minibatches and
        dispatches one scanned computation."""
        wf = self.workflow
        ticks = getattr(wf, "ticks_per_dispatch", 1)
        if ticks > 1 and getattr(wf, "fused", False):
            blocks = self.serve_block(ticks)
            wf.begin_tick()
            wf.execute_block(blocks)
            return
        self.serve_next_minibatch()
        if wf is not None and hasattr(wf, "begin_tick"):
            wf.begin_tick()
        TracedUnit.run(self)
