"""Minibatch stream dump/replay.

Capability parity with the reference (reference: veles/loader/saver.py
— ``MinibatchesSaver``/``MinibatchesLoader``): dump the preprocessed
minibatch stream of a run to disk, then feed later runs from the dump
(skipping the original decode/normalize pipeline).
"""

import gzip
import pickle

import numpy

from ..error import BadFormatError
from ..units import Unit
from .fullbatch import FullBatchLoader

MAGIC = b"VTPUMB1\n"


class MinibatchesSaver(Unit):
    """Appends every served minibatch to a (gzipped) pickle stream.
    Link after the loader:
    ``saver.link_attrs(loader, "minibatch_data", "minibatch_labels",
    "minibatch_mask", "minibatch_class")``."""

    def __init__(self, workflow, **kwargs):
        self.file_name = kwargs.get("file_name", "minibatches.dmp.gz")
        self.compression = kwargs.get("compression", "gz")
        super(MinibatchesSaver, self).__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self._fout_ = None
        self.demand("minibatch_data", "minibatch_mask",
                    "minibatch_class")

    def initialize(self, **kwargs):
        super(MinibatchesSaver, self).initialize(**kwargs)
        opener = gzip.open if self.compression == "gz" else open
        self._fout_ = opener(self.file_name, "wb")
        self._fout_.write(MAGIC)

    def run(self):
        self.minibatch_data.map_read()
        labels = getattr(self, "minibatch_labels", None)
        if labels is not None and labels:
            labels.map_read()
            labels = numpy.array(labels.mem)
        else:
            labels = None
        self.minibatch_mask.map_read()
        mask = numpy.array(self.minibatch_mask.mem)
        record = {
            "data": numpy.array(self.minibatch_data.mem),
            "labels": labels,
            "mask": mask,
            "class": int(self.minibatch_class),
        }
        pickle.dump(record, self._fout_,
                    protocol=pickle.HIGHEST_PROTOCOL)

    def stop(self):
        if self._fout_ is not None:
            self._fout_.close()
            self._fout_ = None


def read_minibatch_stream(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as fin:
        if fin.read(len(MAGIC)) != MAGIC:
            raise BadFormatError("%s is not a minibatch dump" % path)
        while True:
            try:
                yield pickle.load(fin)
            except EOFError:
                return


class MinibatchesLoader(FullBatchLoader):
    """Replays a dump as a fullbatch dataset (valid rows only,
    grouped by sample class)."""

    MAPPING = "minibatches"

    def __init__(self, workflow, **kwargs):
        super(MinibatchesLoader, self).__init__(workflow, **kwargs)
        self.file_name = kwargs["file_name"]

    def load_data(self):
        per_class = {0: ([], []), 1: ([], []), 2: ([], [])}
        for rec in read_minibatch_stream(self.file_name):
            valid = rec["mask"] > 0
            arrs, labs = per_class[rec["class"]]
            arrs.append(rec["data"][valid])
            if rec["labels"] is not None:
                labs.append(rec["labels"][valid])
        datas, labels = [], []
        lengths = [0, 0, 0]
        have_labels = False
        for cls in (0, 1, 2):
            arrs, labs = per_class[cls]
            if not arrs:
                continue
            data = numpy.concatenate(arrs)
            lengths[cls] = len(data)
            datas.append(data)
            if labs:
                have_labels = True
                labels.append(numpy.concatenate(labs))
        if not datas:
            raise BadFormatError("dump %s is empty" % self.file_name)
        self.original_data.mem = numpy.concatenate(datas)
        if have_labels:
            self.original_labels.mem = numpy.concatenate(labels)
        self.class_lengths = lengths
