"""HDF5 dataset loader.

Capability parity with the reference (reference: veles/loader/
loader_hdf5.py — ``HDF5Loader:48-125``): reads per-class HDF5 files
with dataset keys for samples and labels.
"""

import numpy

from ..error import BadFormatError
from .base import TEST, VALID, TRAIN
from .fullbatch import FullBatchLoader


class HDF5Loader(FullBatchLoader):
    """kwargs ``test_path``/``validation_path``/``train_path`` name
    .h5 files; ``data_key``/``labels_key`` select the datasets
    (defaults "data"/"labels", matching the reference files)."""

    MAPPING = "hdf5"

    def __init__(self, workflow, **kwargs):
        super(HDF5Loader, self).__init__(workflow, **kwargs)
        self.paths = {TEST: kwargs.get("test_path"),
                      VALID: kwargs.get("validation_path"),
                      TRAIN: kwargs.get("train_path")}
        self.data_key = kwargs.get("data_key", "data")
        self.labels_key = kwargs.get("labels_key", "labels")

    def load_data(self):
        import h5py
        datas, labels = [], []
        lengths = [0, 0, 0]
        have_labels = False
        for cls in (TEST, VALID, TRAIN):
            path = self.paths[cls]
            if not path:
                continue
            with h5py.File(path, "r") as fin:
                data = numpy.asarray(fin[self.data_key])
                lengths[cls] = len(data)
                datas.append(data)
                if self.labels_key in fin:
                    have_labels = True
                    labels.append(numpy.asarray(
                        fin[self.labels_key], dtype=numpy.int32))
        if not datas:
            raise BadFormatError("%s: no hdf5 paths given" % self)
        self.original_data.mem = numpy.concatenate(datas)
        if have_labels:
            self.original_labels.mem = numpy.concatenate(labels)
        self.class_lengths = lengths
