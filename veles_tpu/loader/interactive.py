"""Queue-fed loaders: interactive and RESTful ingestion.

Capability parity with the reference (reference: veles/loader/
interactive.py — ``InteractiveLoader:57`` fed from IPython;
veles/loader/restful.py — ``RestfulLoader:52`` fed by HTTP POSTs;
veles/zmq_loader.py — ``ZeroMQLoader:74`` fed by external producers):
all three are the same shape — a loader whose minibatches arrive from
an external producer through a thread-safe queue instead of a dataset
file.  :class:`QueueLoader` is that shape; the RESTful API unit and
the interactive shell push into it.
"""

import queue

import numpy

from .base import Loader, TEST


class QueueLoader(Loader):
    """Serves externally-submitted samples (inference streams).

    Producers call :meth:`feed` (blocking queue put); each tick takes
    up to ``minibatch_size`` pending samples, pads, and publishes them
    as a TEST-class minibatch.  ``stop()`` unblocks consumers.
    """

    MAPPING = "queue"

    def __init__(self, workflow, **kwargs):
        super(QueueLoader, self).__init__(workflow, **kwargs)
        from ..memory import Vector
        self.sample_shape = tuple(kwargs.get("sample_shape", ()))
        self.minibatch_data = Vector()
        self.minibatch_labels = Vector()
        self.minibatch_contexts = []
        self.queue = queue.Queue(
            maxsize=kwargs.get("queue_size", 1024))
        self._sentinel = object()

    def feed(self, sample, context=None):
        """Producer side: submit one sample (+ opaque context handed
        back with results)."""
        self.queue.put((numpy.asarray(sample, dtype=numpy.float32),
                        context))

    def load_data(self):
        if not self.sample_shape:
            raise ValueError("%s requires sample_shape" % self)
        # A queue has no dataset: advertise one TEST pseudo-sample so
        # epoch accounting stays well-formed.
        self.class_lengths = [1, 0, 0]

    def create_minibatch_data(self):
        self.minibatch_data.mem = numpy.zeros(
            (self.max_minibatch_size,) + self.sample_shape,
            dtype=numpy.float32)
        self.minibatch_labels.mem = numpy.zeros(
            self.max_minibatch_size, dtype=numpy.int32)
        self.minibatch_contexts = [None] * self.max_minibatch_size

    def serve_next_minibatch(self, slave_id=None):
        self.minibatch_class = TEST
        self.last_minibatch = True
        self.epoch_ended = True
        return []

    def fill_minibatch(self):
        """Blocks for the first sample, then drains up to a full
        minibatch."""
        data = self.minibatch_data.mem
        mask = numpy.zeros(self.max_minibatch_size,
                           dtype=numpy.float32)
        count = 0
        while count < self.max_minibatch_size:
            try:
                item = self.queue.get(block=(count == 0))
            except queue.Empty:
                break
            if item is self._sentinel:
                break
            sample, context = item
            data[count] = sample.reshape(self.sample_shape)
            self.minibatch_contexts[count] = context
            count += 1
        self.minibatch_data.mem = data
        mask[:count] = 1.0
        self.minibatch_mask.mem = mask
        self.minibatch_size = count

    def stop(self):
        self.queue.put(self._sentinel)


class InteractiveLoader(QueueLoader):
    """IPython-session ergonomics alias (reference
    interactive.py:57)."""
    MAPPING = "interactive"


class RestfulLoader(QueueLoader):
    """HTTP-fed alias used by veles_tpu.restful_api
    (reference restful.py:52)."""
    MAPPING = "restful"
