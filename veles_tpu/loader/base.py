"""Minibatch-serving loader base.

Capability parity with the reference loader (reference:
veles/loader/base.py — ``Loader:120``, ``ILoader:100``, sample classes
``:72-80``, ``serve_next_minibatch:724``, coordinator/worker index split
``:626-685``, ``analyze_dataset:753``, ``shuffle:709``, epoch/flag logic
``:856-907``):

  * three sample classes — TEST(0), VALIDATION(1), TRAIN(2) — walked in
    class order within each epoch;
  * a shuffled train index space (validation/test stay ordered);
  * epoch accounting: ``last_minibatch``, ``epoch_ended``,
    ``epoch_number``;
  * a failed-minibatch retry queue — indices whose processing was lost
    (worker death) are re-served before fresh ones
    (reference base.py:194,216-232,677-685);
  * in distributed mode the coordinator serves only **indices** and the
    workers materialize data locally (base.py:629-661) — here the same
    index-space thinking becomes per-device sharding: a global batch of
    indices is laid out along the mesh's data axis (see
    loader/fullbatch.py for the device-side gather).

TPU-era constraint: jitted steps need static shapes, so the final
partial minibatch of a class is PADDED to ``max_minibatch_size`` and a
``minibatch_mask`` marks valid rows (evaluators apply the mask); the
reference instead shrank ``minibatch_size`` per tick.
"""

import numpy

from .. import prng
from ..error import BadFormatError
from ..memory import Vector
from ..registry import MappedUnitRegistry
from ..units import Unit

#: Sample-class ids (reference: loader/base.py:72-80).
TEST, VALID, TRAIN = 0, 1, 2
CLASS_NAME = ("test", "validation", "train")


def init_parser(parser):
    """Loader flags for the aggregated velescli parser (reference:
    --train-ratio, loader/base.py)."""
    parser.add_argument(
        "--train-ratio", type=float, default=None, metavar="R",
        help="train on a random R-fraction of the train set "
             "(sets root.common.loader.train_ratio)")
    parser.add_argument(
        "--shuffle-limit", type=int, default=None, metavar="N",
        help="stop reshuffling train indices after epoch N")


class UserLoaderRegistry(MappedUnitRegistry):
    """String → loader class factory (reference: base.py:83-93)."""
    registry = {}


class ILoader(object):
    """The loader contract (reference: base.py:100)."""

    def load_data(self):
        """Populates class_lengths (and dataset payloads)."""
        raise NotImplementedError()

    def create_minibatch_data(self):
        """Allocates minibatch output vectors."""
        raise NotImplementedError()

    def fill_minibatch(self):
        """Materializes the current minibatch from indices."""
        raise NotImplementedError()


class Loader(Unit, metaclass=UserLoaderRegistry):
    """Serves minibatches tick by tick (reference: base.py:120)."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        self.max_minibatch_size = kwargs.get("minibatch_size", 100)
        self.class_lengths = [0, 0, 0]
        self.epoch_number = 0
        self.prng_key = kwargs.get("prng_key", 0)
        from ..config import root as _root
        self.shuffle_limit = kwargs.get(
            "shuffle_limit",
            _root.common.loader.get("shuffle_limit", numpy.inf))
        if self.shuffle_limit in (-1, None):
            self.shuffle_limit = numpy.inf
        # Per-run config default lets the ensemble trainer vary the
        # train subset without touching workflow constructors
        # (reference: --train-ratio flag, loader/base.py).
        self.train_ratio = kwargs.get(
            "train_ratio", _root.common.loader.get("train_ratio", 1.0))
        # Strict dataset analysis (unseen-label rejection) can be
        # opted out for datasets whose labels are not classification
        # classes (e.g. per-sample ids).
        self.validate_labels = kwargs.get("validate_labels", True)
        super(Loader, self).__init__(workflow, **kwargs)
        self.view_group = "LOADER"
        # Per-tick outputs (host scalars + device vectors).
        self.minibatch_class = TRAIN
        self.minibatch_size = 0
        self.last_minibatch = False
        self.epoch_ended = False
        self.minibatch_indices = Vector()
        self.minibatch_mask = Vector()
        # Device-side copy of minibatch_class so evaluators can route
        # on-device epoch accumulation without a host sync.
        self.minibatch_class_vec = Vector()
        # Epoch state.
        self.global_offset = 0
        self.shuffled_indices = Vector()
        self.failed_minibatches = []
        self._pending_indices_ = {}

    def init_unpickled(self):
        super(Loader, self).init_unpickled()
        # slave -> list of in-flight JOB entries (oldest first), each
        # a list of (indices, class) ticks.  A list, not a single
        # slot: pipelined workers hold several jobs in flight and
        # multi-tick jobs carry several minibatches — a drop must
        # requeue every one of them.
        self._pending_indices_ = {}
        # Worker-side staged multi-tick block (apply_data_from_master
        # of a "block" piece; consumed by the workflow's block
        # dispatch).
        self._staged_block_ = None
        # Minibatches served but possibly not yet committed by the
        # step — elastic recovery (parallel.rebuild_mesh) requeues
        # them.  Single-tick serves hold one entry; a block serve
        # holds the whole block.
        self._in_flight_ = []

    # -- derived sizes -----------------------------------------------------

    @property
    def total_samples(self):
        return sum(self.class_lengths)

    @property
    def class_end_offsets(self):
        ends, acc = [], 0
        for ln in self.class_lengths:
            acc += ln
            ends.append(acc)
        return ends

    @property
    def minibatch_is_training(self):
        return self.minibatch_class == TRAIN

    def class_of_offset(self, offset):
        for cls, end in enumerate(self.class_end_offsets):
            if offset < end:
                return cls
        raise BadFormatError("offset %d beyond dataset" % offset)

    # -- lifecycle ---------------------------------------------------------

    def initialize(self, **kwargs):
        super(Loader, self).initialize(**kwargs)
        self.load_data()
        if self.total_samples == 0:
            raise BadFormatError("loader has no samples after load_data")
        train_subset = None
        if self.class_lengths[TRAIN] > 0 and self.train_ratio < 1.0:
            # A RANDOM subset per run (ensemble bagging diversity) —
            # not the leading slice, which would give every instance
            # the identical samples and discard the tail entirely.
            full_train = self.class_lengths[TRAIN]
            keep = max(1, int(full_train * self.train_ratio))
            train_start = self.class_lengths[0] + self.class_lengths[1]
            train_subset = train_start + numpy.sort(
                prng.get(self.prng_key).choice(
                    full_train, size=keep, replace=False)
                .astype(numpy.int32))
            self.class_lengths[TRAIN] = keep
        resumed = bool(self.shuffled_indices) and \
            self.shuffled_indices.size == self.total_samples
        if not resumed:
            # Fresh run; a snapshot resume keeps the pickled index
            # order + global_offset so the epoch continues mid-walk
            # (reference: loader state rides the workflow pickle).
            base = numpy.arange(self.total_samples, dtype=numpy.int32)
            if train_subset is not None:
                base[self.class_lengths[0] + self.class_lengths[1]:] \
                    = train_subset
            self.shuffled_indices.mem = base
        self.minibatch_indices.mem = numpy.zeros(
            self.max_minibatch_size, dtype=numpy.int32)
        self.minibatch_mask.mem = numpy.zeros(
            self.max_minibatch_size, dtype=numpy.float32)
        self.minibatch_class_vec.mem = numpy.zeros(
            (), dtype=numpy.int32)
        self.create_minibatch_data()
        self.analyze_dataset()
        if not resumed:
            self.shuffle()

    # -- dataset analysis (reference: base.py:753 analyze_dataset) ---------

    def dataset_labels(self):
        """Per-class label arrays ``[test, validation, train]`` (None
        entries for classes without labels; return None to skip
        analysis entirely).  Subclasses with materialized labels
        override this."""
        return None

    def slice_labels_by_class(self, labels):
        """Splits a flat [test|validation|train] label array by the
        class offsets.  The train slice runs to the END of the array,
        not to the (possibly train_ratio-shrunk) offset, so analysis
        always covers the full stored train set."""
        out, start = [], 0
        for cls, end in enumerate(self.class_end_offsets):
            stop = len(labels) if cls == TRAIN else end
            out.append(labels[start:stop] if stop > start else None)
            start = end
        return out

    def analyze_dataset(self):
        """Sanity-checks the loaded dataset at initialize (reference:
        base.py:753 + _setup_labels_mapping:922): per-class sample
        counts, label-range/mapping validation (a validation or test
        label never seen in training fails LOUDLY — it would
        otherwise surface as silently bad accuracy), per-class label
        histograms with imbalance warnings, and a train-vs-other
        distribution comparison."""
        self.info("dataset: %s",
                  ", ".join("%d %s" % (n, CLASS_NAME[cls])
                            for cls, n in
                            enumerate(self.class_lengths) if n))
        labels = self.dataset_labels()
        if labels is None:
            return
        # Sequence models carry PER-TOKEN label arrays (N, S, ...):
        # the dtype/range and unseen-label checks still apply (a
        # vocab id in validation but not training is exactly the
        # silent-bad-accuracy bug this function exists to catch) over
        # the flattened tokens, but per-class BALANCE warnings are
        # meaningless there and are suppressed.  Ragged per-sample
        # label lists cannot be analyzed at all — skip with a notice.
        sequence_labels = False
        flat = []
        for arr in labels:
            if arr is None:
                flat.append(None)
                continue
            try:
                a = numpy.asarray(arr)
            except ValueError:
                # Ragged per-sample lists: keep object dtype so the
                # loop's dtype check below still fails LOUDLY under
                # validate_labels instead of silently skipping.
                a = numpy.asarray(arr, dtype=object)
            # Trailing singleton axes ((N, 1) column vectors) are
            # ordinary class labels, not sequences; only they are
            # squeezed — a (1, S) single-sequence split must stay
            # sequence-shaped.
            while a.ndim > 1 and a.shape[-1] == 1:
                a = a[..., 0]
            if a.ndim > 1:
                sequence_labels = True
                a = a.ravel()
            flat.append(a)
        labels = flat
        self.label_stats = {}
        histograms = {}
        for cls, arr in enumerate(labels):
            if arr is None or not len(arr):
                continue
            if not numpy.issubdtype(arr.dtype, numpy.integer) or \
                    arr.min() < 0:
                problem = ("%s labels are not non-negative integers "
                           "(dtype %s)" % (CLASS_NAME[cls],
                                           arr.dtype))
                if self.validate_labels:
                    raise BadFormatError(
                        problem + " — pass validate_labels=False if "
                        "these are not class labels")
                # Opted out: ids/regression targets — skip histogram
                # analysis for this class.
                self.info("%s; skipping label analysis", problem)
                continue
            values, counts = numpy.unique(arr, return_counts=True)
            histograms[cls] = dict(zip(values.tolist(),
                                       counts.tolist()))
        if not histograms:
            return
        train_hist = histograms.get(TRAIN, {})
        for cls, hist in histograms.items():
            if cls != TRAIN and train_hist and self.validate_labels:
                # Mapping validation: every evaluated label must be
                # learnable (reference _validate_and_fix_other_labels).
                unseen = sorted(set(hist) - set(train_hist))
                if unseen:
                    raise BadFormatError(
                        "%s set contains labels never seen in "
                        "training: %s (pass validate_labels=False "
                        "if these are not class labels)"
                        % (CLASS_NAME[cls], unseen[:10]))
            counts = numpy.array(list(hist.values()), dtype=float)
            mean, std = counts.mean(), counts.std()
            self.label_stats[CLASS_NAME[cls]] = {
                "classes": len(hist),
                "min": int(counts.min()), "max": int(counts.max()),
                "mean": float(mean), "std": float(std)}
            msg = ("%s labels: %d classes, count min %d / mean %d / "
                   "max %d (std %d)" % (CLASS_NAME[cls], len(hist),
                                        counts.min(), mean,
                                        counts.max(), std))
            if sequence_labels:
                # Token-frequency skew is normal language statistics,
                # not a dataset bug — no imbalance warnings.
                self.debug("%s (per-token)", msg)
            elif not self.validate_labels:
                # The user declared these are not real class labels
                # (synthetic benches, ids): stats stay available but
                # imbalance is not a warning-worthy dataset bug.
                self.info("%s", msg)
            elif std > mean / 2:
                self.warning("%s — SEVERELY imbalanced", msg)
            elif std > mean / 10:
                self.warning("%s — imbalanced", msg)
            else:
                self.info("%s", msg)
        # Distribution drift: a validation/test set whose label mix
        # differs wildly from training skews the reported metrics
        # (reference _compare_label_distributions); token mixes of
        # sequence targets are expected to drift — skip the whole
        # computation there, and for declared non-class labels
        # (validate_labels=False) drift is not a dataset bug either.
        if train_hist and not sequence_labels and \
                self.validate_labels:
            total_train = sum(train_hist.values())
            for cls in (TEST, VALID):
                hist = histograms.get(cls)
                if not hist:
                    continue
                total = sum(hist.values())
                drift = max(
                    abs(hist.get(lbl, 0) / total -
                        cnt / total_train)
                    for lbl, cnt in train_hist.items())
                if drift > 0.1:
                    self.warning(
                        "%s label distribution deviates from train "
                        "by up to %.0f%%", CLASS_NAME[cls],
                        drift * 100)

    def shuffle(self):
        """Shuffles ONLY the train tail of the index space
        (reference: base.py:709)."""
        if self.epoch_number >= self.shuffle_limit:
            return
        if self.class_lengths[TRAIN] == 0:
            return
        train_start = self.class_end_offsets[VALID]
        arr = self.shuffled_indices.mem
        prng.get(self.prng_key).shuffle(arr[train_start:])
        self.shuffled_indices.mem = arr

    # -- the tick ----------------------------------------------------------

    def run(self):
        self.serve_next_minibatch()
        self.fill_minibatch()

    def serve_next_minibatch(self, slave_id=None):
        """Advances the global offset and publishes the next minibatch's
        indices + flags (reference: base.py:724)."""
        if self.failed_minibatches:
            # Re-serve lost work first (reference: base.py:677-685);
            # entries carry their sample class so retries don't
            # inherit whatever class was served last.
            indices, cls = self.failed_minibatches.pop()
            self.minibatch_class = cls
            self.last_minibatch = False
            self.epoch_ended = False
        else:
            indices = self._next_fresh_indices()
        if slave_id is not None:
            self._pending_indices_.setdefault(slave_id, []).append(
                [(indices, self.minibatch_class)])
        count = len(indices)
        mask = numpy.zeros(self.max_minibatch_size, dtype=numpy.float32)
        mask[:count] = 1.0
        padded = numpy.zeros(self.max_minibatch_size, dtype=numpy.int32)
        padded[:count] = indices
        self.minibatch_indices.mem = padded
        self.minibatch_mask.mem = mask
        self.minibatch_class_vec.mem = numpy.array(
            self.minibatch_class, dtype=numpy.int32)
        self.minibatch_size = count
        self._in_flight_ = [(numpy.array(indices,
                                         dtype=numpy.int32),
                             self.minibatch_class)]
        return indices

    def _next_fresh_indices(self):
        ends = self.class_end_offsets
        if self.global_offset >= self.total_samples:
            self.global_offset = 0
        # (class_of_offset never yields an empty class: an empty
        # class's end equals its start, and the strict < scan passes
        # it by.)
        cls = self.class_of_offset(self.global_offset)
        self.minibatch_class = cls
        cls_end = ends[cls]
        start = self.global_offset
        stop = min(start + self.max_minibatch_size, cls_end)
        self.global_offset = stop
        indices = numpy.array(
            self.shuffled_indices.mem[start:stop], dtype=numpy.int32)
        self._update_flags(stop)
        return indices

    def _update_flags(self, stop):
        """Epoch/flag logic (reference: base.py:856-907)."""
        ends = self.class_end_offsets
        self.last_minibatch = stop in ends and stop != 0
        self.epoch_ended = (stop == self.total_samples)
        if self.epoch_ended:
            self.epoch_number += 1
            self.global_offset = 0
            self.shuffle()

    def _walk_block(self, max_ticks):
        """The one block walk: serves up to ``max_ticks`` consecutive
        minibatches of the SAME sample class (stopping at class
        boundaries so epoch flags stay truthful; failed-batch retries
        are served singly — they may belong to a different class than
        the current walk).  Returns ``(idxs, masks, entries, cls)``:
        padded per-tick index/mask arrays, the trimmed
        ``[(indices, class), ...]`` in-flight entries, and the block's
        class.  Both the local scan-block dispatch
        (:meth:`serve_block`) and distributed multi-tick jobs
        (:meth:`generate_data_for_slave`) wrap this walk — the break
        conditions must never diverge between them."""
        idxs, masks, entries = [], [], []
        cls = None
        for _ in range(max_ticks):
            if self.failed_minibatches and idxs:
                break
            next_off = self.global_offset \
                if self.global_offset < self.total_samples else 0
            next_cls = self.class_of_offset(next_off)
            if cls is not None and next_cls != cls:
                break
            served = self.serve_next_minibatch()
            cls = self.minibatch_class
            entries.append((numpy.array(served, dtype=numpy.int32),
                            int(cls)))
            idxs.append(self.minibatch_indices.mem.copy())
            masks.append(self.minibatch_mask.mem.copy())
            if self.last_minibatch or self.failed_minibatches:
                break
        return idxs, masks, entries, cls

    def serve_block(self, max_ticks):
        """Serves up to ``max_ticks`` consecutive minibatches of the
        SAME sample class.  Returns {vector_id: (K, ...) array} with
        K = ticks actually served — NOT padded: jit specializes the
        block program per distinct K (a handful per run: the full
        block, the train remainder, the validation remainder), which
        beats burning a full block of conv compute on all-zero masks
        (a 256-sample validation pass used to cost as much as a
        ticks_per_dispatch×batch training block)."""
        idxs, masks, entries, cls = self._walk_block(max_ticks)
        # The WHOLE block is in flight until its one dispatch commits
        # (per-tick serves above each overwrote the record).
        self._in_flight_ = entries
        return {
            str(id(self.minibatch_indices)): numpy.stack(idxs),
            str(id(self.minibatch_mask)): numpy.stack(masks),
            str(id(self.minibatch_class_vec)): numpy.full(
                len(idxs), cls, dtype=numpy.int32),
        }

    # -- distributed contract ----------------------------------------------

    def generate_data_for_slave(self, slave=None):
        """The coordinator ships only indices (reference:
        base.py:629-661).  With a negotiated multi-tick job size
        (``--job-ticks``), one job carries up to K same-class
        minibatches — the worker runs them as one fused scan-block
        dispatch, amortizing one weight sync over K ticks.  Blocks
        stop at class boundaries (and at failed-minibatch retries,
        which are served singly), so every tick of a job shares one
        (epoch, class) accounting bucket — a job never straddles an
        epoch or class edge."""
        get = getattr(self.workflow, "slave_protocol", None)
        ticks = int((get(slave) if get is not None else {})
                    .get("ticks", 1) or 1)
        if ticks <= 1:
            indices = self.serve_next_minibatch(slave_id=slave)
            return {"indices": indices,
                    "minibatch_class": self.minibatch_class,
                    "epoch_number": self.epoch_number}
        epoch = self.epoch_number
        idxs, masks, entries, cls = self._walk_block(ticks)
        if slave is not None:
            self._pending_indices_.setdefault(slave, []).append(
                entries)
        return {"block": {
                    "indices": numpy.stack(idxs),
                    "mask": numpy.stack(masks),
                    "classes": numpy.full(len(idxs), cls,
                                          dtype=numpy.int32)},
                "minibatch_class": cls,
                "epoch_number": epoch}

    def apply_data_from_master(self, data):
        if "block" in data:
            blk = data["block"]
            indices = numpy.asarray(blk["indices"],
                                    dtype=numpy.int32)
            mask = numpy.asarray(blk["mask"], dtype=numpy.float32)
            classes = numpy.asarray(blk["classes"],
                                    dtype=numpy.int32)
            self._staged_block_ = {
                str(id(self.minibatch_indices)): indices,
                str(id(self.minibatch_mask)): mask,
                str(id(self.minibatch_class_vec)): classes,
            }
            # Single-tick vectors mirror the first tick so shape
            # introspection and eager paths stay coherent.
            self.minibatch_indices.mem = indices[0].copy()
            self.minibatch_mask.mem = mask[0].copy()
            self.minibatch_size = int(mask[0].sum())
            self.minibatch_class = int(classes[0])
            self.epoch_number = data["epoch_number"]
            return
        self._staged_block_ = None
        indices = numpy.asarray(data["indices"], dtype=numpy.int32)
        count = len(indices)
        padded = numpy.zeros(self.max_minibatch_size, dtype=numpy.int32)
        padded[:count] = indices
        mask = numpy.zeros(self.max_minibatch_size, dtype=numpy.float32)
        mask[:count] = 1.0
        self.minibatch_indices.mem = padded
        self.minibatch_mask.mem = mask
        self.minibatch_size = count
        self.minibatch_class = data["minibatch_class"]
        self.epoch_number = data["epoch_number"]

    def take_staged_block(self):
        """Worker side: the job's staged multi-tick block ({vector id
        → (K, ...) array}) or None; consumed once per job."""
        block = self._staged_block_
        self._staged_block_ = None
        return block

    def apply_data_from_slave(self, data, slave=None):
        jobs = self._pending_indices_.get(slave)
        if jobs:
            jobs.pop(0)  # oldest job answered (serve order = FIFO)
            if not jobs:
                self._pending_indices_.pop(slave, None)

    def drop_slave(self, slave=None):
        """Requeues every tick of every in-flight job of the dropped
        worker with its class (reference: base.py:677-685)."""
        for entry in self._pending_indices_.pop(slave, ()):
            self.failed_minibatches.extend(entry)

    # -- pickling: pending work is requeued so nothing is lost -------------

    def __getstate__(self):
        state = super(Loader, self).__getstate__()
        pending = [tick
                   for jobs in self._pending_indices_.values()
                   for entry in jobs
                   for tick in entry]
        state["failed_minibatches"] = (
            list(self.failed_minibatches) + pending)
        return state

    # -- subclass hooks ----------------------------------------------------

    def load_data(self):
        raise NotImplementedError()

    def create_minibatch_data(self):
        raise NotImplementedError()

    def fill_minibatch(self):
        """Host-side materialization hook; device-resident loaders do
        the gather inside the fused step instead."""
