"""Attribute-path configuration tree.

Capability parity with the reference config system (reference:
veles/config.py — ``Config:52``, ``root:151``): an auto-vivifying
attribute tree (``root.loader.minibatch_size = 60``), ``update()`` from
nested dicts, protected keys, pretty-printing, and site/user override
layering.  The genetics subsystem wraps leaves in :class:`Tune` to mark
them optimizable (reference: veles/genetics/config.py:45).

TPU-era additions: the default tree carries engine knobs relevant to
JAX/XLA (precision, mesh axis names, checkpoint dirs) instead of
OpenCL/CUDA device settings.
"""

import contextlib
import os
import pprint

PROTECTED_KEYS = {"update", "update_unknown", "print_", "keys", "items",
                  "path_str", "as_dict", "reset"}


class Tune(object):
    """Marks a config leaf as optimizable by the genetics subsystem.

    ``root.lr = Tune(0.01, 0.0001, 0.1)`` declares a gene with the given
    default and [min, max] range (reference: veles/genetics/config.py:45
    ``Tuneable``).
    """

    def __init__(self, default, minv, maxv):
        self.default = default
        self.min = minv
        self.max = maxv

    def __repr__(self):
        return "Tune(%s, %s, %s)" % (self.default, self.min, self.max)

    # Arithmetic/conversion fall back to the default value so un-tuned
    # runs behave as if the plain value had been written.
    def __float__(self):
        return float(self.default)

    def __int__(self):
        return int(self.default)


class Config(object):
    """A node in the configuration tree.

    Attribute access auto-vivifies intermediate nodes
    (reference: veles/config.py:100-107), so
    ``root.a.b.c = 1`` works without declaring ``a`` or ``b`` first.
    """

    def __init__(self, path="root"):
        object.__setattr__(self, "_path", path)

    # -- tree construction -------------------------------------------------

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        child = Config("%s.%s" % (self._path, name))
        object.__setattr__(self, name, child)
        return child

    def __setattr__(self, name, value):
        if name in PROTECTED_KEYS:
            raise AttributeError(
                "'%s' is a protected Config key" % name)
        if isinstance(value, dict) and not name.startswith("_"):
            node = Config("%s.%s" % (self._path, name))
            node.update(value)
            object.__setattr__(self, name, node)
        else:
            object.__setattr__(self, name, value)

    # -- dict-ish API ------------------------------------------------------

    def update(self, tree=None, **kwargs):
        """Deep-merges a nested dict (or kwargs) into this node
        (reference: veles/config.py ``Config.update``)."""
        if tree is None:
            tree = {}
        merged = dict(tree)
        merged.update(kwargs)
        for key, value in merged.items():
            if isinstance(value, dict):
                node = getattr(self, key)
                if not isinstance(node, Config):
                    node = Config("%s.%s" % (self._path, key))
                    object.__setattr__(self, key, node)
                node.update(value)
            else:
                setattr(self, key, value)
        return self

    def keys(self):
        return [k for k in self.__dict__ if not k.startswith("_")]

    def items(self):
        return [(k, v) for k, v in self.__dict__.items()
                if not k.startswith("_")]

    def as_dict(self):
        out = {}
        for k, v in self.items():
            out[k] = v.as_dict() if isinstance(v, Config) else v
        return out

    def path_str(self):
        return self._path

    def reset(self):
        """Drops every child from this node."""
        for k in self.keys():
            object.__delattr__(self, k)

    def get(self, name, default=None):
        """Returns a *set* leaf value or ``default`` — does NOT vivify;
        previously-vivified empty nodes also yield ``default``."""
        value = self.__dict__.get(name, default)
        if isinstance(value, Config):
            return default
        return value

    def __contains__(self, name):
        return name in self.__dict__ and not name.startswith("_")

    def __repr__(self):
        return "<Config %s: %s>" % (self._path, sorted(self.keys()))

    def print_(self, file=None):
        pprint.pprint(self.as_dict(), stream=file)


def get(value, default=None):
    """Returns ``default`` if ``value`` is an unset Config node
    (mirrors the reference's ``veles.config.get`` helper)."""
    if isinstance(value, Config):
        return default
    if isinstance(value, Tune):
        return value.default
    return value


@contextlib.contextmanager
def override_scope(node, overrides):
    """Applies ``{dotted.path: value}`` leaf overrides under ``node``
    and RESTORES the exact prior leaves on exit — previously-set
    values (``Tune`` objects included) are put back by object, and
    leaves that did not exist are deleted again.

    This is the per-run config variation mechanism shared by genetics
    chromosome evaluation, ensemble per-instance variation, and
    population lineages (docs/population.md): the config tree is
    process-global, so any in-process multi-member evaluation that
    writes gene/variation overrides without save/restore leaks them
    into every later member.  Intermediate nodes vivified by the walk
    are left in place (an empty Config node reads as unset).
    """
    saved = []  # (parent, leaf, existed, old_value) in apply order
    try:
        for path, value in overrides.items():
            parts = path.split(".")
            parent = node
            for part in parts[:-1]:
                parent = getattr(parent, part)
            leaf = parts[-1]
            existed = leaf in parent.__dict__
            saved.append((parent, leaf, existed,
                          parent.__dict__.get(leaf)))
            setattr(parent, leaf, value)
        yield
    finally:
        for parent, leaf, existed, old in reversed(saved):
            if existed:
                object.__setattr__(parent, leaf, old)
            elif leaf in parent.__dict__:
                object.__delattr__(parent, leaf)


#: The global configuration root (reference: veles/config.py:151).
root = Config("root")

root.common.update({
    "dirs": {
        "cache": os.path.join(os.path.expanduser("~"), ".veles_tpu/cache"),
        "datasets": os.environ.get(
            "VELES_TPU_DATA",
            os.path.join(os.path.expanduser("~"), ".veles_tpu/datasets")),
        "snapshots": os.path.join(
            os.path.expanduser("~"), ".veles_tpu/snapshots"),
        "events": os.path.join(os.path.expanduser("~"), ".veles_tpu/events"),
        "plots": os.path.join(os.path.expanduser("~"), ".veles_tpu/plots"),
    },
    "engine": {
        # "tpu", "cpu", or "auto" — resolved by backends.Device.
        "backend": os.environ.get("VELES_TPU_BACKEND", "auto"),
        # Matmul/conv accumulation dtype policy.
        "precision_type": "float32",
        # 0: bf16 compute everywhere it is safe; 1: f32 compute;
        # 2: f32 with highest-precision matmuls (replaces the reference's
        # plain/Kahan/multipartial summation levels, config.py:244-247 —
        # on TPU the equivalent knob is matmul precision).
        "precision_level": 0,
        "mesh_axes": {"data": "data", "model": "model"},
        "sync_run": False,
        # Reproducibility guard: replace numpy.random's module-level
        # sampling functions with a loud error while a CLI run is live
        # (reference: prng/random_generator.py:49-61).
        "poison_numpy_random": True,
    },
    "loader": {
        "shuffle_limit": -1,
    },
    "snapshotter": {
        "interval": 1,
        "time_interval": 15.0,
        "compression": "gz",
    },
    "net": {
        # Distributed data-plane knobs (docs/distributed.md).
        # Wire payload codec: "gzip" or "none"; level/threshold feed
        # the codec (frames below threshold bytes ship uncompressed).
        "codec": "gzip",
        "codec_level": 1,
        "codec_threshold": 1 << 16,
        # Delta dtype on the worker→master direction: "fp32" (exact)
        # or "bf16" (2x smaller, lossy — breaks bit-reproducibility).
        "dtype": "fp32",
        # Minibatch ticks per distributed job (sync amortization).
        "job_ticks": 1,
        # "delta" (tensor framing + delta sync, negotiated down to
        # pickle-compat for old peers) or "legacy" (force the old
        # full-pickled-weights protocol).
        "mode": "delta",
        # Refuse pickle-compat fallback: old-format peers get a clean
        # rejection instead of being served legacy frames.
        "require": False,
    },
    "web": {"host": "localhost", "port": 8090},
    "graphics": {"enabled": False},
    "trace": {"enabled": False, "dir": None},
})


def _load_site_overrides():
    """Layered site config: /etc/default/veles_tpu, ~/.veles_tpu/site.py,
    ./site_config.py — each is executed with ``root`` in scope
    (reference: veles/config.py:293-307)."""
    for path in ("/etc/default/veles_tpu",
                 os.path.join(os.path.expanduser("~"),
                              ".veles_tpu", "site.py"),
                 os.path.join(os.getcwd(), "site_config.py")):
        if os.path.isfile(path):
            with open(path, "r") as fin:
                code = fin.read()
            exec(compile(code, path, "exec"), {"root": root})


_load_site_overrides()
