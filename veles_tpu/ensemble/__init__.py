"""Model ensembles: train N varied instances, test them jointly.

Capability parity with the reference ensembles (reference:
veles/ensemble/base_workflow.py — ``EnsembleModelManagerBase:59``;
model_workflow.py:50 — per-instance runs with varied seed +
``--train-ratio``, results collected into one JSON incl.
``EvaluationFitness``; test_workflow.py:50 — re-run saved instances
over data and collect outputs; CLI: ``--ensemble-train N:r``,
``--ensemble-test file``, __main__.py:710-728).

TPU-era upgrades over the reference: instances run in-process (no
subprocess fork per instance; the fused-step compiler caches across
instances), and testing does true probability-averaging on device —
each instance's per-sample softmax outputs are scatter-captured into
an HBM buffer during a frozen evaluation epoch
(EvaluatorSoftmax.enable_capture), then averaged across instances for
a real ensemble error, not just per-instance metric collection.
"""

import gzip
import json
import os
import pickle

import numpy

from ..config import root, get as config_get, override_scope
from ..error import Bug
from ..harness import (FITNESS_KEY, run_workflow_module, seed_to_int)
from ..json_encoders import dump_json
from ..launcher import Launcher
from ..loader.base import VALID, TRAIN
from ..logger import Logger
from ..snapshotter import SnapshotterToFile


class EnsembleTrainer(Logger):
    """Trains N instances with varied seeds/train subsets
    (reference: model_workflow.py:50)."""

    def __init__(self, main, instances, train_ratio=1.0, **kwargs):
        super(EnsembleTrainer, self).__init__()
        self.main = main
        self.module = main.module
        args = main.args
        self.instances = int(instances)
        self.train_ratio = float(train_ratio)
        self.base_seed = seed_to_int(args.random_seed)
        stem = os.path.splitext(os.path.basename(
            getattr(self.module, "__file__", "workflow")))[0]
        self.result_file = args.result_file or \
            "%s_ensemble.json" % stem
        self.snapshot_dir = kwargs.get("snapshot_dir") or config_get(
            root.common.dirs.snapshots, "snapshots")
        self.stem = stem

    #: Seed stride between instances (shared with the population
    #: scheduler so fleet-trained members reproduce this path's
    #: per-instance seeds exactly).
    SEED_STRIDE = 1000003

    def _variation_overrides(self):
        """The per-instance config variation, expressed as the same
        dotted-path override set population lineages use — one
        mechanism (``config.override_scope``) for every in-process
        multi-member run, so variation can never leak between
        instances or into a later run."""
        return {"common.loader.train_ratio": self.train_ratio}

    def _snapshot_workflow(self, index, wf):
        os.makedirs(self.snapshot_dir, exist_ok=True)
        snapshot = os.path.join(
            self.snapshot_dir,
            "ensemble_%s_%02d.pickle.gz" % (self.stem, index))
        with gzip.open(snapshot, "wb") as fout:
            pickle.dump(wf, fout, protocol=pickle.HIGHEST_PROTOCOL)
        return snapshot

    def _describe(self, index, seed, wf):
        results = wf.gather_results()
        return {"index": index, "seed": seed,
                "train_ratio": self.train_ratio,
                "snapshot": self._snapshot_workflow(index, wf),
                "results": results,
                "fitness": results.get(FITNESS_KEY)}

    def _train_one(self, index, seed):
        with override_scope(root, self._variation_overrides()):
            wf = run_workflow_module(self.module, seed=seed)
        return self._describe(index, seed, wf)

    def _payload(self, instances):
        fitnesses = [inst["fitness"] for inst in instances
                     if inst["fitness"] is not None]
        payload = {
            "mode": "ensemble-train",
            "workflow": getattr(self.module, "__file__",
                                self.module.__name__),
            "size": self.instances,
            "train_ratio": self.train_ratio,
            "instances": instances,
            "fitnesses": fitnesses,
        }
        dump_json(payload, self.result_file)
        self.info("ensemble description -> %s", self.result_file)
        return payload

    def run(self):
        if getattr(self.main.args, "ensemble_population", False):
            return self.run_on_population()
        instances = []
        for i in range(self.instances):
            seed = self.base_seed + i * self.SEED_STRIDE
            self.info("training ensemble instance %d/%d (seed %d, "
                      "train_ratio %.2f)", i + 1, self.instances,
                      seed, self.train_ratio)
            instances.append(self._train_one(i, seed))
        return self._payload(instances)

    def run_on_population(self):
        """``--ensemble-train`` over the population scheduler
        (``--ensemble-population``, docs/population.md): instances
        become fleet-scheduled lineages — trained concurrently by
        whatever workers are attached when a master is running
        (``-l``), self-driven in-process otherwise — and produce the
        same per-instance snapshots + description JSON as the
        sequential path (bit-identical trajectories: the seeded
        parity gate in tests/test_population.py)."""
        from ..population import PopulationEngine
        engine = PopulationEngine(
            main=self.main, size=self.instances, mode="train",
            seed_stride=self.SEED_STRIDE,
            base_overrides=self._variation_overrides())
        # The engine owns scheduling; the description JSON is ours.
        engine.result_file = None
        engine.run()
        master = engine.master
        if master is None:
            return None  # worker mode: the coordinator reports
        instances = []
        for i, member in enumerate(master.members):
            if member.wf is None:
                continue
            instances.append(self._describe(i, member.seed,
                                            member.wf))
        return self._payload(instances)


class EnsembleTester(Logger):
    """Runs a saved ensemble jointly over the evaluation data
    (reference: test_workflow.py:50)."""

    def __init__(self, main, ensemble_file, **kwargs):
        super(EnsembleTester, self).__init__()
        self.ensemble_file = ensemble_file
        self.result_file = (main.args.result_file
                            if main is not None else None) or \
            os.path.splitext(ensemble_file)[0] + "_test.json"

    def _test_one(self, inst):
        """One frozen evaluation epoch over a restored instance,
        capturing per-sample probabilities."""
        wf = SnapshotterToFile.import_(inst["snapshot"])
        launcher = Launcher()
        launcher.add_ref(wf)
        decision = getattr(wf, "decision", None)
        if decision is None:
            raise Bug("ensemble instance %r has no decision unit"
                      % inst["snapshot"])
        # One more (frozen) epoch: raise the stop BEFORE initialize —
        # the stop condition is re-evaluated there.  The fail window
        # must widen too: an instance stopped by fail_iterations
        # (not max_epochs) keeps should_stop() true otherwise and the
        # evaluation epoch silently never runs.
        trained_epochs = decision.epoch_number
        decision.max_epochs = trained_epochs + 1
        if hasattr(decision, "fail_iterations"):
            decision.fail_iterations = float("inf")
        wf.frozen = True
        launcher.initialize(snapshot=True)
        evaluator = getattr(wf, "evaluator", None)
        capture = hasattr(evaluator, "enable_capture")
        if capture:
            evaluator.enable_capture(wf.loader)
        launcher.run()
        if decision.epoch_number != trained_epochs + 1:
            raise Bug("frozen evaluation epoch did not run for %r "
                      "(epoch stayed at %d)" %
                      (inst["snapshot"], decision.epoch_number))
        metrics = {
            "validation_err": decision.epoch_metrics[VALID],
            "train_err": decision.epoch_metrics[TRAIN],
        }
        probs = evaluator.read_capture() if capture else None
        return wf, metrics, probs

    def run(self):
        with open(self.ensemble_file) as fin:
            desc = json.load(fin)
        per_instance = []
        prob_sum = None
        labels = None
        val_slice = None
        for inst in desc["instances"]:
            wf, metrics, probs = self._test_one(inst)
            self.info("instance %d: frozen validation err %s",
                      inst["index"], metrics["validation_err"])
            per_instance.append(
                {"index": inst["index"], **metrics})
            if probs is not None:
                prob_sum = probs if prob_sum is None \
                    else prob_sum + probs
                loader = wf.loader
                if labels is None and loader.original_labels:
                    loader.original_labels.map_read()
                    labels = numpy.array(loader.original_labels.mem)
                    ends = loader.class_end_offsets
                    val_slice = slice(ends[VALID - 1] if VALID else 0,
                                      ends[VALID])
        payload = {
            "mode": "ensemble-test",
            "ensemble": self.ensemble_file,
            "size": len(per_instance),
            "instances": per_instance,
        }
        if prob_sum is not None and labels is not None and \
                val_slice.stop > val_slice.start:
            mean_probs = prob_sum / len(per_instance)
            pred = numpy.argmax(mean_probs[val_slice], axis=-1)
            truth = labels[val_slice]
            err = float(numpy.mean(pred != truth))
            payload["ensemble_validation_err"] = err
            payload["mean_probability_margin"] = float(
                numpy.mean(numpy.max(mean_probs[val_slice], axis=-1)))
            self.info("ensemble of %d: joint validation err %.4f",
                      len(per_instance), err)
        dump_json(payload, self.result_file)
        self.info("ensemble test results -> %s", self.result_file)
        return payload
