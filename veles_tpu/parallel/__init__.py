from .mesh import (make_mesh, apply_dp_sharding,  # noqa: F401
                   rebuild_mesh)
