from .mesh import (make_mesh, apply_dp_sharding,  # noqa: F401
                   apply_dp_tp_sharding, apply_dp_sp_sharding,
                   apply_dp_ep_sharding, apply_dp_pp_sharding,
                   apply_dp_pp_tp_sharding, apply_dp_ep_tp_sharding,
                   apply_dp_tp_sp_sharding, apply_zero_sharding,
                   rebuild_mesh)
