"""Mesh construction and data-parallel sharding.

This replaces the reference's master–slave data-parallel engine
(reference: veles/server.py, veles/client.py, veles/distributable.py —
minibatch indices sharded to slaves over ZeroMQ, weights shipped in job
pickles, gradients aggregated in ``apply_data_from_slave``) with the
TPU-native formulation:

  * the device mesh (`jax.sharding.Mesh`) spans all local chips (and,
    multi-host, all processes' chips via ``jax.distributed``);
  * the LOADER still thinks in minibatch indices — exactly like the
    reference coordinator (loader/base.py:629-661) — but instead of
    mailing index lists to worker processes, the index array is laid
    out along the mesh's ``data`` axis, so each chip gathers and
    processes its shard of the global minibatch;
  * parameters are replicated; ``jax.grad`` of the mean loss over a
    sharded batch makes XLA insert the gradient all-reduce (psum) over
    ICI — the explicit ``apply_data_from_slave`` aggregation loop
    disappears into the compiled step.

Elasticity note: the reference drops slaves and requeues their
minibatches (server.py:315-338).  SPMD equivalents operate at mesh
granularity: on chip loss :func:`rebuild_mesh` re-forms the mesh over
the survivors, re-places every step tensor, requeues the interrupted
minibatch (the failed-minibatch queue survives as-is), and the next
tick compiles for the new topology.
"""

from jax.sharding import Mesh, NamedSharding, PartitionSpec


def make_mesh(devices=None, axes=None):
    """Builds a Mesh; ``axes`` maps name → size with -1 = remaining."""
    import jax
    import numpy as np
    if devices is None:
        devices = jax.devices()
    if axes is None:
        axes = {"data": len(devices)}
    names = list(axes)
    sizes = [axes[n] for n in names]
    if -1 in sizes:
        known = 1
        for s in sizes:
            if s != -1:
                known *= s
        sizes[sizes.index(-1)] = len(devices) // known
    count = 1
    for s in sizes:
        count *= s
    return Mesh(np.array(devices[:count]).reshape(sizes), names)


def apply_dp_sharding(workflow, mesh, axis="data"):
    """Marks the workflow's step tensors for data parallelism:
    per-tick batch vectors are sharded along ``axis`` (dim 0), params /
    optimizer state / dataset originals are replicated.

    After this, the SAME compiled step runs 1-chip or N-chip — XLA
    inserts the gradient psum over ICI because the loss is a mean over
    a sharded batch with replicated params.
    """
    compiler = workflow.compiler
    compiler.analyze()
    replicated = NamedSharding(mesh, PartitionSpec())
    sharded = NamedSharding(mesh, PartitionSpec(axis))
    n = mesh.shape[axis]
    for vec in compiler.batch_vectors:
        shape = vec.shape
        if shape and len(shape) >= 1 and shape[0] % n == 0:
            vec.sharding = sharded
        else:
            vec.sharding = replicated
    for vec in compiler._collect("params").values():
        vec.sharding = replicated
    for vec in compiler._collect("state").values():
        vec.sharding = replicated
    for vec in compiler.const_vectors:
        vec.sharding = replicated
    # Persisted step outputs are batch-shaped: shard them like batch
    # vectors so host reads after a rebuild never touch buffers on a
    # departed device set.
    for vec in compiler.persist_vectors:
        shape = vec.shape
        if shape and len(shape) >= 1 and shape[0] % n == 0:
            vec.sharding = sharded
        else:
            vec.sharding = replicated
    workflow.mesh = mesh
    workflow._parallel_style_ = ("dp", axis)
    return workflow


def _transformer_tp_plan(unit, n_model, model_axis):
    """Megatron-style PartitionSpecs for one transformer-family unit,
    or None when its geometry does not divide the model axis.

    The layout is the standard column→row pairing, expressed as
    GSPMD annotations instead of manual collectives (XLA inserts the
    all-reduce after each row-parallel matmul):

      * attention: wq/wk/wv COLUMN-sharded (each model shard computes
        E/n output features = H/n whole heads; the (B,S,H,D) reshape
        keeps the head dim sharded because n | H), wo ROW-sharded
        (partial sums psum to a replicated residual); the FUSED
        (E, 3E) wqkv shards its 3E column dim the same way — its
        head-major layout ([q_h|k_h|v_h] per head) means a contiguous
        3E/n column shard is H/n whole heads' q/k/v, so the
        (B,S,H,3,D) reshape keeps the head dim sharded and the q/k/v
        split indexes a replicated axis;
      * MLP: w1 column, w2 row — the hidden dim lives sharded, the
        residual stream stays replicated;
      * MoE experts: same column/row pairing on the per-expert
        matrices (trailing dims; the leading expert dim is the
        EXPERT axis's business, composable);
      * pipelined stacks: same specs with the leading stage dim left
        to the STAGE axis;
      * LMHead: vocab (output) column-sharded — the loss's
        log-softmax reduction over the sharded vocab becomes an XLA
        collective;
      * Embedding: embed dim sharded (the vocab-dim gather stays
        local per shard); a TIED head then contracts over the sharded
        embed dim — a row-parallel linear ending in a psum.
    """
    from ..znicz.attention import (Embedding, LMHead,
                                   MoETransformerBlock,
                                   PipelinedTransformerStack,
                                   TransformerBlock)

    def spec(*axes):
        return PartitionSpec(*axes)

    if isinstance(unit, (TransformerBlock, PipelinedTransformerStack)):
        inp = getattr(unit, "input", None)
        if inp is None or inp.shape is None:
            # Pre-initialize sharding (no linked input yet): degrade
            # to replicated instead of raising AttributeError.
            return None
        embed = inp.shape[-1]
        hidden = embed * unit.mlp_ratio
        if embed % n_model or hidden % n_model or \
                unit.n_heads % n_model:
            return None
        col, row, vec, rep = ((None, model_axis),
                              (model_axis, None),
                              (model_axis,), ())
        if isinstance(unit, MoETransformerBlock):
            plan = {
                "wq": col, "wk": col, "wv": col, "wo": row,
                "bq": vec, "bk": vec, "bv": vec, "bo": rep,
                # Fused layout: the 3E column dim is head-major, so a
                # column shard is whole heads' q/k/v (see the wqkv
                # note above).
                "wqkv": col, "bqkv": vec,
                "ln1_g": rep, "ln1_b": rep,
                "ln2_g": rep, "ln2_b": rep,
                "router": rep,
                # Per-expert column/row pairing on the TRAILING dims;
                # the leading expert dim stays None here (the expert
                # axis shards it, composably).
                "w1": (None,) + col, "b1": (None,) + vec,
                "w2": (None,) + row, "b2": (None,) + rep,
            }
        elif isinstance(unit, PipelinedTransformerStack):
            plan = {
                "wq": (None,) + col, "wk": (None,) + col,
                "wv": (None,) + col, "wo": (None,) + row,
                "bq": (None,) + vec, "bk": (None,) + vec,
                "bv": (None,) + vec, "bo": (None,) + rep,
                "wqkv": (None,) + col, "bqkv": (None,) + vec,
                "ln1_g": (None,) + rep, "ln1_b": (None,) + rep,
                "ln2_g": (None,) + rep, "ln2_b": (None,) + rep,
                "w1": (None,) + col, "b1": (None,) + vec,
                "w2": (None,) + row, "b2": (None,) + rep,
            }
        else:
            plan = {
                "wq": col, "wk": col, "wv": col, "wo": row,
                "bq": vec, "bk": vec, "bv": vec, "bo": rep,
                "wqkv": col, "bqkv": vec,
                "ln1_g": rep, "ln1_b": rep,
                "ln2_g": rep, "ln2_b": rep,
                "w1": col, "b1": vec, "w2": row, "b2": rep,
            }
        return {name: spec(*axes) for name, axes in plan.items()
                if name in unit.trainables}
    if isinstance(unit, LMHead):
        plan = {}
        w = unit.trainables.get("weights")
        if w and w.shape[-1] % n_model == 0:
            plan["weights"] = spec(None, model_axis)
            b = unit.trainables.get("bias")
            if b:
                plan["bias"] = spec(model_axis)
        return plan or None
    if isinstance(unit, Embedding):
        w = unit.trainables.get("weights")
        if w is None or not w or w.shape[-1] % n_model:
            return None
        plan = {"weights": spec(None, model_axis)}
        if unit.pos:
            plan["pos"] = spec(None, model_axis)
        return plan
    return None


def apply_dp_tp_sharding(workflow, mesh, data_axis="data",
                         model_axis="model"):
    """Data × tensor parallelism over a 2-axis mesh — the "natural
    XLA extension" beyond the reference's DP-only engine (SURVEY
    §2.3): dense layers' weight matrices shard along their OUTPUT
    dimension on ``model_axis`` (so each model-shard computes a slice
    of the layer's neurons from the full input), the transformer
    family gets the full Megatron-style column/row pairing
    (:func:`_transformer_tp_plan`), optimizer momentum shards
    identically, batches shard on ``data_axis``.  No manual
    collectives: XLA's sharding propagation inserts the
    all-gather/reduce-scatter pattern between layers and the gradient
    psum over the data axis — the same compiled step, just annotated
    differently.

    Layers whose geometry does not divide the model-axis size stay
    replicated (correct, merely less parallel).
    """
    from ..znicz.all2all import All2All

    apply_dp_sharding(workflow, mesh, axis=data_axis)
    n_model = mesh.shape[model_axis]
    col_sharded = NamedSharding(mesh,
                                PartitionSpec(None, model_axis))
    vec_sharded = NamedSharding(mesh, PartitionSpec(model_axis))
    gd_of = {gd.target: gd
             for gd in getattr(workflow, "gds", [])
             if getattr(gd, "target", None) is not None}

    def shard_slots_by_name(unit, gd):
        """Optimizer slots mirror their parameter BY NAME
        (velocity_<param>, adam_m_<param>, … — any registered prefix,
        znicz.optimizers.param_of_slot) — shape matching alone could
        collide (e.g. wq/wk/wv are all (E, E)).  Non-mirror slots
        (Adam's scalar step counters) stay replicated."""
        if gd is None:
            return
        from ..znicz.optimizers import param_of_slot
        for name, vec in gd.tstate.items():
            pname = param_of_slot(name) or name
            target = unit.trainables.get(pname)
            if vec and target is not None and \
                    tuple(vec.shape) == tuple(target.shape):
                vec.sharding = target.sharding

    sharded_layers = 0
    for unit in getattr(workflow, "forwards", []):
        plan = _transformer_tp_plan(unit, n_model, model_axis)
        if plan:
            for pname, pspec in plan.items():
                unit.trainables[pname].sharding = \
                    NamedSharding(mesh, pspec)
            shard_slots_by_name(unit, gd_of.get(unit))
            sharded_layers += 1
            continue
        if not isinstance(unit, All2All):
            continue
        weights = unit.trainables.get("weights")
        if weights is None or not weights or \
                weights.shape[-1] % n_model:
            continue
        weights.sharding = col_sharded
        bias = unit.trainables.get("bias")
        if bias:
            bias.sharding = vec_sharded
        sharded_layers += 1
        gd = gd_of.get(unit)
        if gd is not None:
            # Optimizer slots that MIRROR a parameter's shape ride
            # its sharding (velocity_weights ≡ weights); anything
            # non-mirror stays replicated — shape matching cannot
            # mis-shard the way name/rank heuristics can.
            for name, vec in gd.tstate.items():
                if not vec:
                    continue
                if tuple(vec.shape) == tuple(weights.shape):
                    vec.sharding = col_sharded
                elif bias and \
                        tuple(vec.shape) == tuple(bias.shape):
                    vec.sharding = vec_sharded
    if sharded_layers == 0:
        workflow.warning(
            "apply_dp_tp_sharding: no layer geometry divides the "
            "model axis (%d) — the workflow runs data-parallel only"
            % n_model)
    workflow._parallel_style_ = ("dp_tp", data_axis, model_axis)
    return workflow


def apply_dp_tp_sp_sharding(workflow, mesh, data_axis="data",
                            model_axis="model", seq_axis="seq",
                            sp_kernel=None):
    """COMPOSED 3-axis layout: data × tensor × sequence parallelism.

    The Megatron column/row weight sharding comes from
    :func:`apply_dp_tp_sharding`; every transformer unit that
    declares this ``seq_axis`` additionally runs its attention
    sequence-parallel (ring or Ulysses) INSIDE a shard_map whose
    specs now carry the model axis on the HEAD dim — attention is
    per-head, so head-sharding composes with the sequence collectives
    for free: the ring's ppermutes involve only ``seq_axis``, each
    model shard rotates only its own heads' k/v — and the ring-flash
    body (``sp_ring_kernel`` "auto" default) runs the Pallas kernel
    on exactly that local-heads shard, so tp × sp × flash composes
    with no extra collective.  ``sp_kernel`` overrides the knob on
    every sequence-parallel unit ("xla" forces the lax scan,
    "pallas" the flash body — the dryrun's self-verify handle).

    Mesh shape: (data, model, seq).  Activations (B, S, H, D) inside
    attention are sharded (data, seq, model, None).
    """
    apply_dp_tp_sharding(workflow, mesh, data_axis=data_axis,
                         model_axis=model_axis)
    n_model = mesh.shape[model_axis]
    sp_blocks = 0
    for unit in getattr(workflow, "forwards", []):
        if getattr(unit, "seq_axis", None) != seq_axis:
            continue
        unit.batch_axis = data_axis
        if getattr(unit, "n_heads", 0) % n_model == 0:
            unit.head_axis = model_axis
        if sp_kernel is not None:
            unit.sp_kernel = sp_kernel
        sp_blocks += 1
    if sp_blocks == 0:
        workflow.warning(
            "apply_dp_tp_sp_sharding: no forward unit declares "
            "seq_axis=%r — attention runs without sequence "
            "parallelism" % seq_axis)
    workflow._parallel_style_ = ("dp_tp_sp", data_axis, model_axis,
                                 seq_axis)
    return workflow


def apply_dp_sp_sharding(workflow, mesh, data_axis="data",
                         seq_axis="seq"):
    """Data × sequence parallelism — the long-context layout
    (SURVEY §5: absent in the 2013-15 reference; first-class here):
    batches shard on ``data_axis`` exactly as in DP, and every
    TransformerBlock whose ``seq_axis`` names a mesh axis runs its
    attention as a ``shard_map`` ring over that axis
    (ops/attention.py ``ring_attention`` — k/v shards rotate over ICI
    with a streaming-softmax accumulator, so per-device activation
    memory scales as S/N and no device ever holds full K/V).

    Params stay replicated; gradients of the mean loss psum over the
    data axis via GSPMD as in DP; the ring's own collectives are
    explicit ppermutes inserted by the unit.
    """
    apply_dp_sharding(workflow, mesh, axis=data_axis)
    ring_blocks = 0
    for unit in getattr(workflow, "forwards", []):
        if getattr(unit, "seq_axis", None) == seq_axis:
            unit.batch_axis = data_axis
            ring_blocks += 1
    if ring_blocks == 0:
        workflow.warning(
            "apply_dp_sp_sharding: no forward unit declares "
            "seq_axis=%r — the workflow runs data-parallel only"
            % seq_axis)
    workflow._parallel_style_ = ("dp_sp", data_axis, seq_axis)
    return workflow


def apply_dp_ep_sharding(workflow, mesh, data_axis="data",
                         expert_axis="expert"):
    """Data × EXPERT parallelism for Mixture-of-Experts blocks
    (znicz/attention.py MoETransformerBlock): each MoE block's
    expert-stacked parameters (leading ``n_experts`` dimension) and
    their mirroring optimizer slots shard along ``expert_axis``; the
    GShard dispatch/combine einsums (ops/moe.py) then contract a
    sharded expert dimension against replicated tokens, and XLA
    lowers them to the all-to-all pattern of expert-parallel
    frameworks over ICI.  Everything else follows DP.

    Blocks whose ``n_experts`` does not divide the expert-axis size
    stay replicated (correct, merely not expert-parallel).
    """
    apply_dp_sharding(workflow, mesh, axis=data_axis)
    # Optimizer slots match their parameter BY NAME inside the
    # shared overlay (any registered slot prefix — velocity_/
    # adam_m_/…) — shape matching would mis-shard e.g.
    # velocity_router when router (D, E) collides with b2 (E, D).
    if _overlay_leading_axis(workflow, mesh, "expert_params",
                             "n_experts", expert_axis) == 0:
        workflow.warning(
            "apply_dp_ep_sharding: no MoE block's n_experts divides "
            "the expert axis (%d) — the workflow runs data-parallel "
            "only" % mesh.shape[expert_axis])
    workflow._parallel_style_ = ("dp_ep", data_axis, expert_axis)
    return workflow


def apply_dp_pp_sharding(workflow, mesh, data_axis="data",
                         stage_axis="stage"):
    """Data × PIPELINE parallelism (znicz/attention.py
    PipelinedTransformerStack + ops/pipeline.py ``gpipe``): each
    stack's stage-stacked parameters (leading ``n_blocks`` dim) and
    their mirroring optimizer slots shard one stage per device along
    ``stage_axis``; inside the step the stack runs the collective-
    permute pipeline over that axis with microbatching.  Everything
    else follows DP.

    Stacks whose ``n_blocks`` does not divide the stage-axis size
    stay replicated (they then run the sequential scan — correct,
    merely not pipelined).
    """
    apply_dp_sharding(workflow, mesh, axis=data_axis)
    if _overlay_leading_axis(workflow, mesh, "stage_params",
                             "n_blocks", stage_axis) == 0:
        workflow.warning(
            "apply_dp_pp_sharding: no pipelined stack's n_blocks "
            "divides the stage axis (%d) — the workflow runs "
            "data-parallel only" % mesh.shape[stage_axis])
    workflow._parallel_style_ = ("dp_pp", data_axis, stage_axis)
    return workflow


def _overlay_leading_axis(workflow, mesh, params_attr, count_attr,
                          lead_axis):
    """The shared ep/pp leading-dim overlay (used by the plain
    dp×ep / dp×pp appliers AND the ×tp compositions): for every unit
    exposing ``params_attr`` (stage_params / expert_params) whose
    ``count_attr`` (n_blocks / n_experts) divides the ``lead_axis``
    size, put ``lead_axis`` on dim 0 ON TOP of whatever trailing
    axes are already assigned (all-None after plain dp, the Megatron
    column/row pairing after :func:`apply_dp_tp_sharding`), then
    re-point the mirroring optimizer slots by name
    (``znicz.optimizers.param_of_slot`` — shape matching alone could
    collide).  Returns the number of units overlaid."""
    from ..znicz.optimizers import param_of_slot
    n_lead = mesh.shape[lead_axis]
    gd_of = {gd.target: gd
             for gd in getattr(workflow, "gds", [])
             if getattr(gd, "target", None) is not None}
    overlaid = 0
    for unit in getattr(workflow, "forwards", []):
        stacked = getattr(unit, params_attr, None)
        if stacked is None:
            continue
        if getattr(unit, count_attr) % n_lead:
            continue
        for vec in stacked.values():
            cur = ()
            if isinstance(vec.sharding, NamedSharding):
                cur = tuple(vec.sharding.spec)
            axes = list(cur) + [None] * (len(vec.shape) - len(cur))
            axes[0] = lead_axis
            vec.sharding = NamedSharding(mesh, PartitionSpec(*axes))
        overlaid += 1
        gd = gd_of.get(unit)
        if gd is not None:
            for name, vec in gd.tstate.items():
                pname = param_of_slot(name) or name
                target = stacked.get(pname)
                if vec and target is not None and \
                        tuple(vec.shape) == tuple(target.shape):
                    vec.sharding = target.sharding
    return overlaid


def apply_dp_pp_tp_sharding(workflow, mesh, data_axis="data",
                            stage_axis="stage", model_axis="model"):
    """COMPOSED 3-axis layout: data × pipeline × tensor parallelism
    (ISSUE 12).  :func:`apply_dp_tp_sharding` lays the Megatron
    column/row pairing on every transformer unit — the pipelined
    stack's plan deliberately leaves dim 0 alone — then the stage
    axis overlays the stacks' leading dim, so each device stores
    1/(pp·tp) of the stack.  Inside the step the stack runs its
    ppermute schedule over ``stage_axis`` via shard_map whose
    in_specs name only the stage axis: XLA re-gathers the model-dim
    shards at pipeline entry (storage stays sharded; the embedding/
    LM-head compute outside the stack is genuinely tensor-parallel).
    ``dryrun_multichip`` self-verifies the composition against the
    1-device step."""
    apply_dp_tp_sharding(workflow, mesh, data_axis=data_axis,
                         model_axis=model_axis)
    n = _overlay_leading_axis(workflow, mesh, "stage_params",
                              "n_blocks", stage_axis)
    if n == 0:
        workflow.warning(
            "apply_dp_pp_tp_sharding: no pipelined stack's n_blocks "
            "divides the stage axis (%d) — the workflow runs dp×tp "
            "only" % mesh.shape[stage_axis])
    workflow._parallel_style_ = ("dp_pp_tp", data_axis, stage_axis,
                                 model_axis)
    return workflow


def apply_dp_ep_tp_sharding(workflow, mesh, data_axis="data",
                            expert_axis="expert",
                            model_axis="model"):
    """COMPOSED 3-axis layout: data × expert × tensor parallelism
    (ISSUE 12).  The Megatron trailing column/row pairing on each
    expert's matrices comes from :func:`apply_dp_tp_sharding` (the
    MoE plan shards w1/w2's TRAILING dims, leaving the expert dim
    alone); the expert axis then overlays dim 0.  The GShard
    dispatch/combine einsums are plain GSPMD — no shard_map — so
    both axes propagate: XLA lowers the dispatch to all-to-alls over
    the expert axis while each expert's FFN einsums keep the hidden
    dim sharded over the model axis.  ``dryrun_multichip``
    self-verifies the composition against the 1-device step."""
    apply_dp_tp_sharding(workflow, mesh, data_axis=data_axis,
                         model_axis=model_axis)
    n = _overlay_leading_axis(workflow, mesh, "expert_params",
                              "n_experts", expert_axis)
    if n == 0:
        workflow.warning(
            "apply_dp_ep_tp_sharding: no MoE block's n_experts "
            "divides the expert axis (%d) — the workflow runs dp×tp "
            "only" % mesh.shape[expert_axis])
    workflow._parallel_style_ = ("dp_ep_tp", data_axis, expert_axis,
                                 model_axis)
    return workflow


def apply_zero_sharding(workflow, mesh=None, data_axis="data",
                        level=1):
    """ZeRO-1/2 optimizer-state sharding over the ``data`` axis —
    call AFTER one of the ``apply_*_sharding`` appliers (it composes
    with all of them).

    * **Level 1** re-annotates every GD unit's optimizer slot whose
      leading dimension divides the data-axis size: dim 0 gains the
      ``data`` axis ON TOP of whatever model/expert/stage axes the
      style applier put on the other dims, so each dp rank
      persistently stores 1/dp of the optimizer state in HBM.  XLA's
      sharding propagation then computes the slot update shard-local
      and all-gathers the parameter delta — the ZeRO-1 dataflow
      (update your shard, all-gather params) expressed as GSPMD
      annotations instead of hand-written ``shard_map``/
      ``psum_scatter`` collectives (same collectives on the wire,
      zero bespoke step code, and it composes with dp×tp for free).
    * **Level 2** additionally records a sharding constraint for each
      slot-backed gradient (consumed by ``StepCompiler``'s
      ``apply_updates``), so the gradient all-reduce over ``data``
      lowers to a reduce-scatter feeding the sharded update instead
      of a full all-reduce followed by a slice — the ZeRO-2
      grad-shard variant.

    Slots whose geometry does not divide the axis — or whose dim 0
    is already owned by an expert/stage axis — stay as the style
    applier left them (correct, merely not ZeRO-sharded); scalar
    slots (Adam's step counters) always stay replicated.

    Numerics: allclose, not bit-identical — collective reduction
    orders move; ``dryrun_multichip`` self-verifies sharded ==
    1-device under the usual per-precision tolerances.

    Snapshots are UNAFFECTED in shape: Vector pickling gathers the
    full host value regardless of layout, so a ZeRO snapshot restores
    at any dp (re-shard on resume = re-run the appliers + this).
    """
    from ..znicz.nn_units import GradientDescentBase
    from ..znicz.optimizers import param_of_slot
    if mesh is None:
        mesh = getattr(workflow, "mesh", None)
    if mesh is None or data_axis not in mesh.shape:
        raise ValueError(
            "apply_zero_sharding needs a mesh carrying axis %r — "
            "apply a dp/dp×tp/... sharding first" % data_axis)
    dp = mesh.shape[data_axis]
    grad_specs = {}
    compiler = workflow.compiler
    compiler.analyze()
    sharded = 0
    for gd in [u for u in workflow.units
               if isinstance(u, GradientDescentBase)]:
        target = getattr(gd, "target", None)
        for name, vec in gd.tstate.items():
            if not vec or not vec.shape or len(vec.shape) < 1:
                continue  # scalar slots stay replicated
            if dp <= 1 or vec.shape[0] % dp:
                continue
            cur = ()
            if isinstance(vec.sharding, NamedSharding):
                cur = tuple(vec.sharding.spec)
            axes = list(cur) + [None] * (len(vec.shape) - len(cur))
            if axes[0] is not None:
                continue  # dim 0 already owned (expert/stage axis)
            axes[0] = data_axis
            spec = NamedSharding(mesh, PartitionSpec(*axes))
            vec.sharding = spec
            sharded += 1
            if level >= 2 and target is not None:
                pattr = param_of_slot(name)
                pvec = target.trainables.get(pattr) if pattr else None
                if pvec is not None and \
                        tuple(pvec.shape) == tuple(vec.shape):
                    grad_specs[compiler.param_name(target, pattr)] = \
                        spec
    if sharded == 0:
        workflow.warning(
            "apply_zero_sharding: no optimizer slot's leading "
            "dimension divides the data axis (%d) — optimizer state "
            "stays replicated" % dp)
    workflow._zero_grad_shardings_ = grad_specs
    # The recorded dp feeds the optimizer.shard_frac gauge: when
    # nothing sharded, each rank still stores the FULL state — the
    # gauge must say 1.0, not 1/dp (level is kept so rebuild_mesh
    # retries ZeRO over whatever mesh the survivors form).
    workflow._zero_ = (level, dp if sharded else 1, data_axis)
    # The compiled step (and its captured grad constraints)
    # specialized on the old layout.
    compiler._compiled = None
    return workflow


#: Style name → the sharding applier re-run over the shrunk mesh.
#: (2-axis styles all carry (name, data_axis, other_axis); the 3-axis
#: dp_tp_sp carries (name, data, model, seq).)
def _style_appliers():
    return {
        "dp_tp": apply_dp_tp_sharding,
        "dp_sp": apply_dp_sp_sharding,
        "dp_ep": apply_dp_ep_sharding,
        "dp_pp": apply_dp_pp_sharding,
    }


def _seq_axis_fits(workflow, n_seq):
    """Whether every sequence-parallel unit can run over an n_seq-wide
    seq axis: the shard_map specs need S % n_seq == 0, and Ulysses
    additionally needs heads % n_seq == 0.  Unlike tp/ep/pp (whose
    appliers degrade to replicated), an sp unit runs its shard_map
    unconditionally once the mesh carries the axis — an unvalidated
    rebuild would crash the next step instead of degrading."""
    for u in getattr(workflow, "forwards", []):
        if not getattr(u, "seq_axis", None):
            continue
        shape = getattr(getattr(u, "input", None), "shape", None)
        if shape and len(shape) >= 2 and shape[1] % n_seq:
            return False
        if getattr(u, "sp_mode", None) == "ulysses" and \
                getattr(u, "n_heads", 0) % n_seq:
            return False
    return True


def _rebuild_styled_mesh(workflow, surviving_devices, n, style):
    """Re-forms the workflow's non-DP layout over the survivors when
    divisibility allows; returns the new mesh or None (→ dp
    fallback).  On a shrink, every style preserves the OLD data-axis
    size first (so the model/seq/expert/stage axis — which layer
    geometry was validated against — shrinks as little as possible),
    then tries data=2; the non-data axis must keep >= 2 devices or
    the style is meaningless.  On GROWTH the preference inverts: the
    non-data axis keeps its exact old size and the data axis widens.
    A 3-axis style that no longer divides falls to a 2-axis partial
    fit (keep tp, then keep sp) before the DP cliff.

    Host-syncing sharded params during the re-place gathers across
    the OLD device set — fine while the runtime still serves reads,
    the documented precondition."""
    old_mesh = getattr(workflow, "mesh", None)
    if style[0] in _style_appliers() and len(style) == 3:
        name, data_axis, other_axis = style
        old_data = (old_mesh.shape.get(data_axis)
                    if old_mesh is not None else None)
        old_other = (old_mesh.shape.get(other_axis)
                     if old_mesh is not None else None)
        candidates = [old_data, 2]
        if old_data and old_other and n > old_data * old_other \
                and n % old_other == 0:
            # GROWTH: joiners widen the data axis while the non-data
            # axis keeps its exact old size — layer geometry was
            # validated against that size, and the new capacity
            # belongs to batch throughput, not to an unvalidated
            # re-split of the model/seq/expert/stage plane.
            candidates.insert(0, n // old_other)
        seen = set()
        for candidate in candidates:
            if not candidate or candidate in seen:
                continue
            seen.add(candidate)
            if n % candidate == 0 and n // candidate >= 2:
                if name == "dp_sp" and \
                        not _seq_axis_fits(workflow, n // candidate):
                    continue
                mesh = make_mesh(surviving_devices,
                                 {data_axis: candidate,
                                  other_axis: n // candidate})
                kwargs = {"data_axis": data_axis,
                          {"dp_tp": "model_axis",
                           "dp_sp": "seq_axis",
                           "dp_ep": "expert_axis",
                           "dp_pp": "stage_axis"}[name]: other_axis}
                _style_appliers()[name](workflow, mesh, **kwargs)
                return mesh
        return None
    if style[0] == "dp_tp_sp" and len(style) == 4:
        # Exact fit first: model and seq sizes preserved (both were
        # validated against layer geometry / sequence length), the
        # data axis alone absorbing the change.
        _, data_axis, model_axis, seq_axis = style
        if old_mesh is None:
            return None
        m = old_mesh.shape.get(model_axis)
        s = old_mesh.shape.get(seq_axis)
        if not m or not s:
            return None
        if n % (m * s) == 0 and n // (m * s) >= 1 and \
                _seq_axis_fits(workflow, s):
            mesh = make_mesh(surviving_devices,
                             {data_axis: n // (m * s),
                              model_axis: m, seq_axis: s})
            apply_dp_tp_sp_sharding(workflow, mesh,
                                    data_axis=data_axis,
                                    model_axis=model_axis,
                                    seq_axis=seq_axis)
            return mesh
        # Partial fit: the survivors cannot hold the exact m×s plane
        # — shrink ONE axis at a time before the DP cliff wipes both.
        # Keep the tensor axis (drop sequence parallelism) first:
        # tp shards weights, so losing it costs per-chip memory,
        # while losing sp only costs long-sequence activation
        # headroom.  Then keep the seq axis (drop tp).  The applier
        # records the surviving 2-axis style, so later rebuilds walk
        # from what actually survived.
        if m >= 2 and n % m == 0 and n // m >= 1:
            mesh = make_mesh(surviving_devices,
                             {data_axis: n // m, model_axis: m})
            apply_dp_tp_sharding(workflow, mesh,
                                 data_axis=data_axis,
                                 model_axis=model_axis)
            return mesh
        if s >= 2 and n % s == 0 and n // s >= 1 and \
                _seq_axis_fits(workflow, s):
            mesh = make_mesh(surviving_devices,
                             {data_axis: n // s, seq_axis: s})
            apply_dp_sp_sharding(workflow, mesh,
                                 data_axis=data_axis,
                                 seq_axis=seq_axis)
            return mesh
        return None
    return None


def rebuild_mesh(workflow, surviving_devices=None, axis="data",
                 requeue_in_flight=True, epoch=None):
    """Elastic membership change at mesh granularity — SHRINK (the
    drop_slave+requeue equivalent of the reference's server.py:315-338)
    and GROWTH alike: re-form the mesh over the new device set,
    re-place every step tensor (the Vector sharding setter host-syncs
    and frees old buffers when its sharding changes), requeue
    whatever the loader had in flight — the whole block in block
    mode — and force the step to recompile for the new topology.

    ``epoch`` stamps the workflow with the caller's membership epoch
    (the server's ``FleetScheduler`` epoch for a fleet-driven
    rebuild); without one a local monotonic count advances, so every
    rebuild is a numbered event either way.  The stamp is published
    as the ``membership.epoch`` gauge and counted under
    ``membership.rebuilds`` / ``membership.grow`` /
    ``membership.shrink``.

    ``requeue_in_flight`` gives AT-LEAST-ONCE semantics: without a
    commit marker there is no telling whether the interrupted
    dispatch landed, so its minibatches re-train (pass False when the
    caller knows the last step committed — e.g. loss detected between
    epochs).  The in-flight record clears either way, so repeated
    rebuilds (progressive loss 8→4→2) never double-queue.

    Precondition: the jax runtime is still serving reads — parameter
    buffers are replicated, and the host-sync path reads a LOCAL
    addressable shard for replicated arrays (memory._host_sync), so a
    healthy chip sources them; a lost chip only loses its batch
    shard, which the failed-minibatch queue re-serves.  When the
    runtime itself died with the chip (the common real-hardware
    failure), recovery is snapshot-resume (snapshotter.py), not this
    in-process path.
    """
    import jax
    from ..memory import host_resharding
    if surviving_devices is None:
        surviving_devices = jax.devices()
    n = len(surviving_devices)
    prior = getattr(workflow, "mesh", None)
    old_n = int(prior.devices.size) if prior is not None else None
    style = getattr(workflow, "_parallel_style_", None) or \
        ("dp", axis)
    # Recovery context: every re-placement must round-trip through
    # the host (reads a healthy replica shard) — a device-to-device
    # reshard sourced from the departed chips could fail
    # asynchronously past any except clause.
    with host_resharding():
        mesh = _rebuild_styled_mesh(workflow, surviving_devices, n,
                                    style)
        if mesh is None:
            if style[0] != "dp":
                workflow.warning(
                    "rebuild_mesh: %d survivors cannot hold the %s "
                    "layout — falling back to data parallelism"
                    % (n, style[0]))
            mesh = make_mesh(surviving_devices, {axis: n})
            apply_dp_sharding(workflow, mesh, axis=axis)
        # ZeRO re-applies over the shrunk mesh (the style appliers
        # just reset every slot to its non-ZeRO layout); the data
        # axis may now be a different size — slots re-shard 1/dp'.
        zero = getattr(workflow, "_zero_", None)
        if zero:
            level, _old_dp, zaxis = zero
            apply_zero_sharding(
                workflow, mesh,
                data_axis=zaxis if zaxis in mesh.shape else axis,
                level=level)
    # The jitted step specialized on the old device set/shardings.
    workflow.compiler._compiled = False
    loader = getattr(workflow, "loader", None)
    if loader is not None:
        in_flight = list(getattr(loader, "_in_flight_", []))
        loader._in_flight_ = []
        if requeue_in_flight:
            loader.failed_minibatches.extend(in_flight)
        # A streamed loader's prefetched block holds device arrays
        # placed on the PRE-rebuild device set (and its indices were
        # just requeued above) — drop it, never dispatch it.
        invalidate = getattr(loader, "invalidate_staged", None)
        if invalidate is not None:
            invalidate()
    # Membership-epoch stamp: this rebuild is a numbered event.  The
    # gauge is what the heartbeat "fleet" row, web_status, and
    # /metrics surface; the counters say which direction the fleet
    # walked.
    from .. import resilience
    from ..observability import metrics
    workflow._membership_epoch_ = int(epoch) if epoch is not None \
        else getattr(workflow, "_membership_epoch_", 0) + 1
    resilience.stats.incr("membership.rebuilds")
    if old_n is not None and n > old_n:
        resilience.stats.incr("membership.grow")
    elif old_n is not None and n < old_n:
        resilience.stats.incr("membership.shrink")
    metrics.registry.gauge("membership.epoch").set(
        workflow._membership_epoch_)
    return mesh
