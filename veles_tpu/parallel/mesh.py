"""Mesh construction and data-parallel sharding.

This replaces the reference's master–slave data-parallel engine
(reference: veles/server.py, veles/client.py, veles/distributable.py —
minibatch indices sharded to slaves over ZeroMQ, weights shipped in job
pickles, gradients aggregated in ``apply_data_from_slave``) with the
TPU-native formulation:

  * the device mesh (`jax.sharding.Mesh`) spans all local chips (and,
    multi-host, all processes' chips via ``jax.distributed``);
  * the LOADER still thinks in minibatch indices — exactly like the
    reference coordinator (loader/base.py:629-661) — but instead of
    mailing index lists to worker processes, the index array is laid
    out along the mesh's ``data`` axis, so each chip gathers and
    processes its shard of the global minibatch;
  * parameters are replicated; ``jax.grad`` of the mean loss over a
    sharded batch makes XLA insert the gradient all-reduce (psum) over
    ICI — the explicit ``apply_data_from_slave`` aggregation loop
    disappears into the compiled step.

Elasticity note: the reference drops slaves and requeues their
minibatches (server.py:315-338).  SPMD equivalents operate at mesh
granularity: on chip loss the launcher rebuilds the mesh and the loader
requeues in-flight indices (the failed-minibatch queue survives as-is).
"""

from jax.sharding import Mesh, NamedSharding, PartitionSpec


def make_mesh(devices=None, axes=None):
    """Builds a Mesh; ``axes`` maps name → size with -1 = remaining."""
    import jax
    import numpy as np
    if devices is None:
        devices = jax.devices()
    if axes is None:
        axes = {"data": len(devices)}
    names = list(axes)
    sizes = [axes[n] for n in names]
    if -1 in sizes:
        known = 1
        for s in sizes:
            if s != -1:
                known *= s
        sizes[sizes.index(-1)] = len(devices) // known
    count = 1
    for s in sizes:
        count *= s
    return Mesh(np.array(devices[:count]).reshape(sizes), names)


def apply_dp_sharding(workflow, mesh, axis="data"):
    """Marks the workflow's step tensors for data parallelism:
    per-tick batch vectors are sharded along ``axis`` (dim 0), params /
    optimizer state / dataset originals are replicated.

    After this, the SAME compiled step runs 1-chip or N-chip — XLA
    inserts the gradient psum over ICI because the loss is a mean over
    a sharded batch with replicated params.
    """
    compiler = workflow.compiler
    compiler.analyze()
    replicated = NamedSharding(mesh, PartitionSpec())
    sharded = NamedSharding(mesh, PartitionSpec(axis))
    n = mesh.shape[axis]
    for vec in compiler.batch_vectors:
        shape = vec.shape
        if shape and len(shape) >= 1 and shape[0] % n == 0:
            vec.sharding = sharded
        else:
            vec.sharding = replicated
    for vec in compiler._collect("params").values():
        vec.sharding = replicated
    for vec in compiler._collect("state").values():
        vec.sharding = replicated
    for vec in compiler.const_vectors:
        vec.sharding = replicated
    # Activations derive shardings from inputs; persisted outputs too.
    workflow.mesh = mesh
    return workflow
