"""Unified observability: span tracing, typed metrics, device/MFU
attribution (docs/observability.md).

Three legs over one substrate:

* :mod:`.tracing` — ``span("net.send")`` context managers feeding a
  bounded ring collector, cross-node clock alignment, and Chrome
  trace-event export (``--trace-out``);
* :mod:`.metrics` — counters/gauges/histograms in a process
  registry with Prometheus text exposition (``GET /metrics`` on
  web_status and the serving ModelServer); ``resilience.stats`` is
  a thin shim over it, so every PR-1 counter is scrapeable;
* :mod:`.attribution` — ``block_until_ready`` device-time deltas +
  ``cost_analysis()`` FLOPs around the fused step → a live MFU
  gauge (heartbeat ``perf`` section, web_status row), and the
  ``--xprof DIR`` capture window.

Tracing defaults OFF and compiles to a near-zero no-op; metrics are
passive counters; attribution adds one host sync per dispatched
block (``root.common.observability.attribution=False`` disables).
"""

from . import metrics, tracing, attribution  # noqa: F401


def init_parser(parser):
    """Observability flags, aggregated into the velescli parser."""
    parser.add_argument(
        "--trace-out", default=None, metavar="FILE",
        help="enable span tracing and write a Chrome trace-event "
             "JSON (chrome://tracing / Perfetto) here at exit; "
             "worker spans ride the job protocol back to the "
             "master and land on one aligned timeline")
    parser.add_argument(
        "--trace-ring", type=int, default=None, metavar="N",
        help="bounded span-collector size (default 16384 spans; "
             "oldest dropped first)")
    parser.add_argument(
        "--xprof", default=None, metavar="DIR",
        help="open a jax.profiler capture window around the next "
             "--xprof-steps fused step dispatches and write the "
             "trace into DIR (inspect with tensorboard/xprof)")
    parser.add_argument(
        "--xprof-steps", type=int, default=4, metavar="N",
        help="fused dispatches inside the --xprof capture window "
             "(default 4)")
