"""Device-time and MFU attribution for the fused step.

``StepCompiler`` dispatches are asynchronous — ``time.perf_counter``
around the call measures Python dispatch, not the chip.  This module
closes the gap: after each dispatch the compiler hands a small output
leaf to :func:`end_step`, which ``block_until_ready``s it (waiting,
not transferring — all outputs of one XLA computation complete
together) and records the true wall→ready delta.  Combined with a
``cost_analysis()``-derived FLOP estimate per compiled step (one
extra trace per geometry, no extra compile — ``Lowered
.cost_analysis()`` runs XLA's HLO cost model), that yields a **live
MFU gauge** published into the process metrics registry, the
launcher heartbeat's ``perf`` section, and the web_status dashboard.

Also owns the ``--xprof DIR`` capture window: a ``jax.profiler``
trace opened at the first fused dispatch and closed after N of them
— the "give me a profile of exactly the steady-state step" operator
workflow, without bracketing the whole run like ``--profile`` does.

Knobs (``root.common.observability``):

* ``attribution`` (default True) — the per-dispatch sync costs one
  host round-trip per *block* of ticks; flip off for maximally
  async dispatch chains;
* ``peak_tflops`` — the MFU denominator; defaults from the device
  kind table below (v5e bf16 = 197), None on unknown hardware
  (device time still publishes; the MFU gauge just stays silent).

Everything here is wall-clock accounting around an unchanged
computation: bits on device are identical with attribution on, off,
or absent.
"""

import logging
import threading
import time

#: device_kind substring → peak dense bf16 TFLOP/s (the MFU
#: denominator).  Substring match: jax reports kinds like
#: "TPU v5 lite" / "TPU v5e".
DEVICE_PEAK_TFLOPS = (
    ("v5 lite", 197.0),
    ("v5e", 197.0),
    ("v5p", 459.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)

#: EWMA smoothing for the live gauges (per dispatch).
EWMA_ALPHA = 0.25

_lock = threading.Lock()
_state = {
    "device_ms": None,     # EWMA ms per dispatch
    "mfu": None,           # EWMA model-flop utilization
    "flops": None,         # last per-dispatch FLOP estimate
    "dispatches": 0,
    "ticks": 0,
    "device_s_total": 0.0,
}
_xprof = {"dir": None, "steps": 0, "done": 0, "started": False}
#: Optimizer observability (StepCompiler.compile publishes once per
#: compile): the configured kind(s), total slot bytes, and the ZeRO
#: shard fraction each dp rank persistently stores (1.0 = replicated).
_optimizer = {"kind": None, "state_bytes": None, "shard_frac": None}
#: MoE router observability (DecisionGD publishes per class-epoch
#: from the blocks' moe_acc accumulators): mean load-balance aux per
#: tick and the worst expert-load share (1/E = balanced, 1.0 =
#: collapsed).
_moe = {"aux_loss": None, "max_load_frac": None, "n_experts": None}
_timer = time.perf_counter  # injectable for tests
#: configured-peak-value -> resolved FLOP/s (the device probe and
#: config walk are constant per process; never pay them per
#: dispatch).
_peak_cache = {}


def _config(name, default):
    from ..config import root, get as config_get
    return config_get(getattr(root.common.observability, name),
                      default)


def enabled():
    """Device-time attribution on?  (Default True — one host sync
    per dispatched BLOCK of ticks.)"""
    return bool(_config("attribution", True))


def reset():
    """Clears accumulated attribution state AND this module's
    ``device.*`` series in the process registry (test isolation) —
    attribution owns its gauges; the resilience shim's reset only
    touches counters created through it."""
    with _lock:
        _state.update(device_ms=None, mfu=None, flops=None,
                      dispatches=0, ticks=0, device_s_total=0.0)
        _optimizer.update(kind=None, state_bytes=None,
                          shard_frac=None)
        _moe.update(aux_loss=None, max_load_frac=None,
                    n_experts=None)
    _xprof.update(dir=None, steps=0, done=0, started=False)
    _peak_cache.clear()
    from . import metrics
    metrics.registry.remove_prefix("device.")
    metrics.registry.remove_prefix("optimizer.")
    metrics.registry.remove_prefix("moe.")


def peak_flops():
    """The MFU denominator in FLOP/s, or None when unknown.
    Memoized per configured value — this sits on the per-dispatch
    path and neither the config nor the device set changes mid-run."""
    configured = _config("peak_tflops", None)
    if configured in _peak_cache:
        return _peak_cache[configured]
    if configured:
        peak = float(configured) * 1e12
    else:
        peak = None
        try:
            import jax
            kind = str(getattr(jax.devices()[0], "device_kind",
                               "")).lower()
        except Exception as e:
            logging.getLogger("attribution").debug(
                "device-kind probe failed: %s", e)
            kind = ""
        for sub, tflops in DEVICE_PEAK_TFLOPS:
            if sub in kind:
                peak = tflops * 1e12
                break
    _peak_cache[configured] = peak
    return peak


# -- xprof capture window --------------------------------------------------

def configure_xprof(directory, steps=4):
    """Arms the capture window: a ``jax.profiler`` trace spanning the
    next ``steps`` fused dispatches (opened lazily at the first
    one)."""
    _xprof.update(dir=directory, steps=int(steps), done=0,
                  started=False)


def _xprof_step_begin():
    if _xprof["dir"] is None or _xprof["started"] \
            or _xprof["done"] >= _xprof["steps"]:
        return
    try:
        import jax
        jax.profiler.start_trace(_xprof["dir"])
        _xprof["started"] = True
    except Exception:
        # The operator explicitly asked for a capture (--xprof):
        # a disarm must be LOUD, not a mystery empty directory.
        logging.getLogger("attribution").exception(
            "xprof capture could not start — disarming")
        _xprof["dir"] = None  # unusable; disarm rather than retrying

def _xprof_step_end(leaf):
    if not _xprof["started"]:
        return
    _xprof["done"] += 1
    if _xprof["done"] < _xprof["steps"]:
        return
    _device_sync(leaf)
    try:
        import jax
        jax.profiler.stop_trace()
    except Exception as e:
        logging.getLogger("attribution").debug(
            "xprof stop_trace failed: %s", e)
    _xprof["started"] = False
    _xprof["dir"] = None


def _device_sync(leaf):
    """A TRUE device barrier on ``leaf``: fetch one scalar element
    derived from it.  NOT ``block_until_ready`` — through the axon
    TPU tunnel that call is a no-op (see bench.measure's sync note),
    which would collapse device_ms to Python dispatch time and blow
    the MFU gauge past 1.0.  A one-element ``device_get`` costs a
    scalar transfer and genuinely waits for the computation."""
    if leaf is None:
        return
    try:
        import jax
        import numpy
        scalar = leaf
        if getattr(leaf, "ndim", 0):
            scalar = leaf.ravel()[0]
        numpy.array(jax.device_get(scalar))
    except Exception as e:
        logging.getLogger("attribution").debug(
            "device barrier fetch failed: %s", e)


# -- per-dispatch hooks (called by StepCompiler) ---------------------------

class _StepTimer(object):
    __slots__ = ("t0", "ticks", "flops")

    def __init__(self, ticks, flops):
        self.t0 = _timer()
        self.ticks = ticks
        self.flops = flops


def begin_step(ticks=1, flops=None):
    """Called right before a fused dispatch.  Returns a timer token
    for :func:`end_step`, or None when nothing here is active."""
    _xprof_step_begin()
    if not enabled():
        return None
    return _StepTimer(ticks, flops)


def end_step(timer, leaf=None):
    """Called right after the dispatch returns.  Syncs on ``leaf``
    (when given) so the delta covers device execution, then folds the
    measurement into the live gauges."""
    _xprof_step_end(leaf)
    if timer is None:
        return None
    _device_sync(leaf)
    return record_step(_timer() - timer.t0, flops=timer.flops,
                       ticks=timer.ticks)


def record_step(device_seconds, flops=None, ticks=1):
    """Folds one measured dispatch into the attribution state and the
    metrics registry — separated from :func:`end_step` so tests can
    drive the MFU plumbing with a fake device timer."""
    from . import metrics
    device_seconds = max(float(device_seconds), 1e-9)
    mfu = None
    peak = peak_flops() if flops else None
    if flops and peak:
        mfu = float(flops) / device_seconds / peak
    with _lock:
        ms = device_seconds * 1e3
        prev = _state["device_ms"]
        _state["device_ms"] = ms if prev is None else \
            prev + EWMA_ALPHA * (ms - prev)
        if mfu is not None:
            prev = _state["mfu"]
            _state["mfu"] = mfu if prev is None else \
                prev + EWMA_ALPHA * (mfu - prev)
        if flops:
            _state["flops"] = float(flops)
        _state["dispatches"] += 1
        _state["ticks"] += int(ticks)
        _state["device_s_total"] += device_seconds
        snap = dict(_state)
    reg = metrics.registry
    reg.counter("device.dispatches").inc()
    reg.counter("device.ticks").inc(int(ticks))
    reg.gauge("device.step_ms").set(round(snap["device_ms"], 3))
    if snap["mfu"] is not None:
        reg.gauge("device.mfu").set(round(snap["mfu"], 4))
    if snap["flops"] is not None:
        reg.gauge("device.flops_per_dispatch").set(snap["flops"])
    return snap


def note_optimizer(kind, state_bytes, shard_frac=1.0):
    """Publishes the optimizer observability gauges (called by
    ``StepCompiler.compile`` once per compile): ``optimizer.
    state_bytes`` and ``optimizer.shard_frac`` in the process metrics
    registry, labeled with the optimizer kind, plus the heartbeat
    ``perf`` section fields (→ web_status perf row, /metrics)."""
    with _lock:
        _optimizer.update(kind=str(kind),
                          state_bytes=int(state_bytes),
                          shard_frac=float(shard_frac))
    from . import metrics
    reg = metrics.registry
    labels = {"kind": str(kind)}
    reg.gauge("optimizer.state_bytes",
              labels=labels).set(int(state_bytes))
    reg.gauge("optimizer.shard_frac",
              labels=labels).set(round(float(shard_frac), 6))


def note_moe(aux_loss, max_load_frac, n_experts, expert_shares=None):
    """Publishes the MoE router gauges (called by DecisionGD at
    epoch boundaries from the blocks' ``moe_acc`` rows):
    ``moe.aux_loss`` (mean load-balance aux per tick) and
    ``moe.expert_load`` (per-expert share, labeled by block and
    expert index) in the process metrics registry, plus the heartbeat
    ``perf`` section fields (→ web_status perf row, /metrics) — the
    live router-collapse signal."""
    with _lock:
        _moe.update(aux_loss=float(aux_loss),
                    max_load_frac=float(max_load_frac),
                    n_experts=int(n_experts))
    from . import metrics
    reg = metrics.registry
    reg.gauge("moe.aux_loss").set(round(float(aux_loss), 6))
    reg.gauge("moe.max_load_frac").set(
        round(float(max_load_frac), 6))
    for (block, idx), share in (expert_shares or {}).items():
        reg.gauge("moe.expert_load",
                  labels={"block": str(block),
                          "expert": str(idx)}).set(
            round(float(share), 6))


def moe_summary():
    """The last published MoE router stats, or None when no MoE
    epoch has completed."""
    with _lock:
        if _moe["aux_loss"] is None:
            return None
        return dict(_moe)


def optimizer_summary():
    """The last published optimizer stats, or None before the first
    compiled step."""
    with _lock:
        if _optimizer["kind"] is None:
            return None
        return dict(_optimizer)


def estimate_flops(jitted, *args):
    """Per-dispatch FLOP count from XLA's HLO cost analysis of the
    jitted step (``Lowered.cost_analysis()`` — a re-trace, NOT a
    recompile), or None when the backend/version can't say."""
    try:
        cost = jitted.lower(*args).cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception as e:
        logging.getLogger("attribution").debug(
            "HLO cost analysis unavailable: %s", e)
        return None


def perf_summary():
    """The heartbeat ``perf`` section: live device-time and MFU for
    this process's fused step, or None before the first measured
    dispatch."""
    with _lock:
        if not _state["dispatches"]:
            return None
        out = {
            "dispatches": _state["dispatches"],
            "ticks": _state["ticks"],
            "step_ms": round(_state["device_ms"], 3)
            if _state["device_ms"] is not None else None,
            "device_s_total": round(_state["device_s_total"], 3),
        }
        if _state["mfu"] is not None:
            out["mfu"] = round(_state["mfu"], 4)
        if _state["flops"] is not None:
            out["flops_per_dispatch"] = _state["flops"]
        if _optimizer["kind"] is not None:
            out["optimizer"] = _optimizer["kind"]
            out["optimizer_state_bytes"] = _optimizer["state_bytes"]
            out["optimizer_shard_frac"] = _optimizer["shard_frac"]
        if _moe["aux_loss"] is not None:
            out["moe_aux_loss"] = round(_moe["aux_loss"], 6)
            out["moe_max_load_frac"] = round(_moe["max_load_frac"],
                                             6)
    return out
