"""Typed metrics: counters, gauges, histograms behind one registry,
with Prometheus text exposition.

The platform grew three generations of ad-hoc accounting — the PR-1
flat counter registry (``resilience.stats``), the serving-private
``ServingStats`` windows, and one-off gauges riding heartbeats.  This
module is the single substrate underneath all of them:

* :class:`Counter` — monotonic event count (``net.bytes_sent``,
  ``chaos.net.drop``);
* :class:`Gauge` — point-in-time value, latest write wins
  (``device.mfu``, ``serving.kv_blocks_used``);
* :class:`Histogram` — cumulative-bucket distribution
  (``serving.latency_seconds``), the Prometheus shape;
* :class:`MetricsRegistry` — a thread-safe name→metric map with
  optional labels per series.

``resilience.stats`` remains the API every existing call site uses —
it is now a thin shim over the process-wide :data:`registry` (see
:class:`veles_tpu.resilience.ResilienceStats`), so every counter that
used to live in the flat dict automatically gains Prometheus
exposition at ``GET /metrics`` (web_status and the serving
ModelServer) without touching its increment site.

Exposition notes: metric names are sanitized to the Prometheus
charset (dots → underscores), counters gain the conventional
``_total`` suffix, label values are escaped per the text-format spec
(backslash, double-quote, newline), and every family is preceded by
its ``# TYPE`` line.
"""

import re
import threading

#: Default histogram bucket upper bounds (seconds-flavored: latency
#: is the dominant histogram user).  +Inf is implicit.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


class Counter(object):
    """Monotonic counter.  ``inc`` only — a counter that goes down is
    a gauge wearing the wrong hat."""

    kind = "counter"
    __slots__ = ("name", "labels", "_value", "_lock")

    def __init__(self, name, labels=None):
        self.name = name
        self.labels = dict(labels or {})
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value


class Gauge(object):
    """Point-in-time value; the latest ``set`` wins."""

    kind = "gauge"
    __slots__ = ("name", "labels", "_value", "_lock")

    def __init__(self, name, labels=None):
        self.name = name
        self.labels = dict(labels or {})
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value):
        with self._lock:
            self._value = value

    def add(self, delta):
        with self._lock:
            self._value += delta

    @property
    def value(self):
        with self._lock:
            return self._value


class Histogram(object):
    """Cumulative-bucket histogram (the Prometheus shape: ``le``
    buckets + ``_sum`` + ``_count``)."""

    kind = "histogram"
    __slots__ = ("name", "labels", "bounds", "_counts", "_sum",
                 "_count", "_lock")

    def __init__(self, name, labels=None, buckets=None):
        self.name = name
        self.labels = dict(labels or {})
        self.bounds = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self._counts = [0] * (len(self.bounds) + 1)  # + +Inf
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value):
        value = float(value)
        with self._lock:
            i = len(self.bounds)
            for j, bound in enumerate(self.bounds):
                if value <= bound:
                    i = j
                    break
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    @property
    def count(self):
        with self._lock:
            return self._count

    @property
    def sum(self):
        with self._lock:
            return self._sum

    def snapshot(self):
        """(cumulative_bucket_counts, sum, count) — cumulative per
        the exposition format."""
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        cumulative = []
        acc = 0
        for c in counts:
            acc += c
            cumulative.append(acc)
        return cumulative, s, total


def _series_key(name, labels):
    if not labels:
        return name
    return (name, tuple(sorted(labels.items())))


class MetricsRegistry(object):
    """Thread-safe name→metric map.  ``counter``/``gauge``/
    ``histogram`` create-or-return a series; reads go through the
    unlocked dict fast path (CPython dict reads are atomic) with a
    locked fallback for creation."""

    def __init__(self):
        self._metrics = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, labels, **kwargs):
        key = _series_key(name, labels)
        metric = self._metrics.get(key)
        if metric is not None:
            return metric
        with self._lock:
            metric = self._metrics.get(key)
            if metric is None:
                metric = cls(name, labels, **kwargs)
                self._metrics[key] = metric
            return metric

    def counter(self, name, labels=None):
        return self._get_or_create(Counter, name, labels)

    def gauge(self, name, labels=None):
        return self._get_or_create(Gauge, name, labels)

    def histogram(self, name, labels=None, buckets=None):
        return self._get_or_create(Histogram, name, labels,
                                   buckets=buckets)

    def peek(self, name, labels=None):
        """The existing series, or None — never creates (reads must
        not pollute the exposition with zero-valued series)."""
        return self._metrics.get(_series_key(name, labels))

    def metrics(self):
        with self._lock:
            return list(self._metrics.values())

    def counters_snapshot(self):
        """name → value over the counter series only (the flat-dict
        shape the PR-1 ``stats.snapshot()`` contract promises)."""
        return {m.name: m.value for m in self.metrics()
                if m.kind == "counter" and not m.labels}

    def reset(self, kind=None):
        """Drops series — all of them, or only those of one
        ``kind`` ("counter"/"gauge"/"histogram").  The resilience
        shim resets counters only, so a shared registry's gauges and
        histograms survive a counter reset."""
        with self._lock:
            if kind is None:
                self._metrics.clear()
            else:
                for key in [k for k, m in self._metrics.items()
                            if m.kind == kind]:
                    del self._metrics[key]

    def remove_prefix(self, prefix):
        """Drops every series whose name starts with ``prefix``
        (a subsystem clearing exactly its own state)."""
        with self._lock:
            for key in [k for k, m in self._metrics.items()
                        if m.name.startswith(prefix)]:
                del self._metrics[key]


#: The process-wide registry: ``resilience.stats`` shims onto it, the
#: attribution gauges live in it, and ``GET /metrics`` renders it.
registry = MetricsRegistry()


# -- Prometheus text exposition --------------------------------------------

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")

#: Content type of the text exposition format.
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def sanitize_name(name, prefix="veles"):
    """Dotted internal name → Prometheus metric name."""
    out = _NAME_RE.sub("_", str(name))
    if prefix and not out.startswith(prefix):
        out = "%s_%s" % (prefix, out)
    if out[0].isdigit():
        out = "_" + out
    return out


def escape_label_value(value):
    """Label-value escaping per the text format: backslash, newline,
    double-quote."""
    return (str(value).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _format_labels(labels):
    if not labels:
        return ""
    return "{%s}" % ",".join(
        '%s="%s"' % (_NAME_RE.sub("_", str(k)),
                     escape_label_value(v))
        for k, v in sorted(labels.items()))


def _format_value(v):
    if isinstance(v, float):
        if v != v:
            return "NaN"
        if v in (float("inf"), float("-inf")):
            return "+Inf" if v > 0 else "-Inf"
        return repr(v)
    return str(v)


def render_prometheus(registries, extra_samples=(), prefix="veles"):
    """Renders one or more registries (plus ``extra_samples``:
    an iterable of ``(name, labels_dict, value)`` exposed as gauges)
    into the Prometheus text exposition format.  Families are grouped
    so each emits exactly one ``# TYPE`` line."""
    families = {}  # exposed name -> (kind, [lines])

    def family(name, kind):
        fam = families.get(name)
        if fam is None:
            fam = families[name] = (kind, [])
        return fam[1]

    for reg in registries:
        for metric in reg.metrics():
            name = sanitize_name(metric.name, prefix)
            if metric.kind == "counter":
                family(name + "_total", "counter").append(
                    "%s_total%s %s" % (name,
                                       _format_labels(metric.labels),
                                       _format_value(metric.value)))
            elif metric.kind == "gauge":
                family(name, "gauge").append(
                    "%s%s %s" % (name, _format_labels(metric.labels),
                                 _format_value(metric.value)))
            else:  # histogram
                lines = family(name, "histogram")
                cumulative, total_sum, count = metric.snapshot()
                bounds = list(metric.bounds) + [float("inf")]
                for bound, c in zip(bounds, cumulative):
                    labels = dict(metric.labels)
                    labels["le"] = "+Inf" if bound == float("inf") \
                        else _format_value(float(bound))
                    lines.append("%s_bucket%s %d" % (
                        name, _format_labels(labels), c))
                lines.append("%s_sum%s %s" % (
                    name, _format_labels(metric.labels),
                    _format_value(total_sum)))
                lines.append("%s_count%s %d" % (
                    name, _format_labels(metric.labels), count))
    for name, labels, value in extra_samples:
        try:
            value = float(value)
        except (TypeError, ValueError):
            continue
        family(sanitize_name(name, prefix), "gauge").append(
            "%s%s %s" % (sanitize_name(name, prefix),
                         _format_labels(labels),
                         _format_value(value)))
    out = []
    for name in sorted(families):
        kind, lines = families[name]
        out.append("# TYPE %s %s" % (name, kind))
        out.extend(lines)
    return "\n".join(out) + "\n"
