"""Low-overhead cross-node span tracing with Chrome-trace export.

The operator question this answers: *where did this minibatch spend
its 40 ms* — master job generation, serialization, the wire, the
worker's fused step, or the fold back into the master's weights.
Each stage is a **span** (``with trace.span("net.send"): ...``); the
collected spans export as Chrome trace-event JSON (``--trace-out
trace.json``) loadable in ``chrome://tracing`` / Perfetto, with each
process's spans on its own track.

Design constraints, in order:

1. **Disabled is free.**  Tracing defaults OFF; ``span()`` then
   returns a shared no-op context manager — one module-bool check
   and zero allocation per call, so instrumentation can sit on the
   per-frame wire path.
2. **One aligned timeline.**  Worker spans ride the PR-4 job wire
   protocol back to the master (a handshake-negotiated optional
   field — old peers never see it), with the worker's clock offset
   estimated from request/reply timestamp pairs piggybacked on the
   job cycle (:class:`ClockSync`, the classic NTP half-RTT
   estimator, best-of = the minimum-RTT sample).  The master ingests
   the shifted spans, so the exported trace shows ``server.dispatch
   → net.serialize → net.send → worker.step → net.fold`` as one
   timeline across processes.
3. **Bounded memory.**  The collector is a ring (default 16384
   spans); a forgotten ``--trace-out`` on a week-long run costs a
   fixed few MB, never an OOM.

Timestamps are wall-clock (``time.time``) microseconds — the only
clock whose cross-process offset the sync can estimate — durations
are ``perf_counter`` deltas (immune to NTP slew mid-span).
"""

import itertools
import json
import os
import threading
import time
from collections import deque

#: Module-level enable flag — THE fast-path check.  Reads are
#: racy-by-design (a span started just before disable() still
#: records; fine).
_enabled = False

_DEFAULT_RING = 16384
_collector = deque(maxlen=_DEFAULT_RING)
_collector_lock = threading.Lock()
_ids = itertools.count(1)
_local = threading.local()


def enable(ring=None):
    """Turns span collection on (idempotent).  ``ring`` resizes the
    bounded collector."""
    global _enabled, _collector
    if ring is not None and ring != _collector.maxlen:
        with _collector_lock:
            _collector = deque(_collector, maxlen=int(ring))
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def enabled():
    return _enabled


def clear():
    """Drops collected spans (test isolation)."""
    with _collector_lock:
        _collector.clear()


def reset():
    """Disable + clear + restore the default ring size (test
    isolation)."""
    global _collector
    disable()
    with _collector_lock:
        _collector = deque(maxlen=_DEFAULT_RING)


def spans():
    """A snapshot list of the collected span dicts."""
    with _collector_lock:
        return list(_collector)


def _stack():
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    return stack


def _record(span_dict):
    capture_buf = getattr(_local, "capture", None)
    if capture_buf is not None:
        capture_buf.append(span_dict)
        return
    with _collector_lock:
        _collector.append(span_dict)


class _NullSpan(object):
    """The shared disabled-path span: enter/exit/set are no-ops."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        pass

    def finish(self):
        pass

    def cancel(self):
        pass


_NULL = _NullSpan()


class Span(object):
    """One live span.  Use via ``with span(...)`` or the manual
    :func:`begin`/:meth:`finish` pair (for spans that close in a
    later call on the same thread, like the master's per-job
    dispatch window)."""

    __slots__ = ("name", "attrs", "ts", "id", "parent", "trace_id",
                 "_t0", "_done")

    def __init__(self, name, attrs, detached=False):
        self.name = name
        self.attrs = attrs
        self.id = next(_ids)
        if detached:
            # A root span that neither reads nor joins the thread's
            # stack: long-lived windows (the master's per-job
            # dispatch) that may OVERLAP on one handler thread under
            # pipelined workers — stack nesting would chain sibling
            # windows into parent/child.  Children attach explicitly
            # (tracing.attach / the wire trace context).
            self.trace_id, self.parent = self.id, None
            self.ts = time.time() * 1e6
            self._t0 = time.perf_counter()
            self._done = False
            return
        stack = _stack()
        if stack:
            parent = stack[-1]
            self.parent = parent.id
            self.trace_id = parent.trace_id
        else:
            remote = getattr(_local, "remote", None)
            if remote is not None:
                self.trace_id, self.parent = remote
            else:
                self.trace_id, self.parent = self.id, None
        self.ts = time.time() * 1e6
        self._t0 = time.perf_counter()
        self._done = False
        stack.append(self)

    def set(self, **attrs):
        self.attrs.update(attrs)

    def cancel(self):
        """Discards the span unrecorded (a dispatch window opened
        for a job that never materialized)."""
        self._done = True
        stack = _stack()
        if self in stack:
            stack.remove(self)

    def finish(self):
        if self._done:
            return
        self._done = True
        dur = (time.perf_counter() - self._t0) * 1e6
        stack = _stack()
        if self in stack:
            stack.remove(self)
        _record({
            "name": self.name, "ts": self.ts, "dur": dur,
            "id": self.id, "parent": self.parent,
            "trace_id": self.trace_id,
            "tid": threading.get_ident() & 0xFFFFFFFF,
            "attrs": self.attrs or None,
        })

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.finish()
        return False


def span(name, **attrs):
    """The tracing entry point: a context manager recording one span.
    Near-free when tracing is disabled."""
    if not _enabled:
        return _NULL
    return Span(name, attrs)


def begin(name, detached=False, **attrs):
    """Manually-closed span (pair with ``span.finish()``); returns
    the no-op singleton when disabled, so callers need no branch.
    ``detached=True`` makes it a stack-free root window (see
    :class:`Span`)."""
    if not _enabled:
        return _NULL
    return Span(name, attrs, detached=detached)


def current():
    """(trace_id, span_id) of the innermost active span on this
    thread — the context to propagate across the wire — or
    (None, None)."""
    stack = getattr(_local, "stack", None)
    if stack:
        top = stack[-1]
        return top.trace_id, top.id
    remote = getattr(_local, "remote", None)
    if remote is not None:
        return remote
    return None, None


class capture(object):
    """Context manager diverting this THREAD's finishing spans into a
    private list (yielded) instead of the global collector — how a
    worker gathers exactly its job's spans for shipping, even when
    master and worker share a process (loopback tests)."""

    def __init__(self):
        self._prev = None
        self.buf = []

    def __enter__(self):
        self._prev = getattr(_local, "capture", None)
        _local.capture = self.buf
        return self.buf

    def __exit__(self, *exc):
        _local.capture = self._prev
        return False


class attach(object):
    """Adopts a remote parent context: spans opened on this thread
    (with an empty local stack) become children of the remote span,
    sharing its trace id — how a worker's ``worker.step`` nests under
    the master's ``server.dispatch``."""

    def __init__(self, trace_id, parent_id):
        self._ctx = (trace_id, parent_id)
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_local, "remote", None)
        _local.remote = self._ctx
        return self

    def __exit__(self, *exc):
        _local.remote = self._prev
        return False


def shift(span_dicts, offset_seconds):
    """Spans re-timestamped by ``offset_seconds`` (worker clock →
    master clock; ``offset = master - worker``)."""
    delta = offset_seconds * 1e6
    return [dict(d, ts=d["ts"] + delta) for d in span_dicts]


def ingest(span_dicts, proc=None):
    """Folds remote (already clock-shifted) spans into the local
    collector, tagged with the originating process label."""
    for d in span_dicts:
        if not isinstance(d, dict) or "name" not in d:
            continue
        d = dict(d)
        if proc is not None:
            d["proc"] = proc
        _record(d)


# -- clock alignment -------------------------------------------------------

class ClockSync(object):
    """Remote-clock offset estimation from request/reply timestamp
    pairs (piggybacked on the job protocol): for an exchange sent at
    local ``t0``, answered with remote timestamp ``tr``, received at
    local ``t1``, the midpoint estimator gives ``offset ≈ tr -
    (t0+t1)/2`` with error bounded by half the exchange's RTT — so
    the MINIMUM-RTT sample wins (NTP's core trick)."""

    __slots__ = ("offset", "rtt", "samples")

    def __init__(self):
        self.offset = 0.0
        self.rtt = float("inf")
        self.samples = 0

    def sample(self, local_send, remote_ts, local_recv):
        rtt = local_recv - local_send
        if rtt < 0:
            return  # clock stepped mid-exchange; discard
        self.samples += 1
        if rtt <= self.rtt:
            self.rtt = rtt
            self.offset = remote_ts - (local_send + local_recv) / 2.0

    def to_remote(self, local_ts):
        return local_ts + self.offset

    def state(self):
        return {"offset": self.offset, "rtt": self.rtt,
                "samples": self.samples}


# -- export ----------------------------------------------------------------

def chrome_trace_events(span_dicts=None, default_proc=None):
    """Builds the Chrome trace-event list: one complete ("ph":"X")
    event per span plus process_name metadata events, pid-numbered
    per distinct process label."""
    if span_dicts is None:
        span_dicts = spans()
    if default_proc is None:
        default_proc = "master/%d" % os.getpid()
    pids = {}
    events = []
    for d in span_dicts:
        proc = d.get("proc") or default_proc
        pid = pids.get(proc)
        if pid is None:
            pid = pids[proc] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name",
                           "pid": pid, "tid": 0,
                           "args": {"name": proc}})
        args = dict(d.get("attrs") or {})
        args["span_id"] = d.get("id")
        if d.get("parent") is not None:
            args["parent_id"] = d["parent"]
        if d.get("trace_id") is not None:
            args["trace_id"] = d["trace_id"]
        events.append({
            "ph": "X", "cat": "veles",
            "name": d["name"],
            "ts": d["ts"], "dur": d.get("dur", 0.0),
            "pid": pid, "tid": d.get("tid", 0),
            "args": args,
        })
    return events


def export_chrome_trace(path=None, span_dicts=None,
                        default_proc=None):
    """Writes (and returns) the Chrome trace JSON object
    ``{"traceEvents": [...]}``.  ``path=None`` only returns it."""
    obj = {"traceEvents": chrome_trace_events(span_dicts,
                                              default_proc),
           "displayTimeUnit": "ms"}
    if path:
        with open(path, "w") as fout:
            json.dump(obj, fout)
    return obj
