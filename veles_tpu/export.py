"""Trained-workflow export: a Python-independent inference artifact.

Capability parity with the reference export + libVeles loader
(reference: libVeles/src/workflow_loader.cc:46-131 — extract archive,
parse unit table, build an executable chain; libVeles/inc/veles/unit.h:41
— ``Unit::Execute`` forward over float buffers): a trained
:class:`~veles_tpu.accelerated_units.AcceleratedWorkflow`'s forward
chain is serialized to a versioned tar.gz holding

* ``manifest.json`` — format version, unit table (type MAPPING +
  numeric config), input/output specs, provenance;
* ``weights.npz`` — all parameters as named float32 arrays;
* ``model.bin`` — the same topology+weights in a flat binary layout
  the native C++ runtime (``native/veles_infer.cc``) parses without
  Python, JSON, or zlib.

:class:`ExportedModel` re-executes the chain from the artifact alone —
``forward()`` builds a jitted jax chain (serving path, TPU-capable),
``forward_numpy()`` is a dependency-free reference used to validate
the native runtime.
"""

import collections
import hashlib
import io
import json
import struct
import tarfile
import threading
import time

import numpy

from .distributable import SniffedLock
from .error import Bug
from .json_encoders import dumps_json

FORMAT_NAME = "veles-tpu-model"
FORMAT_VERSION = 1
MAGIC = b"VTPM"

#: Unit types the artifact format understands, with their exportable
#: numeric config keys.
EXPORTABLE = {
    "all2all": (), "all2all_tanh": (), "all2all_relu": (),
    "all2all_str": (), "all2all_sigmoid": (), "softmax": (),
    "conv": ("kx", "ky", "n_kernels"),
    "conv_tanh": ("kx", "ky", "n_kernels"),
    "conv_relu": ("kx", "ky", "n_kernels"),
    "conv_str": ("kx", "ky", "n_kernels"),
    "conv_sigmoid": ("kx", "ky", "n_kernels"),
    "max_pooling": ("kx", "ky"),
    "maxabs_pooling": ("kx", "ky"),
    "avg_pooling": ("kx", "ky"),
    "norm": ("alpha", "beta", "k", "n"),
    "dropout": (),
    "mean_disp": (),
    "activation_tanh": (), "activation_relu": (),
    "activation_str": (), "activation_sigmoid": (),
    # Long tail (reference unit_factory.cc registers every forward
    # type): RBM inference = sigmoid dense over the CD-trained
    # weights; tied-weight deconv decoders; Kohonen BMU distances.
    "rbm": (),
    "all2all_deconv": (), "all2all_deconv_sigmoid": (),
    "all2all_deconv_tanh": (),
    "kohonen": (),
    # Transformer family (no reference counterpart — the TPU build's
    # long-context extension, deployable like everything else).
    "embedding": ("vocab_size", "embed_dim"),
    "transformer_block": ("n_heads",),
    "moe_transformer_block": ("n_heads", "n_experts",
                              "capacity_factor"),
    "lm_head": (),
}

TANH_A, TANH_B = 1.7159, 0.6666


def _unit_entry(unit):
    """manifest entry + {param_name: array} for one forward unit."""
    mapping = getattr(type(unit), "MAPPING", None)
    from .mean_disp_normalizer import MeanDispNormalizer
    if isinstance(unit, MeanDispNormalizer):
        mapping = "mean_disp"
    if mapping not in EXPORTABLE:
        raise Bug("unit %s (type %s, MAPPING %r) is not exportable" %
                  (unit.name, type(unit).__name__, mapping))
    config = {}
    for key in EXPORTABLE[mapping]:
        config[key] = getattr(unit, key)
    # Geometry carried uniformly when present.
    for key in ("padding", "sliding"):
        if hasattr(unit, key):
            config[key] = getattr(unit, key)
    if hasattr(unit, "output_sample_shape") and \
            unit.output_sample_shape is not None:
        config["output_sample_shape"] = list(unit.output_sample_shape)
    params = {}
    if mapping == "mean_disp":
        for pname in ("mean", "rdisp"):
            vec = getattr(unit, pname)
            vec.map_read()
            params[pname] = numpy.asarray(
                vec.mem, dtype=numpy.float32)
    elif mapping == "rbm":
        # Inference forward is h = sigmoid(v·W + c): the visible bias
        # only matters for the training-time Gibbs chain, so the
        # artifact carries weights + hidden bias and rides the dense
        # execution path (reference libVeles executes every unit as a
        # forward-only chain, unit.h:41).
        unit.weights.map_read()
        params["weights"] = numpy.asarray(unit.weights.mem,
                                          dtype=numpy.float32)
        if unit.include_bias and unit.bias:
            unit.bias.map_read()
            params["bias"] = numpy.asarray(unit.bias.mem,
                                           dtype=numpy.float32)
    elif mapping.startswith("all2all_deconv"):
        # Tied weights live on the paired encoder; the standalone
        # artifact materializes them transposed so the decoder is an
        # ordinary dense unit (y = x·Wᵀ + b  →  x·(Wᵀ) with W stored
        # pre-transposed) for every runtime.
        enc_w = unit.encoder.weights
        enc_w.map_read()
        w = numpy.asarray(enc_w.mem, dtype=numpy.float32)
        params["weights"] = numpy.ascontiguousarray(w.T)
        if unit.include_bias and unit.vbias:
            unit.vbias.map_read()
            params["bias"] = numpy.asarray(unit.vbias.mem,
                                           dtype=numpy.float32)
        config["output_sample_shape"] = [int(w.shape[0])]
    elif mapping == "kohonen":
        unit.weights.map_read()
        params["weights"] = numpy.asarray(unit.weights.mem,
                                          dtype=numpy.float32)
        config["output_sample_shape"] = [int(unit.n_neurons)]
    elif mapping == "embedding":
        for pname, vec in unit.trainables.items():
            vec.map_read()
            params[pname] = numpy.asarray(vec.mem,
                                          dtype=numpy.float32)
    elif mapping in ("transformer_block", "moe_transformer_block"):
        config["causal"] = int(unit.causal)
        for pname, vec in unit.trainables.items():
            vec.map_read()
            params[pname] = numpy.asarray(vec.mem,
                                          dtype=numpy.float32)
    elif mapping == "lm_head":
        # Tied heads materialize the embedding weights transposed so
        # the artifact is standalone (same treatment as deconv).
        if unit.tie_to is not None:
            src = unit.tie_to.weights
            src.map_read()
            w = numpy.ascontiguousarray(
                numpy.asarray(src.mem, dtype=numpy.float32).T)
        else:
            unit.weights.map_read()
            w = numpy.asarray(unit.weights.mem, dtype=numpy.float32)
        params["weights"] = w
        if unit.include_bias and unit.bias:
            unit.bias.map_read()
            params["bias"] = numpy.asarray(unit.bias.mem,
                                           dtype=numpy.float32)
        config["output_sample_shape"] = [int(w.shape[1])]
    else:
        for pname, vec in getattr(unit, "trainables", {}).items():
            if not vec:
                continue
            vec.map_read()
            params[pname] = numpy.asarray(
                vec.mem, dtype=numpy.float32)
    return {"name": unit.name, "type": mapping,
            "config": config}, params


def forward_chain(workflow):
    """The exportable forward units, in execution order.  Uses the
    ``forwards`` convention (every sample workflow defines it), with
    any normalizer between loader and first layer included."""
    chain = []
    forwards = getattr(workflow, "forwards", None)
    if not forwards:
        raise Bug("workflow %s has no .forwards chain to export"
                  % workflow.name)
    first = forwards[0]
    norm = getattr(workflow, "normalizer", None)
    if norm is not None and getattr(first, "input", None) is \
            getattr(norm, "output", None):
        chain.append(norm)
    chain.extend(forwards)
    return chain


def _expand_unit(unit):
    """One forward unit → one or more (entry, params) pairs.  A
    pipelined transformer stack (stage-stacked parameters with a
    leading n_blocks dim) UNSTACKS into n_blocks ordinary
    transformer_block entries — the pipeline is a TRAINING layout,
    not an inference format, so a stack trained under dp×pp deploys
    through the same artifact/native/REST surfaces as a sequential
    model (sequential and pipelined are bit-identical by
    construction, ops/pipeline.py)."""
    from .znicz.attention import PipelinedTransformerStack
    if not isinstance(unit, PipelinedTransformerStack):
        return [_unit_entry(unit)]
    out = []
    for i in range(unit.n_blocks):
        params = {}
        for pname, vec in unit.trainables.items():
            vec.map_read()
            params[pname] = numpy.ascontiguousarray(
                numpy.asarray(vec.mem, dtype=numpy.float32)[i])
        entry = {"name": "%s_block%d" % (unit.name, i),
                 "type": "transformer_block",
                 "config": {"n_heads": unit.n_heads,
                            "causal": int(unit.causal)}}
        out.append((entry, params))
    return out


def export_workflow(workflow, path):
    """Writes the inference artifact for a trained workflow."""
    chain = forward_chain(workflow)
    units = []
    weight_arrays = {}
    for unit in chain:
        for entry, params in _expand_unit(unit):
            entry["params"] = {}
            for pname, arr in params.items():
                key = "%s__%s" % (entry["name"], pname)
                if key in weight_arrays:
                    raise Bug("duplicate weight key %r — unit names "
                              "in the chain must be unique" % key)
                weight_arrays[key] = arr
                entry["params"][pname] = key
            units.append(entry)
    for entry in units:
        shape = entry["config"].get("output_sample_shape")
        if shape is not None and len(shape) > 1:
            # model.bin flattens dense outputs to n_out; a spatial
            # dense output feeding a conv/pool would lose geometry in
            # the native runtime — refuse rather than mis-execute.
            raise Bug("unit %s has multi-dim dense output shape %s — "
                      "not representable in the native artifact" %
                      (entry["name"], shape))
    in_vec = chain[0].input
    out_vec = chain[-1].output
    manifest = {
        "format": FORMAT_NAME,
        "version": FORMAT_VERSION,
        "workflow": type(workflow).__name__,
        "checksum": workflow.checksum,
        "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "input": {"sample_shape": list(in_vec.shape[1:]),
                  # Token models declare int32; the wire format for
                  # forward() inputs stays float (values are cast).
                  "dtype": str(in_vec.dtype)},
        "output": {"sample_shape": list(out_vec.shape[1:])},
        "units": units,
    }
    npz_buf = io.BytesIO()
    numpy.savez(npz_buf, **weight_arrays)
    blobs = {
        "manifest.json": dumps_json(manifest, indent=2).encode(),
        "weights.npz": npz_buf.getvalue(),
        "model.bin": _pack_binary(manifest, weight_arrays),
    }
    with tarfile.open(path, "w:gz") as tar:
        for name, blob in blobs.items():
            info = tarfile.TarInfo(name)
            info.size = len(blob)
            tar.addfile(info, io.BytesIO(blob))
    return path


# -- model.bin (native runtime format) ----------------------------------

def _pack_str(s):
    data = s.encode("utf-8")
    return struct.pack("<H", len(data)) + data


def _flat_config(config):
    """Flattens geometry tuples into scalar keys the native parser
    reads: padding → pt/pb/pl/pr, sliding → sh/sw."""
    flat = {}
    for key, value in config.items():
        if key == "padding":
            (pt, pb), (pl, pr) = value
            flat.update(pad_top=pt, pad_bottom=pb, pad_left=pl,
                        pad_right=pr)
        elif key == "sliding":
            sh, sw = value
            flat.update(stride_h=sh, stride_w=sw)
        elif key == "output_sample_shape":
            flat["n_out"] = int(numpy.prod(value))
        else:
            flat[key] = float(value)
    return flat


def _pack_binary(manifest, weight_arrays):
    out = [MAGIC, struct.pack("<II", FORMAT_VERSION,
                              len(manifest["units"]))]
    in_shape = manifest["input"]["sample_shape"]
    out.append(struct.pack("<I", len(in_shape)))
    out.append(struct.pack("<%dI" % len(in_shape), *in_shape))
    for entry in manifest["units"]:
        out.append(_pack_str(entry["type"]))
        out.append(_pack_str(entry["name"]))
        flat = _flat_config(entry["config"])
        out.append(struct.pack("<I", len(flat)))
        for key in sorted(flat):
            out.append(_pack_str(key))
            out.append(struct.pack("<d", float(flat[key])))
        params = entry["params"]
        out.append(struct.pack("<I", len(params)))
        for pname in sorted(params):
            arr = weight_arrays[params[pname]]
            out.append(_pack_str(pname))
            out.append(struct.pack("<I", arr.ndim))
            out.append(struct.pack("<%dI" % arr.ndim, *arr.shape))
            out.append(numpy.ascontiguousarray(
                arr, dtype=numpy.float32).tobytes())
    return b"".join(out)


# -- paged KV cache: the block pool --------------------------------------

#: Storage dtypes the paged KV pool supports.  "f32" is the exact
#: path (byte-for-byte today's arithmetic — the bit-identical greedy
#: anchor); "bf16" is a scale-free cast; "int8" and "fp8" carry
#: per-(block, head) f32 scales alongside the block tensors and
#: quantize on scatter / dequantize on gather (KIVI-style block
#: granularity, so refcounts, COW, and prefix-cache sha1 keys never
#: see the quantization — they only ever address whole blocks).
KV_DTYPES = ("f32", "bf16", "int8", "fp8")

#: Symmetric clip range per scaled storage dtype (None: scale-free).
_KV_QMAX = {"f32": None, "bf16": None, "int8": 127.0, "fp8": 448.0}

#: Bytes per stored k/v element.
_KV_ITEMSIZE = {"f32": 4, "bf16": 2, "int8": 1, "fp8": 1}


def kv_dtype_supported(kv_dtype):
    """Whether this jax build can hold the storage dtype.  fp8 needs
    ``jnp.float8_e4m3fn`` (capable platforms only); the int8 and bf16
    planes work everywhere."""
    if kv_dtype not in KV_DTYPES:
        return False
    if kv_dtype != "fp8":
        return True
    import jax.numpy as jnp
    return hasattr(jnp, "float8_e4m3fn")


def check_kv_dtype(kv_dtype):
    """Canonical KV storage dtype name (None → "f32"), or Bug naming
    the valid set for unknown/unsupported names."""
    kv_dtype = "f32" if kv_dtype is None else str(kv_dtype)
    if kv_dtype not in KV_DTYPES:
        raise Bug("unknown KV storage dtype %r — valid: %s" %
                  (kv_dtype, ", ".join(KV_DTYPES)))
    if not kv_dtype_supported(kv_dtype):
        raise Bug("KV storage dtype %r is not supported by this jax "
                  "build (fp8 needs float8_e4m3fn)" % (kv_dtype,))
    return kv_dtype


def _kv_storage_jnp(kv_dtype):
    import jax.numpy as jnp
    return {"f32": jnp.float32, "bf16": jnp.bfloat16,
            "int8": jnp.int8,
            "fp8": getattr(jnp, "float8_e4m3fn", None)}[kv_dtype]


def _kv_unpack(storage):
    """``(ks, vs, sks, svs)`` from a pool storage tuple — the scale
    lists are None for scale-free (f32/bf16) pools."""
    if len(storage) == 4:
        return storage
    ks, vs = storage
    return ks, vs, None, None


def _kv_quantize(vals, scale_full, kv_dtype):
    """Quantize f32 ``vals`` at an already-broadcast ``scale_full``
    (zero scale → zero code, so never-written rows stay exact
    zeros).  int8 rounds-to-nearest; fp8 clips then lets the cast
    round — both deterministic, the paged parity gates replay
    byte-identical sessions."""
    import jax.numpy as jnp
    qmax = _KV_QMAX[kv_dtype]
    safe = jnp.where(scale_full > 0.0, scale_full, 1.0)
    x = jnp.clip(vals / safe, -qmax, qmax)
    if kv_dtype == "int8":
        x = jnp.round(x)
    return jnp.where(scale_full > 0.0, x,
                     0.0).astype(_kv_storage_jnp(kv_dtype))


class KVBlockPool(object):
    """A vLLM-style block pool for the paged serving decode path:
    the device holds one fixed tensor of ``(n_blocks, block_size, H,
    D)`` k/v blocks per layer (``storage``, owned by the model that
    built the pool), and every request addresses it through a
    per-request BLOCK TABLE of physical block ids — so N concurrent
    streams of wildly different lengths share one allocation instead
    of each owning a dense ``(B, L, H, D)`` cache sized to its max.

    This object is the HOST-side half: block accounting (free list +
    per-block refcounts), the prompt-prefix cache (full-block
    prefixes keyed by token hash, LRU-bounded, each entry holding a
    ref on its blocks so a common system prompt stays resident and
    is prefilled ONCE), and copy-on-write (a row about to WRITE into
    a shared block gets a private copy first).  Device tensors are
    opaque here — the owning model supplies ``copy_fn(storage, src,
    dst) -> storage`` and mutates ``storage`` through its own jitted
    gather/scatter programs.

    Block 0 is the TRASH block: table padding and out-of-range
    writes land there, so padded rows in a coalesced device batch
    can scatter junk without owning real blocks.  Accounting is
    lock-guarded: the engine's device thread allocates/frees while
    HTTP threads read ``occupancy()`` for ``/stats``.
    """

    TRASH = 0

    def __init__(self, n_blocks, block_size, storage=None,
                 copy_fn=None, prefix_capacity=256, kv_dtype=None,
                 block_bytes=0):
        n_blocks = int(n_blocks)
        block_size = int(block_size)
        if n_blocks < 2:
            raise Bug("a KV block pool needs >= 2 blocks (block 0 "
                      "is the trash block), got %d" % n_blocks)
        if block_size < 1:
            raise Bug("block_size must be >= 1, got %d" % block_size)
        self.n_blocks = n_blocks
        self.block_size = block_size
        self.storage = storage
        self._copy_fn = copy_fn
        # Storage dtype + per-block device bytes (geometry × itemsize
        # + scale rows): immutable after construction, so occupancy()
        # reads them lock-free — only the COUNTS need the lock.
        self.kv_dtype = check_kv_dtype(kv_dtype)
        self.block_bytes = int(block_bytes)
        self.prefix_capacity = int(prefix_capacity)
        self._lock = SniffedLock(name="KVBlockPool.lock")
        # LIFO free list: recently-freed blocks are re-used first
        # (their pages are warm).  Block 0 (trash) is never free.
        self._free = list(range(n_blocks - 1, 0, -1))  # guarded-by: _lock
        self._refs = {}  # guarded-by: _lock
        # digest -> tuple(block ids); OrderedDict as LRU (most
        # recently hit last).  Entries hold one ref per block.
        self._prefix = collections.OrderedDict()  # guarded-by: _lock
        self.prefix_hits = 0  # guarded-by: _lock
        self.prefix_misses = 0  # guarded-by: _lock
        self.cow_copies = 0  # guarded-by: _lock

    @property
    def usable(self):
        """Blocks available to requests (total minus trash)."""
        return self.n_blocks - 1

    def free_count(self):
        with self._lock:
            return len(self._free)

    def used_count(self):
        with self._lock:
            return self.usable - len(self._free)

    def blocks_for(self, n_tokens):
        """Blocks needed to hold ``n_tokens`` cache slots."""
        return -(-int(n_tokens) // self.block_size)

    # -- allocation ------------------------------------------------------

    def alloc(self, n):
        """``n`` fresh block ids (ref 1 each), or None when the pool
        cannot supply them even after evicting cached prefixes —
        the caller sheds load.  Prefix entries are evicted LRU-first
        under pressure: cached prompts are an optimization, never a
        reason to refuse live traffic."""
        n = int(n)
        with self._lock:
            while len(self._free) < n and self._prefix:
                _, ids = self._prefix.popitem(last=False)
                self._release_locked(ids)
            if len(self._free) < n:
                return None
            out = [self._free.pop() for _ in range(n)]
            for b in out:
                self._refs[b] = 1
            return out

    def retain(self, ids):
        """Adds one ref per block — the generic counterpart of
        :meth:`release` for callers that hand a table to a second
        owner (``lookup_prefix``/``register_prefix`` take their own
        refs internally)."""
        with self._lock:
            for b in ids:
                if b == self.TRASH:
                    continue
                self._refs[b] += 1

    def release(self, ids):
        """Drops one ref per block; blocks at zero return to the
        free list.  Trash ids are ignored (table padding)."""
        with self._lock:
            self._release_locked(ids)

    def refs_of(self, block_id):
        """Current refcount of one block (0 for free/trash) — the
        speculative-decode rewind path asks before writing into a
        table tail block whether anyone else holds it (refs > 1 ⇒
        copy-on-write first, exactly the prefix-sharing discipline)."""
        with self._lock:
            return self._refs.get(int(block_id), 0)

    def _release_locked(self, ids):
        for b in ids:
            if b == self.TRASH:
                continue
            left = self._refs[b] - 1
            if left:
                self._refs[b] = left
            else:
                del self._refs[b]
                self._free.append(b)

    # -- prefix sharing --------------------------------------------------

    def prefix_chain(self, tokens):
        """Chained per-block digests (digest_j = sha1(digest_{j-1} ·
        block_j tokens), the vLLM scheme): O(L) total hashing for
        every full-block prefix of a prompt, computed OUTSIDE the
        pool lock so adoption never blocks ``occupancy()`` readers
        on hashing.  Callers doing lookup-then-register pass the
        same chain to both so each prompt is hashed ONCE."""
        tokens = numpy.ascontiguousarray(tokens, dtype=numpy.int32)
        bs = self.block_size
        chain = []
        digest = b""
        for j in range(len(tokens) // bs):
            digest = hashlib.sha1(
                digest + tokens[j * bs:(j + 1) * bs].tobytes()
            ).digest()
            chain.append(digest)
        return chain

    def lookup_prefix(self, tokens, chain=None):
        """The longest cached full-block prefix of ``tokens``:
        ``(n_full_blocks_matched, block_ids)`` with one ref per
        returned block ALREADY TAKEN for the caller, or ``(0, [])``.
        Matching is by token-content hash at full-block granularity
        — a request sharing a system prompt adopts its blocks
        instead of re-prefilling them."""
        if chain is None:
            chain = self.prefix_chain(tokens)
        with self._lock:
            for j in range(len(chain), 0, -1):
                ids = self._prefix.get(chain[j - 1])
                if ids is None:
                    continue
                self._prefix.move_to_end(chain[j - 1])
                for b in ids:
                    self._refs[b] += 1
                self.prefix_hits += 1
                return j, list(ids)
            self.prefix_misses += 1
            return 0, []

    def register_prefix(self, tokens, block_ids, chain=None):
        """Registers every full-block prefix of a just-prefilled
        prompt (``block_ids`` = its table, position-ordered) so later
        requests can adopt the blocks.  Existing entries are kept
        (their blocks already hold the same content); the LRU bound
        evicts the coldest entries past ``prefix_capacity``."""
        if chain is None:
            chain = self.prefix_chain(tokens)
        with self._lock:
            for j, key in enumerate(chain, start=1):
                if key in self._prefix:
                    self._prefix.move_to_end(key)
                    continue
                ids = tuple(block_ids[:j])
                for b in ids:
                    self._refs[b] += 1
                self._prefix[key] = ids
            while len(self._prefix) > self.prefix_capacity:
                _, ids = self._prefix.popitem(last=False)
                self._release_locked(ids)

    # -- cross-pool export / adoption ------------------------------------

    def export_prefix_blocks(self, tokens, chain=None):
        """The longest cached full-block prefix of ``tokens`` as an
        EXPORTABLE handle: ``(n_full_blocks, block_ids)`` with one
        ref per block taken for the caller — identical contract to
        :meth:`lookup_prefix`, named for the disaggregation wire
        (docs/serving.md "Serving fabric"): the caller serializes the
        addressed device blocks (``ExportedModel.export_kv_blocks``)
        and then MUST :meth:`release` the ids.  The refs pin the
        blocks against eviction/COW while their bytes are in flight."""
        return self.lookup_prefix(tokens, chain=chain)

    def adopt_prefix_blocks(self, tokens, n_blocks, write_fn=None,
                            chain=None):
        """Adopts ``n_blocks`` full blocks of remotely-prefilled KV
        into THIS pool's prefix cache: allocates destination blocks,
        lets ``write_fn(ids)`` scatter the shipped tensor data into
        them (``ExportedModel.import_kv_blocks``), then registers
        every full-block prefix so the next local request with the
        same prompt adopts the blocks instead of re-prefilling.

        Refcount-correct by construction: after registration the
        alloc refs are RELEASED, so the prefix-cache entries are the
        only owners — block ``j`` (0-based) is held by entries
        ``j+1 .. n`` exactly as a locally-prefilled prefix would be,
        and LRU eviction / ``drop_prefixes`` return the blocks to the
        free list with no residue.  Idempotent: if the full chain is
        already cached the existing ids are returned untouched.
        Returns the block ids, or None when the pool cannot supply
        ``n_blocks`` even after evicting colder prefixes (the caller
        skips adoption — it is an optimization, never load-bearing)."""
        if chain is None:
            chain = self.prefix_chain(tokens)
        n_blocks = min(int(n_blocks), len(chain))
        if n_blocks <= 0:
            return []
        with self._lock:
            ids = self._prefix.get(chain[n_blocks - 1])
            if ids is not None:
                self._prefix.move_to_end(chain[n_blocks - 1])
                return list(ids)
        ids = self.alloc(n_blocks)
        if ids is None:
            return None
        if write_fn is not None:
            try:
                write_fn(ids)
            except Exception:
                self.release(ids)
                raise
        bs = self.block_size
        tokens = numpy.ascontiguousarray(tokens,
                                         dtype=numpy.int32)
        self.register_prefix(tokens[:n_blocks * bs], ids,
                             chain=chain[:n_blocks])
        self.release(ids)
        return ids

    # -- copy-on-write ---------------------------------------------------

    def cow_copy(self, block_id):
        """Copy-on-write: a fresh private block holding a device copy
        of ``block_id``'s content (the caller is about to WRITE into
        a position that falls inside a shared block — e.g. a fully
        prefix-cached prompt re-feeding its last token).  The caller
        keeps responsibility for releasing its ref on the shared
        original.  Returns the new id, or None when the pool is
        exhausted."""
        ids = self.alloc(1)
        if ids is None:
            return None
        if self._copy_fn is not None:
            self.storage = self._copy_fn(self.storage, int(block_id),
                                         int(ids[0]))
        with self._lock:
            self.cow_copies += 1
        return ids[0]

    def drop_prefixes(self):
        """Releases every cached prompt-prefix entry (blocks return
        to the free list once unreferenced) and returns how many were
        dropped.  Hot weight reload calls this: cached prefixes hold
        k/v computed under the OLD weights, and serving them to a
        post-swap request would mix two models in one sequence.  Live
        rows keep their tables — only the cache is invalidated."""
        with self._lock:
            dropped = len(self._prefix)
            while self._prefix:
                _, ids = self._prefix.popitem(last=False)
                self._release_locked(ids)
            return dropped

    # -- observability ---------------------------------------------------

    def occupancy(self):
        """The ``/stats`` pool section: block occupancy plus prefix-
        cache and COW counters, and the BYTES the blocks occupy
        (blocks × block geometry × storage dtype, scale rows
        included) — the figure that makes a quantized pool's capacity
        win visible on the dashboard."""
        with self._lock:
            used = self.usable - len(self._free)
            return {
                "block_size": self.block_size,
                "blocks_total": self.usable,
                "blocks_free": len(self._free),
                "blocks_used": used,
                "storage_dtype": self.kv_dtype,
                "block_bytes": self.block_bytes,
                "bytes_total": self.usable * self.block_bytes,
                "bytes_used": used * self.block_bytes,
                "prefix_entries": len(self._prefix),
                "prefix_hits": self.prefix_hits,
                "prefix_misses": self.prefix_misses,
                "cow_copies": self.cow_copies,
            }


# -- shared LM decode helpers -------------------------------------------
# ONE implementation of the head projection and the per-row
# greedy/temperature select, shared by the dense bucketed programs and
# the paged extend/step programs: a sampling fix applied to one copy
# but not another would silently break the documented bit-identical
# greedy guarantee between the two paths.

def _head_logits(x_last, head_w, head_b, head_s=None):
    if head_s is not None:
        # Weight-only int8 head: dequant-in-kernel — the int8 weight
        # feeds the dot directly and the per-output-channel scale
        # applies to the f32 accumulator (LLM.int8-style).
        y = (x_last @ head_w.astype(head_s.dtype)) * head_s
    else:
        y = x_last @ head_w
    return y + head_b if head_b is not None else y


def _mm(h, p, name):
    """``h @ W`` for a decode-program weight: when the parameter
    pytree carries a ``<name>__s`` per-output-channel scale (the
    weight-only int8 plane), the int8 weight feeds the dot and the
    scale applies to the accumulator — dequant-in-kernel, never a
    materialized f32 copy of the weight."""
    s = p.get(name + "__s")
    if s is None:
        return h @ p[name]
    return (h @ p[name].astype(s.dtype)) * s


def _sample_rows(logits, keys, temps):
    """Greedy/temperature select per row; temperatures are TRACED
    (never a compile key) and each row draws from its own PRNG
    stream."""
    import jax
    import jax.numpy as jnp
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    sampled = jax.vmap(jax.random.categorical)(
        keys, scaled).astype(jnp.int32)
    return jnp.where(temps > 0.0, sampled, greedy)


# -- execution from the artifact ----------------------------------------

class ExportedModel(object):
    """Loads an artifact and re-executes its forward chain
    (the Python mirror of the native runtime)."""

    def __init__(self, path, compile_capacity=32):
        if hasattr(path, "read"):
            # A file object (e.g. an already-verified in-memory blob
            # from the reload path — what was hashed is exactly what
            # loads, no second read of a file that may have changed).
            tar = tarfile.open(fileobj=path, mode="r:gz")
            self.path = getattr(path, "name", None)
        else:
            tar = tarfile.open(path, "r:gz")
            self.path = path
        with tar:
            manifest_blob = tar.extractfile("manifest.json").read()
            weights_blob = tar.extractfile("weights.npz").read()
        self.manifest = json.loads(manifest_blob)
        if self.manifest.get("format") != FORMAT_NAME:
            raise Bug("%s is not a %s artifact" % (path, FORMAT_NAME))
        if self.manifest.get("version", 0) > FORMAT_VERSION:
            raise Bug("artifact version %s is newer than this "
                      "runtime (%d)" % (self.manifest.get("version"),
                                        FORMAT_VERSION))
        self.weights = dict(numpy.load(io.BytesIO(weights_blob)))
        self.units = self.manifest["units"]
        self.input_shape = tuple(
            self.manifest["input"]["sample_shape"])
        self._jit_forward = None
        self.compile_capacity = int(compile_capacity)
        self._compile_cache = None
        #: Monotonically increasing weight generation: 1 at load,
        #: bumped by every :meth:`swap_weights` — the serving layer
        #: surfaces it as the ``weight_version`` gauge.
        self.weight_version = 1
        self._jax_weights = None
        self._lm_params_cache = None

    @property
    def compile_cache(self):
        """The bounded LRU of built executables (generate geometries
        and forward shape sentinels) — every compile key is
        client-reachable through the serving endpoints, so the set is
        hard-capped; evicting a forward sentinel resets the monolithic
        forward jit (its per-shape cache hides behind one callable)."""
        if self._compile_cache is None:
            from .serving.buckets import CompileCache

            def on_evict(key, value):
                if key and key[0] == "fwd":
                    # The forward executables all hide behind ONE jit
                    # callable, so dropping it invalidates every fwd
                    # sentinel — remove them together or the
                    # survivors would report cache HITs while
                    # forward() silently recompiles.
                    self._jit_forward = None
                    self._compile_cache.drop_where(
                        lambda k: k and k[0] == "fwd")

            self._compile_cache = CompileCache(
                capacity=self.compile_capacity, on_evict=on_evict)
        return self._compile_cache

    @property
    def max_position(self):
        """The LM positional-table size (prompt+generated tokens must
        fit), or None when the artifact is not a causal LM."""
        try:
            emb, _, _ = self._lm_chain()
        except Bug:
            return None
        return int(self.weights[emb["params"]["pos"]].shape[0])

    # ---- hot weight swap ----------------------------------------------

    def _device_weights(self):
        """The full weight dict as device-resident arrays — one
        host→device transfer per weight generation, not per call.
        Every jitted program takes its weights from here as a TRACED
        pytree argument, so a same-geometry swap reuses the compiled
        executables (same shapes/dtypes → same program)."""
        if self._jax_weights is None:
            import jax.numpy as jnp
            self._jax_weights = {k: jnp.asarray(v)
                                 for k, v in self.weights.items()}
        return self._jax_weights

    @staticmethod
    def _decode_weight_mode():
        """The weight plane of the decode program family:
        ``root.common.serving.weight_dtype`` — "f32" (default, the
        parity anchor) or "int8" (weight-only int8 matmuls with
        per-output-channel scales, dequant-in-kernel).  The dense
        ``forward`` path never quantizes — it stays the f32 oracle
        the perplexity-delta gate compares against.  The mode string
        rides every decode compile-cache key like ``attend=`` does."""
        from .config import root, get as config_get
        mode = str(config_get(root.common.serving.weight_dtype,
                              "f32"))
        if mode not in ("f32", "int8"):
            raise Bug("unknown decode weight dtype %r — valid: "
                      "f32, int8" % (mode,))
        return mode

    #: 2-D decode matmul weights that ride the weight-only int8 plane
    #: (embeddings are gathers, norms/biases stay f32).
    _WQ_NAMES = ("wq", "wk", "wv", "wqkv", "wo", "w1", "w2")

    @staticmethod
    def _quantize_weight(d, name):
        """Per-output-channel symmetric int8: ``W ≈ Q · s`` with
        ``s = amax(|W|, axis=0) / 127`` — stored as ``<name>`` (int8)
        plus ``<name>__s`` (f32 row vector).  Zero columns quantize
        to zero codes with zero scale, an exact round trip."""
        import jax.numpy as jnp
        w = d.get(name)
        if w is None or w.ndim != 2:
            return
        s = jnp.max(jnp.abs(w), axis=0) / 127.0
        safe = jnp.where(s > 0.0, s, 1.0)
        d[name] = jnp.clip(jnp.round(w / safe), -127,
                           127).astype(jnp.int8)
        d[name + "__s"] = s

    def _lm_params(self):
        """The LM decode-program parameter pytree (embedding, head,
        per-block dicts), built from :meth:`_device_weights` and
        invalidated with it on :meth:`swap_weights` — which is why a
        hot swap re-quantizes automatically: the swapped weights
        rebuild this cache (on the device thread, where every decode
        program runs) under the current weight mode."""
        mode = self._decode_weight_mode()
        cached = self._lm_params_cache
        if cached is None or cached[0] != mode:
            emb, blocks, head = self._lm_chain()
            dev = self._device_weights()
            params = {
                "emb_w": dev[emb["params"]["weights"]],
                "emb_pos": dev[emb["params"]["pos"]],
                "head_w": dev[head["params"]["weights"]],
                "head_b": dev[head["params"]["bias"]]
                if "bias" in head["params"] else None,
                "blocks": [{n: dev[e["params"][n]]
                            for n in e["params"]} for e in blocks],
            }
            if mode == "int8":
                for bp in params["blocks"]:
                    for name in self._WQ_NAMES:
                        self._quantize_weight(bp, name)
                self._quantize_weight(params, "head_w")
            self._lm_params_cache = (mode, params)
        return self._lm_params_cache[1]

    def geometry_of(self):
        """The swap-compatibility fingerprint: the unit table plus
        every weight's shape.  Two artifacts with equal geometry can
        hot-swap weights through the SAME compiled programs."""
        return (self.units,
                {k: tuple(v.shape) for k, v in self.weights.items()})

    def same_geometry(self, other):
        """True when ``other``'s weights can be swapped into this
        model's compiled programs in place."""
        return self.geometry_of() == other.geometry_of()

    def swap_weights(self, new_weights):
        """In-place hot weight swap: replaces every parameter with
        the same-named array from ``new_weights`` and bumps
        :attr:`weight_version`.  The compile cache survives untouched
        — weights are traced arguments, so the cached executables
        simply read the new values on their next call.  Raises
        :class:`Bug` on any geometry mismatch (missing/extra/reshaped
        keys); the caller falls back to a full model replacement
        (drain-and-swap)."""
        new = {k: numpy.asarray(v, dtype=numpy.float32)
               for k, v in new_weights.items()}
        mine = {k: tuple(v.shape) for k, v in self.weights.items()}
        theirs = {k: tuple(v.shape) for k, v in new.items()}
        if mine != theirs:
            missing = sorted(set(mine) - set(theirs))
            extra = sorted(set(theirs) - set(mine))
            reshaped = sorted(
                "%s %s->%s" % (k, mine[k], theirs[k])
                for k in set(mine) & set(theirs)
                if mine[k] != theirs[k])
            raise Bug(
                "weight geometry mismatch — in-place swap impossible"
                " (missing: %s; new: %s; reshaped: %s)" %
                (missing or "-", extra or "-", reshaped or "-"))
        self.weights = new
        self._jax_weights = None
        self._lm_params_cache = None
        self.weight_version += 1
        return self.weight_version

    # ---- numpy reference path (native-runtime mirror) -----------------

    def _shape_input(self, x):
        """Reshapes flat samples to the manifest geometry; a 2-D
        input over a 1-D sample shape of DIFFERENT length passes
        through — token models accept any sequence length (the pos
        table is sliced to fit), e.g. the generation parity tests
        feed growing prefixes."""
        if tuple(x.shape[1:]) == self.input_shape:
            return x
        n = 1
        for d in self.input_shape:
            n *= d
        if x.size == x.shape[0] * n:
            return x.reshape((x.shape[0],) + self.input_shape)
        if x.ndim == 2 and len(self.input_shape) == 1 and \
                self.units and self.units[0]["type"] == "embedding":
            # Token models only: any sequence length is legitimate
            # (the pos table is sliced to fit).  Dense artifacts keep
            # the strict-width check — the numpy path mirrors the
            # native runtime, which rejects wrong-size samples.
            return x
        raise Bug("input shape %s does not fit samples of %s" %
                  (x.shape, self.input_shape))

    def forward_numpy(self, x):
        x = numpy.asarray(x, dtype=numpy.float32)
        x = self._shape_input(x)
        for entry in self.units:
            x = self._run_numpy(entry, x)
        return x

    def _param(self, entry, name):
        return self.weights[entry["params"][name]]

    def _run_numpy(self, entry, x):
        t = entry["type"]
        cfg = entry["config"]
        if t == "mean_disp":
            return (x - self._param(entry, "mean")) * \
                self._param(entry, "rdisp")
        if t == "dropout":
            return x
        if t.startswith("activation_"):
            return _ACTS[t.split("activation_")[1]](x)
        if t.startswith("all2all") or t in ("softmax", "rbm"):
            w = self._param(entry, "weights")
            y = x.reshape(x.shape[0], -1) @ w
            if "bias" in entry["params"]:
                y = y + self._param(entry, "bias")
            y = _ACTS[_DENSE_ACT[t]](y)
            shape = cfg.get("output_sample_shape")
            if shape:
                y = y.reshape((x.shape[0],) + tuple(shape))
            return y
        if t == "kohonen":
            return self._kohonen_numpy(entry, x)
        if t == "embedding":
            w = self._param(entry, "weights")
            # Clamp OOV ids like the native runtime and jax indexing
            # do — the mirror must not raise/wrap where they clamp.
            tokens = numpy.clip(x.astype(numpy.int32), 0,
                                w.shape[0] - 1)
            return (w[tokens] +
                    self._param(entry, "pos")[:tokens.shape[1]]
                    ).astype(numpy.float32)
        if t == "transformer_block":
            return self._transformer_numpy(entry, x)
        if t == "moe_transformer_block":
            return self._transformer_numpy(
                entry, x,
                mlp=lambda h, p: self._moe_ffn_numpy(entry, h, p))
        if t == "lm_head":
            w = self._param(entry, "weights")
            y = x @ w
            if "bias" in entry["params"]:
                y = y + self._param(entry, "bias")
            return y.astype(numpy.float32)
        if t.startswith("conv"):
            return self._conv_numpy(entry, x)
        if t.endswith("pooling"):
            return self._pool_numpy(entry, x)
        if t == "norm":
            return self._lrn_numpy(cfg, x)
        raise Bug("unknown unit type %r in artifact" % t)

    def _transformer_numpy(self, entry, x, mlp=None):
        """Pre-LN block, numpy mirror of znicz/attention.py
        ``transformer_block_apply``.  ``mlp(h, p)`` overrides the
        dense FFN (the MoE variant passes its routed experts)."""
        cfg = entry["config"]
        H = int(cfg["n_heads"])
        causal = bool(cfg.get("causal", 1))
        p = {n: self._param(entry, n) for n in entry["params"]}

        def ln(v, g, b, eps=1e-5):
            mu = v.mean(axis=-1, keepdims=True)
            var = ((v - mu) ** 2).mean(axis=-1, keepdims=True)
            return (v - mu) / numpy.sqrt(var + eps) * g + b

        B, S, E = x.shape
        D = E // H
        h = ln(x, p["ln1_g"], p["ln1_b"])
        if "wqkv" in p:
            # Fused-QKV artifact: one (E, 3E) head-major projection
            # (znicz/attention.fuse_qkv_arrays layout).
            qkv = (h @ p["wqkv"] + p["bqkv"]).reshape(B, S, H, 3, D)
            q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        else:
            q = (h @ p["wq"] + p["bq"]).reshape(B, S, H, D)
            k = (h @ p["wk"] + p["bk"]).reshape(B, S, H, D)
            v = (h @ p["wv"] + p["bv"]).reshape(B, S, H, D)
        scores = numpy.einsum("bqhd,bkhd->bhqk", q, k) / \
            numpy.sqrt(D)
        if causal:
            mask = numpy.tril(numpy.ones((S, S), bool))
            scores = numpy.where(mask, scores, -1e30)
        scores -= scores.max(axis=-1, keepdims=True)
        pattn = numpy.exp(scores)
        pattn /= pattn.sum(axis=-1, keepdims=True)
        attn = numpy.einsum("bhqk,bkhd->bqhd", pattn, v) \
            .reshape(B, S, E)
        x = x + attn @ p["wo"] + p["bo"]
        h = ln(x, p["ln2_g"], p["ln2_b"])
        if mlp is not None:
            return (x + mlp(h, p)).astype(numpy.float32)
        h = numpy.maximum(h @ p["w1"] + p["b1"], 0.0)
        return (x + h @ p["w2"] + p["b2"]).astype(numpy.float32)

    def _moe_ffn_numpy(self, entry, h, p):
        """Top-1 capacity routing, numpy mirror of ops/moe.py
        ``moe_ffn``: tokens flatten batch-major, each goes to its
        argmax expert while the expert has queue slots left
        (capacity = cf·T/E over the WHOLE batch, cumulative in token
        order); overflow tokens contribute zero (the residual path
        carries them)."""
        cfg = entry["config"]
        nexp = int(cfg["n_experts"])
        cf = float(cfg.get("capacity_factor", 1.25))
        B, S, E = h.shape
        tok = h.reshape(B * S, E).astype(numpy.float32)
        T = tok.shape[0]
        capacity = max(1, int(cf * T / nexp))
        logits = tok @ p["router"]
        logits -= logits.max(axis=-1, keepdims=True)
        probs = numpy.exp(logits)
        probs /= probs.sum(axis=-1, keepdims=True)
        gate = probs.max(axis=-1)
        expert = probs.argmax(axis=-1)
        y = numpy.zeros_like(tok)
        count = numpy.zeros(nexp, dtype=numpy.int64)
        for t in range(T):
            e = int(expert[t])
            if count[e] < capacity:
                h1 = numpy.maximum(tok[t] @ p["w1"][e] + p["b1"][e],
                                   0.0)
                y[t] = gate[t] * (h1 @ p["w2"][e] + p["b2"][e])
            count[e] += 1
        return y.reshape(B, S, E)

    def _kohonen_numpy(self, entry, x):
        # Squared distance to each SOM neuron (KohonenForward emits
        # the full distance map; BMU = argmin over the last axis).
        # float64 accumulation: the expanded form cancels near zero
        # exactly where the SOM converged, and the native runtime
        # accumulates exact squared differences in double.
        w = self._param(entry, "weights") \
            .astype(numpy.float64)  # (n_neurons, n_in)
        xf = x.reshape(x.shape[0], -1).astype(numpy.float64)
        return ((xf * xf).sum(1, keepdims=True) - 2.0 * (xf @ w.T) +
                (w * w).sum(1)).astype(numpy.float32)

    def _conv_numpy(self, entry, x):
        cfg = entry["config"]
        w = self._param(entry, "weights")  # HWIO
        ky, kx = w.shape[0], w.shape[1]
        (pt, pb), (pl, pr) = cfg["padding"]
        sh, sw = cfg["sliding"]
        xp = numpy.pad(x, ((0, 0), (pt, pb), (pl, pr), (0, 0)))
        n, H, W, C = xp.shape
        out_h = (H - ky) // sh + 1
        out_w = (W - kx) // sw + 1
        # im2col: patches (n, out_h, out_w, ky*kx*C)
        cols = numpy.empty((n, out_h, out_w, ky * kx * C),
                           dtype=numpy.float32)
        for iy in range(ky):
            for ix in range(kx):
                patch = xp[:, iy:iy + sh * out_h:sh,
                           ix:ix + sw * out_w:sw, :]
                cols[..., (iy * kx + ix) * C:(iy * kx + ix + 1) * C] \
                    = patch
        y = cols @ w.reshape(-1, w.shape[-1])
        if "bias" in entry["params"]:
            y = y + self._param(entry, "bias")
        act = {"conv": "linear", "conv_tanh": "tanh",
               "conv_relu": "softplus", "conv_str": "str",
               "conv_sigmoid": "sigmoid"}[entry["type"]]
        return _ACTS[act](y)

    def _pool_numpy(self, entry, x):
        cfg = entry["config"]
        t = entry["type"]
        ky, kx = int(cfg["ky"]), int(cfg["kx"])
        sh, sw = cfg["sliding"]
        (pt, pb), (pl, pr) = cfg["padding"]
        n, H, W, C = x.shape
        # Ceil-mode output + tail padding (matches Pooling
        # _window_padding).
        out_h = -(-(H + pt + pb - ky) // sh) + 1
        out_w = -(-(W + pl + pr - kx) // sw) + 1
        need_h = (out_h - 1) * sh + ky - (H + pt)
        need_w = (out_w - 1) * sw + kx - (W + pl)
        pb2, pr2 = max(pb, need_h), max(pr, need_w)
        if t == "avg_pooling":
            fill = 0.0
        else:
            fill = numpy.nan  # excluded via nan-aware reductions
        xp = numpy.full((n, H + pt + pb2, W + pl + pr2, C), fill,
                        dtype=numpy.float32)
        xp[:, pt:pt + H, pl:pl + W, :] = x
        y = numpy.empty((n, out_h, out_w, C), dtype=numpy.float32)
        if t == "avg_pooling":
            # Sum over zero-padded windows, divided by the true
            # (unpadded) window population.
            ones = numpy.zeros_like(xp)
            ones[:, pt:pt + H, pl:pl + W, :] = 1.0
        for oy in range(out_h):
            for ox in range(out_w):
                win = xp[:, oy * sh:oy * sh + ky,
                         ox * sw:ox * sw + kx, :]
                flat = win.reshape(n, -1, C)
                if t == "avg_pooling":
                    cnt = ones[:, oy * sh:oy * sh + ky,
                               ox * sw:ox * sw + kx, :] \
                        .reshape(n, -1, C).sum(axis=1)
                    y[:, oy, ox] = flat.sum(axis=1) / \
                        numpy.maximum(cnt, 1.0)
                elif t == "maxabs_pooling":
                    # nan→-inf (not nanargmax: an all-padding window
                    # must yield NaN, matching the native runtime,
                    # rather than raise on the all-NaN slice).
                    absf = numpy.where(numpy.isnan(flat),
                                       -numpy.inf, numpy.abs(flat))
                    idx = absf.argmax(axis=1)
                    y[:, oy, ox] = numpy.take_along_axis(
                        flat, idx[:, None, :], axis=1)[:, 0]
                else:
                    y[:, oy, ox] = numpy.nanmax(flat, axis=1)
        return y

    @staticmethod
    def _lrn_numpy(cfg, x):
        alpha, beta, k, n = (cfg["alpha"], cfg["beta"], cfg["k"],
                             int(cfg["n"]))
        c = x.shape[-1]
        half = n // 2
        sq = x * x
        ssum = numpy.zeros_like(x)
        for j in range(c):
            lo, hi = max(0, j - half), min(c, j + (n - 1 - half) + 1)
            ssum[..., j] = sq[..., lo:hi].sum(axis=-1)
        return x / (k + (alpha / n) * ssum) ** beta

    # ---- jax serving path ---------------------------------------------

    def forward(self, x):
        """Jitted jax forward (compiles once per batch shape; the
        weights ride as a traced pytree argument so a hot swap reuses
        the compiled executable)."""
        import jax
        if self._jit_forward is None:
            self._jit_forward = jax.jit(
                lambda weights, x: self._jax_chain(x, weights))
        return numpy.asarray(self._jit_forward(
            self._device_weights(),
            numpy.asarray(x, dtype=numpy.float32)))

    def forward_bucketed(self, x, batch_bucket):
        """Serving forward with the batch dim padded up to
        ``batch_bucket`` (zeros — rows are independent, pad outputs
        are dropped), so the compile-key set the serving layer can
        reach is the bucket grid, not every client batch size.  Shape
        sentinels ride the LRU compile cache for hit/miss accounting
        and the hard entry cap (eviction resets the forward jit)."""
        x = numpy.asarray(x, dtype=numpy.float32)
        if x.ndim == 1:
            x = x[None]
        n = x.shape[0]
        batch_bucket = max(int(batch_bucket), n)
        if batch_bucket > n:
            x = numpy.concatenate(
                [x, numpy.zeros((batch_bucket - n,) + x.shape[1:],
                                numpy.float32)], axis=0)
        self.compile_cache.get_or_build(
            ("fwd",) + tuple(x.shape), lambda: True)
        return self.forward(x)[:n]

    @staticmethod
    def _serving_attend(causal):
        """The serving attention: f32 intermediates, XLA formulation
        — PINNED, regardless of the attention fast-path knobs.  A
        training process flipping ``attention_dtype``/``kernel``
        must never change deployed bits (greedy decode is promised
        bit-stable); the fast path reaches serving only through an
        explicit future gate, not a global knob."""
        import functools
        from .ops.attention import attention
        return functools.partial(attention, causal=causal,
                                 precision="f32", kernel="xla")

    @staticmethod
    def _decode_kernel_mode():
        """The ONE explicit gate through which the attention fast
        path may reach serving: ``root.common.engine.decode_kernel``
        ("off" default — the f32/xla pin stands until the decode
        kernel's token-identity gate passes on the target platform).
        "pallas"/"auto" engage the flash-decode kernel where the
        compiled probe and geometry allow; "interpret" forces the
        interpret-mode kernel (the CPU token-identity tests — never
        a production setting)."""
        from .config import root, get as config_get
        mode = str(config_get(root.common.engine.decode_kernel,
                              "off"))
        if mode not in ("off", "pallas", "auto", "interpret"):
            raise Bug("unknown decode kernel mode %r — valid: off, "
                      "pallas, auto, interpret" % (mode,))
        return mode

    @classmethod
    def _decode_attend(cls):
        """None (the dense inline math) unless the decode-kernel
        gate is on; otherwise an ``attend(q, kc, vc, key_mask)``
        hook — the serving twin of the training path's ``attend=``
        override — that returns the flash-decode result, or None
        when the traced shapes sit outside the decode contract
        (prefill chunks, odd geometry) so the caller's dense
        formulation proceeds unchanged.  Resolved at program BUILD
        time; the mode string rides every decode compile-cache key,
        so flipping the knob can never serve a stale executable."""
        mode = cls._decode_kernel_mode()
        if mode == "off":
            return None
        import jax.numpy as jnp
        from .ops import pallas_attention as PA
        interpret = mode == "interpret"

        def attend(q, kc, vc, key_mask, k_scale=None, v_scale=None):
            if not PA.supports_decode(q.shape, kc.shape,
                                      interpret=interpret):
                return None
            if not interpret and not PA.pallas_decode_available():
                return None
            # f32 operands: the serving surfaces promise f32 math —
            # the kernel changes the REDUCTION ORDER only, which the
            # token-identity gate covers.  On a quantized pool the
            # k/v arrive as stored codes plus per-position scales and
            # the DEQUANT HAPPENS INSIDE THE KERNEL's k/v gather —
            # the dequantized cache is never materialized in HBM.
            return PA.pallas_decode_attention(
                q, kc, vc, key_mask, operand_dtype=jnp.float32,
                interpret=interpret, k_scale=k_scale,
                v_scale=v_scale)

        return attend

    def _jax_chain(self, x, weights=None):
        """The traced forward chain.  ``weights`` is the pytree the
        jit passes as an ARGUMENT (hot-swappable); None falls back to
        the host dict for direct/debug calls."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        if weights is None:
            weights = self.weights

        def par(entry, name):
            return weights[entry["params"][name]]

        x = self._shape_input(x)
        for entry in self.units:
            t = entry["type"]
            cfg = entry["config"]
            if t == "mean_disp":
                x = (x - par(entry, "mean")) * par(entry, "rdisp")
            elif t == "dropout":
                pass
            elif t.startswith("activation_"):
                x = _jax_act(t.split("activation_")[1], x)
            elif t.startswith("all2all") or t in ("softmax", "rbm"):
                w = par(entry, "weights")
                y = x.reshape(x.shape[0], -1) @ w
                if "bias" in entry["params"]:
                    y = y + par(entry, "bias")
                x = _jax_act(_DENSE_ACT[t], y)
                shape = cfg.get("output_sample_shape")
                if shape:
                    x = x.reshape((x.shape[0],) + tuple(shape))
            elif t == "embedding":
                w = jnp.asarray(par(entry, "weights"))
                # Explicit clamp: jnp indexing wraps negatives where
                # the native runtime (and the numpy mirror) clamp.
                tokens = jnp.clip(x.astype(jnp.int32), 0,
                                  w.shape[0] - 1)
                x = (w[tokens] +
                     par(entry, "pos")[:tokens.shape[1]])
            elif t == "transformer_block":
                from .znicz.attention import transformer_block_apply
                p = {n: par(entry, n) for n in entry["params"]}
                x = transformer_block_apply(
                    p, x, int(cfg["n_heads"]),
                    bool(cfg.get("causal", 1)), jnp.float32,
                    attend=self._serving_attend(
                        bool(cfg.get("causal", 1))))
            elif t == "moe_transformer_block":
                from .znicz.attention import transformer_block_apply
                from .ops.moe import moe_ffn
                p = {n: jnp.asarray(par(entry, n))
                     for n in entry["params"]}
                cf = float(cfg.get("capacity_factor", 1.25))

                def moe_mlp(h, p=p, cf=cf):
                    B_, S_, E_ = h.shape
                    y, _aux, _load = moe_ffn(
                        h.reshape(B_ * S_, E_), p["router"],
                        p["w1"], p["b1"], p["w2"], p["b2"],
                        capacity_factor=cf)
                    return y.reshape(B_, S_, E_)

                x = transformer_block_apply(
                    p, x, int(cfg["n_heads"]),
                    bool(cfg.get("causal", 1)), jnp.float32,
                    attend=self._serving_attend(
                        bool(cfg.get("causal", 1))),
                    mlp=moe_mlp)
            elif t == "lm_head":
                w = par(entry, "weights")
                y = x @ w
                if "bias" in entry["params"]:
                    y = y + par(entry, "bias")
                x = y
            elif t == "kohonen":
                w = par(entry, "weights")
                xf = x.reshape(x.shape[0], -1)
                # Expanded ‖x−w‖² cancels catastrophically under the
                # TPU's default bf16-input matmul — distances sit near
                # zero exactly where the SOM converged. Force full f32.
                xw = lax.dot(xf, w.T,
                             precision=jax.lax.Precision.HIGHEST)
                x = ((xf * xf).sum(1, keepdims=True) - 2.0 * xw +
                     (w * w).sum(1))
            elif t.startswith("conv"):
                w = par(entry, "weights")
                y = lax.conv_general_dilated(
                    x, w, window_strides=tuple(cfg["sliding"]),
                    padding=tuple(tuple(p) for p in cfg["padding"]),
                    dimension_numbers=("NHWC", "HWIO", "NHWC"))
                if "bias" in entry["params"]:
                    y = y + par(entry, "bias")
                act = {"conv": "linear", "conv_tanh": "tanh",
                       "conv_relu": "softplus", "conv_str": "str",
                       "conv_sigmoid": "sigmoid"}[t]
                x = _jax_act(act, y)
            elif t.endswith("pooling"):
                x = self._jax_pool(t, cfg, x)
            elif t == "norm":
                c = x.shape[-1]
                half = int(cfg["n"]) // 2
                i = jnp.arange(c)
                d = i[:, None] - i[None, :]
                band = ((d >= -half) &
                        (d <= int(cfg["n"]) - 1 - half)
                        ).astype(jnp.float32)
                ssum = jnp.einsum("...c,cd->...d", x * x, band)
                x = x / (cfg["k"] + (cfg["alpha"] / cfg["n"]) *
                         ssum) ** cfg["beta"]
            else:
                raise Bug("unknown unit type %r" % t)
        return x

    # ---- autoregressive generation (KV cache) -------------------------

    def _lm_chain(self):
        """(embedding, [blocks], lm_head) entries, or Bug when the
        artifact is not a causal LM.  Dropout entries are inert at
        inference and skipped."""
        entries = [e for e in self.units if e["type"] != "dropout"]
        if any(e["type"] == "moe_transformer_block" for e in entries):
            # A precise refusal: the routed-expert FFN has no cached
            # decode path yet, and the generic chain-shape message
            # would mislead (the chain IS embedding→blocks→head).
            raise Bug("MoE blocks are not yet supported by "
                      "generate() — serve moe_transformer_block "
                      "artifacts through forward()")
        if len(entries) < 3 or entries[0]["type"] != "embedding" or \
                entries[-1]["type"] != "lm_head" or \
                any(e["type"] != "transformer_block"
                    for e in entries[1:-1]):
            raise Bug(
                "generate() needs an embedding → transformer_block* "
                "→ lm_head chain; artifact has %s" %
                [e["type"] for e in self.units])
        for e in entries[1:-1]:
            if not e["config"].get("causal", 1):
                raise Bug("generate() requires causal attention "
                          "(block %s is bidirectional)" % e["name"])
        return entries[0], entries[1:-1], entries[-1]

    def _cached_block(self, p, x, ck, cv, start, n_heads,
                      key_mask=None, attend=None):
        """One pre-LN block over a chunk of positions
        [start, start+s) with a (B, L, H, D) KV cache: the chunk's
        k/v are written into the cache, queries attend the WHOLE
        cache under the global causal mask (unfilled positions are
        in the masked future by construction).  Used for BOTH
        prefill (s = prompt length, start = 0) and incremental
        decode (s = 1) — one code path, so prefill/decode parity is
        structural.

        ``key_mask`` (B, S_, L) overrides the causal mask with a
        per-BATCH-ELEMENT valid-key mask — the bucketed serving path
        uses it to exclude each row's pad slots, so coalesced
        requests of different true lengths cannot see each other's
        padding (attention is permutation-invariant over key slots:
        masking pads and keeping logical positions in the embeddings
        reproduces the unpadded computation exactly).

        ``attend`` (the :meth:`_decode_attend` hook): when set AND it
        accepts the traced shapes, attention runs through the
        flash-decode kernel instead of the dense einsums — the SAME
        mask, so masked slots stay exact zeros; it returns None for
        out-of-contract shapes (prefills) and the dense path below
        proceeds untouched."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        def ln(v, g, b, eps=1e-5):
            mu = v.mean(axis=-1, keepdims=True)
            var = ((v - mu) ** 2).mean(axis=-1, keepdims=True)
            return (v - mu) * jnp.reciprocal(jnp.sqrt(var + eps)) \
                * g + b

        B, S_, E = x.shape
        H = n_heads
        D = E // H
        L = ck.shape[1]
        h = ln(x, p["ln1_g"], p["ln1_b"])
        if "wqkv" in p:
            # Fused-QKV artifact: same head-major (E, 3E) layout as
            # the training/serving forward paths.
            qkv = (_mm(h, p, "wqkv") +
                   p["bqkv"]).reshape(B, S_, H, 3, D)
            q, kn, vn = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        else:
            q = (_mm(h, p, "wq") + p["bq"]).reshape(B, S_, H, D)
            kn = (_mm(h, p, "wk") + p["bk"]).reshape(B, S_, H, D)
            vn = (_mm(h, p, "wv") + p["bv"]).reshape(B, S_, H, D)
        ck = lax.dynamic_update_slice(ck, kn, (0, start, 0, 0))
        cv = lax.dynamic_update_slice(cv, vn, (0, start, 0, 0))
        if key_mask is None:
            qpos = start + jnp.arange(S_)
            kmask = jnp.broadcast_to(
                (qpos[:, None] >= jnp.arange(L)[None, :])[None],
                (B, S_, L))
        else:
            kmask = key_mask
        attn = attend(q, ck, cv, kmask) if attend is not None \
            else None
        if attn is None:
            scores = jnp.einsum(
                "bqhd,bkhd->bqhk", q, ck,
                preferred_element_type=jnp.float32) / (D ** 0.5)
            scores = jnp.where(kmask[:, :, None, :], scores, -1e30)
            w = jax.nn.softmax(scores, axis=-1)
            attn = jnp.einsum("bqhk,bkhd->bqhd", w, cv)
        x = x + _mm(attn.reshape(B, S_, E), p, "wo") + p["bo"]
        h = ln(x, p["ln2_g"], p["ln2_b"])
        x = x + _mm(jnp.maximum(_mm(h, p, "w1") + p["b1"], 0.0),
                    p, "w2") + p["b2"]
        return x.astype(jnp.float32), ck, cv

    def _build_generate(self, S0, max_new):
        """Jitted (prompt, key) → (tokens, step_logits): prefill the
        KV caches over the prompt in one batched pass, then lax.scan
        one-token decode steps — each step touches O(L) cache, never
        O(L²) scores, the KV-cache deployment contract."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        emb, blocks, head = self._lm_chain()
        n_heads = [int(e["config"]["n_heads"]) for e in blocks]
        # Static geometry from the weights AT BUILD TIME; the weight
        # VALUES arrive as a traced pytree argument per call, so a
        # same-geometry hot swap rides this compiled program.
        P, E = self.weights[emb["params"]["pos"]].shape
        V = self.weights[emb["params"]["weights"]].shape[0]
        L = S0 + max_new
        if L > P:
            raise Bug(
                "prompt %d + %d new tokens exceeds the model's "
                "positional table (%d)" % (S0, max_new, P))

        def embed(params, tokens, start):
            t = jnp.clip(tokens.astype(jnp.int32), 0, V - 1)
            pos = lax.dynamic_slice(params["emb_pos"], (start, 0),
                                    (t.shape[1], E))
            return params["emb_w"][t] + pos

        def logits_of(params, x_last):
            return _head_logits(x_last, params["head_w"],
                                params["head_b"],
                                params.get("head_w__s"))

        def sample(logits, key, temperature):
            """Greedy/temperature select with temperature as a TRACED
            scalar — it must not be a compile-cache key (a serving
            client could otherwise force a fresh multi-second jit per
            distinct float)."""
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            sampled = jax.random.categorical(
                key, logits / jnp.maximum(temperature, 1e-6),
                axis=-1).astype(jnp.int32)
            return jnp.where(temperature > 0.0, sampled, greedy)

        att = self._decode_attend()

        def run(params, prompt, key, temperature):
            B = prompt.shape[0]
            block_params = params["blocks"]
            x = embed(params, prompt, 0)
            caches = []
            for p, H in zip(block_params, n_heads):
                ck = jnp.zeros((B, L, H, E // H), jnp.float32)
                cv = jnp.zeros((B, L, H, E // H), jnp.float32)
                x, ck, cv = self._cached_block(p, x, ck, cv, 0, H,
                                               attend=att)
                caches.append((ck, cv))
            first_logits = logits_of(params, x[:, -1])
            tok0 = sample(first_logits, jax.random.fold_in(key, 0),
                          temperature)

            def body(carry, j):
                prev_tok, caches = carry
                t = S0 + j  # position the previous token occupies
                x = embed(params, prev_tok[:, None], t)
                new_caches = []
                for (ck, cv), p, H in zip(caches, block_params,
                                          n_heads):
                    x, ck, cv = self._cached_block(p, x, ck, cv, t, H,
                                                   attend=att)
                    new_caches.append((ck, cv))
                logits = logits_of(params, x[:, 0])
                tok = sample(logits, jax.random.fold_in(key, j + 1),
                             temperature)
                return (tok, new_caches), (prev_tok, logits)

            if max_new > 1:
                (last_tok, _), (toks, step_logits) = lax.scan(
                    body, (tok0, caches), jnp.arange(max_new - 1))
                tokens = jnp.concatenate(
                    [toks.swapaxes(0, 1), last_tok[:, None]], axis=1)
                all_logits = jnp.concatenate(
                    [first_logits[:, None],
                     step_logits.swapaxes(0, 1)], axis=1)
            else:
                tokens = tok0[:, None]
                all_logits = first_logits[:, None]
            return tokens, all_logits

        return jax.jit(run)

    def generate(self, prompt, max_new_tokens, temperature=0.0,
                 seed=0, return_logits=False):
        """Autoregressive decoding from the artifact: greedy when
        ``temperature`` == 0, else temperature sampling.  Returns the
        (B, prompt+new) token array — with ``return_logits``, also
        the (B, new, V) pre-sampling logits (what the parity tests
        compare against the full forward).  Prompt lengths round up
        to a power-of-two bucket and ride the padded
        ``generate_bucketed`` program (greedy output is bit-identical
        — the bucketed parity gate), so a serving workload of
        arbitrary lengths compiles O(log S) programs, one per bucket
        — temperature stays a TRACED input, deliberately excluded
        from the compile-cache key (a serving client could otherwise
        force a fresh multi-second jit per distinct float); the KV
        cache makes each decode step O(L·E) instead of re-running the
        full O(L²) forward (the incremental-serving obligation the
        reference's RESTful role implies, restful_api.py:78)."""
        import jax
        import jax.numpy as jnp
        prompt = numpy.atleast_2d(
            numpy.asarray(prompt, dtype=numpy.int32))
        if prompt.shape[1] < 1:
            raise Bug("prompt must contain at least one token")
        if max_new_tokens < 1:
            raise Bug("max_new_tokens must be >= 1")
        temperature = float(temperature)
        if not numpy.isfinite(temperature) or temperature < 0.0:
            raise Bug("temperature must be finite and >= 0")
        S0, max_new = prompt.shape[1], int(max_new_tokens)
        limit = self.max_position
        if limit is not None and S0 + max_new > limit:
            raise Bug(
                "prompt %d + %d new tokens exceeds the model's "
                "positional table (%d)" % (S0, max_new, limit))
        if not return_logits:
            # Decode-serving compile policy: round the prompt length
            # up to a power-of-two bucket and ride the padded
            # ``generate_bucketed`` path (greedy decode is
            # bit-identical by the bucketed-parity gate), so a
            # workload of arbitrary prompt lengths compiles O(log S)
            # programs instead of one per distinct length.  The
            # ``return_logits`` debugging path keeps the exact-length
            # program (what the parity tests pin).
            from .serving.buckets import bucket_of
            B = prompt.shape[0]
            S0b = bucket_of(S0, floor=16, cap=limit)
            padded = numpy.zeros((B, S0b), dtype=numpy.int32)
            padded[:, :S0] = prompt
            # Per-row seeds: generate_bucketed folds a PRNG key per
            # row, so a broadcast scalar would sample every row from
            # the same stream (identical prompts → identical
            # continuations at temperature > 0).  Greedy ignores the
            # seed entirely, so this keeps the bit-identical gate.
            gen = self.generate_bucketed(
                padded, numpy.full(B, S0, dtype=numpy.int32),
                max_new, temperatures=temperature,
                seeds=(int(seed) + numpy.arange(B)) & 0xFFFFFFFF)
            return numpy.concatenate([prompt, gen], axis=1)
        # Compile cache keyed ONLY by geometry (temperature is a
        # traced input), bounded LRU — the key is client-reachable
        # through the serving endpoint, so it must not grow without
        # bound.
        fn = self.compile_cache.get_or_build(
            ("gen", S0, max_new, self._decode_weight_mode(),
             self._decode_kernel_mode()),
            lambda: self._build_generate(S0, max_new))
        tokens, logits = fn(self._lm_params(), prompt,
                            jax.random.PRNGKey(seed),
                            jnp.float32(temperature))
        tokens = numpy.asarray(tokens)
        full = numpy.concatenate([prompt, tokens], axis=1)
        if return_logits:
            return full, numpy.asarray(logits)
        return full

    # ---- shape-bucketed serving decode --------------------------------

    def _build_generate_bucketed(self, S0b, max_new):
        """Jitted (prompts, lengths, seeds, temperatures) → generated
        tokens for a PADDED prompt bucket: prompts are right-padded
        to ``S0b`` columns and each row carries its true length.

        Exactness argument (what makes coalescing different-length
        requests safe): right-padding keeps every real prompt token
        at its true position 0..len-1, so prefill under the plain
        causal mask is bit-identical for real positions; the first
        logits are gathered per row at position len-1; each decode
        step embeds the new token at its LOGICAL position (len+j,
        per row) while writing its K/V into the uniform cache slot
        S0b+j, and the per-row key mask admits exactly {real prompt
        slots} ∪ {generated slots so far}.  Attention is permutation-
        invariant over key slots, so excluding pad slots and keeping
        logical positions reproduces the unpadded computation
        exactly — greedy decode matches ``generate()`` bit-for-bit.
        (Sampling draws per-ROW keys here — deterministic per seed,
        but a different stream than the single-key batch draw of
        ``generate()``.)"""
        import jax
        import jax.numpy as jnp
        from jax import lax
        emb, blocks, head = self._lm_chain()
        n_heads = [int(e["config"]["n_heads"]) for e in blocks]
        P, E = self.weights[emb["params"]["pos"]].shape
        V = self.weights[emb["params"]["weights"]].shape[0]
        if S0b > P:
            raise Bug("prompt bucket %d exceeds the model's "
                      "positional table (%d)" % (S0b, P))
        L = S0b + max_new

        def logits_of(params, x_last):
            return _head_logits(x_last, params["head_w"],
                                params["head_b"],
                                params.get("head_w__s"))

        sample_rows = _sample_rows
        att = self._decode_attend()

        def run(params, prompts, lengths, seeds, temps):
            B = prompts.shape[0]
            emb_w = params["emb_w"]
            emb_pos = params["emb_pos"]
            block_params = params["blocks"]
            keys0 = jax.vmap(jax.random.PRNGKey)(seeds)
            t = jnp.clip(prompts.astype(jnp.int32), 0, V - 1)
            x = emb_w[t] + emb_pos[:S0b]
            caches = []
            for p, H in zip(block_params, n_heads):
                ck = jnp.zeros((B, L, H, E // H), jnp.float32)
                cv = jnp.zeros((B, L, H, E // H), jnp.float32)
                x, ck, cv = self._cached_block(p, x, ck, cv, 0, H,
                                               attend=att)
                caches.append((ck, cv))
            idx = jnp.clip(lengths - 1, 0, S0b - 1)
            first_logits = logits_of(params, x[jnp.arange(B), idx])
            tok0 = sample_rows(
                first_logits,
                jax.vmap(lambda k: jax.random.fold_in(k, 0))(keys0),
                temps)
            slots = jnp.arange(L)

            def body(carry, j):
                prev_tok, caches = carry
                slot = S0b + j
                # Logical position (len+j per row) for the embedding;
                # clipped so bucket-overrun junk steps (a neighbor in
                # the batch wanted more tokens) read in-bounds and
                # stay discardable instead of faulting.
                posn = jnp.clip(lengths + j, 0, P - 1)
                pe = jnp.take(emb_pos, posn, axis=0)
                xj = emb_w[jnp.clip(prev_tok, 0, V - 1)][:, None] \
                    + pe[:, None]
                kmask = ((slots[None, :] < lengths[:, None]) |
                         ((slots[None, :] >= S0b) &
                          (slots[None, :] <= slot)))[:, None, :]
                new_caches = []
                for (ck, cv), p, H in zip(caches, block_params,
                                          n_heads):
                    xj, ck, cv = self._cached_block(
                        p, xj, ck, cv, slot, H, key_mask=kmask,
                        attend=att)
                    new_caches.append((ck, cv))
                logits = logits_of(params, xj[:, 0])
                tok = sample_rows(
                    logits,
                    jax.vmap(lambda k: jax.random.fold_in(k, j + 1))(
                        keys0),
                    temps)
                return (tok, new_caches), prev_tok

            if max_new > 1:
                (last_tok, _), toks = lax.scan(
                    body, (tok0, caches), jnp.arange(max_new - 1))
                return jnp.concatenate(
                    [toks.swapaxes(0, 1), last_tok[:, None]], axis=1)
            return tok0[:, None]

        return jax.jit(run)

    def generate_bucketed(self, prompts, lengths, max_new_tokens,
                          temperatures=0.0, seeds=0):
        """The serving engine's coalesced decode entry point:
        ``prompts`` (B, S0b) right-padded int32, ``lengths`` (B,)
        true prompt lengths, scalar-or-(B,) ``temperatures`` /
        ``seeds``.  Returns the (B, max_new_tokens) GENERATED tokens
        (the caller holds the true prompts).  Compiles once per
        (B, S0b, max_new_tokens) bucket triple — with power-of-two
        bucketing upstream the reachable key set is O(log² span),
        hard-capped by the LRU compile cache."""
        prompts = numpy.atleast_2d(
            numpy.asarray(prompts, dtype=numpy.int32))
        B, S0b = prompts.shape
        lengths = numpy.asarray(lengths, dtype=numpy.int32)
        if lengths.shape != (B,):
            raise Bug("lengths shape %s does not match batch %d" %
                      (lengths.shape, B))
        if S0b < 1 or (lengths < 1).any() or (lengths > S0b).any():
            raise Bug("prompt lengths must lie in [1, %d]" % S0b)
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise Bug("max_new_tokens must be >= 1")
        temps = numpy.ascontiguousarray(numpy.broadcast_to(
            numpy.asarray(temperatures, numpy.float32), (B,)))
        if not numpy.isfinite(temps).all() or (temps < 0.0).any():
            raise Bug("temperature must be finite and >= 0")
        seeds = numpy.ascontiguousarray(numpy.broadcast_to(
            numpy.asarray(seeds, numpy.uint32), (B,)))
        limit = self.max_position
        # The bucket must fit the positional table (prefill embeds
        # 0..S0b-1) and every row must have room for at least one
        # generated token.  max_new is a BUCKET, deliberately not
        # validated against the table: decode steps whose logical
        # position would overrun it read clamped embeddings and
        # produce junk a caller slices away — the serving engine
        # validates each request's TRUE (len + max_new) eagerly, so
        # one long-decode neighbor cannot 400 a whole coalesced
        # batch.
        if limit is not None and (S0b > limit or
                                  int(lengths.max()) >= limit):
            raise Bug(
                "prompt of %d tokens exceeds the model's positional "
                "table (%d)" % (max(S0b, int(lengths.max())), limit))
        fn = self.compile_cache.get_or_build(
            ("genb", B, S0b, max_new, self._decode_weight_mode(),
             self._decode_kernel_mode()),
            lambda: self._build_generate_bucketed(S0b, max_new))
        return numpy.asarray(fn(self._lm_params(), prompts, lengths,
                                seeds, temps))

    # ---- paged serving decode (block-pool KV cache) -------------------

    def _paged_geometry(self):
        """(n_layers, n_heads, head_dim) of the LM chain, or Bug —
        the paged pool stacks every layer's blocks in one per-layer
        tensor list, so the head geometry must be uniform."""
        emb, blocks, _ = self._lm_chain()
        heads = {int(e["config"]["n_heads"]) for e in blocks}
        if len(heads) != 1:
            raise Bug("paged decode requires a uniform head count "
                      "across blocks, got %s" % sorted(heads))
        H = heads.pop()
        E = int(self.weights[emb["params"]["weights"]].shape[1])
        if E % H:
            raise Bug("embed dim %d not divisible by %d heads" %
                      (E, H))
        return len(blocks), H, E // H

    def make_kv_pool(self, n_blocks, block_size=16, kv_dtype=None):
        """A :class:`KVBlockPool` backed by per-layer device tensors
        of ``(n_blocks, block_size, H, D)`` k/v blocks — the paged
        substrate the serving engine's decode-step batching runs on.
        ``kv_dtype`` picks the storage plane (default: the
        ``root.common.serving.kv_dtype`` config, "f32"): "f32" is
        byte-for-byte today's exact path, "bf16" a scale-free cast,
        "int8"/"fp8" carry per-(block, head) f32 scale tensors
        alongside the blocks and quantize on scatter / dequantize on
        gather.  Raises Bug when the artifact is not a causal LM."""
        import jax.numpy as jnp
        from .config import root, get as config_get
        if kv_dtype is None:
            kv_dtype = config_get(root.common.serving.kv_dtype,
                                  "f32")
        kv_dtype = check_kv_dtype(kv_dtype)
        L, H, D = self._paged_geometry()
        n, bs = int(n_blocks), int(block_size)
        sd = _kv_storage_jnp(kv_dtype)
        ks = [jnp.zeros((n, bs, H, D), sd) for _ in range(L)]
        vs = [jnp.zeros((n, bs, H, D), sd) for _ in range(L)]
        block_bytes = 2 * L * bs * H * D * _KV_ITEMSIZE[kv_dtype]
        if _KV_QMAX[kv_dtype] is not None:
            sks = [jnp.zeros((n, H), jnp.float32) for _ in range(L)]
            svs = [jnp.zeros((n, H), jnp.float32) for _ in range(L)]
            storage = (ks, vs, sks, svs)
            block_bytes += 2 * L * H * 4
        else:
            storage = (ks, vs)
        return KVBlockPool(n_blocks, block_size, storage=storage,
                           copy_fn=self._kv_copy_block,
                           kv_dtype=kv_dtype,
                           block_bytes=block_bytes)

    def _kv_copy_block(self, storage, src, dst):
        """Device-side block copy for the pool's copy-on-write (one
        jitted program per pool geometry; src/dst are traced, so
        every copy rides the same executable).  On a quantized pool
        the per-(block, head) scale rows copy WITH the codes — the
        copy is bit-exact, so a COW'd block dequantizes to exactly
        the shared original's values."""
        import jax
        ks, vs, sks, svs = _kv_unpack(storage)
        key = ("pcopy", ks[0].shape[0], ks[0].shape[1], len(ks),
               sks is not None)

        def build():
            if sks is None:
                def run(ks, vs, src, dst):
                    ks = [k.at[dst].set(k[src]) for k in ks]
                    vs = [v.at[dst].set(v[src]) for v in vs]
                    return ks, vs
                return jax.jit(run, donate_argnums=(0, 1))

            def run(ks, vs, sks, svs, src, dst):
                ks = [k.at[dst].set(k[src]) for k in ks]
                vs = [v.at[dst].set(v[src]) for v in vs]
                sks = [s.at[dst].set(s[src]) for s in sks]
                svs = [s.at[dst].set(s[src]) for s in svs]
                return ks, vs, sks, svs
            return jax.jit(run, donate_argnums=(0, 1, 2, 3))

        fn = self.compile_cache.get_or_build(key, build)
        src_dst = jax.device_put((numpy.int32(src),
                                  numpy.int32(dst)))
        if sks is None:
            return fn(ks, vs, *src_dst)
        return fn(ks, vs, sks, svs, *src_dst)

    def export_kv_blocks(self, pool, ids):
        """The addressed pool blocks as ONE host array ``(L, 2, n,
        block_size, H, D)`` f32 (k then v per layer) — the tensor the
        disaggregation wire ships (``serving.fabric.disagg`` frames
        it zero-copy via ``encode_tensor_parts``).  Quantized pools
        DEQUANTIZE on export, so the wire format is
        storage-dtype-agnostic: an int8 prefill worker can feed an
        f32 decode replica and vice versa.  The caller holds refs on
        ``ids`` (``export_prefix_blocks``) so the device rows cannot
        be reused mid-read."""
        import jax.numpy as jnp
        idx = numpy.asarray(list(ids), dtype=numpy.int32)
        ks, vs, sks, svs = _kv_unpack(pool.storage)
        out = []
        for i, (k, v) in enumerate(zip(ks, vs)):
            kb = k[idx].astype(jnp.float32)
            vb = v[idx].astype(jnp.float32)
            if sks is not None:
                kb = kb * sks[i][idx][:, None, :, None]
                vb = vb * svs[i][idx][:, None, :, None]
            out.append(numpy.stack([numpy.asarray(kb),
                                    numpy.asarray(vb)]))
        return numpy.stack(out)

    def import_kv_blocks(self, pool, ids, blocks):
        """Scatters a shipped ``(L, 2, n, block_size, H, D)`` host
        array (from :meth:`export_kv_blocks` on the peer) into THIS
        pool's storage at ``ids`` — re-quantizing with fresh
        per-(block, head) scales when this pool is int8/fp8 (the
        wire is always f32).  Produces new per-layer device tensors
        functionally, exactly like the COW copy — callers on the
        serving path route through the engine's device-thread op
        queue so the write never races a donated decode step."""
        import jax.numpy as jnp
        blocks = numpy.asarray(blocks, dtype=numpy.float32)
        idx = jnp.asarray(list(ids), dtype=jnp.int32)
        ks, vs, sks, svs = _kv_unpack(pool.storage)
        L = len(ks)
        if blocks.shape[:2] != (L, 2) or \
                blocks.shape[2] != len(ids) or \
                blocks.shape[3:] != ks[0].shape[1:]:
            raise Bug("imported KV block shape %s does not match "
                      "pool geometry (L=%d, block=%s, n=%d)" %
                      (blocks.shape, L, ks[0].shape[1:], len(ids)))
        if sks is None:
            ks = [k.at[idx].set(
                jnp.asarray(blocks[i, 0]).astype(k.dtype))
                for i, k in enumerate(ks)]
            vs = [v.at[idx].set(
                jnp.asarray(blocks[i, 1]).astype(v.dtype))
                for i, v in enumerate(vs)]
            pool.storage = (ks, vs)
            return
        qmax = _KV_QMAX[pool.kv_dtype]
        new_ks, new_vs, new_sks, new_svs = [], [], [], []
        for i in range(L):
            kb = jnp.asarray(blocks[i, 0])  # (n, bs, H, D)
            vb = jnp.asarray(blocks[i, 1])
            sk = jnp.max(jnp.abs(kb), axis=(1, 3)) / qmax  # (n, H)
            sv = jnp.max(jnp.abs(vb), axis=(1, 3)) / qmax
            qk = _kv_quantize(kb, sk[:, None, :, None],
                              pool.kv_dtype)
            qv = _kv_quantize(vb, sv[:, None, :, None],
                              pool.kv_dtype)
            new_ks.append(ks[i].at[idx].set(qk))
            new_vs.append(vs[i].at[idx].set(qv))
            new_sks.append(sks[i].at[idx].set(sk))
            new_svs.append(svs[i].at[idx].set(sv))
        pool.storage = (new_ks, new_vs, new_sks, new_svs)

    def _paged_block(self, p, x, pk, pv, tables, wblock, wslot,
                     key_mask, n_heads, attend=None, sk=None,
                     sv=None, kv_dtype="f32"):
        """One pre-LN block against the POOLED cache: the chunk's
        k/v scatter to ``(wblock, wslot)`` (physical block, in-block
        slot — per row AND per chunk position, so rows at different
        sequence positions coexist in one static-shape batch), then
        the whole table is gathered back ``(B, T·bs, H, D)`` and
        queries attend it under ``key_mask``.  Same arithmetic as
        :meth:`_cached_block` — masked slots are exact zeros after
        softmax and real keys keep their relative order, so paged
        greedy decode is bit-identical to the dense cached path.
        ``attend`` is the flag-gated flash-decode hook, exactly as
        in :meth:`_cached_block` (same mask, same zeros).

        QUANTIZED pools (``sk``/``sv``: per-(block, head) f32 scale
        tensors): the quantize happens INSIDE this scatter — the
        written blocks' scales grow monotonically (scatter-max over
        the chunk's |k|,|v| amax), only the written blocks get their
        stored codes rescaled by old/new (an untouched block's ratio
        is EXACTLY 1.0, an exact code round trip — which is why a
        shared prefix block, never written by a reader, stays
        bit-stable under COW/refcount semantics), and the chunk's
        values quantize at the grown scale.  The gather dequantizes:
        either inside the flash-decode kernel (codes + per-position
        scales feed ``attend``) or as ``codes·scale`` for the dense
        fallback einsum."""
        import jax
        import jax.numpy as jnp

        def ln(v, g, b, eps=1e-5):
            mu = v.mean(axis=-1, keepdims=True)
            var = ((v - mu) ** 2).mean(axis=-1, keepdims=True)
            return (v - mu) * jnp.reciprocal(jnp.sqrt(var + eps)) \
                * g + b

        B, S_, E = x.shape
        H = n_heads
        D = E // H
        h = ln(x, p["ln1_g"], p["ln1_b"])
        if "wqkv" in p:
            qkv = (_mm(h, p, "wqkv") +
                   p["bqkv"]).reshape(B, S_, H, 3, D)
            q, kn, vn = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        else:
            q = (_mm(h, p, "wq") + p["bq"]).reshape(B, S_, H, D)
            kn = (_mm(h, p, "wk") + p["bk"]).reshape(B, S_, H, D)
            vn = (_mm(h, p, "wv") + p["bv"]).reshape(B, S_, H, D)
        T = tables.shape[1]
        bs = pk.shape[1]
        k_scale = v_scale = None
        if sk is None:
            if pk.dtype == jnp.float32:
                # The exact plane: byte-for-byte the original path.
                pk = pk.at[wblock, wslot].set(kn)
                pv = pv.at[wblock, wslot].set(vn)
                kc = pk[tables].reshape(B, -1, H, D)
                vc = pv[tables].reshape(B, -1, H, D)
            else:
                # Scale-free cast storage (bf16).
                pk = pk.at[wblock, wslot].set(kn.astype(pk.dtype))
                pv = pv.at[wblock, wslot].set(vn.astype(pv.dtype))
                kc = pk[tables].astype(jnp.float32) \
                    .reshape(B, -1, H, D)
                vc = pv[tables].astype(jnp.float32) \
                    .reshape(B, -1, H, D)
        else:
            qmax = _KV_QMAX[kv_dtype]
            # 1. Grow the written blocks' scales (scatter-max; all
            #    pad writes land on the trash block, whose content
            #    is junk by contract).
            amax_k = jnp.max(jnp.abs(kn), axis=-1) / qmax  # (B,S_,H)
            amax_v = jnp.max(jnp.abs(vn), axis=-1) / qmax
            sk_new = sk.at[wblock].max(amax_k)
            sv_new = sv.at[wblock].max(amax_v)
            # 2. Rescale ONLY the written blocks' existing codes by
            #    old/new.  Duplicate wblock entries (chunk positions
            #    in one block) write identical rescaled rows, so the
            #    scatter collision is benign.
            rk = sk / jnp.where(sk_new > 0.0, sk_new, 1.0)
            rv = sv / jnp.where(sv_new > 0.0, sv_new, 1.0)
            old_k = pk[wblock].astype(jnp.float32) * \
                rk[wblock][:, :, None, :, None]
            old_v = pv[wblock].astype(jnp.float32) * \
                rv[wblock][:, :, None, :, None]
            if kv_dtype == "int8":
                old_k = jnp.round(old_k)
                old_v = jnp.round(old_v)
            pk = pk.at[wblock].set(old_k.astype(pk.dtype))
            pv = pv.at[wblock].set(old_v.astype(pv.dtype))
            # 3. Quantize the chunk's k/v at the grown scale and
            #    scatter the codes.
            pk = pk.at[wblock, wslot].set(_kv_quantize(
                kn, sk_new[wblock][..., None], kv_dtype))
            pv = pv.at[wblock, wslot].set(_kv_quantize(
                vn, sv_new[wblock][..., None], kv_dtype))
            sk, sv = sk_new, sv_new
            # 4. Gather codes + per-position scales; the dequant
            #    rides the attend kernel when it engages, else the
            #    dense fallback below.
            kc = pk[tables].reshape(B, -1, H, D)
            vc = pv[tables].reshape(B, -1, H, D)
            k_scale = jnp.broadcast_to(
                sk[tables][:, :, None, :],
                (B, T, bs, H)).reshape(B, -1, H)
            v_scale = jnp.broadcast_to(
                sv[tables][:, :, None, :],
                (B, T, bs, H)).reshape(B, -1, H)
        attn = attend(q, kc, vc, key_mask, k_scale=k_scale,
                      v_scale=v_scale) if attend is not None \
            else None
        if attn is None:
            if k_scale is not None:
                kc = kc.astype(jnp.float32) * k_scale[..., None]
                vc = vc.astype(jnp.float32) * v_scale[..., None]
            scores = jnp.einsum(
                "bqhd,bkhd->bqhk", q, kc,
                preferred_element_type=jnp.float32) / (D ** 0.5)
            scores = jnp.where(key_mask[:, :, None, :], scores,
                               -1e30)
            w = jax.nn.softmax(scores, axis=-1)
            attn = jnp.einsum("bqhk,bkhd->bqhd", w, vc)
        x = x + _mm(attn.reshape(B, S_, E), p, "wo") + p["bo"]
        h = ln(x, p["ln2_g"], p["ln2_b"])
        x = x + _mm(jnp.maximum(_mm(h, p, "w1") + p["b1"], 0.0),
                    p, "w2") + p["b2"]
        return x.astype(jnp.float32), pk, pv, sk, sv

    def _paged_lm_static(self):
        """Static geometry of the paged programs: (n_heads per block,
        positional-table size, vocab size).  The weight VALUES arrive
        per call through :meth:`_lm_params`."""
        emb, blocks, _head = self._lm_chain()
        n_heads = [int(e["config"]["n_heads"]) for e in blocks]
        P = int(self.weights[emb["params"]["pos"]].shape[0])
        V = int(self.weights[emb["params"]["weights"]].shape[0])
        return n_heads, P, V

    @staticmethod
    def _paged_storage_args(pool):
        """The storage leaves of a pool as jitted-program positional
        args, plus whether the pool is scaled-quantized — the shared
        unpack of every paged entry point."""
        ks, vs, sks, svs = _kv_unpack(pool.storage)
        if sks is None:
            return (ks, vs), False
        return (ks, vs, sks, svs), True

    def _build_paged_extend(self, Sc, T, block_size,
                            kv_dtype="f32"):
        """Jitted chunk prefill/extension against the block pool:
        each row's ``chunk_len`` real tokens (right-padded to the
        ``Sc`` bucket) are embedded at logical positions ``prior +
        i``, their k/v scattered into the row's table blocks, and
        the chunk attends the pool causally over absolute positions
        — ``prior = 0`` is a fresh prefill, ``prior = k·bs`` extends
        a shared prefix of k cached blocks, and a single-token chunk
        at ``prior = len-1`` re-derives the first logits of a fully
        prefix-cached prompt.  Returns the sampled first generated
        token per row (PRNG fold index 0, matching the bucketed
        path's stream)."""
        import jax
        import jax.numpy as jnp
        n_heads, P, V = self._paged_lm_static()
        bs = int(block_size)
        S_keys = T * bs

        def logits_of(params, x_last):
            return _head_logits(x_last, params["head_w"],
                                params["head_b"],
                                params.get("head_w__s"))

        sample_rows = _sample_rows
        att = self._decode_attend()
        quantized = _KV_QMAX[kv_dtype] is not None

        def run(params, pks, pvs, sks, svs, tables, tokens, prior,
                chunk_len, temps, seeds):
            B = tables.shape[0]
            keys0 = jax.vmap(jax.random.PRNGKey)(seeds)
            offs = jnp.arange(Sc)
            # Logical positions (clipped: pad columns past the table
            # read junk that is never unmasked).
            posn = jnp.clip(prior[:, None] + offs[None, :], 0, P - 1)
            t = jnp.clip(tokens.astype(jnp.int32), 0, V - 1)
            x = params["emb_w"][t] + \
                jnp.take(params["emb_pos"], posn, axis=0)
            wpos = jnp.clip(prior[:, None] + offs[None, :], 0,
                            S_keys - 1)
            wblock = jnp.take_along_axis(tables, wpos // bs, axis=1)
            # Pad columns past each row's true chunk write to the
            # TRASH block explicitly: tables now cover exactly the
            # row's real span (lazy allocation), so the positional
            # clip above can land a junk column ON a real slot —
            # and a scatter collision with a real write is
            # update-order-undefined.
            wblock = jnp.where(offs[None, :] < chunk_len[:, None],
                               wblock, KVBlockPool.TRASH)
            wslot = wpos % bs
            qpos = prior[:, None] + offs[None, :]
            key_mask = (jnp.arange(S_keys)[None, None, :] <=
                        qpos[:, :, None])
            new_pks, new_pvs, new_sks, new_svs = [], [], [], []
            for i, (pk, pv, p, H) in enumerate(
                    zip(pks, pvs, params["blocks"], n_heads)):
                x, pk, pv, sk, sv = self._paged_block(
                    p, x, pk, pv, tables, wblock, wslot, key_mask, H,
                    attend=att, sk=sks[i] if quantized else None,
                    sv=svs[i] if quantized else None,
                    kv_dtype=kv_dtype)
                new_pks.append(pk)
                new_pvs.append(pv)
                new_sks.append(sk)
                new_svs.append(sv)
            idx = jnp.clip(chunk_len - 1, 0, Sc - 1)
            first_logits = logits_of(params, x[jnp.arange(B), idx])
            tok0 = sample_rows(
                first_logits,
                jax.vmap(lambda k: jax.random.fold_in(k, 0))(keys0),
                temps)
            return new_pks, new_pvs, new_sks, new_svs, tok0

        return jax.jit(run, donate_argnums=(1, 2, 3, 4))

    def _build_paged_step(self, T, block_size, kv_dtype="f32"):
        """Jitted one-token decode step over the block pool: each
        row feeds its previous token at position ``pos`` (k/v
        scattered to table block ``pos // bs``, slot ``pos % bs``),
        attends positions 0..pos through the gathered table, and
        samples the next token with PRNG fold index ``gen_idx`` —
        the same per-row stream as ``generate_bucketed``.  Rows of
        DIFFERENT requests, lengths, and ages share one call; pad
        rows carry all-trash tables and scatter junk into block 0."""
        import jax
        import jax.numpy as jnp
        n_heads, P, V = self._paged_lm_static()
        bs = int(block_size)
        S_keys = T * bs

        def logits_of(params, x_last):
            return _head_logits(x_last, params["head_w"],
                                params["head_b"],
                                params.get("head_w__s"))

        sample_rows = _sample_rows
        att = self._decode_attend()
        quantized = _KV_QMAX[kv_dtype] is not None

        def run(params, pks, pvs, sks, svs, tables, pos, tok,
                gen_idx, temps, seeds):
            keys0 = jax.vmap(jax.random.PRNGKey)(seeds)
            posn = jnp.clip(pos, 0, P - 1)
            x = params["emb_w"][jnp.clip(tok, 0, V - 1)][:, None] + \
                jnp.take(params["emb_pos"], posn, axis=0)[:, None]
            wpos = jnp.clip(pos, 0, S_keys - 1)
            wblock = jnp.take_along_axis(
                tables, (wpos // bs)[:, None], axis=1)
            wslot = (wpos % bs)[:, None]
            key_mask = (jnp.arange(S_keys)[None, None, :] <=
                        pos[:, None, None])
            new_pks, new_pvs, new_sks, new_svs = [], [], [], []
            for i, (pk, pv, p, H) in enumerate(
                    zip(pks, pvs, params["blocks"], n_heads)):
                x, pk, pv, sk, sv = self._paged_block(
                    p, x, pk, pv, tables, wblock, wslot, key_mask, H,
                    attend=att, sk=sks[i] if quantized else None,
                    sv=svs[i] if quantized else None,
                    kv_dtype=kv_dtype)
                new_pks.append(pk)
                new_pvs.append(pv)
                new_sks.append(sk)
                new_svs.append(sv)
            logits = logits_of(params, x[:, 0])
            tok_new = sample_rows(
                logits, jax.vmap(jax.random.fold_in)(keys0, gen_idx),
                temps)
            return new_pks, new_pvs, new_sks, new_svs, tok_new

        return jax.jit(run, donate_argnums=(1, 2, 3, 4))

    def _build_paged_verify(self, K, T, block_size,
                            kv_dtype="f32"):
        """Jitted speculative-verify step over the block pool: each
        row feeds its current token PLUS ``K`` draft tokens as one
        ``K+1``-position chunk at positions ``pos..pos+K`` (k/v
        scattered through the table exactly like a prefill chunk),
        attends the pool under the per-position causal mask, and
        SAMPLES the target's token at EVERY chunk position — column
        ``j`` with PRNG fold index ``gen_idx + j``, the same per-row
        stream ``_build_paged_step`` would use at that generation
        index.  The caller compares column ``j``'s output against
        draft ``j+1`` host-side: the longest matching prefix is
        accepted and the first non-matching output is the bonus
        token, so greedy decode is BIT-IDENTICAL to the plain step
        loop (argmax over the same logits) and sampled decode draws
        the SAME stream the non-speculative path is the oracle for —
        for the deterministic drafters this is exactly the
        Leviathan accept/residual rule (accept draft x with
        probability p(x); on rejection the emitted token is p
        conditioned on != x).  Junk columns past a row's true draft
        count (``dlens``) scatter to the TRASH block — tables cover
        exactly the verify span under lazy allocation, so letting a
        clipped junk write land beside (or scatter-collide with) a
        real slot would corrupt the cache."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        n_heads, P, V = self._paged_lm_static()
        bs = int(block_size)
        S_keys = T * bs
        Sq = int(K) + 1

        def logits_of(params, x_last):
            return _head_logits(x_last, params["head_w"],
                                params["head_b"],
                                params.get("head_w__s"))

        sample_rows = _sample_rows
        att = self._decode_attend()
        quantized = _KV_QMAX[kv_dtype] is not None

        def run(params, pks, pvs, sks, svs, tables, pos, toks,
                dlens, gen_idx, temps, seeds):
            keys0 = jax.vmap(jax.random.PRNGKey)(seeds)
            offs = jnp.arange(Sq)
            posn = jnp.clip(pos[:, None] + offs[None, :], 0, P - 1)
            x = params["emb_w"][jnp.clip(toks, 0, V - 1)] + \
                jnp.take(params["emb_pos"], posn, axis=0)
            wpos = jnp.clip(pos[:, None] + offs[None, :], 0,
                            S_keys - 1)
            wblock = jnp.take_along_axis(tables, wpos // bs, axis=1)
            # Column 0 is the row's current token, columns 1..dlen
            # its drafts; pad columns write to trash (see
            # _build_paged_extend — a clipped junk write colliding
            # with a real one is scatter-order-undefined).
            wblock = jnp.where(offs[None, :] <= dlens[:, None],
                               wblock, KVBlockPool.TRASH)
            wslot = wpos % bs
            qpos = pos[:, None] + offs[None, :]
            key_mask = (jnp.arange(S_keys)[None, None, :] <=
                        qpos[:, :, None])
            new_pks, new_pvs, new_sks, new_svs = [], [], [], []
            for i, (pk, pv, p, H) in enumerate(
                    zip(pks, pvs, params["blocks"], n_heads)):
                x, pk, pv, sk, sv = self._paged_block(
                    p, x, pk, pv, tables, wblock, wslot, key_mask, H,
                    attend=att, sk=sks[i] if quantized else None,
                    sv=svs[i] if quantized else None,
                    kv_dtype=kv_dtype)
                new_pks.append(pk)
                new_pvs.append(pv)
                new_sks.append(sk)
                new_svs.append(sv)
            logits = logits_of(params, x)  # (B, Sq, V)
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

            def drawn(_):
                # Per-column PRNG streams, exactly the plain step's
                # folds — only materialized when some row actually
                # samples (Sq categorical draws are a measurable
                # slice of the verify budget under greedy traffic,
                # and greedy IS argmax: _sample_rows would discard
                # the draw anyway).
                outs = []
                for j in range(Sq):
                    keys_j = jax.vmap(jax.random.fold_in)(
                        keys0, gen_idx + j)
                    outs.append(sample_rows(logits[:, j], keys_j,
                                            temps))
                return jnp.stack(outs, axis=1)

            out = lax.cond(jnp.any(temps > 0.0), drawn,
                           lambda _: greedy, None)
            return new_pks, new_pvs, new_sks, new_svs, out

        return jax.jit(run, donate_argnums=(1, 2, 3, 4))

    def paged_verify(self, pool, tables, pos, toks, draft_lens,
                     gen_idx, temps, seeds):
        """Speculative verify entry point for the serving engine:
        ``toks`` (B, K+1) holds each row's current token followed by
        up to K draft tokens (``draft_lens`` true counts); returns
        the (B, K+1) TARGET tokens (column j sampled with PRNG fold
        ``gen_idx + j``).  The caller accepts the longest prefix
        where draft j+1 equals output j and feeds the output at the
        first mismatch as the bonus token.  Compiles once per
        (B, K, T, n_blocks, block_size) — pool geometry and the
        decode-kernel mode ride the key like every paged program."""
        import jax
        tables = numpy.ascontiguousarray(tables, dtype=numpy.int32)
        toks = numpy.ascontiguousarray(toks, dtype=numpy.int32)
        B, T = tables.shape
        Sq = toks.shape[1]
        fn = self.compile_cache.get_or_build(
            ("pver", B, Sq, T, pool.n_blocks, pool.block_size,
             pool.kv_dtype, self._decode_weight_mode(),
             self._decode_kernel_mode()),
            lambda: self._build_paged_verify(Sq - 1, T,
                                             pool.block_size,
                                             pool.kv_dtype))
        store, quantized = self._paged_storage_args(pool)
        # Explicit upload — see paged_extend (strict_step contract).
        args = jax.device_put((
            tables,
            numpy.ascontiguousarray(pos, dtype=numpy.int32),
            toks,
            numpy.ascontiguousarray(draft_lens, dtype=numpy.int32),
            numpy.ascontiguousarray(gen_idx, dtype=numpy.int32),
            numpy.ascontiguousarray(temps, dtype=numpy.float32),
            numpy.ascontiguousarray(seeds, dtype=numpy.uint32)))
        ks, vs, sks, svs, out = fn(
            self._lm_params(), store[0], store[1],
            store[2] if quantized else None,
            store[3] if quantized else None, *args)
        pool.storage = (ks, vs, sks, svs) if quantized else (ks, vs)
        return numpy.asarray(out)

    def paged_extend(self, pool, tables, tokens, prior, chunk_lens,
                     temps, seeds):
        """Prefill/extend entry point for the serving engine:
        ``tables`` (B, T) int32 block tables (trash-padded),
        ``tokens`` (B, Sc) right-padded chunk tokens, ``prior`` (B,)
        cached positions per row, ``chunk_lens`` (B,) real chunk
        lengths.  Updates ``pool.storage`` in place (donated on
        accelerators) and returns the (B,) first generated tokens.
        Compiles once per (B, Sc, T, n_blocks, block_size) — POOL
        GEOMETRY IS PART OF THE KEY: resizing the pool or its blocks
        must never serve a stale program."""
        import jax
        tables = numpy.ascontiguousarray(tables, dtype=numpy.int32)
        tokens = numpy.ascontiguousarray(tokens, dtype=numpy.int32)
        B, T = tables.shape
        Sc = tokens.shape[1]
        fn = self.compile_cache.get_or_build(
            ("pext", B, Sc, T, pool.n_blocks, pool.block_size,
             pool.kv_dtype, self._decode_weight_mode(),
             self._decode_kernel_mode()),
            lambda: self._build_paged_extend(Sc, T, pool.block_size,
                                             pool.kv_dtype))
        store, quantized = self._paged_storage_args(pool)
        # EXPLICIT upload of the per-call host arrays: the serving
        # decode loop runs under analysis.runtime.strict_step, where
        # an implicit numpy→device transfer at dispatch raises.
        args = jax.device_put((
            tables, tokens,
            numpy.ascontiguousarray(prior, dtype=numpy.int32),
            numpy.ascontiguousarray(chunk_lens, dtype=numpy.int32),
            numpy.ascontiguousarray(temps, dtype=numpy.float32),
            numpy.ascontiguousarray(seeds, dtype=numpy.uint32)))
        ks, vs, sks, svs, tok0 = fn(
            self._lm_params(), store[0], store[1],
            store[2] if quantized else None,
            store[3] if quantized else None, *args)
        pool.storage = (ks, vs, sks, svs) if quantized else (ks, vs)
        return numpy.asarray(tok0)

    def paged_step(self, pool, tables, pos, tok, gen_idx, temps,
                   seeds):
        """One decode step for the engine's continuous batch: every
        active row advances one token through the pool.  Compiles
        once per (B, T, n_blocks, block_size)."""
        import jax
        tables = numpy.ascontiguousarray(tables, dtype=numpy.int32)
        B, T = tables.shape
        fn = self.compile_cache.get_or_build(
            ("pstep", B, T, pool.n_blocks, pool.block_size,
             pool.kv_dtype, self._decode_weight_mode(),
             self._decode_kernel_mode()),
            lambda: self._build_paged_step(T, pool.block_size,
                                           pool.kv_dtype))
        store, quantized = self._paged_storage_args(pool)
        # Explicit upload — see paged_extend (strict_step contract).
        args = jax.device_put((
            tables,
            numpy.ascontiguousarray(pos, dtype=numpy.int32),
            numpy.ascontiguousarray(tok, dtype=numpy.int32),
            numpy.ascontiguousarray(gen_idx, dtype=numpy.int32),
            numpy.ascontiguousarray(temps, dtype=numpy.float32),
            numpy.ascontiguousarray(seeds, dtype=numpy.uint32)))
        ks, vs, sks, svs, tok_new = fn(
            self._lm_params(), store[0], store[1],
            store[2] if quantized else None,
            store[3] if quantized else None, *args)
        pool.storage = (ks, vs, sks, svs) if quantized else (ks, vs)
        return numpy.asarray(tok_new)

    @staticmethod
    def _jax_pool(t, cfg, x):
        import jax.numpy as jnp
        from jax import lax
        ky, kx = int(cfg["ky"]), int(cfg["kx"])
        sh, sw = cfg["sliding"]
        (pt, pb), (pl, pr) = cfg["padding"]
        H, W = x.shape[1], x.shape[2]
        out_h = -(-(H + pt + pb - ky) // sh) + 1
        out_w = -(-(W + pl + pr - kx) // sw) + 1
        need_h = (out_h - 1) * sh + ky - (H + pt)
        need_w = (out_w - 1) * sw + kx - (W + pl)
        pad = ((0, 0), (pt, max(pb, need_h)),
               (pl, max(pr, need_w)), (0, 0))
        dims, strides = (1, ky, kx, 1), (1, sh, sw, 1)
        if t == "avg_pooling":
            ssum = lax.reduce_window(x, 0.0, lax.add, dims, strides,
                                     pad)
            cnt = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add,
                                    dims, strides, pad)
            return ssum / cnt
        if t == "maxabs_pooling":
            hi = lax.reduce_window(x, -jnp.inf, lax.max, dims,
                                   strides, pad)
            lo = lax.reduce_window(x, jnp.inf, lax.min, dims,
                                   strides, pad)
            return jnp.where(-lo > hi, lo, hi)
        return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides,
                                 pad)


def _np_softmax(v):
    e = numpy.exp(v - v.max(axis=-1, keepdims=True))
    return e / e.sum(axis=-1, keepdims=True)


#: Activation per dense-family unit type (shared by the numpy mirror
#: and the jax serving chain).
_DENSE_ACT = {
    "all2all": "linear", "all2all_tanh": "tanh",
    "all2all_relu": "softplus", "all2all_str": "str",
    "all2all_sigmoid": "sigmoid", "softmax": "softmax",
    "rbm": "sigmoid",
    "all2all_deconv": "linear",
    "all2all_deconv_sigmoid": "sigmoid",
    "all2all_deconv_tanh": "tanh",
}


_ACTS = {
    "linear": lambda v: v,
    "tanh": lambda v: TANH_A * numpy.tanh(TANH_B * v),
    "softplus": lambda v: numpy.log1p(numpy.exp(-numpy.abs(v))) +
    numpy.maximum(v, 0.0),
    "str": lambda v: numpy.maximum(v, 0.0),
    "sigmoid": lambda v: 1.0 / (1.0 + numpy.exp(-v)),
    "softmax": _np_softmax,
}


def _jax_act(name, v):
    import jax
    import jax.numpy as jnp
    return {
        "linear": lambda u: u,
        "tanh": lambda u: TANH_A * jnp.tanh(TANH_B * u),
        "softplus": jax.nn.softplus,
        "str": jax.nn.relu,
        "sigmoid": jax.nn.sigmoid,
        "softmax": lambda u: jax.nn.softmax(u, axis=-1),
    }[name](v)
