"""Interactive shell unit.

Capability parity with the reference interaction unit (reference:
veles/interaction.py:49 ``Shell`` — an IPython shell embedded as a
workflow unit, firing wherever it is linked so the user can inspect
and mutate live state between ticks; notebook usage ran the reactor
in a background thread, launcher.py:556-563).

TPU-era form: IPython when importable, stdlib
``code.InteractiveConsole`` otherwise — both see ``workflow``,
``launcher``, ``units`` (name → unit) and numpy in their namespace.
``commands=[...]`` executes a scripted list instead of reading stdin
(automation + tests); ``once=True`` drops the shell after its first
firing.
"""

import code

import numpy

from .units import Unit


class Shell(Unit):
    """Embedded interactive shell (reference: interaction.py:49)."""

    def __init__(self, workflow, **kwargs):
        super(Shell, self).__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.commands = kwargs.get("commands")
        self.once = kwargs.get("once", False)
        self.banner = kwargs.get(
            "banner", "veles_tpu shell — `workflow`, `launcher`, "
                      "`units`, `numpy` are in scope; ^D resumes "
                      "the run")
        self._fired = False

    def namespace(self):
        wf = self.workflow
        return {
            "workflow": wf,
            "launcher": getattr(wf, "launcher", None),
            "units": {u.name: u for u in wf.units},
            "numpy": numpy,
        }

    def run(self):
        if self.once and self._fired:
            return
        self._fired = True
        ns = self.namespace()
        if self.commands is not None:
            console = code.InteractiveConsole(ns)
            for command in self.commands:
                console.push(command)
            return
        try:
            from IPython import embed
            embed(user_ns=ns, banner1=self.banner,
                  colors="neutral")
        except ImportError:
            code.interact(banner=self.banner, local=ns)
