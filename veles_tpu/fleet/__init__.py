"""Elastic fleet membership: epoch-numbered join/leave events and the
one scheduler every job kind (training lineages, eval ticks, warm
serving replicas, respawns) places through.  See
:mod:`veles_tpu.fleet.scheduler`.
"""

from .scheduler import (  # noqa: F401
    FleetScheduler,
    live_fleet_summary,
    wire_mesh_rebuild,
)
