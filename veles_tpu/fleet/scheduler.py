"""Fleet-wide membership accounting and placement.

Membership change is a NORMAL event here, not an exception path: every
join and leave bumps a monotonically increasing **membership epoch**
that downstream consumers key off — ``rebuild_mesh`` stamps the epoch
on the workflow when it re-forms the device mesh, the launcher
heartbeat ships it in the ``fleet`` section, and ``web_status`` /
``GET /metrics`` expose it as the ``membership.epoch`` gauge.  An
operator (or a test) can therefore answer "did the fleet change shape,
and when?" without diffing worker logs.

:class:`FleetScheduler` also unifies the three bespoke placement
policies that grew independently across the control plane:

* rank assignment for joiners — the server's lowest-free-shard-rank
  rule (:meth:`FleetScheduler.lowest_free_rank`);
* affinity scheduling — the population engine's
  affinity-first / fresh-next / steal-oldest member pick
  (:meth:`FleetScheduler.pick_affine`), which keeps a lineage's ticks
  on the worker already holding its synced base so jobs ride the
  delta plane instead of a full ship;
* respawn/replica placement — the launcher's least-loaded-node rule
  (:meth:`FleetScheduler.least_loaded`).

Training lineages, eval ticks, and warm serving replicas all flow
through the same primitives, so "which worker should take this?" has
one answer per policy rather than one per subsystem.

Leaves are classified: a **drain** (the worker finished its in-flight
job, shipped the update, and said ``bye`` — planned preemption, scale
down) versus a **drop** (crash, hang, dead peer).  The distinction is
what makes preemption cheap: a drained leave requeues nothing, so the
tick order — and therefore the bit-parity trajectory — is preserved
across a fleet walk.

Counters (``resilience.stats``): ``fleet.join``, ``fleet.leave``,
``fleet.drain``.  Gauges (process metrics registry):
``membership.epoch``, ``fleet.size``.
"""

import threading
import time
import weakref

from collections import deque

from .. import resilience


#: Live schedulers in this process, feeding the launcher-heartbeat
#: "fleet" section and the web_status fleet row (mirrors the
#: population engine's live-master registry).
_LIVE_SCHEDULERS = weakref.WeakSet()


def live_fleet_summary():
    """Aggregate across this process's live fleet schedulers for the
    heartbeat ``fleet`` section, or None when no membership event has
    happened yet (a quiet section beats a row of zeros)."""
    scheds = [s for s in list(_LIVE_SCHEDULERS) if s.epoch > 0]
    if not scheds:
        return None
    out = {"schedulers": len(scheds), "epoch": 0, "size": 0,
           "joins": 0, "leaves": 0, "drains": 0}
    last = None
    for sched in scheds:
        snap = sched.snapshot()
        out["epoch"] = max(out["epoch"], snap["epoch"])
        out["size"] += snap["size"]
        out["joins"] += snap["joins"]
        out["leaves"] += snap["leaves"]
        out["drains"] += snap["drains"]
        if snap.get("last_event") is not None:
            if last is None or snap["last_event"][0] > last[0]:
                last = snap["last_event"]
    if last is not None:
        out["last_event"] = list(last)
    return out


class FleetScheduler(object):
    """Epoch-numbered membership registry + shared placement policy.

    Thread-safe: the server's per-slave threads call :meth:`join` /
    :meth:`leave` concurrently with heartbeat snapshots.  The
    placement primitives are static — they encode policy, not state —
    so subsystems with their own bookkeeping (the population master's
    member table, the launcher's process table) can reuse the policy
    without adopting this registry.
    """

    #: Event-ring depth: enough to reconstruct a full chaos-soak walk
    #: from the heartbeat, small enough to ship in every beat.
    MAX_EVENTS = 64

    def __init__(self):
        self._lock = threading.Lock()
        self.epoch = 0
        self.members = {}  # sid -> {"mid", "power", "joined", "epoch"}
        self.joins = 0
        self.leaves = 0
        self.drains = 0
        self.events = deque(maxlen=self.MAX_EVENTS)
        self._epoch_callbacks = []  # guarded-by: _lock
        _LIVE_SCHEDULERS.add(self)

    # -- membership --------------------------------------------------------

    def on_epoch_change(self, callback):
        """Subscribes ``callback(epoch, event, sid)`` to every
        membership-epoch bump (``event`` is ``"join"`` / ``"drain"``
        / ``"drop"``).  Callbacks fire on the thread that caused the
        bump, OUTSIDE the scheduler lock — a subscriber may call back
        into the scheduler (snapshot, placement) but must do its own
        serialization for anything heavier.  This is how the SPMD
        mesh layer follows the fleet without caller wiring: see
        :func:`wire_mesh_rebuild`."""
        with self._lock:
            self._epoch_callbacks.append(callback)

    def _notify_epoch(self, epoch, event, sid):
        with self._lock:
            callbacks = list(self._epoch_callbacks)
        for cb in callbacks:
            try:
                cb(epoch, event, sid)
            except Exception:
                import logging
                logging.getLogger("FleetScheduler").exception(
                    "epoch-change callback failed (epoch %d %s %s)",
                    epoch, event, sid)

    def join(self, sid, mid=None, power=1.0):
        """Admits ``sid``; returns the new membership epoch."""
        with self._lock:
            self.epoch += 1
            self.joins += 1
            self.members[sid] = {
                "mid": mid, "power": power,
                "joined": time.time(), "epoch": self.epoch}
            self.events.append((self.epoch, "join", sid))
            epoch = self.epoch
        resilience.stats.incr("fleet.join")
        self._publish_gauges()
        self._notify_epoch(epoch, "join", sid)
        return epoch

    def leave(self, sid, clean=False):
        """Retires ``sid``; returns the new membership epoch.

        ``clean`` marks a drain (orderly ``bye``) rather than a drop;
        an sid that never joined (handshake died before admission)
        leaves the epoch untouched.
        """
        with self._lock:
            if self.members.pop(sid, None) is None:
                return self.epoch
            self.epoch += 1
            self.leaves += 1
            if clean:
                self.drains += 1
            self.events.append(
                (self.epoch, "drain" if clean else "drop", sid))
            epoch = self.epoch
        resilience.stats.incr("fleet.leave")
        if clean:
            resilience.stats.incr("fleet.drain")
        self._publish_gauges()
        self._notify_epoch(epoch, "drain" if clean else "drop", sid)
        return epoch

    @property
    def size(self):
        return len(self.members)

    def snapshot(self):
        """The heartbeat ``fleet`` section payload."""
        with self._lock:
            out = {"epoch": self.epoch, "size": len(self.members),
                   "joins": self.joins, "leaves": self.leaves,
                   "drains": self.drains}
            if self.events:
                out["last_event"] = tuple(self.events[-1])
        return out

    def _publish_gauges(self):
        """membership.* / fleet.* gauges in the process metrics
        registry (scraped on /metrics; docs/observability.md)."""
        from ..observability import metrics
        reg = metrics.registry
        with self._lock:
            reg.gauge("membership.epoch").set(self.epoch)
            reg.gauge("fleet.size").set(len(self.members))

    # -- placement policy (stateless, shared) ------------------------------

    @staticmethod
    def lowest_free_rank(world, held):
        """The lowest shard rank in ``range(world)`` not in ``held``,
        or None when every rank is taken (the joiner replicates a
        full shard set instead of extending it).  This is the
        server's ZeRO rank-assignment rule for joiners: ranks vacated
        by leavers are refilled first, so shard coverage heals before
        it grows."""
        taken = set(held)
        for rank in range(world):
            if rank not in taken:
                return rank
        return None

    @staticmethod
    def pick_affine(candidates, worker, affinity_of, age_of):
        """Affinity-first placement over ``candidates``:

        1. a candidate whose affinity is ``worker`` — the one served
           longest ago (its synced base already lives there: the job
           ships as a delta, not a full ship);
        2. else a fresh candidate (no affinity yet) — first in order;
        3. else steal the stalest candidate overall (its old worker
           is busy or gone; locality lost, progress preserved).

        Returns None when ``candidates`` is empty.
        """
        candidates = list(candidates)
        if not candidates:
            return None
        affine = [c for c in candidates if affinity_of(c) == worker]
        if affine:
            return min(affine, key=age_of)
        fresh = [c for c in candidates if affinity_of(c) is None]
        if fresh:
            return fresh[0]
        return min(candidates, key=age_of)

    @staticmethod
    def least_loaded(items, load_of):
        """The item with the smallest load (ties: first in order) —
        the launcher's respawn/replica placement rule.  Returns None
        when ``items`` is empty."""
        items = list(items)
        if not items:
            return None
        return min(items, key=load_of)

    def __repr__(self):
        return "FleetScheduler(epoch=%d, size=%d)" % (
            self.epoch, len(self.members))


def wire_mesh_rebuild(scheduler, workflow, rebuild=None):
    """Auto-wires SPMD mesh rebuilds to fleet membership epochs — the
    remaining half of ROADMAP item 5's plumbing: today ``rebuild_mesh``
    is called explicitly by whoever noticed the fleet changed; after
    this call it follows the scheduler's epoch bumps directly.

    Exactly ONE rebuild fires per epoch bump (re-entrant joins/leaves
    from inside a rebuild are deduped by epoch number), each stamped
    with the epoch that caused it so ``workflow._membership_epoch_``
    and ``membership.epoch`` agree.  ``rebuild`` is injectable for
    tests; it defaults to :func:`veles_tpu.parallel.mesh.rebuild_mesh`.
    Returns the subscribed callback (handy for asserting wiring)."""
    if rebuild is None:
        from ..parallel.mesh import rebuild_mesh as rebuild

    state = {"last": scheduler.epoch}
    state_lock = threading.Lock()

    def _on_epoch(epoch, event, sid):
        with state_lock:
            if epoch <= state["last"]:
                return
            state["last"] = epoch
        rebuild(workflow, epoch=epoch)

    scheduler.on_epoch_change(_on_epoch)
    return _on_epoch
