"""Web status dashboard.

Capability parity with the reference status server (reference:
veles/web_status.py:113-243 — Tornado server receiving master status
POSTs from launcher heartbeats launcher.py:853-886, UI listing running
workflows + their workers, ``/service`` pause/resume commands):
a stdlib ThreadingHTTPServer with

* ``POST /update`` — launchers post heartbeat JSON; the response
  carries any queued commands for that master (the command round-trip
  rides the heartbeat instead of a callback socket — no inbound
  connection to the master needed);
* ``GET /`` — HTML dashboard of running workflows and their workers;
* ``GET /api/status`` — the raw JSON;
* ``POST /service`` — queue ``pause``/``resume`` (optionally
  per-worker) for a master.

Stale masters (no heartbeat for ``expiry`` seconds) are dropped, the
reference's garbage-collection behavior.
"""

import html
import json
import threading
import time

from .http_common import JsonHttpServer, JsonRequestHandler

_PAGE = """<!DOCTYPE html>
<html><head><title>veles_tpu status</title>
<meta http-equiv="refresh" content="5">
<style>
body {{ font-family: sans-serif; margin: 2em; }}
table {{ border-collapse: collapse; }}
td, th {{ border: 1px solid #999; padding: 4px 10px; }}
th {{ background: #eee; }}
.dead {{ color: #999; }}
</style></head>
<body><h1>veles_tpu — running workflows</h1>
{rows}
<p>{count} master(s); page refreshes every 5 s.</p>
</body></html>"""


class WebStatusServer(JsonHttpServer):
    """The dashboard server (reference: web_status.py:113)."""

    def __init__(self, host="127.0.0.1", port=8090, expiry=30.0,
                 token=None):
        self.expiry = expiry
        self.token = token
        self._masters = {}  # id -> {payload, received}
        self._commands = {}  # id -> [command dicts]
        self._lock = threading.Lock()

        class Handler(JsonRequestHandler):
            def do_GET(self):
                outer = self.outer
                if self.path in ("/", "/index.html"):
                    self.reply(200, outer.render_page(),
                               "text/html")
                elif self.path == "/api/status":
                    self.reply(200, outer.status())
                elif self.path == "/metrics":
                    from .observability.metrics import CONTENT_TYPE
                    self.reply(200, outer.metrics_text(),
                               CONTENT_TYPE)
                else:
                    self.reply(404, {"error": "not found"})

            def do_POST(self):
                outer = self.outer
                if outer.token is not None and \
                        not self.check_token(outer.token):
                    self.reply(403, {"error": "bad token"})
                    return
                try:
                    payload = self.read_json()
                except ValueError:
                    self.reply(400, {"error": "bad json"})
                    return
                if self.path == "/update":
                    self.reply(200,
                               {"commands": outer.update(payload)})
                elif self.path == "/service":
                    try:
                        outer.queue_command(payload)
                        self.reply(200, {"status": "queued"})
                    except KeyError as e:
                        self.reply(400, {"error": str(e)})
                else:
                    self.reply(404, {"error": "not found"})

        super(WebStatusServer, self).__init__(
            Handler, host=host, port=port,
            thread_name="veles-web-status")

    # -- state -------------------------------------------------------------

    def update(self, payload):
        """Records a heartbeat; returns + clears queued commands.
        Launchers ship heavy static sections (graph, plots) only when
        new or changed — missing sections carry over from the
        previous beat."""
        mid = payload.get("id")
        if not mid:
            return []
        with self._lock:
            prev = self._masters.get(mid)
            if prev is not None:
                for section in ("graph", "plots"):
                    if section not in payload and \
                            section in prev["payload"]:
                        payload[section] = prev["payload"][section]
            self._masters[mid] = {"payload": payload,
                                  "received": time.time()}
            self._gc_locked()
            return self._commands.pop(mid, [])

    def queue_command(self, payload):
        mid = payload["master"]
        command = payload["command"]
        if command not in ("pause", "resume", "stop"):
            raise KeyError("unknown command %r" % command)
        with self._lock:
            if mid not in self._masters:
                raise KeyError("unknown master %r" % mid)
            self._commands.setdefault(mid, []).append(
                {"command": command,
                 "slave": payload.get("slave")})

    def status(self):
        with self._lock:
            self._gc_locked()
            now = time.time()
            return {mid: dict(entry["payload"],
                              age=now - entry["received"])
                    for mid, entry in self._masters.items()}

    def _gc_locked(self):
        cutoff = time.time() - self.expiry
        for mid in [m for m, e in self._masters.items()
                    if e["received"] < cutoff]:
            del self._masters[mid]
            self._commands.pop(mid, None)

    #: Heartbeat sections whose numeric leaves are re-exposed as
    #: labeled Prometheus gauges on ``GET /metrics`` — ONE scrape
    #: endpoint covers every master this dashboard tracks.
    METRIC_SECTIONS = ("comms", "resilience", "perf", "serving",
                      "fabric", "population", "fleet", "metrics")

    def metrics_text(self):
        """Prometheus text exposition: this process's own registry
        plus, per tracked master, every numeric value from the
        heartbeat's metric-bearing sections as a gauge labeled
        ``{master="<id>"}`` (docs/observability.md)."""
        from .observability import metrics as obs_metrics
        samples = []
        for mid, info in sorted(self.status().items()):
            for section in self.METRIC_SECTIONS:
                data = info.get(section)
                if not isinstance(data, dict):
                    continue
                for key, value in sorted(data.items()):
                    if isinstance(value, bool) or \
                            not isinstance(value, (int, float)):
                        continue
                    samples.append(("%s.%s" % (section, key),
                                    {"master": mid}, value))
            age = info.get("age")
            if isinstance(age, (int, float)):
                samples.append(("master.heartbeat_age_seconds",
                                {"master": mid}, age))
        return obs_metrics.render_prometheus(
            [obs_metrics.registry], extra_samples=samples)

    def render_page(self):
        # Heartbeat JSON is network-supplied: escape every interpolated
        # field so a hostile peer cannot store XSS into the dashboard.
        esc = lambda v: html.escape(str(v), quote=True)  # noqa: E731
        status = self.status()
        rows = []
        for mid, info in sorted(status.items()):
            workers = info.get("slaves", {})
            wtable = "".join(
                "<tr><td>%s</td><td>%s</td><td>%s</td><td>%s</td>"
                "</tr>" %
                (esc(sid), esc(w.get("state")),
                 esc(w.get("jobs_done")),
                 esc(w.get("jobs_per_s", "")))
                for sid, w in workers.items())
            try:
                runtime = float(info.get("runtime", 0.0))
            except (TypeError, ValueError):
                runtime = 0.0
            resilience = info.get("resilience")
            resilience_row = (
                "<tr><th>resilience</th><td>%s</td></tr>" %
                esc(json.dumps(resilience, sort_keys=True))
                if isinstance(resilience, dict) and resilience
                else "")
            # Comms row: wire bytes/frames and serialize/compress/
            # send timing totals from the distributed data plane.
            comms = info.get("comms")
            comms_row = (
                "<tr><th>comms</th><td>%s</td></tr>" %
                esc(json.dumps(comms, sort_keys=True))
                if isinstance(comms, dict) and comms else "")
            # Serving row: decode tok/s + paged KV-pool occupancy
            # from any in-process serving engine riding the beat.
            serving = info.get("serving")
            serving_row = (
                "<tr><th>serving</th><td>%s</td></tr>" %
                esc(json.dumps(serving, sort_keys=True))
                if isinstance(serving, dict) and serving else "")
            # Perf row: live device-time + MFU attribution of the
            # fused step (observability heartbeat "perf" section).
            perf = info.get("perf")
            perf_row = (
                "<tr><th>perf</th><td>%s</td></tr>" %
                esc(json.dumps(perf, sort_keys=True))
                if isinstance(perf, dict) and perf else "")
            # Training health (guardian heartbeat section): flag a
            # master that detected NaN/spike events prominently.
            health = info.get("health")
            health_row = ""
            if isinstance(health, dict) and health:
                style = ' style="color:#b00"' if \
                    health.get("events") else ""
                health_row = (
                    "<tr><th>health</th><td%s>%s</td></tr>" %
                    (style, esc(json.dumps(health, sort_keys=True,
                                           default=str))))
            # Population row: live per-member fitness + lineage
            # generations from the population engine's heartbeat
            # section (docs/population.md).
            population = info.get("population")
            population_row = (
                "<tr><th>population</th><td>%s</td></tr>" %
                esc(json.dumps(population, sort_keys=True))
                if isinstance(population, dict) and population
                else "")
            # Fabric row: replica count, draining, routed totals and
            # the cross-replica prefix hit-rate from any serving
            # fabric riding the beat (docs/serving.md).
            fabric = info.get("fabric")
            fabric_row = (
                "<tr><th>fabric</th><td>%s</td></tr>" %
                esc(json.dumps(fabric, sort_keys=True))
                if isinstance(fabric, dict) and fabric else "")
            # Fleet row: membership epoch, live size, and the
            # join/leave/drain tallies from the elastic fleet's
            # heartbeat section (docs/distributed.md).
            fleet = info.get("fleet")
            fleet_row = (
                "<tr><th>fleet</th><td>%s</td></tr>" %
                esc(json.dumps(fleet, sort_keys=True))
                if isinstance(fleet, dict) and fleet else "")
            rows.append(
                "<h2>%s <small>(%s)</small></h2>"
                "<table><tr><th>mode</th><td>%s</td></tr>"
                "<tr><th>epoch</th><td>%s</td></tr>"
                "<tr><th>runtime</th><td>%.0f s</td></tr>"
                "<tr><th>metrics</th><td>%s</td></tr>%s%s%s%s%s%s%s%s"
                "</table>" %
                (esc(info.get("workflow", "?")), esc(mid),
                 esc(info.get("mode", "?")), esc(info.get("epoch", "?")),
                 runtime,
                 esc(json.dumps(info.get("metrics", {}))),
                 health_row, resilience_row, comms_row,
                 serving_row, fabric_row, perf_row, population_row,
                 fleet_row) +
                ("<h3>workers</h3><table><tr><th>id</th><th>state"
                 "</th><th>jobs</th><th>jobs/s</th></tr>%s</table>"
                 % wtable if workers else "") +
                self._render_graph(info.get("graph")) +
                self._render_plots(info.get("plots")))
        return _PAGE.format(rows="\n".join(rows) or
                            "<p>nothing running.</p>",
                            count=len(status))

    #: DOT → rendered-SVG-img cache (graphviz layout is expensive and
    #: the graph is static; render each distinct DOT once, not per
    #: page load). Class-level, bounded.
    _SVG_CACHE = {}
    _SVG_CACHE_MAX = 32

    def _render_graph(self, dot):
        """Workflow graph section (reference: web_status.py:113-243
        shows the Graphviz graph).  When the graphviz binary exists
        the DOT is rendered server-side to SVG and embedded as a
        data-URI <img> (img context: embedded scripts in a hostile
        SVG never execute); the DOT source is always available in a
        collapsible block.

        Server-side rendering runs ONLY when heartbeat POSTs require
        the status token: without auth, any client could POST
        arbitrary DOT to be parsed by the graphviz C library (a
        memory-unsafety attack surface) and each hash-distinct DOT
        costs a subprocess with a 10 s timeout (cheap DoS).  Unauth'd
        deployments still get the escaped DOT source block."""
        if not dot or not isinstance(dot, str) or len(dot) > 65536:
            return ""
        import base64
        import hashlib
        import shutil
        import subprocess
        dot_src = ("<details><summary>workflow graph (DOT)</summary>"
                   "<pre>%s</pre></details>" %
                   html.escape(dot, quote=True))
        if self.token is None:
            # No cache interaction either: a token-less instance must
            # not poison the class-level cache with empty renders for
            # an authed instance in the same process.
            return "<h3>graph</h3>" + dot_src
        cls = type(self)
        key = hashlib.sha256(dot.encode()).hexdigest()
        svg_img = cls._SVG_CACHE.get(key)
        if svg_img is None:
            svg_img = ""
            dot_bin = shutil.which("dot")
            if dot_bin:
                try:
                    proc = subprocess.run(
                        [dot_bin, "-Tsvg"], input=dot.encode(),
                        capture_output=True, timeout=10)
                    if proc.returncode == 0:
                        svg_img = (
                            '<p><img alt="workflow graph" '
                            'src="data:image/svg+xml;base64,%s">'
                            "</p>" % base64.b64encode(
                                proc.stdout).decode())
                except (OSError, subprocess.SubprocessError):
                    pass
            if len(cls._SVG_CACHE) >= cls._SVG_CACHE_MAX:
                cls._SVG_CACHE.clear()
            cls._SVG_CACHE[key] = svg_img
        return "<h3>graph</h3>" + svg_img + dot_src

    @staticmethod
    def _render_plots(plots):
        """Latest plot images riding the heartbeat, embedded as
        data-URI <img> after validating each blob really is a PNG."""
        if not isinstance(plots, dict) or not plots:
            return ""
        import base64
        imgs = []
        for name in sorted(plots)[:8]:
            blob = plots[name]
            if not isinstance(blob, str) or len(blob) > 512 * 1024:
                continue
            try:
                raw = base64.b64decode(blob, validate=True)
            except (ValueError, TypeError):
                continue
            if not raw.startswith(b"\x89PNG\r\n\x1a\n"):
                continue
            imgs.append(
                '<figure style="display:inline-block">'
                '<img alt="%s" style="max-width:420px" '
                'src="data:image/png;base64,%s">'
                "<figcaption>%s</figcaption></figure>" %
                (html.escape(str(name), quote=True), blob,
                 html.escape(str(name), quote=True)))
        if not imgs:
            return ""
        return "<h3>plots</h3>" + "".join(imgs)

    # -- lifecycle: start/serve/stop inherited from JsonHttpServer ---------

    def start(self):
        super(WebStatusServer, self).start()
        self.info("web status on port %d", self.port)
        return self

    def serve(self):
        self.info("web status on port %d", self.port)
        super(WebStatusServer, self).serve()


def main(argv=None):
    import argparse
    parser = argparse.ArgumentParser(prog="veles_tpu.web_status")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8090)
    parser.add_argument(
        "--token", default=None,
        help="shared secret required (X-Status-Token header) on POSTs")
    args = parser.parse_args(argv)
    server = WebStatusServer(host=args.host, port=args.port,
                             token=args.token)
    try:
        server.serve()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
