"""Results-contribution interface.

Capability parity with the reference result provider (reference:
veles/result_provider.py — ``IResultProvider:41``): units implementing
this contribute to the ``--result-file`` metrics JSON gathered by
``Workflow.gather_results`` (reference: workflow.py:814-836).
"""


class IResultProvider(object):
    """Mixin marker: implement ``get_metric_names`` and
    ``get_metric_values``."""

    def get_metric_names(self):
        raise NotImplementedError()

    def get_metric_values(self):
        raise NotImplementedError()
