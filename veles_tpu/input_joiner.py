"""Input joiner unit.

Capability parity with the reference (reference: veles/input_joiner.py
— ``InputJoiner:49``, backed by the Jinja-templated ocl/join.jcl /
cuda/join.jcu kernels): concatenates N input Vectors feature-wise into
one output, registering ``offset_<i>``/``length_<i>`` attributes so
downstream units can address sub-ranges.

TPU-era mapping: a traced ``jnp.concatenate`` that XLA fuses with its
consumers — the templated multi-input copy kernel disappears.
"""

import numpy

from .accelerated_units import TracedUnit
from .memory import Vector


class InputJoiner(TracedUnit):
    def __init__(self, workflow, **kwargs):
        super(InputJoiner, self).__init__(workflow, **kwargs)
        self.view_group = "WORKER"
        self.inputs = list(kwargs.get("inputs", ()))
        self.output = Vector()

    def link_inputs(self, *vectors):
        self.inputs.extend(vectors)
        return self

    def initialize(self, device=None, **kwargs):
        if not self.inputs:
            raise ValueError("%s has no inputs" % self)
        if any(not v for v in self.inputs):
            raise AttributeError(
                "%s: inputs not allocated yet" % self.name)
        super(InputJoiner, self).initialize(device=device, **kwargs)
        batch = self.inputs[0].shape[0]
        offset = 0
        for i, v in enumerate(self.inputs):
            length = v.size // batch
            setattr(self, "offset_%d" % i, offset)
            setattr(self, "length_%d" % i, length)
            offset += length
        self.output.mem = numpy.zeros((batch, offset),
                                      dtype=numpy.float32)
        self.output.initialize(self.device)

    def tforward(self, read, write, params, ctx, state=None):
        import jax.numpy as jnp
        parts = []
        for v in self.inputs:
            x = read(v)
            parts.append(x.reshape(x.shape[0], -1).astype(
                jnp.float32))
        write(self.output, jnp.concatenate(parts, axis=1))
