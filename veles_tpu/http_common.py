"""Shared JSON-over-HTTP scaffolding for the service endpoints
(REST serving, web status).  One copy of the request/response
plumbing and the threaded-server lifecycle so fixes land everywhere.
"""

import json
import threading

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .json_encoders import dumps_json
from .logger import Logger


class JsonRequestHandler(BaseHTTPRequestHandler):
    """Handler base: quiet logging + JSON reply/read helpers.  The
    owning server sets ``outer`` (a Logger) on the subclass."""

    outer = None

    def log_message(self, fmt, *args):
        if self.outer is not None:
            self.outer.debug("http: " + fmt, *args)

    def reply(self, code, obj, ctype="application/json",
              headers=None):
        if isinstance(obj, (dict, list)):
            blob = dumps_json(obj).encode()
        elif isinstance(obj, str):
            blob = obj.encode()
        else:
            blob = obj
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(blob)))
        if headers:
            for name, value in headers.items():
                self.send_header(name, str(value))
        self.end_headers()
        self.wfile.write(blob)

    def client_id(self):
        """The admission-control identity of this connection: the
        remote address (one shared limiter bucket per host — NAT'd
        crowds share fate, which is the conservative direction for
        backpressure)."""
        return self.client_address[0]

    #: Request-body cap.  Bodies are drained before auth/rate-limit
    #: replies (closing an unread socket resets the client), so an
    #: unauthenticated Content-Length must not be able to buffer
    #: gigabytes per connection (PR 1 capped network frames for the
    #: same reason).
    MAX_BODY = 64 << 20

    def read_json(self):
        length = int(self.headers.get("Content-Length", 0))
        if length < 0 or length > self.MAX_BODY:
            # Negative lengths matter too: rfile.read(-1) blocks
            # until client EOF, pinning a handler thread forever.
            raise ValueError(
                "request body of %d bytes exceeds the %d-byte cap" %
                (length, self.MAX_BODY))
        return json.loads(self.rfile.read(length) or b"{}")

    def check_token(self, token):
        """Constant-time shared-secret check of the X-Status-Token
        header.  Bytes, not str: ``compare_digest`` raises TypeError
        on non-ASCII str operands.  latin-1 is the exact inverse of
        http.server's header decode (recovers the client's wire
        bytes losslessly); the token matches as its UTF-8 bytes —
        what curl-style clients send.  One copy here so the serving
        and web-status gates cannot drift apart."""
        import hmac
        return hmac.compare_digest(
            (self.headers.get("X-Status-Token") or "")
            .encode("latin-1"),
            token.encode("utf-8"))


class _ThreadingHTTPServer(ThreadingHTTPServer):
    """The stock server with a serving-grade listen backlog — the
    socketserver default of 5 resets connections under a burst of
    concurrent clients before the accept loop ever sees them."""

    request_queue_size = 128


class JsonHttpServer(Logger):
    """Threaded server lifecycle: ``start()`` (background),
    ``serve()`` (blocking), ``stop()``."""

    def __init__(self, handler_cls, host="0.0.0.0", port=0,
                 thread_name="veles-http"):
        super(JsonHttpServer, self).__init__()
        handler_cls.outer = self
        self._httpd = _ThreadingHTTPServer((host, port), handler_cls)
        self.port = self._httpd.server_address[1]
        self._thread = None
        self._thread_name = thread_name

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=self._thread_name)
        self._thread.start()
        return self

    def serve(self):
        self._httpd.serve_forever()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
