"""Shared JSON-over-HTTP scaffolding for the service endpoints
(REST serving, web status).  One copy of the request/response
plumbing and the threaded-server lifecycle so fixes land everywhere.
"""

import json
import threading

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .json_encoders import dumps_json
from .logger import Logger


class JsonRequestHandler(BaseHTTPRequestHandler):
    """Handler base: quiet logging + JSON reply/read helpers.  The
    owning server sets ``outer`` (a Logger) on the subclass."""

    outer = None

    def log_message(self, fmt, *args):
        if self.outer is not None:
            self.outer.debug("http: " + fmt, *args)

    def reply(self, code, obj, ctype="application/json"):
        if isinstance(obj, (dict, list)):
            blob = dumps_json(obj).encode()
        elif isinstance(obj, str):
            blob = obj.encode()
        else:
            blob = obj
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(blob)))
        self.end_headers()
        self.wfile.write(blob)

    def read_json(self):
        length = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(length) or b"{}")


class JsonHttpServer(Logger):
    """Threaded server lifecycle: ``start()`` (background),
    ``serve()`` (blocking), ``stop()``."""

    def __init__(self, handler_cls, host="0.0.0.0", port=0,
                 thread_name="veles-http"):
        super(JsonHttpServer, self).__init__()
        handler_cls.outer = self
        self._httpd = ThreadingHTTPServer((host, port), handler_cls)
        self.port = self._httpd.server_address[1]
        self._thread = None
        self._thread_name = thread_name

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=self._thread_name)
        self._thread.start()
        return self

    def serve(self):
        self._httpd.serve_forever()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
