"""Graphics pub/sub server: live plot streaming to external viewers.

Capability parity with the reference graphics stack (reference:
veles/graphics_server.py:73-193 — ZMQ PUB socket publishing pickled
plotter payloads, endpoint registry, ``launch()`` spawning a separate
matplotlib client process): plotter units publish their payloads here;
any number of :mod:`veles_tpu.graphics_client` processes subscribe
over plain TCP (the framework's length-framed transport,
network_common) and redraw with matplotlib.

Payload design change vs the reference: the reference pickled whole
plotter *units* (dragging Twisted/unit machinery along); here a
payload is ``(plotter_class, plain-data dict)`` — the class's static
``render(data, fig)`` re-creates the figure client-side, nothing of
the unit graph crosses the wire.
"""

import socket
import threading

from .config import root, get as config_get
from .logger import Logger
from .network_common import send_message, parse_address


class GraphicsServer(Logger):
    """Accepts subscriber connections and broadcasts plot payloads
    (reference: graphics_server.py:73)."""

    _instance = None

    def __init__(self, address=None):
        super(GraphicsServer, self).__init__()
        if address is None:
            address = "%s:%d" % (
                config_get(root.common.graphics.host, "0.0.0.0"),
                config_get(root.common.graphics.port, 0))
        host, port = parse_address(address)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR,
                              1)
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(8)
        self._subscribers = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.published = 0
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="veles-graphics-accept")
        self._accept_thread.start()
        self.info("graphics server on port %d", self.port)

    @classmethod
    def launch(cls):
        """Returns the process-wide server, creating it on first use
        (reference: graphics_server.py:174)."""
        if cls._instance is None or cls._instance._stop.is_set():
            cls._instance = cls()
        return cls._instance

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, addr = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._subscribers.append(conn)
            self.debug("viewer connected from %s", addr)

    def publish(self, payload):
        """Broadcasts one payload; dead subscribers are dropped."""
        with self._lock:
            alive = []
            for conn in self._subscribers:
                try:
                    send_message(conn, payload)
                    alive.append(conn)
                except (OSError, BrokenPipeError):
                    try:
                        conn.close()
                    except OSError:
                        pass
            self._subscribers = alive
            self.published += 1

    @property
    def subscriber_count(self):
        with self._lock:
            return len(self._subscribers)

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            for conn in self._subscribers:
                try:
                    conn.close()
                except OSError:
                    pass
            self._subscribers = []
        if GraphicsServer._instance is self:
            GraphicsServer._instance = None
