"""Host/device array pairs.

Capability parity with the reference memory module (reference:
veles/memory.py — ``Array:110`` (a.k.a. Vector), ``Watcher:56-107``):
every tensor a unit owns is a :class:`Vector` pairing a host numpy array
with a device buffer, moved between the two by an explicit
``map_read`` / ``map_write`` / ``map_invalidate`` / ``unmap`` protocol
(reference memory.py:371-384) so host code never observes stale data.

TPU-era mapping:

  * the device buffer is a ``jax.Array`` resident in HBM (the reference's
    OpenCL zero-copy / CUDA to_device paths, memory.py:408-511, become
    ``jax.device_put`` with an optional ``NamedSharding`` so one Vector
    can span a whole mesh);
  * ``map_read`` pulls device→host only when the device copy is newer;
    ``map_write`` marks the host copy authoritative; ``unmap`` (or any
    device access) uploads if needed — same discipline, same names;
  * device-memory accounting (the reference's ``Watcher`` metaclass)
    is a class-level byte counter updated on upload/free.

Pickling maps device→host first (reference memory.py:284-292); a
``shallow_pickle`` flag sends only shape/dtype metadata — used by the
control plane to describe tensors without shipping them
(reference memory.py:290-299).
"""

import contextlib
import threading

import numpy

from .distributable import Pickleable

_accounting_lock = threading.Lock()

#: When set (host_resharding context), sharding changes take the
#: host-sync path unconditionally.  Elastic rebuild needs this: a
#: device-to-device reshard sourced from a partially-departed device
#: set may fail ASYNCHRONOUSLY (the transfer enqueues and returns
#: before touching the dead chip), which a try/except cannot catch —
#: while the host path reads one healthy replica shard and always
#: recovers.
_force_host_reshard = threading.local()


@contextlib.contextmanager
def host_resharding():
    """Forces sharding changes inside the block to round-trip through
    the host (see :attr:`_force_host_reshard`)."""
    prev = getattr(_force_host_reshard, "on", False)
    _force_host_reshard.on = True
    try:
        yield
    finally:
        _force_host_reshard.on = prev


class Vector(Pickleable):
    """A host+device array (reference: memory.py:110 ``Array``)."""

    #: Total bytes currently uploaded to devices (reference Watcher).
    total_device_bytes = 0

    def __init__(self, data=None, shallow_pickle=False):
        super(Vector, self).__init__()
        self._mem = None
        self.shallow_pickle = shallow_pickle
        self._sharding = None
        if data is not None:
            self.mem = data

    def init_unpickled(self):
        super(Vector, self).init_unpickled()
        self._devmem_ = None
        self._device_ = None
        # Three states: host authoritative (_host_dirty_), device
        # authoritative with stale host (_host_stale_), or synced
        # (neither) — repeats of map_read/unmap are then free.
        self._host_dirty_ = True
        self._host_stale_ = False
        self._device_bytes_ = 0
        # Device→host transfer count (see host_sync_count): the
        # steady-state fast path keeps step tensors device-resident,
        # and tests pin that invariant with this counter.
        self._host_syncs_ = 0
        self._lock_ = threading.RLock()

    # -- host side ---------------------------------------------------------

    @property
    def mem(self):
        return self._mem

    @mem.setter
    def mem(self, value):
        with self._lock_:
            if value is None:
                self.reset()
                return
            self._mem = numpy.ascontiguousarray(value)
            self._host_dirty_ = True
            self._host_stale_ = False

    @property
    def plain(self):
        """Flattened host view (reference API)."""
        return self._mem.reshape(-1) if self._mem is not None else None

    @property
    def shape(self):
        if self._mem is not None:
            return self._mem.shape
        if self._devmem_ is not None:
            return tuple(self._devmem_.shape)
        return self.__dict__.get("_shallow_shape")

    @property
    def dtype(self):
        if self._mem is not None:
            return self._mem.dtype
        if self._devmem_ is not None:
            return numpy.dtype(self._devmem_.dtype)
        shallow = self.__dict__.get("_shallow_dtype")
        return numpy.dtype(shallow) if shallow is not None else None

    @property
    def size(self):
        shape = self.shape
        if shape is None:
            return 0
        n = 1
        for d in shape:
            n *= d
        return n

    @property
    def nbytes(self):
        if self._mem is not None:
            return self._mem.nbytes
        if self._devmem_ is not None:
            return self._devmem_.size * self._devmem_.dtype.itemsize
        return 0

    def __bool__(self):
        return self._mem is not None or self._devmem_ is not None

    __nonzero__ = __bool__

    def __len__(self):
        shape = self.shape
        return shape[0] if shape else 0

    def __getitem__(self, key):
        self.map_read()
        return self._mem[key]

    def __setitem__(self, key, value):
        self.map_write()
        self._mem[key] = value

    def __repr__(self):
        return "<Vector shape=%s dtype=%s device=%s>" % (
            self.shape, self.dtype,
            "yes" if self._devmem_ is not None else "no")

    # -- device side -------------------------------------------------------

    @property
    def device(self):
        return self._device_

    @property
    def sharding(self):
        return self._sharding

    @sharding.setter
    def sharding(self, value):
        with self._lock_:
            if value is self._sharding:
                return
            self._sharding = value
            if self._devmem_ is None:
                return
            if not self._host_stale_:
                # Host copy is current: just drop the device copy and
                # re-upload lazily under the new layout.
                self._free_device()
                return
            # Device copy is authoritative.  Reshard DEVICE-TO-DEVICE
            # when possible (jax.device_put between shardings) — a
            # host round-trip for e.g. a 2.5 GB momentum tensor costs
            # minutes through a slow link for no reason.  NOT under
            # host_resharding(): elastic rebuild forces the host path
            # there, because a D2D transfer sourced from a
            # partially-departed device set can fail ASYNCHRONOUSLY
            # (enqueue-then-die), which no try/except here can catch,
            # while the host path reads one healthy replica shard.
            if value is not None and \
                    not getattr(_force_host_reshard, "on", False):
                try:
                    import jax
                    arr = jax.device_put(self._devmem_, value)
                except Exception as e:
                    import logging
                    logging.getLogger("Vector").debug(
                        "D2D reshard failed (%s) — host path", e)
                    arr = None
                if arr is not None:
                    self.devmem = arr
                    return
            self._host_sync()
            self._free_device()

    def initialize(self, device):
        """Attaches to a device; upload is lazy (reference:
        memory.py:347)."""
        with self._lock_:
            if device is self._device_:
                return
            if self._devmem_ is not None:
                self._host_sync()
                self._free_device()
            self._device_ = device

    @property
    def devmem(self):
        """The current ``jax.Array`` — uploads host data first if the
        host copy is authoritative."""
        with self._lock_:
            if self._host_dirty_ or self._devmem_ is None:
                self._upload()
            return self._devmem_

    @devmem.setter
    def devmem(self, value):
        """Accepts a freshly-computed ``jax.Array`` (the output of a
        jitted step); the device copy becomes authoritative and the
        host copy stale — no transfer happens until ``map_read``."""
        with self._lock_:
            self._account(-self._device_bytes_)
            self._devmem_ = value
            self._device_bytes_ = (
                value.size * value.dtype.itemsize if value is not None
                else 0)
            self._account(self._device_bytes_)
            self._host_dirty_ = False
            self._host_stale_ = value is not None
            if value is not None and self._mem is not None and \
                    tuple(value.shape) != self._mem.shape:
                self._mem = None

    def _upload(self):
        import jax
        if self._mem is None:
            return
        data = self._mem
        if self._sharding is not None:
            arr = jax.device_put(data, self._sharding)
        elif self._device_ is not None and \
                getattr(self._device_, "default_device", None) is not None:
            arr = jax.device_put(data, self._device_.default_device)
        else:
            arr = jax.device_put(data)
        self._account(-self._device_bytes_)
        self._devmem_ = arr
        self._device_bytes_ = arr.size * arr.dtype.itemsize
        self._account(self._device_bytes_)
        self._host_dirty_ = False
        self._host_stale_ = False

    def _host_sync(self):
        """Device → host only when the device copy is authoritative
        AND the host copy is stale — repeat calls are free.
        ``numpy.asarray`` on a jax.Array yields a read-only view, so
        copy into a writable buffer.  Fully-replicated arrays read
        from ONE local shard — no cross-device gather, and elastic
        recovery can source replicated params from any healthy chip
        (parallel.rebuild_mesh)."""
        if self._devmem_ is not None and self._host_stale_:
            # Steady-state contract: the fused step reads and writes
            # step tensors (params, optimizer slots) purely through
            # ``devmem`` — this transfer runs only at snapshot/
            # rollback/wire-sync boundaries, never per tick, and
            # ``host_sync_count`` lets tests assert exactly that.
            self._host_syncs_ += 1
            arr = self._devmem_
            try:
                if arr.is_fully_replicated and \
                        arr.addressable_shards:
                    self._mem = numpy.array(
                        arr.addressable_shards[0].data)
                elif not arr.is_fully_addressable:
                    # Multi-controller SPMD: a data-sharded array
                    # spans other processes' devices.  All processes
                    # run the same program, so they reach this read
                    # in lockstep — gather the global value
                    # collectively.
                    from jax.experimental import multihost_utils
                    self._mem = numpy.array(
                        multihost_utils.process_allgather(
                            arr, tiled=True))
                else:
                    self._mem = numpy.array(arr)
            except AttributeError:  # non-sharded array types
                self._mem = numpy.array(arr)
            self._host_stale_ = False

    def _free_device(self):
        self._account(-self._device_bytes_)
        self._device_bytes_ = 0
        self._devmem_ = None
        self._host_dirty_ = self._mem is not None
        self._host_stale_ = False

    @classmethod
    def _account(cls, delta):
        with _accounting_lock:
            cls.total_device_bytes += delta

    @property
    def host_sync_count(self):
        """Device→host transfers this Vector has performed since
        creation/unpickling.  Optimizer slots and params must show 0
        growth across steady-state stepping (the fused step hands
        jax.Arrays around; only snapshot/rollback/wire-sync
        boundaries map them back) — asserted by
        tests/test_optimizers.py."""
        return self._host_syncs_

    # -- map protocol (reference memory.py:371-384) ------------------------

    def map_read(self):
        """Ensures the host copy reflects the freshest data."""
        with self._lock_:
            self._host_sync()

    def map_write(self):
        """Host copy becomes authoritative; device copy is stale."""
        with self._lock_:
            self._host_sync()
            if self._mem is None and self._devmem_ is not None:
                self._mem = numpy.array(self._devmem_)
            self._host_dirty_ = True
            self._host_stale_ = False

    def map_invalidate(self):
        """Host copy becomes authoritative WITHOUT downloading first
        (caller will overwrite everything)."""
        with self._lock_:
            self._host_dirty_ = True
            self._host_stale_ = False

    def unmap(self):
        """Pushes host data to the device if the host copy is
        authoritative."""
        with self._lock_:
            if self._host_dirty_ and self._mem is not None and (
                    self._device_ is not None or
                    self._sharding is not None):
                self._upload()

    def reset(self, new_mem=None):
        """Drops all data (reference: memory.py ``reset``)."""
        with self._lock_:
            self._free_device()
            self._mem = None
            if new_mem is not None:
                self.mem = new_mem

    # -- pickling ----------------------------------------------------------

    def __getstate__(self):
        if self.shallow_pickle:
            # Describe without shipping: no device→host transfer.
            state = super(Vector, self).__getstate__()
            state["_mem"] = None
            state["_shallow_shape"] = self.shape
            state["_shallow_dtype"] = str(self.dtype) \
                if self.dtype is not None else None
            state["_sharding"] = None
            return state
        self.map_read()
        state = super(Vector, self).__getstate__()
        # A NamedSharding holds the live Mesh/Device objects — never
        # picklable, and topology-bound anyway: a snapshot restores
        # onto WHATEVER devices exist then (possibly fewer/more), and
        # the parallel appliers re-annotate at that point (SURVEY §7
        # cross-topology resume).
        state["_sharding"] = None
        return state


#: Reference-compatible alias (veles.memory.Array).
Array = Vector


def assert_addr(*vectors):
    """No-op on TPU: the reference asserted device-pointer identity for
    zero-copy aliasing (memory.py / numpy_ext); jax.Arrays are
    immutable, so aliasing is structural, not address-based."""


def roundup(num, align):
    d = num % align
    return num if d == 0 else num + (align - d)
