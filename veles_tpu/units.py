"""The dataflow unit model.

Capability parity with the reference unit system (reference:
veles/units.py — ``IUnit:59``, ``Unit:108``, ``TrivialUnit:891``,
``Container:899``): a workflow is a directed graph of units with

  * **control links** — ``dst.link_from(src)`` (reference units.py:542);
    a unit runs when ALL of its incoming links have fired
    (``open_gate``, units.py:512);
  * **gates** — ``gate_block`` suppresses run+propagation,
    ``gate_skip`` propagates without running (units.py:279-306); both
    are lazily-evaluated :class:`~veles_tpu.mutable.Bool` expressions so
    loop conditions track live state;
  * **attribute links** — ``dst.link_attrs(src, "weights", ...)``
    aliases data attributes (units.py:612);
  * **demands** — ``self.demand("minibatch_data")`` declares required
    attributes, verified at initialize time (units.py:656).

Execution-model change for TPU: the reference dispatches each unit run
onto a Twisted thread pool (units.py:473-493) because each unit owns its
own OpenCL/CUDA kernels.  Here the host graph driver is a synchronous
work queue owned by the Workflow (thread parallelism would only add
nondeterminism), and the *device* parallelism comes from XLA: units in
the training loop contribute pure functions that the workflow fuses into
a single jitted step (see accelerated_units.py).  Per-unit wall-time
accounting (units.py:168-194,779) is kept.
"""

import time

from .config import root, get as config_get
from .distributable import Distributable
from .error import Bug
from .mutable import Bool, LinkableAttribute
from .registry import UnitRegistry

# Types treated as "mutable" for link_attrs: linking copies the object
# reference, so src and dst observe the same value forever
# (reference: units.py:742-754 picks LinkableAttribute only for
# immutables).
_MUTABLE_TYPES_CACHE = [None]


def _mutable_types():
    if _MUTABLE_TYPES_CACHE[0] is None:
        import numpy
        from .memory import Vector
        _MUTABLE_TYPES_CACHE[0] = (Vector, Bool, list, dict, set,
                                   bytearray, numpy.ndarray)
    return _MUTABLE_TYPES_CACHE[0]


class IUnit(object):
    """The unit contract (reference: units.py:59): ``initialize`` may
    raise AttributeError to signal unmet demands (the workflow requeues
    it), ``run`` does one tick of work."""

    def initialize(self, **kwargs):
        raise NotImplementedError()

    def run(self):
        raise NotImplementedError()


class Unit(Distributable, metaclass=UnitRegistry):
    """A node in the workflow graph (reference: units.py:108)."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        self.name = kwargs.get("name", type(self).__name__)
        self.view_group = kwargs.get("view_group", "PLUMBING")
        self.timings = config_get(root.common.timings, False) or \
            kwargs.get("timings", False)
        self._links_from = {}
        self._links_to = {}
        self.gate_block = Bool(False)
        self.gate_skip = Bool(False)
        self._demanded = set()
        self._linked_attrs = {}
        self._workflow = None
        self._is_initialized = False
        self._stopped = False
        self.run_time = 0.0
        self.run_count = 0
        super(Unit, self).__init__(**kwargs)
        if workflow is not None:
            workflow.add_ref(self)

    def init_unpickled(self):
        super(Unit, self).init_unpickled()
        self._gate_visited_ = {}

    # -- identity ----------------------------------------------------------

    @property
    def workflow(self):
        return self._workflow

    @workflow.setter
    def workflow(self, value):
        if self._workflow is not None and value is not None \
                and value is not self._workflow:
            self._workflow.del_ref(self)
        self._workflow = value

    @property
    def is_initialized(self):
        return self._is_initialized

    @property
    def is_standalone(self):
        return self.workflow.launcher.is_standalone \
            if self.workflow is not None else True

    @property
    def is_master(self):
        return self.workflow is not None and \
            self.workflow.launcher.is_master

    @property
    def is_slave(self):
        return self.workflow is not None and \
            self.workflow.launcher.is_slave

    @property
    def stopped(self):
        """True when this unit or its workflow was stopped; per-unit
        flag is resettable (FireStarter re-arms finished sub-graphs,
        reference plumbing.py:92)."""
        if self._stopped:
            return True
        return self.workflow.stopped if self.workflow is not None else False

    @stopped.setter
    def stopped(self, value):
        self._stopped = bool(value)

    def __repr__(self):
        return '<%s "%s">' % (type(self).__name__, self.name)

    # -- control links -----------------------------------------------------

    @property
    def links_from(self):
        return self._links_from

    @property
    def links_to(self):
        return self._links_to

    def link_from(self, *sources):
        """Adds control dependencies; self runs after ALL sources fired
        (reference: units.py:542)."""
        for src in sources:
            self._links_from[src] = True
            src._links_to[self] = True
            self._gate_visited_.setdefault(src, False)
        return self

    def unlink_from(self, *sources):
        for src in sources:
            self._links_from.pop(src, None)
            src._links_to.pop(self, None)
            self._gate_visited_.pop(src, None)
        return self

    def unlink_all(self):
        self.unlink_before()
        self.unlink_after()
        return self

    def unlink_before(self):
        for src in tuple(self._links_from):
            self.unlink_from(src)

    def unlink_after(self):
        for dst in tuple(self._links_to):
            dst.unlink_from(self)

    def open_gate(self, src):
        """Marks the link from ``src`` as fired; True when every
        incoming link has fired (the gate "opens") — visited flags are
        then reset for the next loop iteration
        (reference: units.py:512)."""
        if src not in self._links_from:
            raise Bug("open_gate from non-linked unit %s -> %s" %
                      (src, self))
        self._gate_visited_[src] = True
        if all(self._gate_visited_.get(s, False)
               for s in self._links_from):
            for s in self._links_from:
                self._gate_visited_[s] = False
            return True
        return False

    # -- attribute links ---------------------------------------------------

    def link_attrs(self, other, *args, two_way=False):
        """Aliases attributes from ``other`` (reference: units.py:612).

        Each arg is either a name (same on both sides) or a tuple
        ``(my_name, other_name)``.  Mutable values (Vector, Bool, numpy
        arrays, containers) are linked by reference; immutables get a
        live :class:`LinkableAttribute` entry resolved on access.
        """
        for arg in args:
            if isinstance(arg, tuple):
                mine, theirs = arg
            else:
                mine = theirs = arg
            value = getattr(other, theirs)
            if isinstance(value, _mutable_types()):
                setattr(self, mine, value)
            else:
                self._linked_attrs[mine] = LinkableAttribute(
                    other, theirs, two_way=two_way)
        return self

    def __getattr__(self, name):
        # Only called when normal lookup fails or for linked attrs
        # resolved below via __setattr__/__getattribute__ interplay.
        if name.startswith("_"):
            raise AttributeError(name)
        linked = self.__dict__.get("_linked_attrs")
        if linked and name in linked:
            return linked[name].get()
        raise AttributeError("%r has no attribute %r (demanded: %s)" %
                             (self, name, sorted(self._demanded)
                              if "_demanded" in self.__dict__ else "?"))

    def __getattribute__(self, name):
        if not name.startswith("_"):
            linked = object.__getattribute__(self, "__dict__").get(
                "_linked_attrs")
            if linked is not None and name in linked:
                return linked[name].get()
        return object.__getattribute__(self, name)

    def __setattr__(self, name, value):
        if not name.startswith("_"):
            linked = self.__dict__.get("_linked_attrs")
            if linked is not None and name in linked:
                entry = linked[name]
                if entry.two_way:
                    entry.set(value)
                    return
                # One-way link: local assignment breaks the link
                # (matches reference property-set semantics for
                # two_way=False: writes are local).
                del linked[name]
        object.__setattr__(self, name, value)

    def demand(self, *attrs):
        """Declares required attributes (reference: units.py:656); the
        workflow retries ``initialize`` until they are satisfied."""
        self._demanded.update(attrs)

    def verify_interface(self):
        missing = [a for a in sorted(self._demanded)
                   if not self._has_attr(a)]
        if missing:
            raise AttributeError(
                "%s lacks demanded attribute(s): %s" %
                (self, ", ".join(missing)))

    def _has_attr(self, name):
        if name in self._linked_attrs:
            try:
                self._linked_attrs[name].get()
                return True
            except AttributeError:
                return False
        return hasattr(self, name) and getattr(self, name) is not None

    # -- lifecycle ---------------------------------------------------------

    def initialize(self, **kwargs):
        """Default initialize verifies demands; subclasses extend.
        May raise AttributeError → the workflow requeues this unit
        (reference: workflow.py:307-331)."""
        self.verify_interface()
        self._is_initialized = True

    def run(self):
        pass

    def stop(self):
        """Called on workflow stop for units holding external resources."""

    # -- execution ---------------------------------------------------------

    def _run_timed(self):
        t0 = time.time()
        try:
            self.run()
        finally:
            dt = time.time() - t0
            self.run_time += dt
            self.run_count += 1
            if self.timings:
                self.debug("%s ran in %.3f ms", self.name, dt * 1e3)

    def check_gate_and_run(self, src):
        """The hot-loop body (reference: units.py:756-777
        ``_check_gate_and_run``)."""
        if not self.open_gate(src):
            return
        if self.gate_block:
            return
        if self.stopped:
            # Run-after-stop: a control-flow-link error (or an external
            # stop racing the queue drain).  Warn by default, raise when
            # root.common.exceptions.run_after_stop is set (reference:
            # units.py:793-819).
            from .error import RunAfterStopError
            msg = ("%s's run() was called after stop() — check the "
                   "control-flow links of workflow %s" %
                   (self.name, self.workflow))
            if bool(root.common.exceptions.get("run_after_stop",
                                               False)):
                raise RunAfterStopError(msg)
            self.warning(
                "%s (set root.common.exceptions.run_after_stop to "
                "raise instead)", msg)
            return
        if not self.gate_skip:
            if self._is_initialized or self.workflow is None:
                self._run_timed()
            else:
                raise Bug("%s run before initialize" % self)
        self.run_dependent()

    def run_dependent(self):
        """Schedules all downstream units (reference: units.py:473)."""
        wf = self.workflow
        for dst in self._links_to:
            if wf is not None:
                wf.schedule(dst, self)
            else:
                dst.check_gate_and_run(self)

    # -- distributed aggregation default ----------------------------------

    def apply_data_from_master(self, data):
        pass

    def generate_data_for_master(self):
        return None

    # -- pickling ----------------------------------------------------------

    def __getstate__(self):
        state = super(Unit, self).__getstate__()
        # Control links are restored by the Workflow's own state; keep
        # them (they are Unit references which pickle with the graph).
        return state


class TrivialUnit(Unit):
    """Concrete no-op unit (reference: units.py:891)."""

    def initialize(self, **kwargs):
        super(TrivialUnit, self).initialize(**kwargs)

    def run(self):
        pass


class Container(Unit):
    """Marker base for units containing other units
    (reference: units.py:899)."""
    hide_from_registry = True
