"""Test fixtures: dummy launcher/workflow.

Capability parity with the reference dummies (reference: veles/dummy.py
— ``DummyLauncher``, ``DummyWorkflow``): satisfy the launcher/workflow
contracts so a single unit can be constructed and run standalone in
tests and micro-benchmarks (used by the reference's own device benchmark,
backends.py:708-717).
"""

from .launcher import Launcher
from .workflow import Workflow


class DummyLauncher(Launcher):
    """Standalone-mode launcher that never blocks."""

    def __init__(self, **kwargs):
        super(DummyLauncher, self).__init__(**kwargs)

    def initialize(self, **kwargs):
        from . import backends
        self.device = kwargs.pop("device", None) or \
            backends.Device.create("auto")
        if self.workflow is not None:
            self.workflow.initialize(device=self.device, **kwargs)
        return self

    def on_workflow_finished(self):
        self._finished.set()


class DummyWorkflow(Workflow):
    """A workflow pre-wired to a DummyLauncher."""

    def __init__(self, **kwargs):
        super(DummyWorkflow, self).__init__(DummyLauncher(), **kwargs)
