"""RESTful model serving.

Capability parity with the reference REST stack (reference:
veles/restful_api.py:78-217 — ``RESTfulAPI`` unit exposing a trained
workflow as HTTP POST /api, base64 or JSON-array inputs, prediction
out; paired input feed loader/restful.py:52): here serving runs from
the EXPORTED artifact (export.py) through the jitted jax chain — the
server compiles the forward once per batch shape and answers from
device, so the same artifact serves on TPU or CPU and the training
process does not have to stay alive (the reference kept the whole
Twisted workflow process up to serve).

Two forms:

* :class:`ModelServer` — standalone: ``ModelServer(artifact).serve()``
  or ``python -m veles_tpu.serve model.veles.tgz --port 8180``.
* :class:`RESTfulAPI` — a Unit linked after training: on its first
  run it exports its workflow's forward chain and starts serving in a
  background thread (the reference's in-workflow form).
"""

import base64

import numpy

from .error import Bug
from .export import ExportedModel, export_workflow
from .http_common import JsonHttpServer, JsonRequestHandler
from .units import Unit


def _decode_input(payload, input_shape):
    """Accepts {"input": nested lists} or {"input": base64, "shape":
    [...]} (reference accepted both forms, restful_api.py:137-165)."""
    if "input" not in payload:
        raise Bug("request JSON lacks 'input'")
    raw = payload["input"]
    if isinstance(raw, str):
        blob = base64.b64decode(raw)
        x = numpy.frombuffer(blob, dtype=numpy.float32).copy()
        shape = payload.get("shape")
        if shape:
            x = x.reshape(shape)
    else:
        x = numpy.asarray(raw, dtype=numpy.float32)
    sample = int(numpy.prod(input_shape)) if input_shape else x.size
    if x.ndim == 1 and sample and x.size == sample:
        x = x[None]  # single flat sample
    if x.ndim >= 1 and sample and x.size % sample == 0:
        return x.reshape(-1, sample)
    raise Bug("input of %d elements does not tile %d-element samples"
              % (x.size, sample))


class ModelServer(JsonHttpServer):
    """Serves an exported artifact over HTTP."""

    def __init__(self, model, host="0.0.0.0", port=8180):
        if isinstance(model, str):
            model = ExportedModel(model)
        self.model = model

        class Handler(JsonRequestHandler):
            def do_GET(self):
                outer = self.outer
                if self.path in ("/", "/health"):
                    m = outer.model.manifest
                    self.reply(200, {
                        "status": "ok",
                        "workflow": m.get("workflow"),
                        "units": [u["type"] for u in m["units"]],
                        "input": m["input"], "output": m["output"],
                    })
                else:
                    self.reply(404, {"error": "not found"})

            def do_POST(self):
                outer = self.outer
                if self.path == "/api/generate":
                    self._generate()
                    return
                if self.path != "/api":
                    self.reply(404, {"error": "not found"})
                    return
                try:
                    x = _decode_input(
                        self.read_json(),
                        outer.model.manifest["input"]["sample_shape"])
                except Exception as e:  # malformed request -> 400
                    outer.warning("bad /api request: %s", e)
                    self.reply(400, {"error": str(e)})
                    return
                try:
                    probs = outer.model.forward(x)
                    flat = probs.reshape(probs.shape[0], -1)
                    self.reply(200, {
                        "output": flat,
                        "labels": numpy.argmax(flat, axis=-1),
                    })
                except Exception:  # server-side fault -> 500
                    outer.exception("/api forward failed")
                    self.reply(500,
                               {"error": "internal server error"})

            def _generate(self):
                """POST /api/generate — KV-cache incremental decoding
                over an LM artifact: {"tokens": [[...]],
                "max_new_tokens": N, "temperature": T, "seed": S} →
                {"tokens": full sequences, "generated": new part}.
                (The deployment surface the reference's RESTful role
                implies for a language model, restful_api.py:78.)"""
                outer = self.outer
                try:
                    payload = self.read_json()
                    tokens = numpy.atleast_2d(numpy.asarray(
                        payload["tokens"], dtype=numpy.int32))
                    max_new = int(payload.get("max_new_tokens", 32))
                    if not 1 <= max_new <= 4096:
                        raise Bug("max_new_tokens out of range")
                    temperature = float(
                        payload.get("temperature", 0.0))
                    seed = int(payload.get("seed", 0))
                except Exception as e:
                    outer.warning("bad /api/generate request: %s", e)
                    self.reply(400, {"error": str(e)})
                    return
                try:
                    full = outer.model.generate(
                        tokens, max_new, temperature=temperature,
                        seed=seed)
                except Bug as e:
                    # Not-an-LM artifact / over-long request: the
                    # client's problem, with the reason.
                    self.reply(400, {"error": str(e)})
                    return
                except Exception:
                    outer.exception("/api/generate failed")
                    self.reply(500,
                               {"error": "internal server error"})
                    return
                self.reply(200, {
                    "tokens": full,
                    "generated": full[:, tokens.shape[1]:],
                })

        super(ModelServer, self).__init__(
            Handler, host=host, port=port,
            thread_name="veles-model-server")

    def serve(self):
        self.info("serving model on port %d (POST /api)", self.port)
        super(ModelServer, self).serve()


class RESTfulAPI(Unit):
    """In-workflow serving unit (reference: restful_api.py:78): link
    it after the Decision; when the workflow finishes training it
    exports the forward chain and serves until stopped."""

    def __init__(self, workflow, **kwargs):
        super(RESTfulAPI, self).__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.host = kwargs.get("host", "0.0.0.0")
        self.port = kwargs.get("port", 8180)
        self.artifact_path = kwargs.get("artifact_path",
                                        "served.veles.tgz")
        self.blocking = kwargs.get("blocking", False)
        self.server = None

    def run(self):
        if self.server is not None:
            return
        export_workflow(self.workflow, self.artifact_path)
        self.server = ModelServer(self.artifact_path, host=self.host,
                                  port=self.port)
        self.port = self.server.port
        if self.blocking:
            self.server.serve()
        else:
            self.server.start()

    def stop(self):
        if self.server is not None:
            self.server.stop()
            self.server = None
        super(RESTfulAPI, self).stop()
