"""RESTful model serving.

Capability parity with the reference REST stack (reference:
veles/restful_api.py:78-217 — ``RESTfulAPI`` unit exposing a trained
workflow as HTTP POST /api, base64 or JSON-array inputs, prediction
out; paired input feed loader/restful.py:52): here serving runs from
the EXPORTED artifact (export.py) through the jitted jax chain — and,
past the reference's one-request-one-forward Twisted handler, through
the :mod:`veles_tpu.serving` subsystem: HTTP threads only enqueue
into a bounded queue; a dedicated device thread coalesces compatible
requests into shape-bucketed padded batches (per-request masking), so
the compile surface is a small fixed bucket grid and throughput
scales with batch occupancy instead of request count.  Admission
control fronts the queue: per-client token-bucket rate limiting,
429 + ``Retry-After`` backpressure when the queue is at depth, and
per-request deadlines that cancel abandoned work.  ``GET /stats``
exposes queue depth, batch occupancy, compile-cache hits/misses, and
p50/p99 latency; ``GET /health`` never touches the device, so it
answers while the queue drains.

Two forms:

* :class:`ModelServer` — standalone: ``ModelServer(artifact).serve()``
  or ``python -m veles_tpu.serve model.veles.tgz --port 8180``
  (operator flags: ``--warmup`` precompiles the bucket grid,
  ``--max-batch`` bounds coalescing, ``--rate-limit`` enables the
  per-client token bucket, ``--token`` gates ``/api/generate``).
* :class:`RESTfulAPI` — a Unit linked after training: on its first
  run it exports its workflow's forward chain and starts serving in a
  background thread (the reference's in-workflow form).  The same
  knobs arrive as kwargs, with CLI defaults via ``--serve-*`` flags
  (``root.common.serving`` in the config tree).
"""

import base64

import numpy

from .config import root
from .error import Bug
from .export import KV_DTYPES, ExportedModel, export_workflow
from .http_common import JsonHttpServer, JsonRequestHandler
from .resilience import Deadline
from .serving import AdmissionError, RateLimiter, ServingEngine
from .serving.reload import ArtifactRejected
from .units import Unit


def init_parser(parser):
    """Serving flags for the in-workflow :class:`RESTfulAPI` unit,
    aggregated into the velescli parser (handed off through
    ``root.common.serving`` by ``__main__.apply_subsystem_flags``)."""
    parser.add_argument(
        "--serve-max-batch", type=int, default=None, metavar="N",
        help="serving: max rows coalesced into one device batch "
             "(default 8)")
    parser.add_argument(
        "--serve-queue-depth", type=int, default=None, metavar="N",
        help="serving: bounded request-queue depth; requests beyond "
             "it get 429 + Retry-After (default 64)")
    parser.add_argument(
        "--serve-rate-limit", type=float, default=None, metavar="R",
        help="serving: per-client token-bucket rate in requests/s "
             "(default: no limit)")
    parser.add_argument(
        "--serve-deadline", type=float, default=None, metavar="SEC",
        help="serving: per-request deadline; expired requests are "
             "cancelled unserved (default 30)")
    parser.add_argument(
        "--serve-token", default=None, metavar="SECRET",
        help="serving: require X-Status-Token on /api/generate (the "
             "same shared-secret scheme web_status uses)")
    parser.add_argument(
        "--serve-warmup", action="store_true",
        help="serving: precompile the shape-bucket grid at startup "
             "so the first request never pays an XLA compile")
    parser.add_argument(
        "--serve-kv-blocks", type=int, default=None, metavar="N",
        help="serving: paged KV cache pool size in blocks (default: "
             "sized so max-batch rows can each hold a full-length "
             "sequence)")
    parser.add_argument(
        "--serve-kv-block-size", type=int, default=None, metavar="N",
        help="serving: tokens per paged KV cache block (default 16)")
    parser.add_argument(
        "--serve-kv-dtype", default=None, choices=KV_DTYPES,
        help="serving: paged KV cache storage dtype (default f32); "
             "int8/fp8 quantize per (block, head) with f32 scales "
             "stored alongside the block tables — 4x the streams "
             "per byte of HBM, token-level quality gated in tier-1")
    parser.add_argument(
        "--serve-weight-dtype", default=None,
        choices=("f32", "int8"),
        help="serving: decode-matmul weight storage (default f32); "
             "int8 = weight-only quantization with per-output-"
             "channel scales, dequantized inside the matmul — "
             "training weights and the f32 parity oracle are "
             "untouched")
    parser.add_argument(
        "--serve-no-paged", action="store_true",
        help="serving: disable paged decode-step batching and fall "
             "back to whole-request generate batching")
    parser.add_argument(
        "--serve-spec", action="store_true",
        help="serving: enable speculative decoding with the "
             "prompt-lookup (n-gram) drafter — greedy output stays "
             "bit-identical to plain paged decode")
    parser.add_argument(
        "--serve-spec-draft", default=None, metavar="PATH",
        help="serving: speculative draft model artifact (same "
             "vocabulary, geometry-checked); implies --serve-spec")
    parser.add_argument(
        "--serve-spec-max-k", type=int, default=None, metavar="K",
        help="serving: max draft tokens verified per dispatch "
             "(1..15, default 4)")
    parser.add_argument(
        "--serve-spec-draft-blocks", type=int, default=None,
        metavar="N",
        help="serving: draft-model KV pool size in blocks "
             "(default: the target pool's size)")
    parser.add_argument(
        "--serve-drain-timeout", type=float, default=None,
        metavar="SEC",
        help="serving: graceful-stop budget — on SIGTERM/stop "
             "admissions close with 503 + Retry-After and live "
             "decode rows get this long to finish (default 30)")
    parser.add_argument(
        "--serve-reload-watch", default=None, metavar="PATH",
        help="serving: hot-reload watch target — a serving artifact "
             "or a snapshotter *_current.lnk pointer (with "
             "--snapshot-artifact the trainer exports a verified "
             "artifact next to every snapshot); when it changes, the "
             "manifest-verified artifact is hot-swapped in without "
             "dropping live streams")
    parser.add_argument(
        "--serve-reload-poll", type=float, default=None,
        metavar="SEC",
        help="serving: reload-watch poll interval (default 5)")
    parser.add_argument(
        "--serve-fabric-replicas", type=int, default=None,
        metavar="N",
        help="serving fabric: run N engine replicas behind the "
             "prefix-affinity router (default 1: no fabric)")
    parser.add_argument(
        "--serve-fabric-disagg", action="store_true",
        help="serving fabric: disaggregate prefill from decode — a "
             "dedicated prefill worker fills KV blocks and ships "
             "them to the decode replicas over the zero-copy tensor "
             "wire")
    parser.add_argument(
        "--serve-tenant", action="append", default=None,
        metavar="NAME=RATE[:BURST][@ARTIFACT]",
        help="serving fabric: register a tenant with a token-bucket "
             "quota (repeatable); once any tenant is registered, "
             "requests without a known X-Tenant get 403 and "
             "over-quota tenants get 429 + Retry-After without "
             "shedding siblings")


def serving_config_defaults():
    """Serving kwargs from ``root.common.serving`` (populated by the
    ``--serve-*`` flags); explicit unit kwargs win."""
    out = {}
    for key in ("max_batch", "queue_depth", "rate_limit", "deadline",
                "token", "warmup", "kv_blocks", "kv_block_size",
                "kv_dtype", "paged", "drain_timeout", "reload_watch",
                "reload_poll", "spec", "spec_draft", "spec_max_k",
                "spec_draft_blocks", "fabric_replicas",
                "fabric_disagg", "tenant"):
        value = root.common.serving.get(key)
        if value is not None:
            out[key] = value
    return out


def _decode_input(payload, input_shape):
    """Accepts {"input": nested lists} or {"input": base64, "shape":
    [...]} (reference accepted both forms, restful_api.py:137-165)."""
    if "input" not in payload:
        raise Bug("request JSON lacks 'input'")
    raw = payload["input"]
    if isinstance(raw, str):
        blob = base64.b64decode(raw)
        x = numpy.frombuffer(blob, dtype=numpy.float32).copy()
        shape = payload.get("shape")
        if shape:
            x = x.reshape(shape)
    else:
        x = numpy.asarray(raw, dtype=numpy.float32)
    sample = int(numpy.prod(input_shape)) if input_shape else x.size
    if x.ndim == 1 and sample and x.size == sample:
        x = x[None]  # single flat sample
    if x.ndim >= 1 and sample and x.size % sample == 0:
        return x.reshape(-1, sample)
    raise Bug("input of %d elements does not tile %d-element samples"
              % (x.size, sample))


class ModelServer(JsonHttpServer):
    """Serves an exported artifact over HTTP through the serving
    engine (bounded queue, dynamic batching, admission control)."""

    def __init__(self, model, host="0.0.0.0", port=8180, token=None,
                 max_batch=8, queue_depth=64, rate_limit=None,
                 deadline=30.0, warmup=False, policy=None,
                 paged=None, kv_blocks=None, kv_block_size=16,
                 kv_dtype=None,
                 drain_timeout=30.0, reload_watch=None,
                 reload_poll=5.0, spec=False, spec_draft=None,
                 spec_max_k=4, spec_draft_blocks=None,
                 fabric_replicas=1, fabric_disagg=False,
                 tenant=None):
        if isinstance(model, str):
            model = ExportedModel(model)
        self.token = token
        self.deadline = deadline
        self.warmup = warmup

        def build_engine():
            # Replicas share the MODEL object (weights + compile
            # cache: one warmup covers the fleet) but own their
            # queue, device thread, and KV pool.
            return ServingEngine(
                model, max_batch=max_batch,
                queue_depth=queue_depth, policy=policy,
                default_deadline=deadline, paged=paged,
                kv_blocks=kv_blocks, kv_block_size=kv_block_size,
                kv_dtype=kv_dtype,
                spec=spec, spec_draft=spec_draft,
                spec_max_k=spec_max_k,
                spec_draft_blocks=spec_draft_blocks,
                drain_timeout=drain_timeout)

        self.engine = build_engine()
        self.fabric = None
        self._fabric_engines = [self.engine]
        fabric_replicas = int(fabric_replicas or 1)
        if fabric_replicas > 1 or fabric_disagg or tenant:
            from .serving.fabric import (ModelRegistry,
                                         PrefillWorker,
                                         ReplicaRouter,
                                         parse_tenant_spec)
            registry = None
            if tenant:
                registry = ModelRegistry()
                specs = [tenant] if isinstance(tenant, str) \
                    else list(tenant)
                for spec in specs:
                    name, rate, burst, artifact = \
                        parse_tenant_spec(spec) \
                        if isinstance(spec, str) else spec
                    registry.register(name, rate=rate, burst=burst,
                                      artifact=artifact)
            prefill = PrefillWorker(build_engine()) \
                if fabric_disagg else None
            self.fabric = ReplicaRouter(registry=registry,
                                        prefill=prefill)
            self.fabric.add_replica("r0", self.engine)
            for i in range(1, fabric_replicas):
                engine = build_engine()
                self._fabric_engines.append(engine)
                self.fabric.add_replica("r%d" % i, engine)
        self.limiter = RateLimiter(rate_limit) if rate_limit else None
        self.reload_watch = reload_watch
        self.reload_poll = reload_poll
        self.watcher = None

        class Handler(JsonRequestHandler):
            def do_GET(self):
                outer = self.outer
                if self.path in ("/", "/health"):
                    m = outer.model.manifest
                    self.reply(200, {
                        "status": "ok",
                        "workflow": m.get("workflow"),
                        "units": [u["type"] for u in m["units"]],
                        "input": m["input"], "output": m["output"],
                        "queue_depth":
                            outer.engine.queue_depth_now(),
                    })
                elif self.path == "/stats":
                    self.reply(200, outer.stats_payload())
                elif self.path == "/metrics":
                    from .observability.metrics import CONTENT_TYPE
                    self.reply(200, outer.metrics_text(),
                               CONTENT_TYPE)
                else:
                    self.reply(404, {"error": "not found"})

            def _admit(self):
                """Rate-limit gate; replies 429 and returns False
                when the client's bucket is dry."""
                outer = self.outer
                if outer.limiter is None:
                    return True
                try:
                    outer.limiter.admit(self.client_id())
                    return True
                except AdmissionError as e:
                    outer.engine.stats.incr("rejected.rate_limited")
                    self.reply(e.status, {"error": str(e)},
                               headers=_retry_headers(e))
                    return False

            def _deadline(self, payload):
                """The request's deadline: client-suggested (clamped
                to the server budget) or the server default."""
                budget = self.outer.deadline
                try:
                    want = float(payload.get("deadline", budget))
                except (TypeError, ValueError):
                    want = budget
                if budget is None:
                    return Deadline(want) if want else None
                return Deadline(max(0.0, min(want, budget)))

            def _tenant(self, payload):
                """Tenant identity: the ``X-Tenant`` header wins,
                else ``payload["tenant"]``, else anonymous (the
                ``default`` tenant when tenancy is configured)."""
                tenant = self.headers.get("X-Tenant")
                if tenant is None and isinstance(payload, dict):
                    tenant = payload.get("tenant")
                return tenant

            def do_POST(self):
                outer = self.outer
                if self.path == "/api/generate":
                    self._generate()
                    return
                if self.path == "/admin/reload":
                    self._admin_reload()
                    return
                if self.path != "/api":
                    self.reply(404, {"error": "not found"})
                    return
                try:
                    # Read the body BEFORE any early reply — closing
                    # the socket with the request unread resets the
                    # client's connection instead of delivering the
                    # status.
                    payload = self.read_json()
                except Exception as e:
                    self.reply(400, {"error": str(e)})
                    return
                if not self._admit():
                    return
                try:
                    x = _decode_input(
                        payload,
                        outer.model.manifest["input"]["sample_shape"])
                except Exception as e:  # malformed request -> 400
                    outer.warning("bad /api request: %s", e)
                    self.reply(400, {"error": str(e)})
                    return
                try:
                    probs = outer.submit_classify(
                        x, deadline=self._deadline(payload),
                        tenant=self._tenant(payload))
                    flat = probs.reshape(probs.shape[0], -1)
                    self.reply(200, {
                        "output": flat,
                        "labels": numpy.argmax(flat, axis=-1),
                    })
                except AdmissionError as e:  # backpressure/deadline
                    self.reply(e.status, {"error": str(e)},
                               headers=_retry_headers(e))
                except Bug as e:  # client-shaped fault -> 400
                    self.reply(400, {"error": str(e)})
                except Exception:  # server-side fault -> 500
                    outer.exception("/api forward failed")
                    self.reply(500,
                               {"error": "internal server error"})

            def _generate(self):
                """POST /api/generate — KV-cache incremental decoding
                over an LM artifact: {"tokens": [[...]],
                "max_new_tokens": N, "temperature": T, "seed": S} →
                {"tokens": full sequences, "generated": new part}.
                Decode steps of concurrent requests coalesce into
                shape-bucketed batches on the device thread.  When
                the server holds a token, the X-Status-Token header
                must match (the same shared-secret gate web_status
                uses for graphviz rendering — compile-heavy surfaces
                are not left open)."""
                outer = self.outer
                try:
                    # Drain the body before any early reply (see
                    # do_POST).
                    payload = self.read_json()
                except Exception as e:
                    self.reply(400, {"error": str(e)})
                    return
                if outer.token is not None and \
                        not self.check_token(outer.token):
                    self.reply(403, {"error": "bad token"})
                    return
                if not self._admit():
                    return
                try:
                    tokens = numpy.atleast_2d(numpy.asarray(
                        payload["tokens"], dtype=numpy.int32))
                    max_new = int(payload.get("max_new_tokens", 32))
                    cap = outer.engine.policy.new_cap or 4096
                    if not 1 <= max_new <= cap:
                        # Same bound the engine enforces (its
                        # policy.new_cap) — checked here too so the
                        # refusal costs no queue slot.
                        raise Bug("max_new_tokens out of range "
                                  "(1..%d)" % cap)
                    temperature = float(
                        payload.get("temperature", 0.0))
                    seed = int(payload.get("seed", 0))
                except Exception as e:
                    outer.warning("bad /api/generate request: %s", e)
                    self.reply(400, {"error": str(e)})
                    return
                try:
                    full = outer.submit_generate(
                        tokens, max_new, temperature=temperature,
                        seed=seed, deadline=self._deadline(payload),
                        tenant=self._tenant(payload))
                except AdmissionError as e:
                    self.reply(e.status, {"error": str(e)},
                               headers=_retry_headers(e))
                    return
                except Bug as e:
                    # Not-an-LM artifact / over-long request: the
                    # client's problem, with the reason.
                    self.reply(400, {"error": str(e)})
                    return
                except Exception:
                    outer.exception("/api/generate failed")
                    self.reply(500,
                               {"error": "internal server error"})
                    return
                self.reply(200, {
                    "tokens": full,
                    "generated": full[:, tokens.shape[1]:],
                })

            def _admin_reload(self):
                """POST /admin/reload — hot weight reload of a named
                (or the watched) artifact.  AUTHENTICATED: the server
                must hold a token and the X-Status-Token header must
                match — an open endpoint that loads
                operator-supplied paths would be an arbitrary-file
                primitive, so tokenless servers refuse outright."""
                outer = self.outer
                try:
                    payload = self.read_json()
                except Exception as e:
                    self.reply(400, {"error": str(e)})
                    return
                if outer.token is None:
                    self.reply(403, {"error": "reload requires the "
                                              "server to hold a "
                                              "--token"})
                    return
                if not self.check_token(outer.token):
                    self.reply(403, {"error": "bad token"})
                    return
                path = payload.get("artifact")
                try:
                    if payload.get("draft"):
                        # {"draft": true}: hot-swap the speculative
                        # DRAFT model instead of the target (same
                        # verified-read chain).
                        version = outer.reload_draft_artifact(path)
                    else:
                        version = outer.reload_artifact(path)
                except ArtifactRejected as e:
                    self.reply(409, {"error": str(e)})
                    return
                except AdmissionError as e:
                    self.reply(e.status, {"error": str(e)},
                               headers=_retry_headers(e))
                    return
                except Exception as e:
                    outer.exception("/admin/reload failed")
                    self.reply(500, {"error": str(e)})
                    return
                self.reply(200, {"status": "reloaded",
                                 "weight_version": version})

        super(ModelServer, self).__init__(
            Handler, host=host, port=port,
            thread_name="veles-model-server")

    @property
    def model(self):
        """The CURRENTLY served model — owned by the engine, so a
        drain-and-swap reload is visible to /health and /stats the
        moment it lands."""
        return self.engine.model

    def submit_generate(self, tokens, max_new, temperature=0.0,
                        seed=0, deadline=None, tenant=None):
        """Generate through the fabric when one is configured
        (tenant admission + prefix-affine replica routing), else
        straight into the single engine."""
        if self.fabric is not None:
            return self.fabric.submit_generate(
                tokens, max_new, temperature=temperature, seed=seed,
                deadline=deadline, tenant=tenant)
        return self.engine.submit_generate(
            tokens, max_new, temperature=temperature, seed=seed,
            deadline=deadline)

    def submit_classify(self, x, deadline=None, tenant=None):
        if self.fabric is not None:
            return self.fabric.submit_classify(x, deadline=deadline,
                                               tenant=tenant)
        return self.engine.submit_classify(x, deadline=deadline)

    def reload_artifact(self, path=None, require_manifest=None):
        """Verify-and-reload: ``path`` (default: whatever the watch
        target currently names) is read once, gated through its
        sha256 sidecar manifest (and the ``serve.reload_corrupt``
        chaos point), and hot-swapped into the engine.  Manifests are
        REQUIRED for watcher-driven reloads (unattended deployment
        trusts nothing unverified) and optional for explicit
        operator paths.  Returns the new weight version; raises
        :class:`~veles_tpu.serving.reload.ArtifactRejected` and
        keeps the old weights on any verification failure."""
        from .serving.reload import read_verified, resolve_artifact
        explicit = path is not None
        if path is None:
            if self.reload_watch is None:
                raise ArtifactRejected(
                    "no artifact named and no --reload-watch target "
                    "configured")
            path = resolve_artifact(self.reload_watch)
            if path is None:
                raise ArtifactRejected(
                    "watch target %s names no serving artifact yet"
                    % self.reload_watch)
        if require_manifest is None:
            require_manifest = not explicit
        blob = read_verified(path, injector=self.engine.injector,
                             require_manifest=require_manifest)
        version = self.engine.reload(blob)
        self.engine.stats.incr("reload.artifacts")
        self.info("hot-reloaded %s -> weight version %d", path,
                  version)
        return version

    def reload_draft_artifact(self, path):
        """Verify-and-reload for the speculative DRAFT model: the
        artifact is read once through the same sha256-sidecar gate
        as a target reload, geometry/vocabulary-checked against the
        served model, and hot-swapped into the drafter — live target
        streams never notice (drafts are proposals, not truth)."""
        from .serving.reload import read_verified
        if path is None:
            raise ArtifactRejected(
                "a draft reload needs an explicit artifact path")
        blob = read_verified(path, injector=self.engine.injector,
                             require_manifest=False)
        version = self.engine.reload_draft(blob)
        self.engine.stats.incr("spec.draft_artifacts")
        self.info("hot-reloaded draft %s -> draft version %d", path,
                  version)
        return version

    def _on_watch_change(self, path):
        self.reload_artifact(path, require_manifest=True)

    def stats_payload(self):
        """The /stats body: engine + compile-cache observability."""
        payload = self.engine.stats.snapshot()
        payload["queue_depth"] = self.engine.queue_depth_now()
        payload["max_batch"] = self.engine.max_batch
        payload["weight_version"] = self.engine.weight_version
        cache = getattr(self.model, "compile_cache", None)
        if cache is not None:
            payload["compile_cache"] = cache.stats()
        pool = self.engine.kv_pool
        if pool is not None:
            payload["kv_pool"] = pool.occupancy()
        if self.limiter is not None:
            payload["rate_limit"] = {"rate": self.limiter.rate,
                                     "clients": len(self.limiter)}
        if self.fabric is not None:
            payload["fabric"] = self.fabric.occupancy()
        return payload

    def metrics_text(self):
        """``GET /metrics``: Prometheus text exposition of the
        process registry (net.*, chaos.*, device MFU gauges — the
        resilience shim feeds it) plus this engine's serving registry
        (request/batch counters, latency histograms, KV-pool gauges),
        with the derived gauges refreshed at scrape time
        (docs/observability.md)."""
        from .observability import metrics as obs_metrics
        stats = self.engine.stats
        stats.refresh_gauges()
        stats.set_gauge("queue_depth", self.engine.queue_depth_now())
        pool = self.engine.kv_pool
        if pool is not None:
            occ = pool.occupancy()
            stats.set_gauge("kv_blocks_used", occ["blocks_used"])
            stats.set_gauge("kv_blocks_total", occ["blocks_total"])
        return obs_metrics.render_prometheus(
            [obs_metrics.registry, stats.registry])

    def _spin_up(self):
        for engine in self._fabric_engines:
            engine.start()
        if self.fabric is not None and self.fabric.prefill is not None:
            self.fabric.prefill.engine.start()
        if self.warmup:
            # Replicas share the model's compile cache: warming the
            # primary warms the program family for the whole fleet.
            self.engine.warmup()
        if self.reload_watch is not None and self.watcher is None:
            from .serving.reload import ArtifactWatcher
            self.watcher = ArtifactWatcher(
                self.reload_watch, self._on_watch_change,
                poll=self.reload_poll).start()

    def start(self):
        self._spin_up()
        return super(ModelServer, self).start()

    def serve(self):
        self._spin_up()
        self.info("serving model on port %d (POST /api)", self.port)
        super(ModelServer, self).serve()

    def stop(self, drain=False, timeout=None):
        """``drain=True`` is the graceful path: the engine closes
        admissions (503 + Retry-After), live decode rows finish
        within the drain budget, THEN the listener goes down — so
        every in-flight HTTP response is delivered and late arrivals
        get an honest 503 instead of a connection reset."""
        if self.watcher is not None:
            self.watcher.stop()
            self.watcher = None
        if drain:
            if self.fabric is not None:
                self.fabric.stop(drain=True, timeout=timeout)
            else:
                self.engine.stop(drain=True, timeout=timeout)
            super(ModelServer, self).stop()
        else:
            super(ModelServer, self).stop()
            if self.fabric is not None:
                self.fabric.stop(drain=False, timeout=timeout)
            else:
                self.engine.stop()


def _retry_headers(e):
    if e.retry_after is None:
        return None
    return {"Retry-After": "%d" % max(1, round(e.retry_after))}


class RESTfulAPI(Unit):
    """In-workflow serving unit (reference: restful_api.py:78): link
    it after the Decision; when the workflow finishes training it
    exports the forward chain and serves until stopped — through the
    serving engine (shape-bucketed dynamic batching, admission
    control, paged decode-step batching over LM artifacts),
    configured by the ``--serve-max-batch`` /
    ``--serve-queue-depth`` / ``--serve-rate-limit`` /
    ``--serve-deadline`` / ``--serve-token`` / ``--serve-warmup`` /
    ``--serve-kv-blocks`` / ``--serve-kv-block-size`` /
    ``--serve-no-paged`` / ``--serve-spec`` /
    ``--serve-spec-draft`` / ``--serve-spec-max-k`` /
    ``--serve-spec-draft-blocks`` / ``--serve-drain-timeout`` /
    ``--serve-reload-watch`` / ``--serve-reload-poll`` CLI flags or
    the matching kwargs below."""

    def __init__(self, workflow, **kwargs):
        super(RESTfulAPI, self).__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        kwargs = dict(serving_config_defaults(), **kwargs)
        self.host = kwargs.get("host", "0.0.0.0")
        self.port = kwargs.get("port", 8180)
        self.artifact_path = kwargs.get("artifact_path",
                                        "served.veles.tgz")
        self.blocking = kwargs.get("blocking", False)
        self.max_batch = kwargs.get("max_batch", 8)
        self.queue_depth = kwargs.get("queue_depth", 64)
        self.rate_limit = kwargs.get("rate_limit", None)
        self.deadline = kwargs.get("deadline", 30.0)
        self.token = kwargs.get("token", None)
        self.warmup = kwargs.get("warmup", False)
        self.paged = kwargs.get("paged", None)
        self.kv_blocks = kwargs.get("kv_blocks", None)
        self.kv_block_size = kwargs.get("kv_block_size", 16)
        self.drain_timeout = kwargs.get("drain_timeout", 30.0)
        self.reload_watch = kwargs.get("reload_watch", None)
        self.reload_poll = kwargs.get("reload_poll", 5.0)
        self.spec = kwargs.get("spec", False)
        self.spec_draft = kwargs.get("spec_draft", None)
        self.spec_max_k = kwargs.get("spec_max_k", 4)
        self.spec_draft_blocks = kwargs.get("spec_draft_blocks",
                                            None)
        self.fabric_replicas = kwargs.get("fabric_replicas", 1)
        self.fabric_disagg = kwargs.get("fabric_disagg", False)
        self.tenant = kwargs.get("tenant", None)
        self.server = None

    def run(self):
        if self.server is not None:
            return
        export_workflow(self.workflow, self.artifact_path)
        self.server = ModelServer(
            self.artifact_path, host=self.host, port=self.port,
            token=self.token, max_batch=self.max_batch,
            queue_depth=self.queue_depth, rate_limit=self.rate_limit,
            deadline=self.deadline, warmup=self.warmup,
            paged=self.paged, kv_blocks=self.kv_blocks,
            kv_block_size=self.kv_block_size,
            spec=self.spec, spec_draft=self.spec_draft,
            spec_max_k=self.spec_max_k,
            spec_draft_blocks=self.spec_draft_blocks,
            drain_timeout=self.drain_timeout,
            reload_watch=self.reload_watch,
            reload_poll=self.reload_poll,
            fabric_replicas=self.fabric_replicas,
            fabric_disagg=self.fabric_disagg,
            tenant=self.tenant)
        self.port = self.server.port
        if self.blocking:
            self.server.serve()
        else:
            self.server.start()

    def stop(self):
        if self.server is not None:
            self.server.stop()
            self.server = None
        super(RESTfulAPI, self).stop()
