"""Workflow: the unit-graph container and host-side run driver.

Capability parity with the reference workflow (reference:
veles/workflow.py — ``Workflow:78``, ``initialize:286``, ``run:338``,
``generate_graph:615``, ``checksum:839``): owns the unit set plus
StartPoint/EndPoint, initializes units in dependency order with
partial-init requeue (workflow.py:307-331), aggregates the
IDistributable contract over member units (workflow.py:443-543),
executes worker jobs (``do_job``, workflow.py:545), renders a Graphviz
graph, collects per-unit runtime stats (workflow.py:754-812) and results
JSON (workflow.py:814-836), and identifies itself by a source checksum
for coordinator/worker matching (workflow.py:839-853).

Execution-model change for TPU: the reference runs units concurrently on
a Twisted thread pool; here :meth:`run` drives a deterministic FIFO work
queue on the host — cheap, reproducible, and sufficient because the
actual compute is inside jitted step functions that XLA parallelizes
on-device (see accelerated_units.AcceleratedWorkflow, which fuses the
whole Repeater loop body into one XLA computation per tick).
"""

import collections
import hashlib
import inspect
import threading
import time

from .error import Bug
from .mutable import Bool
from .plumbing import StartPoint, EndPoint
from .result_provider import IResultProvider
from .units import Container


class Workflow(Container):
    """A directed graph of units (reference: workflow.py:78)."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        self._units = []
        self._stopped_b = Bool(False)
        self._finished_ = threading.Event()
        self._queue_ = collections.deque()
        self.result_file = kwargs.get("result_file")
        super(Workflow, self).__init__(workflow, **kwargs)
        self.start_point = StartPoint(self)
        self.end_point = EndPoint(self)
        self.negotiates_on_connect = True
        self._sync = kwargs.get("sync", True)
        self.run_is_blocking = self._sync

    def init_unpickled(self):
        super(Workflow, self).init_unpickled()
        self._finished_ = threading.Event()
        self._queue_ = collections.deque()
        self._run_time_started_ = time.time()
        # Wire-protocol state (transient — renegotiated per session):
        # master side keys per-slave negotiated capabilities, worker
        # side holds this session's negotiated protocol; both are
        # consulted by units' distributed-contract methods
        # (docs/distributed.md).
        self._slave_proto_ = {}
        self._net_proto_ = {}
        self._weights_version_ = 0

    @property
    def mesh(self):
        """The device mesh the parallel appliers bound (TRANSIENT —
        a jax Mesh holds live Device objects, so it must never ride a
        snapshot; restore re-applies shardings onto whatever topology
        exists then, the SURVEY §7 'resume onto a different topology'
        contract)."""
        return getattr(self, "_mesh_", None)

    @mesh.setter
    def mesh(self, value):
        self._mesh_ = value

    # -- ownership ---------------------------------------------------------

    @property
    def launcher(self):
        """The owning launcher (walks up through parent workflows)."""
        parent = self._workflow
        if parent is None:
            return None
        if isinstance(parent, Workflow):
            return parent.launcher
        return parent  # a Launcher-like object

    @property
    def workflow(self):
        return self._workflow

    @workflow.setter
    def workflow(self, value):
        self._workflow = value

    @property
    def is_main(self):
        return not isinstance(self._workflow, Workflow)

    @property
    def units(self):
        return list(self._units)

    @property
    def units_in_dependency_order(self):
        return self._topological_order()

    def add_ref(self, unit):
        """Registers a unit; names are made unique (step-state keys and
        ``wf[name]`` lookups depend on it)."""
        if unit is self:
            raise Bug("a workflow cannot contain itself")
        if unit not in self._units:
            taken = {u.name for u in self._units}
            if unit.name in taken:
                i = 1
                while "%s_%d" % (unit.name, i) in taken:
                    i += 1
                unit.name = "%s_%d" % (unit.name, i)
            self._units.append(unit)
        unit.workflow = self

    def del_ref(self, unit):
        if unit in self._units:
            self._units.remove(unit)

    def __getitem__(self, name):
        for unit in self._units:
            if unit.name == name:
                return unit
        raise KeyError(name)

    def index_of(self, unit):
        return self._units.index(unit)

    # -- stopping ----------------------------------------------------------

    @property
    def stopped(self):
        return bool(self._stopped_b)

    @stopped.setter
    def stopped(self, value):
        self._stopped_b <<= value

    @property
    def is_running(self):
        return not self._finished_.is_set()

    # -- initialize --------------------------------------------------------

    def initialize(self, **kwargs):
        """Initializes units in dependency order; units raising
        AttributeError (unmet demands) are requeued until a full pass
        makes no progress (reference: workflow.py:307-331)."""
        self._is_initialized = True
        pending = self._topological_order()
        max_rounds = len(pending) + 2
        for _ in range(max_rounds):
            if not pending:
                break
            retry = []
            errors = {}
            for unit in pending:
                if unit is self:
                    continue
                try:
                    unit.initialize(**kwargs)
                except AttributeError as e:
                    errors[unit] = e
                    retry.append(unit)
            if len(retry) == len(pending):
                details = "; ".join(
                    "%s: %s" % (u.name, e) for u, e in errors.items())
                raise AttributeError(
                    "workflow initialize deadlock — units with unmet "
                    "demands: %s" % details)
            pending = retry
        self.debug("%s initialized (%d units)", self.name,
                   len(self._units))
        return self

    def _topological_order(self):
        """Kahn's algorithm over control links, falling back to insertion
        order for unlinked units."""
        units = [u for u in self._units]
        indeg = {u: 0 for u in units}
        for u in units:
            for dst in u.links_to:
                if dst in indeg:
                    indeg[dst] += 1
        queue = collections.deque(
            u for u in units if indeg[u] == 0)
        order = []
        seen = set()
        while queue:
            u = queue.popleft()
            if u in seen:
                continue
            seen.add(u)
            order.append(u)
            for dst in u.links_to:
                if dst in indeg:
                    indeg[dst] -= 1
                    if indeg[dst] <= 0 and dst not in seen:
                        queue.append(dst)
        # Cycles (the Repeater loop) leave units unvisited; append them
        # in insertion order.
        for u in units:
            if u not in seen:
                order.append(u)
        return order

    # -- run driver --------------------------------------------------------

    def schedule(self, dst, src):
        """Enqueues a (unit, fired-from) control event."""
        self._queue_.append((dst, src))

    def run(self):
        """Runs the graph to completion (reference: workflow.py:338).

        Deterministic FIFO propagation: StartPoint fires, events are
        drained until the EndPoint runs (``on_workflow_finished``) or
        the queue empties.
        """
        self._finished_.clear()
        self.stopped = False
        self._run_time_started_ = time.time()
        self.event("workflow_run", "begin", workflow=self.name)
        self.start_point._run_timed()
        self.start_point.run_dependent()
        while self._queue_ and not self._finished_.is_set():
            dst, src = self._queue_.popleft()
            dst.check_gate_and_run(src)
        if not self._finished_.is_set():
            # Graph drained without reaching the end point — that is a
            # completed run for loop-less diagnostic graphs.
            self.on_workflow_finished()
        self.event("workflow_run", "end", workflow=self.name)

    def on_workflow_finished(self):
        self._finished_.set()
        self._queue_.clear()
        launcher = self.launcher
        if self.is_main and launcher is not None:
            launcher.on_workflow_finished()

    def stop(self):
        """Requests a stop: running loop units observe ``stopped`` and
        gate out (reference: workflow.py ``stop``)."""
        self.stopped = True
        for unit in self._units:
            if unit is not self:
                unit.stop()
        self.on_workflow_finished()

    # -- worker-job execution (control plane) ------------------------------

    def do_job(self, data, update, callback):
        """Executes one coordinator-issued job on this worker
        (reference: workflow.py:545): apply master data, run the graph,
        hand results back."""
        self.apply_data_from_master(data)
        if update is not None:
            self.apply_update_from_master(update)
        self.run()
        callback(self.generate_data_for_master())

    # -- IDistributable aggregation over units -----------------------------

    def generate_data_for_slave(self, slave=None):
        data = {}
        for unit in self._units:
            if unit is self:
                continue
            # The unit's deadlock-sniffing data lock guards its
            # distributed state against the other control-plane
            # threads (snapshotter, serving, watchdog) — the
            # reference's ``_data_threadsafe`` wrapper
            # (distributable.py:139-157), applied at the aggregation
            # point instead of per-method decorators.
            with unit.data_threadsafe():
                piece = unit.generate_data_for_slave(slave)
            if piece is not None:
                data[unit.name] = piece
        return data

    def generate_initial_data_for_slave(self, slave=None):
        """Handshake-phase data from units with
        ``negotiates_on_connect`` (reference: workflow.py:565-602)."""
        data = {}
        for unit in self._units:
            if unit is self or not unit.negotiates_on_connect:
                continue
            with unit.data_threadsafe():
                piece = unit.generate_data_for_slave(slave)
            if piece is not None:
                data[unit.name] = piece
        return data

    def apply_data_from_slave(self, data, slave=None):
        for unit in self._units:
            if unit is self:
                continue
            if data and unit.name in data:
                with unit.data_threadsafe():
                    unit.apply_data_from_slave(data[unit.name],
                                               slave)
        if self.is_main:
            # One version bump per applied worker update (delta-sync
            # staleness bookkeeping; nested workflows defer to the
            # main one so the counter is bumped exactly once).
            self.bump_weights_version()

    def apply_data_from_master(self, data):
        for unit in self._units:
            if unit is self:
                continue
            if data and unit.name in data:
                with unit.data_threadsafe():
                    unit.apply_data_from_master(data[unit.name])

    def apply_update_from_master(self, update):
        self.apply_data_from_master(update)

    def generate_data_for_master(self):
        data = {}
        for unit in self._units:
            if unit is self:
                continue
            piece = unit.generate_data_for_master()
            if piece is not None:
                data[unit.name] = piece
        return data

    def drop_slave(self, slave=None):
        for unit in self._units:
            if unit is not self:
                unit.drop_slave(slave)
        self._slave_proto_.pop(slave, None)

    # -- wire-protocol negotiation state (docs/distributed.md) -------------

    def note_slave_protocol(self, slave, proto):
        """Master side: records the handshake-negotiated protocol for
        one worker (delta sync on/off, job ticks, wire dtype) — units
        consult :meth:`slave_protocol` when generating/applying that
        worker's data."""
        self._slave_proto_[slave] = dict(proto or {})

    def slave_protocol(self, slave):
        """The negotiated protocol dict for ``slave`` ({} = legacy
        pickle-compat peer).  Nested workflows delegate to their
        parent — the Server only notifies the main workflow."""
        proto = self._slave_proto_.get(slave)
        if proto is None and isinstance(self._workflow, Workflow):
            return self._workflow.slave_protocol(slave)
        return proto or {}

    def note_net_proto(self, proto):
        """Worker side: records this session's negotiated protocol
        (set by the Client after its handshake)."""
        self._net_proto_ = dict(proto or {})

    @property
    def net_proto(self):
        """The worker session's negotiated protocol ({} = legacy)."""
        if not self._net_proto_ and isinstance(self._workflow,
                                               Workflow):
            return self._workflow.net_proto
        return self._net_proto_

    @property
    def weights_version(self):
        """Monotonic master-side weights version: bumps once per
        applied worker update; rides job metadata so staleness is
        observable and delta bases are verifiable.  Nested workflows
        read the main workflow's counter."""
        if isinstance(self._workflow, Workflow):
            return self._workflow.weights_version
        return self._weights_version_

    def bump_weights_version(self):
        if isinstance(self._workflow, Workflow):
            return self._workflow.bump_weights_version()
        self._weights_version_ += 1
        return self._weights_version_

    # -- introspection -----------------------------------------------------

    def generate_graph(self, filename=None, write_on_disk=True):
        """Renders the control graph as Graphviz DOT text
        (reference: workflow.py:615)."""
        lines = ["digraph %s {" % type(self).__name__.replace(" ", "_")]
        ids = {u: "u%d" % i for i, u in enumerate(self._units)}
        for u in self._units:
            shape = "rect"
            if u is self.start_point or u is self.end_point:
                shape = "circle"
            lines.append('  %s [label="%s" shape=%s];' %
                         (ids[u], u.name, shape))
        for u in self._units:
            for dst in u.links_to:
                if dst in ids:
                    lines.append("  %s -> %s;" % (ids[u], ids[dst]))
        lines.append("}")
        text = "\n".join(lines)
        if write_on_disk and filename is not None:
            with open(filename, "w") as fout:
                fout.write(text)
        return text

    def print_stats(self, top_number=5, flat=False):
        """Logs top-N units by accumulated run time
        (reference: workflow.py:754-812).

        Counters are grouped by their dotted prefix (``net``,
        ``chaos``, ``server``, ``device``, …) with zero-valued
        entries and empty sections suppressed, so the exit report
        stays readable as the metric set grows; ``flat=True`` keeps
        the historical one-line ``name=value`` format (tests that
        grep for full dotted names use it)."""
        stats = sorted(((u.run_time, u) for u in self._units
                        if u is not self),
                       key=lambda p: p[0], reverse=True)
        total = sum(p[0] for p in stats) or 1e-12
        self.info("Run time: %.2fs; top units:",
                  time.time() - self._run_time_started_)
        for rt, u in stats[:top_number]:
            self.info("  %-24s %8.3fs (%4.1f%%, %d runs)",
                      u.name, rt, 100.0 * rt / total, u.run_count)
        # Resilience/comms/device counters ride the same stats report
        # so degraded runs are visible right next to the timing table.
        from . import resilience
        events = {k: v for k, v in
                  resilience.stats.snapshot().items() if v}
        if events:
            if flat:
                self.info("Resilience events: %s", "; ".join(
                    "%s=%d" % (k, v)
                    for k, v in sorted(events.items())))
            else:
                groups = {}
                for name, value in events.items():
                    prefix, _, rest = name.partition(".")
                    groups.setdefault(prefix, []).append(
                        (rest or name, value))
                self.info("Counters:")
                for prefix in sorted(groups):
                    self.info("  %-10s %s", prefix + ":", "; ".join(
                        "%s=%s" % (k, v)
                        for k, v in sorted(groups[prefix])))
        # Training health: a recovered run must still LOOK sick in
        # the exit report, or nobody audits what the guardian ate.
        guardian = getattr(self, "guardian", None)
        if guardian is not None and getattr(guardian, "events", None):
            self.warning(
                "Health events (%d, policy %s): %s",
                len(guardian.events), guardian.policy, "; ".join(
                    "epoch %s %s->%s" % (e.get("epoch"),
                                         e.get("kind"),
                                         e.get("action"))
                    for e in guardian.events[-5:]))

    def gather_results(self):
        """Collects metrics from IResultProvider units into a dict
        (reference: workflow.py:814-836)."""
        results = {}
        for unit in self._units:
            if isinstance(unit, IResultProvider) and unit is not self:
                names = unit.get_metric_names()
                values = unit.get_metric_values()
                if isinstance(values, dict):
                    results.update(values)
                else:
                    for n, v in zip(names, values):
                        results[n] = v
        return results

    @property
    def checksum(self):
        """SHA1 of the defining source file, for coordinator/worker
        match verification (reference: workflow.py:839-853)."""
        try:
            src = inspect.getsourcefile(type(self))
            with open(src, "rb") as fin:
                data = fin.read()
        except (TypeError, OSError):
            data = type(self).__name__.encode()
        return hashlib.sha1(data).hexdigest() + "_" + type(self).__name__

    # -- pickling ----------------------------------------------------------

    def __getstate__(self):
        """Snapshots exclude the launcher — it holds live process state
        (locks, events) and is re-attached on resume
        (reference: __main__.py:597-609)."""
        state = super(Workflow, self).__getstate__()
        if not isinstance(state.get("_workflow"), Workflow):
            state["_workflow"] = None
        return state

    # -- running as a nested unit ------------------------------------------

    def check_gate_and_run(self, src):
        if not self.open_gate(src):
            return
        if self.gate_block:
            return
        if not self.gate_skip:
            self._run_timed()
        self.run_dependent()
