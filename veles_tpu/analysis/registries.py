"""Registry-contract pass (VL301/VL302).

**VL301 — names are literals.**  The docs-consistency gate
(tests/test_docs_consistency.py) proves every documented stat / span
/ chaos / metric name exists as a *source literal* — which only works
if call sites actually pass literals.  This pass closes the loop:
the name argument of ``stats.incr``, ``set_gauge``,
``observe_latency`` / ``observe_batch`` / ``observe_request``,
registry ``counter``/``gauge``/``histogram``, ``tracing.span`` /
``tracing.begin``, and injector ``check``/``tick`` must be a string
literal or a ``"prefix.%s" % …`` format with a literal left side.
A bare ``Name`` is accepted only when it is a parameter of the
enclosing function (the pass-through idiom: ``RetryPolicy.call(...,
stat=...)`` — its call sites pass literals and are themselves
checked) or a local assigned from a literal.

**VL302 — no silent broad excepts.**  A ``except Exception`` (or
bare ``except:``) handler must do at least one of: re-raise, call a
logging method (``self.exception``/``warning``/…, ``log.*``,
``logging.*``), count via ``stats.incr``, or USE the bound exception
object (storing it for a caller — ``req.error = e`` — propagates it;
dropping it swallows it).  Handlers in device-thread and server-loop
paths should log **and** count (see docs/analysis.md).
"""

import ast
import re

from .core import Finding

#: Dotted observability-name literals (``"net.bytes_sent"``,
#: ``"chaos.%s"``) — the docs-consistency gate's source-scan
#: pattern, owned here so the gate and the linter share ONE
#: definition of "registered literal".
DOTTED_LITERAL_RE = re.compile(
    r"""["']([a-z][a-z0-9_%]*(?:\.[a-z0-9_%]+)+)["']""")


def dotted_source_literals(project):
    """Every dotted string literal in the project's sources as
    ``(exact, wildcards)``: a set of exact names plus compiled
    regexes for ``%s``/``%d``-parameterized families.  This is
    tests/test_docs_consistency.py's scan, generalized into a
    reusable pass — documented stat/span/chaos names must resolve
    against it, and VL301 keeps call sites literal so the scan stays
    sound."""
    literals = set()
    for sf in project.files:
        literals.update(DOTTED_LITERAL_RE.findall(sf.text))
    exact = {lit for lit in literals if "%" not in lit}
    wildcards = [
        re.compile("^" + re.sub(r"%[sd]", r"[a-z0-9_.]+",
                                re.escape(lit).replace(
                                    r"\%s", "%s").replace(
                                    r"\%d", "%d")) + "$")
        for lit in literals if "%" in lit]
    return exact, wildcards

_NAME_SINKS = frozenset((
    "incr", "set_gauge", "observe_latency", "observe_batch",
    "observe_request", "counter", "gauge", "histogram", "span",
    "begin", "check", "tick",
))

#: Receiver spellings that make an attribute call a registry sink.
_RECV_HINTS = ("stats", "registry", "tracing", "trace", "injector",
               "inj")

_LOG_METHODS = frozenset(("debug", "info", "warning", "warn",
                          "error", "exception", "critical", "log",
                          "print_exc"))


def _recv_text(expr):
    """Best-effort dotted text of a receiver expression."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
    elif isinstance(expr, ast.Call):
        func = expr.func
        if isinstance(func, ast.Attribute):
            parts.append(func.attr + "()")
        elif isinstance(func, ast.Name):
            parts.append(func.id + "()")
    return ".".join(reversed(parts))


def _is_sink(call):
    func = call.func
    if not isinstance(func, ast.Attribute) or \
            func.attr not in _NAME_SINKS:
        return False
    recv = _recv_text(func.value)
    last = recv.split(".")[-1] if recv else ""
    if func.attr in ("span", "begin"):
        return last in ("tracing", "trace")
    if func.attr in ("check", "tick"):
        return ("injector" in recv or last in ("inj",) or
                "effective()" in recv)
    if func.attr in ("counter", "gauge", "histogram"):
        return "registry" in recv
    return "stats" in recv or last == "stats"


def _literal_ok(arg):
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return True
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Mod):
        return _literal_ok(arg.left)
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add):
        return _literal_ok(arg.left)
    return False


def _enclosing_scopes(tree):
    """Yields (function node, [statement nodes]) with parent links
    enough to know params + local literal assignments."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _module_literal_consts(sf):
    """Module-level ``_NAME = "literal"`` constants — a registered
    literal by definition (the docs gate's source scan sees them)."""
    out = set()
    for node in sf.tree.body:
        if isinstance(node, ast.Assign) and \
                len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                _literal_ok(node.value):
            out.add(node.targets[0].id)
    return out


def _check_names(sf):
    findings = []
    module_consts = _module_literal_consts(sf)
    for fn in _enclosing_scopes(sf.tree):
        params = {a.arg for a in fn.args.args +
                  fn.args.kwonlyargs + fn.args.posonlyargs}
        if fn.args.vararg:
            params.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            params.add(fn.args.kwarg.arg)
        literal_locals = set()
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and \
                    len(sub.targets) == 1 and \
                    isinstance(sub.targets[0], ast.Name) and \
                    _literal_ok(sub.value):
                literal_locals.add(sub.targets[0].id)
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call) or not _is_sink(sub):
                continue
            if not sub.args:
                continue
            arg = sub.args[0]
            if _literal_ok(arg):
                continue
            if isinstance(arg, ast.Name) and (
                    arg.id in params or arg.id in literal_locals or
                    arg.id in module_consts):
                continue
            func = sub.func
            findings.append(Finding(
                sf.rel, sub.lineno, "VL301",
                "name passed to %s.%s() is not a registered string "
                "literal" % (_recv_text(func.value) or "?",
                             func.attr)))
    # Deduplicate: nested function defs are walked once per
    # enclosing scope.
    seen = set()
    out = []
    for f in findings:
        if (f.line, f.message) not in seen:
            seen.add((f.line, f.message))
            out.append(f)
    return out


def _is_broad(handler):
    if handler.type is None:
        return True
    t = handler.type
    if isinstance(t, ast.Name) and t.id in ("Exception",
                                            "BaseException"):
        return True
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and
                   e.id in ("Exception", "BaseException")
                   for e in t.elts)
    return False


def _handler_is_silent(handler):
    """True when the handler neither raises, logs, counts, nor uses
    the bound exception."""
    exc_name = handler.name
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return False
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) and \
                    func.attr in _LOG_METHODS:
                return False
            if isinstance(func, ast.Attribute) and \
                    func.attr == "incr":
                return False
        if exc_name and isinstance(node, ast.Name) and \
                node.id == exc_name and \
                isinstance(node.ctx, ast.Load):
            return False
    return True


def _check_excepts(sf):
    findings = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Try):
            continue
        for handler in node.handlers:
            if not _is_broad(handler):
                continue
            if _handler_is_silent(handler):
                findings.append(Finding(
                    sf.rel, handler.lineno, "VL302",
                    "broad except swallows the error silently — "
                    "log it (self.exception/log.*), count it "
                    "(resilience.stats.incr), use it, or re-raise"))
    return findings


def run(project):
    findings = []
    for sf in project.files:
        findings.extend(_check_names(sf))
        findings.extend(_check_excepts(sf))
    return findings
