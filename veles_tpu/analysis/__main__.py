"""CLI for veles-lint: ``python -m veles_tpu.analysis``.

Emits findings as ``path:line: RULE-ID message`` (greppable; exit 1
when any unsuppressed, un-baselined finding remains).  ``--baseline``
subtracts a recorded finding set; ``--write-baseline`` records the
current one (the adopt-then-burn-down workflow, docs/analysis.md).
"""

import argparse
import sys

from . import core


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="veles_tpu.analysis",
        description="veles-lint: project-aware static analysis "
                    "(trace hazards, lock discipline, registry "
                    "contracts)")
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: veles_tpu/, "
             "bench.py, __graft_entry__.py)")
    parser.add_argument(
        "--baseline", metavar="FILE", default=None,
        help="suppress findings recorded in FILE")
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="write the current findings to --baseline FILE "
             "(or .veleslint-baseline) and exit 0")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit")
    parser.add_argument(
        "--quiet", action="store_true",
        help="suppress the summary line (findings only)")
    args = parser.parse_args(argv)
    if args.list_rules:
        for rule in sorted(core.RULES):
            print("%s  %s" % (rule, core.RULES[rule]))
        return 0
    root = core.repo_root()
    paths = args.paths or None
    findings = core.run(paths=paths, root=root)
    baseline_path = args.baseline
    if args.write_baseline:
        baseline_path = baseline_path or ".veleslint-baseline"
        core.write_baseline(baseline_path, findings)
        print("wrote %d finding(s) to %s" %
              (len(findings), baseline_path))
        return 0
    if baseline_path:
        findings = core.apply_baseline(
            findings, core.load_baseline(baseline_path))
    for f in findings:
        print(core.format_finding(f))
    if not args.quiet:
        print("veles-lint: %d finding(s)" % len(findings),
              file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
