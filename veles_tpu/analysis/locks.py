"""Lock-discipline pass (VL201/VL202).

**VL201 — guarded-by annotations.**  A field of a threaded class is
annotated where it is initialized::

    self._pending = collections.deque()  # guarded-by: _cond

(or on a ``#:``/``#`` comment line directly above the assignment).
Every other write to that field — plain/augmented/subscript
assignment, ``del``, or a mutating method call (``append``, ``pop``,
``update``, …) — must then sit lexically inside ``with
self.<lock>:``.  Three contexts are exempt by convention:

* construction (``__init__`` / ``init_unpickled`` / ``__setstate__``
  / ``__del__``): the object is not shared yet (or no longer);
* methods whose name ends in ``_locked``: the project's
  caller-holds-the-lock convention (``_fail_queued_locked``);
* an inline ``# lint-ok: VL201 reason`` for the rare justified case.

**VL202 — static acquisition-order graph.**  Within each class the
pass records the lexical nesting of ``with self.<lock>:`` blocks
(plus one level of same-class call expansion: acquiring B inside a
method called under A orders A→B) and reports any cycle in the
resulting directed graph.  Cross-object cycles are the RUNTIME
recorder's job (analysis.runtime.LockOrderRecorder) — static and
runtime enforcement split the problem deliberately.
"""

import ast
import re

from .core import Finding

_GUARDED_RE = re.compile(r"#[:\s]*guarded-by:\s*"
                         r"(?:self\.)?([A-Za-z_][A-Za-z0-9_]*)")

#: Lock-ish constructors: a ``self.X = <ctor>(...)`` marks X a lock.
_LOCK_CTORS = frozenset(("Lock", "RLock", "Condition", "SniffedLock"))

#: Container methods that mutate their receiver.
_MUTATORS = frozenset((
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "remove", "discard", "pop", "popleft", "popitem", "clear",
    "update", "setdefault", "sort", "reverse",
))

#: Methods where unguarded writes are construction, not racing.
_CTOR_METHODS = frozenset(("__init__", "init_unpickled",
                           "__setstate__", "__del__"))


class ClassScan(object):
    def __init__(self, sf, node):
        self.sf = sf
        self.node = node
        self.locks = set()
        self.guarded = {}   # field -> (lock, decl lineno)
        self._find_locks()
        self._find_annotations()

    def _methods(self):
        for item in self.node.body:
            if isinstance(item, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                yield item

    def _find_locks(self):
        for method in self._methods():
            for sub in ast.walk(method):
                if not isinstance(sub, ast.Assign):
                    continue
                if not isinstance(sub.value, ast.Call):
                    continue
                func = sub.value.func
                name = func.attr if isinstance(func, ast.Attribute) \
                    else getattr(func, "id", None)
                if name not in _LOCK_CTORS:
                    continue
                for target in sub.targets:
                    if isinstance(target, ast.Attribute) and \
                            isinstance(target.value, ast.Name) and \
                            target.value.id == "self":
                        self.locks.add(target.attr)

    def _find_annotations(self):
        """guarded-by comments inside the class body, attached to the
        ``self.field = …`` assignment on the same line or on the
        first assignment within the next 3 lines (comment-above
        style)."""
        assign_at = {}
        for method in self._methods():
            for sub in ast.walk(method):
                if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    targets = sub.targets if isinstance(
                        sub, ast.Assign) else [sub.target]
                    for target in targets:
                        if isinstance(target, ast.Attribute) and \
                                isinstance(target.value, ast.Name) \
                                and target.value.id == "self":
                            assign_at.setdefault(sub.lineno,
                                                 target.attr)
        end = getattr(self.node, "end_lineno",
                      self.node.lineno + 10000)
        for lineno in range(self.node.lineno, end + 1):
            m = _GUARDED_RE.search(self.sf.line_text(lineno))
            if not m:
                continue
            lock = m.group(1)
            for cand in range(lineno, min(lineno + 4, end + 1)):
                field = assign_at.get(cand)
                if field is not None:
                    self.guarded[field] = (lock, lineno)
                    break

    # -- write enforcement -------------------------------------------------

    def check_writes(self):
        findings = []
        for method in self._methods():
            if method.name in _CTOR_METHODS or \
                    method.name.endswith("_locked"):
                continue
            findings.extend(self._check_method(method))
        return findings

    def _with_locks(self, node):
        """Lock attrs acquired by a With statement's items."""
        out = []
        for item in node.items:
            expr = item.context_expr
            if isinstance(expr, ast.Attribute) and \
                    isinstance(expr.value, ast.Name) and \
                    expr.value.id == "self":
                out.append(expr.attr)
            elif isinstance(expr, ast.Call) and \
                    isinstance(expr.func, ast.Attribute) and \
                    isinstance(expr.func.value, ast.Name) and \
                    expr.func.value.id == "self" and \
                    expr.func.attr == "data_threadsafe":
                out.append("_data_lock_")
        return out

    def _check_method(self, method):
        findings = []
        cls = self.node.name

        def visit(node, held):
            if isinstance(node, ast.With):
                held = held | set(self._with_locks(node))
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef,
                                   ast.Lambda)):
                # A nested def's body executes later, under whatever
                # locks ITS caller holds — start it from scratch so
                # a callback defined under the lock is not assumed
                # to run under it.
                held = frozenset()
            self._check_node(node, held, cls, method, findings)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        visit(method, frozenset())
        return findings

    def _field_of(self, expr):
        """The self-attribute a write expression targets, unwrapping
        subscripts (``self.f[k] = v`` writes f)."""
        while isinstance(expr, ast.Subscript):
            expr = expr.value
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self":
            return expr.attr
        return None

    def _check_node(self, node, held, cls, method, findings):
        writes = []
        if isinstance(node, ast.Assign):
            writes = [self._field_of(t) for t in node.targets]
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            writes = [self._field_of(node.target)]
        elif isinstance(node, ast.Delete):
            writes = [self._field_of(t) for t in node.targets]
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS:
            writes = [self._field_of(node.func.value)]
        for field in writes:
            if field is None or field not in self.guarded:
                continue
            lock, _decl = self.guarded[field]
            if lock in held:
                continue
            findings.append(Finding(
                self.sf.rel, node.lineno, "VL201",
                "%s.%s is `guarded-by: %s` but written in %s() "
                "outside `with self.%s`" %
                (cls, field, lock, method.name, lock)))

    # -- acquisition order -------------------------------------------------

    def order_edges(self):
        """[(outer, inner, lineno)] lock-order edges this class's
        methods establish, with one level of same-class call
        expansion."""
        method_locks = {}
        for method in self._methods():
            acquired = set()
            for sub in ast.walk(method):
                if isinstance(sub, ast.With):
                    acquired.update(self._with_locks(sub))
            method_locks[method.name] = acquired
        edges = []

        def visit(node, held, method_name):
            if isinstance(node, ast.With):
                new = self._with_locks(node)
                for inner in new:
                    for outer in held:
                        if outer != inner:
                            edges.append((outer, inner, node.lineno))
                held = held | set(new)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.Lambda)):
                if method_name is not None:
                    held = frozenset()
            elif isinstance(node, ast.Call) and held and \
                    isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "self":
                for inner in method_locks.get(node.func.attr, ()):
                    for outer in held:
                        if outer != inner:
                            edges.append((outer, inner, node.lineno))
            for child in ast.iter_child_nodes(node):
                visit(child, held, method_name)

        for method in self._methods():
            visit(method, frozenset(), method.name)
        return edges


def _find_cycles(graph):
    """Simple DFS cycle finder; returns a list of cycles (each a list
    of nodes, smallest-first canonical rotation, deduplicated)."""
    cycles = set()

    def dfs(node, path, on_path):
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_path:
                cyc = path[path.index(nxt):]
                pivot = cyc.index(min(cyc))
                cycles.add(tuple(cyc[pivot:] + cyc[:pivot]))
            elif len(path) < 16:
                dfs(nxt, path + [nxt], on_path | {nxt})

    for start in sorted(graph):
        dfs(start, [start], {start})
    return [list(c) for c in sorted(cycles)]


def run(project):
    findings = []
    graph = {}
    sites = {}
    for sf in project.files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            scan = ClassScan(sf, node)
            if scan.guarded:
                findings.extend(scan.check_writes())
            for outer, inner, lineno in scan.order_edges():
                a = "%s.%s" % (node.name, outer)
                b = "%s.%s" % (node.name, inner)
                graph.setdefault(a, set()).add(b)
                sites.setdefault((a, b), (sf.rel, lineno))
    for cycle in _find_cycles(graph):
        edge = (cycle[0], cycle[1 % len(cycle)])
        rel, lineno = sites.get(edge, (project.files[0].rel, 1))
        findings.append(Finding(
            rel, lineno, "VL202",
            "lock-acquisition-order cycle: %s" %
            " -> ".join(cycle + [cycle[0]])))
    return findings
