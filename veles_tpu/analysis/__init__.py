"""veles-lint: project-aware static analysis + runtime enforcers.

``python -m veles_tpu.analysis`` lints the repo (zero findings is a
tier-1 gate — tests/test_static_analysis.py); the submodules are
reusable passes:

* :mod:`.core` — file model, rule catalog, suppressions, baselines;
* :mod:`.callgraph` — VL101/VL102 trace hazards via a call-graph
  walk from the jit entry points;
* :mod:`.locks` — VL201 guarded-by discipline + VL202 static lock
  order;
* :mod:`.registries` — VL301 literal observability names + VL302
  silent broad excepts;
* :mod:`.runtime` — the :class:`~.runtime.LockOrderRecorder` and
  :func:`~.runtime.strict_step` runtime enforcers.

See docs/analysis.md for the catalog and conventions.
"""

from .core import (Finding, RULES, apply_baseline, baseline_key,  # noqa
                   default_targets, format_finding, load_baseline,
                   repo_root, run, write_baseline)


def main(argv=None):
    from .__main__ import main as _main
    return _main(argv)
