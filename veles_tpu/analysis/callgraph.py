"""Trace-hazard pass (VL101/VL102): a call-graph walk from the known
jit entry points, flagging host-sync and retrace-nondeterminism calls
in everything the tracer can reach.

Entry points are discovered, not configured per-file:

* any function passed to a JAX tracing transform (``jax.jit``,
  ``jax.pmap``, ``jax.vmap``, ``jax.grad``, ``jax.value_and_grad``,
  ``jax.checkpoint``/``remat``, ``lax.scan``/``cond``/``while_loop``/
  ``fori_loop``/``switch``) — this is how ``StepCompiler.compile``'s
  ``train_step``/``infer_step``/``block_step`` and every
  ``export.py`` decode program register themselves;
* the project's traced-method conventions: every ``tforward`` /
  ``tupdate`` method (called inside the fused step's trace by
  ``StepCompiler.run_forward``/``apply_updates``) and every
  ``update`` method on an ``Optimizer`` subclass (called from
  ``tupdate`` through the registry).

From those roots the walk follows calls it can resolve statically:
local/nested functions, module-level functions, ``self.method`` (with
project-wide base-class resolution), imported-module attributes, and
single-assignment local aliases (``sample = _sample_rows``).  Code
inside a reached function but lexically inside a NESTED def is only
scanned once that nested def is itself reached — host-side builder
functions that merely *define* jitted closures stay host code.
"""

import ast

from .core import Finding

#: Transform attributes whose function arguments get traced.
TRACERS = frozenset((
    "jit", "pmap", "vmap", "grad", "value_and_grad", "checkpoint",
    "remat", "custom_jvp", "custom_vjp", "shard_map",
))
#: Modules that export ``shard_map`` (the pipeline schedule closures
#: register through it — ISSUE 12).
_SHARD_MAP_MODULES = frozenset(("jax", "jax.experimental.shard_map"))
#: lax control-flow: every callable argument is traced.
LAX_TRACERS = frozenset((
    "scan", "cond", "while_loop", "fori_loop", "switch", "map",
    "associative_scan",
))
#: Method names the fused step calls inside its trace, and the base
#: class gating them (None = any class).
TRACED_METHODS = (("tforward", None), ("tupdate", None),
                  ("update", "Optimizer"))

#: VL101: modules whose array-materializing calls force a device→host
#: sync (or break the trace) when reached from traced code.
_NUMPY_SYNC_ATTRS = frozenset(("asarray", "array", "copyto",
                               "ascontiguousarray"))
#: VL102 hazards: attribute calls keyed by resolved module name.
_NONDET = {
    "time": frozenset(("time", "time_ns", "monotonic",
                       "monotonic_ns", "perf_counter",
                       "perf_counter_ns")),
    "os": frozenset(("urandom", "getpid")),
    "uuid": frozenset(("uuid1", "uuid4", "getnode")),
}


class FuncInfo(object):
    __slots__ = ("node", "sf", "qualname", "parent", "cls",
                 "nested", "reached_from")

    def __init__(self, node, sf, qualname, parent, cls):
        self.node = node
        self.sf = sf
        self.qualname = qualname
        self.parent = parent    # enclosing FuncInfo or None
        self.cls = cls          # owning ClassInfo or None
        self.nested = {}        # name -> FuncInfo defined directly in
        self.reached_from = None


class ClassInfo(object):
    __slots__ = ("node", "sf", "name", "methods", "bases")

    def __init__(self, node, sf):
        self.node = node
        self.sf = sf
        self.name = node.name
        self.methods = {}
        self.bases = []
        for base in node.bases:
            if isinstance(base, ast.Name):
                self.bases.append(base.id)
            elif isinstance(base, ast.Attribute):
                self.bases.append(base.attr)


class ModuleIndex(object):
    """Per-file symbol tables: functions, classes, imports."""

    def __init__(self, sf, project):
        self.sf = sf
        self.project = project
        self.functions = {}      # module-level name -> FuncInfo
        self.classes = {}        # name -> ClassInfo
        self.import_mods = {}    # alias -> dotted module
        self.from_imports = {}   # name -> (dotted module, attr)
        self.all_funcs = []
        self._index_body(sf.tree.body, parent=None, cls=None,
                         prefix=sf.modname)

    def _index_body(self, body, parent, cls, prefix):
        for node in body:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                qual = "%s.%s" % (prefix, node.name)
                info = FuncInfo(node, self.sf, qual, parent, cls)
                self.all_funcs.append(info)
                if parent is not None:
                    parent.nested[node.name] = info
                elif cls is not None:
                    cls.methods[node.name] = info
                else:
                    self.functions[node.name] = info
                self._index_body(node.body, parent=info, cls=cls,
                                 prefix=qual)
            elif isinstance(node, ast.ClassDef):
                cinfo = ClassInfo(node, self.sf)
                self.classes[node.name] = cinfo
                self._index_body(node.body, parent=None, cls=cinfo,
                                 prefix="%s.%s" % (prefix, node.name))
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    self.import_mods[alias.asname or
                                     alias.name.split(".")[0]] = \
                        alias.name
            elif isinstance(node, ast.ImportFrom):
                mod = self.project.resolve_relative(
                    self.sf, node.level, node.module)
                for alias in node.names:
                    self.from_imports[alias.asname or alias.name] = \
                        (mod, alias.name)
            elif isinstance(node, (ast.If, ast.Try, ast.With)):
                # Conditional imports / guarded defs still index.
                for sub in ast.iter_child_nodes(node):
                    if isinstance(sub, ast.stmt):
                        self._index_body([sub], parent, cls, prefix)
                    elif hasattr(sub, "body"):
                        self._index_body(sub.body, parent, cls,
                                         prefix)


def _own_statements(fn_node):
    """The function's own AST nodes, stopping at nested function /
    class definitions (their bodies are separate walk subjects)."""
    out = []
    stack = list(fn_node.body)
    while stack:
        node = stack.pop()
        out.append(node)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda,
                                  ast.ClassDef)):
                continue
            stack.append(child)
    return out


class TraceWalker(object):
    def __init__(self, project):
        self.project = project
        self.modules = {}
        for sf in project.files:
            self.modules[sf.modname] = ModuleIndex(sf, project)
        # Global class index (by bare name) for base-class method
        # resolution across modules.
        self.class_index = {}
        for idx in self.modules.values():
            for cinfo in idx.classes.values():
                self.class_index.setdefault(cinfo.name, cinfo)

    # -- resolution --------------------------------------------------------

    def _local_aliases(self, info):
        """Single-target ``name = resolvable`` aliases in the
        function's own body."""
        aliases = {}
        for node in _own_statements(info.node):
            if isinstance(node, ast.Assign) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                aliases[node.targets[0].id] = node.value
        return aliases

    def resolve_call(self, func, info, idx, aliases, depth=0):
        """FuncInfo a call expression statically resolves to, or
        None."""
        if depth > 3:
            return None
        if isinstance(func, ast.Name):
            name = func.id
            # scope chain: nested defs of enclosing functions
            cur = info
            while cur is not None:
                if name in cur.nested:
                    return cur.nested[name]
                cur = cur.parent
            if name in aliases:
                target = aliases[name]
                if isinstance(target, (ast.Name, ast.Attribute)):
                    return self.resolve_call(target, info, idx,
                                             {}, depth + 1)
                return None
            if name in idx.functions:
                return idx.functions[name]
            if name in idx.from_imports:
                mod, attr = idx.from_imports[name]
                other = self.modules.get(mod)
                if other is not None:
                    return other.functions.get(attr)
            return None
        if isinstance(func, ast.Attribute):
            value = func.value
            if isinstance(value, ast.Name) and value.id in ("self",
                                                            "cls"):
                return self._resolve_method(info.cls, func.attr)
            if isinstance(value, ast.Name):
                mod = idx.import_mods.get(value.id)
                if mod is None and value.id in idx.from_imports:
                    fmod, fattr = idx.from_imports[value.id]
                    # ``from . import export`` style module import.
                    mod = ("%s.%s" % (fmod, fattr)) if fmod else fattr
                if mod is not None:
                    other = self.modules.get(mod)
                    if other is not None:
                        fn = other.functions.get(func.attr)
                        if fn is not None:
                            return fn
                        # Class-level staticmethod reference.
                        cinfo = other.classes.get(func.attr)
                        _ = cinfo
            return None
        return None

    def _is_partial_call(self, call, idx):
        """``functools.partial(fn, ...)`` in either import form —
        the wrapper ops/attention's sequence-parallel dispatch hands
        to ``shard_map`` (the ring/ulysses bodies register through
        it, ISSUE 13)."""
        func = call.func
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name):
            return idx.import_mods.get(func.value.id) == \
                "functools" and func.attr == "partial"
        if isinstance(func, ast.Name):
            return idx.from_imports.get(func.id) == \
                ("functools", "partial")
        return False

    def _tracer_arg_targets(self, arg, info, idx, aliases, depth=0):
        """Every FuncInfo a tracer-call argument may statically
        denote.  Beyond plain names/attributes this unwraps
        ``functools.partial(fn, ...)`` (yielding fn's targets) and
        follows single-assignment aliases through DICT-LITERAL
        subscripts (``modes = {"ring": ring_attention, ...};
        inner = modes[mode]`` — the sequence-parallel dispatch
        table: every value is a potential entry, so ALL are
        yielded)."""
        if depth > 6:
            # partial(name) → alias → subscript → alias → dict is a
            # 5-hop chain; 6 bounds pathological self-references.
            return []
        if isinstance(arg, (ast.Name, ast.Attribute)):
            targets = []
            target = self.resolve_call(arg, info, idx, aliases)
            if target is not None:
                targets.append(target)
            if isinstance(arg, ast.Name) and arg.id in aliases:
                value = aliases[arg.id]
                if not isinstance(value, (ast.Name, ast.Attribute)):
                    targets.extend(self._tracer_arg_targets(
                        value, info, idx, aliases, depth + 1))
            return targets
        if isinstance(arg, ast.Call) and \
                self._is_partial_call(arg, idx):
            if arg.args:
                return self._tracer_arg_targets(
                    arg.args[0], info, idx, aliases, depth + 1)
            return []
        if isinstance(arg, ast.Subscript):
            return self._tracer_arg_targets(
                arg.value, info, idx, aliases, depth + 1)
        if isinstance(arg, ast.Dict):
            out = []
            for value in arg.values:
                if isinstance(value, (ast.Name, ast.Attribute)):
                    target = self.resolve_call(value, info, idx,
                                               aliases)
                    if target is not None:
                        out.append(target)
            return out
        return []

    def _resolve_method(self, cls, name, seen=None):
        if cls is None:
            return None
        seen = seen or set()
        if cls.name in seen:
            return None
        seen.add(cls.name)
        if name in cls.methods:
            return cls.methods[name]
        for base in cls.bases:
            binfo = self.class_index.get(base)
            if binfo is not None:
                found = self._resolve_method(binfo, name, seen)
                if found is not None:
                    return found
        return None

    # -- entry discovery ---------------------------------------------------

    def _is_tracer_call(self, call, idx):
        """True when ``call`` is a JAX tracing transform whose
        function arguments become traced."""
        func = call.func
        if isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name):
                mod = idx.import_mods.get(base.id)
                if mod == "jax" and func.attr in TRACERS:
                    return True
                if base.id == "lax" and func.attr in LAX_TRACERS:
                    return True
                if mod in ("jax.lax",) and func.attr in LAX_TRACERS:
                    return True
            if isinstance(base, ast.Attribute) and \
                    base.attr == "lax" and func.attr in LAX_TRACERS:
                return True
        elif isinstance(func, ast.Name):
            fi = idx.from_imports.get(func.id)
            if fi is not None:
                mod, attr = fi
                if mod == "jax" and attr in TRACERS:
                    return True
                if mod in ("jax.lax", "jax") and attr in LAX_TRACERS:
                    return True
                # ``from jax.experimental.shard_map import shard_map``
                # (or ``from jax import shard_map``): the wrapped
                # stage/schedule closures are traced entry points.
                if mod in _SHARD_MAP_MODULES and attr == "shard_map":
                    return True
        return False

    def entries(self):
        out = []
        for modname, idx in self.modules.items():
            for info in idx.all_funcs:
                name = info.node.name
                for mname, base in TRACED_METHODS:
                    if name != mname or info.cls is None:
                        continue
                    if base is None or base in info.cls.bases or \
                            info.cls.name == base:
                        out.append(info)
                        break
            for info in idx.all_funcs:
                aliases = self._local_aliases(info)
                for node in _own_statements(info.node):
                    if not isinstance(node, ast.Call) or \
                            not self._is_tracer_call(node, idx):
                        continue
                    for arg in node.args:
                        out.extend(self._tracer_arg_targets(
                            arg, info, idx, aliases))
            # Module-level tracer calls (decorator-style jit at
            # import time: ``fn = jax.jit(fn)`` or ``@jax.jit``).
            for info in idx.all_funcs:
                for deco in info.node.decorator_list:
                    call = deco if isinstance(deco, ast.Call) \
                        else None
                    target = deco.func if call is not None else deco
                    if isinstance(target, ast.Attribute) and \
                            isinstance(target.value, ast.Name) and \
                            idx.import_mods.get(target.value.id) == \
                            "jax" and target.attr in TRACERS:
                        out.append(info)
        return out

    # -- reachability + hazard scan ----------------------------------------

    def walk(self):
        reached = {}
        queue = []
        for info in self.entries():
            if id(info.node) not in reached:
                reached[id(info.node)] = info
                info.reached_from = info.qualname
                queue.append(info)
        while queue:
            info = queue.pop()
            idx = self.modules[info.sf.modname]
            aliases = self._local_aliases(info)
            for node in _own_statements(info.node):
                callees = []
                if isinstance(node, ast.Call):
                    callee = self.resolve_call(node.func, info, idx,
                                               aliases)
                    if callee is not None:
                        callees.append(callee)
                    if self._is_tracer_call(node, idx):
                        for a in node.args:
                            callees.extend(self._tracer_arg_targets(
                                a, info, idx, aliases))
                for callee in callees:
                    if id(callee.node) not in reached:
                        reached[id(callee.node)] = callee
                        callee.reached_from = info.reached_from
                        queue.append(callee)
        return list(reached.values())

    def hazards(self, info):
        idx = self.modules[info.sf.modname]
        sf = info.sf
        out = []

        def emit(rule, node, what):
            out.append(Finding(
                sf.rel, node.lineno, rule,
                "%s inside jit-traced code (reachable from %s)" %
                (what, info.reached_from)))

        for node in _own_statements(info.node):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute):
                recv = func.value
                # .item() on anything: a device sync by definition.
                if func.attr == "item" and not node.args:
                    emit("VL101", node, "`.item()` host sync")
                    continue
                if isinstance(recv, ast.Name):
                    mod = idx.import_mods.get(recv.id)
                    if mod == "numpy" and \
                            func.attr in _NUMPY_SYNC_ATTRS:
                        emit("VL101", node,
                             "`%s.%s` materializes on host" %
                             (recv.id, func.attr))
                        continue
                    if mod == "jax" and func.attr == "device_get":
                        emit("VL101", node,
                             "`jax.device_get` host sync")
                        continue
                    if mod in _NONDET and \
                            func.attr in _NONDET[mod]:
                        emit("VL102", node,
                             "`%s.%s()` is retrace-nondeterministic" %
                             (mod, func.attr))
                        continue
                    if mod == "random":
                        emit("VL102", node,
                             "stdlib `random.%s` draws hidden "
                             "global state" % func.attr)
                        continue
                # numpy.random.* / np.random.*
                if isinstance(recv, ast.Attribute) and \
                        recv.attr == "random" and \
                        isinstance(recv.value, ast.Name) and \
                        idx.import_mods.get(recv.value.id) == \
                        "numpy":
                    emit("VL102", node,
                         "`numpy.random.%s` draws host-side state" %
                         func.attr)
                    continue
            elif isinstance(func, ast.Name):
                fi = idx.from_imports.get(func.id)
                if fi == ("jax", "device_get"):
                    emit("VL101", node, "`device_get` host sync")
                    continue
                if func.id in ("float", "int") and \
                        len(node.args) == 1 and not isinstance(
                            node.args[0], ast.Constant):
                    emit("VL101", node,
                         "`%s()` on a traced value forces a host "
                         "sync / concretization" % func.id)
                    continue
        return out


def run(project):
    walker = TraceWalker(project)
    findings = []
    for info in walker.walk():
        findings.extend(walker.hazards(info))
    return findings
