"""Runtime enforcers backing the static rules.

Two tripwires the linter cannot prove statically:

* :class:`LockOrderRecorder` — a process-wide debug recorder every
  :class:`~veles_tpu.distributable.SniffedLock` reports to when
  enabled.  It keeps a per-thread stack of held locks; each
  acquisition adds held→new edges to a global graph, and
  :meth:`LockOrderRecorder.assert_acyclic` (test teardown) raises
  with the offending chain when two code paths ever ordered the same
  locks differently.  Nodes are per-INSTANCE (``name#seq``) so two
  units sharing a lock *name* cannot fabricate a cycle.  Disabled
  (the default) the hook is one ``is None`` check per acquisition.

* :func:`strict_step` — wraps a steady-state hot region in
  ``jax.transfer_guard("disallow")`` (any implicit host↔device
  transfer raises inside the region) **and** a compile sentinel:
  :func:`note_compile` is called by ``StepCompiler.compile`` and by
  the serving ``CompileCache`` on every miss, and ``strict_step``
  raises :class:`StrictStepViolation` when the region compiled more
  than its ``allowed_compiles`` budget.  This hardens the
  ``Vector.host_sync_count`` *pins* into *enforcement*: a stray
  ``.item()`` or a bucket-key bug now fails the wrapped test instead
  of silently costing MFU.
"""

import contextlib
import threading

#: Registered chaos/enforcement counters (greppable literals — the
#: docs-consistency + VL301 contracts).
_STAT_COMPILES = "analysis.compiles"
_STAT_EDGES = "analysis.lock_edges"


class LockOrderViolation(AssertionError):
    """Two code paths acquired the same locks in opposite orders."""


class StrictStepViolation(AssertionError):
    """A strict_step region compiled past its budget."""


class LockOrderRecorder(object):
    """Process-wide lock-acquisition-order graph (debug tool)."""

    def __init__(self):
        self._tls = threading.local()
        self._lock = threading.Lock()
        #: (outer_id, inner_id) -> "thread/outer->inner" first site.
        self.edges = {}

    # -- hooks (SniffedLock calls these when a recorder is live;
    # -- node ids are the locks' own per-instance order_ids) ---------------

    def note_acquire(self, lock_id):
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        if held:
            thread = threading.current_thread().name
            with self._lock:
                for outer in held:
                    if outer == lock_id:
                        continue
                    edge = (outer, lock_id)
                    if edge not in self.edges:
                        self.edges[edge] = thread
        held.append(lock_id)

    def note_release(self, lock_id):
        held = getattr(self._tls, "held", None)
        if held and lock_id in held:
            # Remove the LAST occurrence: locks release LIFO in the
            # with-statement world this records.
            for i in range(len(held) - 1, -1, -1):
                if held[i] == lock_id:
                    del held[i]
                    break

    # -- analysis ----------------------------------------------------------

    def graph(self):
        with self._lock:
            graph = {}
            for outer, inner in self.edges:
                graph.setdefault(outer, set()).add(inner)
            return graph

    def find_cycle(self):
        """One acquisition-order cycle as a node list, or None."""
        graph = self.graph()
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {}

        def dfs(node, path):
            color[node] = GRAY
            for nxt in sorted(graph.get(node, ())):
                c = color.get(nxt, WHITE)
                if c == GRAY:
                    return path[path.index(nxt):] + [nxt]
                if c == WHITE:
                    found = dfs(nxt, path + [nxt])
                    if found:
                        return found
            color[node] = BLACK
            return None

        for start in sorted(graph):
            if color.get(start, WHITE) == WHITE:
                found = dfs(start, [start])
                if found:
                    return found
        return None

    def assert_acyclic(self):
        """Raises :class:`LockOrderViolation` naming the cycle; the
        canonical test-teardown check."""
        cycle = self.find_cycle()
        if cycle is not None:
            with self._lock:
                sites = {e: t for e, t in self.edges.items()}
            detail = []
            for a, b in zip(cycle, cycle[1:]):
                detail.append("%s -> %s (thread %s)" %
                              (a, b, sites.get((a, b), "?")))
            raise LockOrderViolation(
                "lock-acquisition-order cycle:\n  " +
                "\n  ".join(detail))

    def edge_count(self):
        with self._lock:
            return len(self.edges)


#: The live recorder, or None (disabled — the hot-path state).
_recorder = None
_recorder_guard = threading.Lock()


def recorder():
    """The live :class:`LockOrderRecorder`, or None when disabled."""
    return _recorder


def enable_lock_order():
    """Installs (or returns) the process-wide recorder."""
    global _recorder
    with _recorder_guard:
        if _recorder is None:
            _recorder = LockOrderRecorder()
        return _recorder


def disable_lock_order():
    global _recorder
    with _recorder_guard:
        rec, _recorder = _recorder, None
    return rec


@contextlib.contextmanager
def lock_order_recording():
    """Scoped recorder: enables, yields it, disables, and asserts
    the recorded graph is acyclic on clean exit."""
    rec = enable_lock_order()
    try:
        yield rec
    finally:
        disable_lock_order()
    rec.assert_acyclic()


# -- compile sentinel ------------------------------------------------------

_compile_lock = threading.Lock()
_compile_count = [0]
_recent_compiles = []


def note_compile(tag):
    """Called by every project compile path (``StepCompiler.compile``,
    serving ``CompileCache`` misses) so :func:`strict_step` can prove
    a steady-state region stayed compile-free."""
    with _compile_lock:
        _compile_count[0] += 1
        _recent_compiles.append(str(tag))
        del _recent_compiles[:-16]
    from .. import resilience
    resilience.stats.incr(_STAT_COMPILES)


def compile_count():
    with _compile_lock:
        return _compile_count[0]


@contextlib.contextmanager
def strict_step(allowed_compiles=0, transfer="disallow"):
    """Strict steady-state region: implicit host↔device transfers
    raise immediately (``jax.transfer_guard``), and compiling more
    than ``allowed_compiles`` programs inside the region raises
    :class:`StrictStepViolation` naming the offending compile keys.

    Wrap the fused training step or the serving decode loop AFTER
    warmup::

        with strict_step():
            workflow.execute_step(trigger=unit)
    """
    import jax
    base = compile_count()
    with jax.transfer_guard(transfer):
        yield
    grew = compile_count() - base
    if grew > allowed_compiles:
        with _compile_lock:
            recent = list(_recent_compiles[-grew:])
        raise StrictStepViolation(
            "strict_step region compiled %d program(s) "
            "(budget %d): %s" % (grew, allowed_compiles,
                                 ", ".join(recent)))
