"""veles-lint core: file model, rule registry, suppressions,
baselines, and the pass driver.

The linter is PROJECT-AWARE, not generic: every rule encodes a
contract this codebase already promises elsewhere (the docs
consistency gate, ``Vector.host_sync_count`` pins, the ``SniffedLock``
threading discipline) and turns it from reviewer vigilance into a
tier-1 zero-findings gate.  See docs/analysis.md for the rule catalog
and the annotation conventions.

Two suppression mechanisms:

* **inline** — a trailing ``# lint-ok: VL101 reason`` comment
  suppresses the named rule(s) on that line; the reason is mandatory
  culture, not parsed syntax;
* **baseline** — ``--baseline FILE`` subtracts previously recorded
  findings (keyed by ``(path, rule, message)`` so line drift does not
  resurrect them); ``--write-baseline`` records the current set.
"""

import ast
import os
import re
import tokenize
from collections import namedtuple

#: rule id → one-line description (the catalog docs/analysis.md
#: renders; ``python -m veles_tpu.analysis --list-rules`` prints it).
RULES = {
    "VL101": "host-sync call reachable inside jit-traced code "
             "(.item(), float()/int() on arrays, numpy.asarray, "
             "jax.device_get)",
    "VL102": "retrace/nondeterminism hazard reachable inside "
             "jit-traced code (time.*, random.*, numpy.random.*, "
             "os.urandom, uuid.*)",
    "VL201": "field annotated `# guarded-by: <lock>` written outside "
             "`with <lock>`",
    "VL202": "static lock-acquisition-order cycle",
    "VL301": "observability/chaos name is not a registered string "
             "literal",
    "VL302": "broad `except Exception` swallows silently (no log, "
             "stat counter, re-raise, or use of the error)",
}

Finding = namedtuple("Finding", "path line rule message")


def format_finding(f):
    """The greppable ``path:line: RULE-ID message`` form."""
    return "%s:%d: %s %s" % (f.path, f.line, f.rule, f.message)


def baseline_key(f):
    """Baseline identity: line numbers drift with unrelated edits, so
    a recorded finding is keyed by (path, rule, message) instead."""
    return (f.path, f.rule, f.message)


_SUPPRESS_RE = re.compile(r"#\s*lint-ok:\s*((?:VL\d{3}[\s,]*)+)")
_FINDING_RE = re.compile(r"^(?P<path>[^:]+):(?P<line>\d+):\s+"
                         r"(?P<rule>VL\d{3})\s+(?P<msg>.*)$")


class SourceFile(object):
    """One parsed source file: AST, raw lines, and the per-line
    suppression map (``# lint-ok: VLnnn``)."""

    def __init__(self, path, rel, modname):
        self.path = path
        self.rel = rel
        self.modname = modname
        with tokenize.open(path) as fin:
            self.text = fin.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=path)
        self.suppress = {}
        for lineno, line in enumerate(self.lines, 1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = set(re.findall(r"VL\d{3}", m.group(1)))
            self.suppress.setdefault(lineno, set()).update(rules)
            if line.lstrip().startswith("#"):
                # A standalone suppression comment covers the next
                # non-comment line (comment-above style for long
                # statements).
                nxt = lineno + 1
                while nxt <= len(self.lines) and \
                        self.lines[nxt - 1].lstrip().startswith("#"):
                    nxt += 1
                self.suppress.setdefault(nxt, set()).update(rules)

    def suppressed(self, lineno, rule):
        return rule in self.suppress.get(lineno, ())

    def line_text(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


class Project(object):
    """The file set one lint run analyzes (package dirs + scripts)."""

    def __init__(self, root, paths):
        self.root = os.path.abspath(root)
        self.files = []
        self.by_module = {}
        self.errors = []
        for path in sorted(self._expand(paths)):
            rel = os.path.relpath(path, self.root)
            modname = self._modname(rel)
            try:
                sf = SourceFile(path, rel, modname)
            except SyntaxError as e:
                self.errors.append(Finding(
                    rel, e.lineno or 1, "VL000",
                    "file does not parse: %s" % e.msg))
                continue
            self.files.append(sf)
            self.by_module[modname] = sf

    @staticmethod
    def _expand(paths):
        for path in paths:
            path = os.path.abspath(path)
            if os.path.isfile(path):
                yield path
                continue
            for base, dirs, files in os.walk(path):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                for name in files:
                    if name.endswith(".py"):
                        yield os.path.join(base, name)

    @staticmethod
    def _modname(rel):
        mod = rel[:-3] if rel.endswith(".py") else rel
        mod = mod.replace(os.sep, ".")
        if mod.endswith(".__init__"):
            mod = mod[:-len(".__init__")]
        return mod

    def resolve_relative(self, sf, level, module):
        """Absolute dotted name for a ``from ...X import`` in ``sf``."""
        if level == 0:
            return module or ""
        parts = sf.modname.split(".")
        # A package __init__ counts as the package itself.
        is_pkg = sf.rel.endswith("__init__.py")
        base = parts[:len(parts) - level + (1 if is_pkg else 0)]
        if module:
            base.append(module)
        return ".".join(base)


def default_targets(root):
    """The tier-1 gate's file set: the package plus the top-level
    entry scripts."""
    out = [os.path.join(root, "veles_tpu")]
    for extra in ("bench.py", "__graft_entry__.py"):
        path = os.path.join(root, extra)
        if os.path.isfile(path):
            out.append(path)
    return out


def repo_root():
    """The checkout root (parent of the installed package dir)."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def run(paths=None, root=None):
    """Runs every pass over ``paths`` (default: the tier-1 target
    set) and returns the sorted, suppression-filtered findings."""
    from . import callgraph, locks, registries
    root = root or repo_root()
    paths = paths or default_targets(root)
    project = Project(root, paths)
    findings = list(project.errors)
    for pass_fn in (callgraph.run, locks.run, registries.run):
        findings.extend(pass_fn(project))
    out = []
    for f in findings:
        sf = next((s for s in project.files if s.rel == f.path), None)
        if sf is not None and sf.suppressed(f.line, f.rule):
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return out


def load_baseline(path):
    """Recorded findings as a set of baseline keys (missing file =
    empty baseline)."""
    keys = set()
    if not path or not os.path.isfile(path):
        return keys
    with open(path) as fin:
        for line in fin:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            m = _FINDING_RE.match(line)
            if m:
                keys.add((m.group("path"), m.group("rule"),
                          m.group("msg")))
    return keys


def write_baseline(path, findings):
    with open(path, "w") as fout:
        fout.write("# veles-lint baseline — regenerate with\n"
                   "#   python -m veles_tpu.analysis "
                   "--write-baseline\n")
        for f in findings:
            fout.write(format_finding(f) + "\n")


def apply_baseline(findings, baseline_keys):
    return [f for f in findings if baseline_key(f)
            not in baseline_keys]
