"""Data normalization registry.

Capability parity with the reference normalizers (reference:
veles/normalization.py — ``NormalizerRegistry:110``,
``MeanDispersionNormalizer:284``, plus linear/range/external-mean/none
entries): stateful objects with the contract

    analyze(data)      — accumulate dataset statistics (callable
                         repeatedly over slabs — streaming-friendly);
    normalize(data)    — in-place-style transform → returns the array;
    denormalize(data)  — inverse transform;
    state is picklable and shared coordinator→worker.

Loaders construct them by registry string via ``NormalizerRegistry``
(``normalization_type`` kwarg in the reference loader).  Normalization
runs on host (prep path); the on-device fused variant for byte
pipelines is veles_tpu.mean_disp_normalizer.
"""

import numpy

from .registry import MappedObjectRegistry
from .error import NotExistsError  # noqa: F401  (registry raises)


class NormalizerRegistry(MappedObjectRegistry):
    """String → normalizer class (reference: normalization.py:110)."""
    registry = {}


def normalizer_factory(name, **kwargs):
    return NormalizerRegistry.get_factory(name)(**kwargs)


class NormalizerBase(object, metaclass=NormalizerRegistry):
    """Common machinery; subclasses fill _analyze/_apply/_invert."""

    def __init__(self, **kwargs):
        self.state = {}

    @property
    def is_analyzed(self):
        return bool(self.state)

    def analyze(self, data):
        self._analyze(numpy.asarray(data))

    def normalize(self, data):
        data = numpy.asarray(data, dtype=numpy.float32)
        if not self.is_analyzed:
            self.analyze(data)
        return self._apply(data)

    def denormalize(self, data):
        data = numpy.asarray(data, dtype=numpy.float32)
        return self._invert(data)

    # -- hooks --------------------------------------------------------------

    def _analyze(self, data):
        self.state["analyzed"] = True

    def _apply(self, data):
        raise NotImplementedError()

    def _invert(self, data):
        raise NotImplementedError()

    def __getstate__(self):
        return self.__dict__

    def __setstate__(self, state):
        self.__dict__.update(state)


class NoneNormalizer(NormalizerBase):
    """Identity (reference "none")."""
    MAPPING = "none"

    def _apply(self, data):
        return data

    def _invert(self, data):
        return data


class LinearNormalizer(NormalizerBase):
    """Linear map of the observed [min, max] onto [-1, 1]
    (reference "linear")."""
    MAPPING = "linear"

    def _analyze(self, data):
        mn = float(data.min())
        mx = float(data.max())
        if "min" in self.state:
            mn = min(mn, self.state["min"])
            mx = max(mx, self.state["max"])
        self.state["min"] = mn
        self.state["max"] = mx

    def _scale(self):
        spread = self.state["max"] - self.state["min"]
        return (spread / 2.0) or 1.0

    def _apply(self, data):
        mid = (self.state["max"] + self.state["min"]) / 2.0
        return (data - mid) / self._scale()

    def _invert(self, data):
        mid = (self.state["max"] + self.state["min"]) / 2.0
        return data * self._scale() + mid


class RangeLinearNormalizer(NormalizerBase):
    """Linear map of a GIVEN source interval onto a target interval
    (reference "range_linear"; e.g. bytes 0..255 → [-1, 1])."""
    MAPPING = "range_linear"

    def __init__(self, interval=(0, 255), target=(-1, 1), **kwargs):
        super(RangeLinearNormalizer, self).__init__(**kwargs)
        self.interval = tuple(interval)
        self.target = tuple(target)

    def _analyze(self, data):
        self.state["analyzed"] = True

    def _apply(self, data):
        a, b = self.interval
        c, d = self.target
        return (data - a) * ((d - c) / float(b - a)) + c

    def _invert(self, data):
        a, b = self.interval
        c, d = self.target
        return (data - c) * ((b - a) / float(d - c)) + a


class MeanDispersionNormalizer(NormalizerBase):
    """(x − mean) / (max − min) with per-feature statistics
    accumulated in streaming fashion (reference "mean_disp",
    normalization.py:284 — which documents that "disp" is the
    max−min spread, NOT the statistical dispersion)."""
    MAPPING = "mean_disp"

    def _analyze(self, data):
        flat = data.reshape(len(data), -1).astype(numpy.float64)
        s = self.state
        s.setdefault("n", 0)
        s.setdefault("sum", numpy.zeros(flat.shape[1]))
        s["n"] += len(flat)
        s["sum"] += flat.sum(axis=0)
        mn = flat.min(axis=0)
        mx = flat.max(axis=0)
        if "min" in s:
            mn = numpy.minimum(mn, s["min"])
            mx = numpy.maximum(mx, s["max"])
        s["min"] = mn
        s["max"] = mx
        s["shape"] = data.shape[1:]

    def _stats(self):
        s = self.state
        mean = s["sum"] / s["n"]
        disp = s["max"] - s["min"]
        disp[disp == 0] = 1.0
        shape = tuple(s["shape"])
        return (mean.reshape(shape).astype(numpy.float32),
                disp.reshape(shape).astype(numpy.float32))

    def _apply(self, data):
        mean, disp = self._stats()
        return (data - mean) / disp

    def _invert(self, data):
        mean, disp = self._stats()
        return data * disp + mean


class ExternalMeanNormalizer(NormalizerBase):
    """Subtracts a caller-provided mean array (reference
    "external_mean" — e.g. the ImageNet mean image file)."""
    MAPPING = "external_mean"

    def __init__(self, mean_source=None, **kwargs):
        super(ExternalMeanNormalizer, self).__init__(**kwargs)
        if mean_source is None:
            raise ValueError("external_mean requires mean_source")
        if isinstance(mean_source, str):
            mean_source = numpy.load(mean_source)
        self.mean = numpy.asarray(mean_source, dtype=numpy.float32)

    def _analyze(self, data):
        self.state["analyzed"] = True

    def _apply(self, data):
        return data - self.mean

    def _invert(self, data):
        return data + self.mean


class PointwiseNormalizer(NormalizerBase):
    """Per-feature linear map of observed per-feature [min,max] onto
    [-1, 1] (reference "pointwise")."""
    MAPPING = "pointwise"

    def _analyze(self, data):
        flat = data.reshape(len(data), -1)
        mn = flat.min(axis=0).astype(numpy.float64)
        mx = flat.max(axis=0).astype(numpy.float64)
        if "min" in self.state:
            mn = numpy.minimum(mn, self.state["min"])
            mx = numpy.maximum(mx, self.state["max"])
        self.state["min"] = mn
        self.state["max"] = mx
        self.state["shape"] = data.shape[1:]

    def _maps(self):
        s = self.state
        shape = tuple(s["shape"])
        mid = ((s["max"] + s["min"]) / 2.0).reshape(shape)
        half = ((s["max"] - s["min"]) / 2.0).reshape(shape)
        half[half == 0] = 1.0
        return (mid.astype(numpy.float32), half.astype(numpy.float32))

    def _apply(self, data):
        mid, half = self._maps()
        return (data - mid) / half

    def _invert(self, data):
        mid, half = self._maps()
        return data * half + mid
