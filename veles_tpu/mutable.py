"""Lazily-evaluated boolean expressions and attribute linking.

Capability parity with the reference mutable-value module (reference:
veles/mutable.py — ``Bool:44``, ``LinkableAttribute``): ``Bool`` builds a
small expression DAG over ``&``, ``|``, ``~`` whose truth value is
computed on demand, so a gate condition like ``~loader.epoch_ended |
decision.complete`` keeps tracking its sources after they are reassigned
with ``<<=``.

The reference pickles closure bytecode via ``marshal`` to ship these to
worker processes (mutable.py:163-185); here expressions are plain object
graphs of picklable ``Bool`` nodes, so no bytecode marshalling is
needed — checkpoints capture them directly.
"""

import operator


class Bool(object):
    """A mutable, lazily-evaluated boolean value.

    >>> a, b = Bool(True), Bool(False)
    >>> c = a & ~b
    >>> bool(c)
    True
    >>> a <<= False       # rebind a's value; c tracks it
    >>> bool(c)
    False
    """

    __slots__ = ("_value", "_op", "_sources", "on_true", "on_false")

    def __init__(self, value=False):
        if isinstance(value, Bool):
            value = bool(value)
        self._value = bool(value)
        self._op = None
        self._sources = ()
        # Optional callbacks fired by <<= on edge transitions.
        self.on_true = None
        self.on_false = None

    # -- evaluation --------------------------------------------------------

    def __bool__(self):
        if self._op is None:
            return self._value
        return self._op(*[bool(s) for s in self._sources])

    __nonzero__ = __bool__

    # -- rebinding ---------------------------------------------------------

    def __ilshift__(self, value):
        """``b <<= x`` assigns a new underlying value in place, preserving
        object identity so derived expressions keep tracking it."""
        if self._op is not None:
            raise ValueError(
                "cannot assign to a derived Bool expression")
        old = self._value
        self._value = bool(value)
        if self._value and not old and self.on_true is not None:
            self.on_true(self)
        if not self._value and old and self.on_false is not None:
            self.on_false(self)
        return self

    # -- expression DAG ----------------------------------------------------
    # Operators use module-level named functions so expression nodes
    # pickle (lambdas would not).

    @staticmethod
    def _derived(op, *sources):
        b = Bool()
        b._op = op
        b._sources = tuple(s if isinstance(s, Bool) else Bool(s)
                           for s in sources)
        return b

    def __and__(self, other):
        return Bool._derived(_and, self, other)

    __rand__ = __and__

    def __or__(self, other):
        return Bool._derived(_or, self, other)

    __ror__ = __or__

    def __xor__(self, other):
        return Bool._derived(operator.xor, self, other)

    __rxor__ = __xor__

    def __invert__(self):
        return Bool._derived(operator.not_, self)

    # -- misc --------------------------------------------------------------

    def __repr__(self):
        kind = "derived" if self._op is not None else "value"
        return "<Bool %s %s>" % (kind, bool(self))

    def __getstate__(self):
        # on_true/on_false callbacks are excluded — they are re-attached
        # by their owners after unpickling (same policy as the
        # reference's attrs-ending-with-underscore exclusion).
        return {"value": self._value, "op": self._op,
                "sources": self._sources}

    def __setstate__(self, state):
        self._value = state["value"]
        self._op = state["op"]
        self._sources = state["sources"]
        self.on_true = None
        self.on_false = None


def _and(x, y):
    return x and y


def _or(x, y):
    return x or y


class LinkableAttribute(object):
    """Descriptor record aliasing ``obj.name`` to ``src.src_name``.

    The reference installs real properties per class
    (veles/mutable.py ``LinkableAttribute``); here link resolution is
    cooperative: classes that support linking (``Unit``) consult their
    ``_linked_attrs`` table inside ``__getattr__``/``__setattr__``
    (see units.py).  This object is the table entry.
    """

    __slots__ = ("src", "src_name", "two_way")

    def __init__(self, src, src_name, two_way=False):
        self.src = src
        self.src_name = src_name
        self.two_way = two_way

    def get(self):
        return getattr(self.src, self.src_name)

    def set(self, value):
        setattr(self.src, self.src_name, value)

    def __repr__(self):
        return "<link -> %s.%s>" % (
            getattr(self.src, "name", self.src), self.src_name)
