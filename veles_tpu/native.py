"""ctypes binding to the native inference runtime.

The C++ library (``native/veles_infer.cc``, libVeles role — reference:
libVeles/inc/veles/unit.h:41 ``Unit::Execute`` chain) is built on
demand with the repo Makefile; this wrapper exposes it as
:class:`NativeModel` with the same ``forward(x)`` contract as
:class:`veles_tpu.export.ExportedModel`, so parity tests can compare
the two directly.
"""

import ctypes
import os
import subprocess

import numpy

from .error import Bug

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libveles_infer.so")
_lib = None


def build_native(force=False):
    """Builds libveles_infer.so via make (g++ + system zlib only).
    Always invokes make — its dependency check is near-free and keeps
    the library fresh after source edits."""
    argv = ["make", "-C", _NATIVE_DIR]
    if force:
        argv.insert(1, "-B")
    proc = subprocess.run(argv, capture_output=True, text=True)
    if proc.returncode != 0:
        raise Bug("native build failed:\n%s" % proc.stderr[-2000:])
    return _LIB_PATH


def _load_lib():
    global _lib
    if _lib is not None:
        return _lib
    build_native()
    lib = ctypes.CDLL(_LIB_PATH)
    lib.vt_load.restype = ctypes.c_void_p
    lib.vt_load.argtypes = [ctypes.c_char_p]
    lib.vt_input_size.argtypes = [ctypes.c_void_p]
    lib.vt_output_size.argtypes = [ctypes.c_void_p]
    lib.vt_unit_count.argtypes = [ctypes.c_void_p]
    lib.vt_unit_type.restype = ctypes.c_char_p
    lib.vt_unit_type.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.vt_forward.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
        ctypes.c_int, ctypes.POINTER(ctypes.c_float)]
    lib.vt_free.argtypes = [ctypes.c_void_p]
    lib.vt_error.restype = ctypes.c_char_p
    _lib = lib
    return lib


class NativeModel(object):
    """An exported artifact loaded by the C++ runtime."""

    def __init__(self, path):
        self._lib = _load_lib()
        self._handle = self._lib.vt_load(
            os.fsencode(os.path.abspath(path)))
        if not self._handle:
            raise Bug("native load failed: %s" %
                      self._lib.vt_error().decode())
        self.input_size = self._lib.vt_input_size(self._handle)
        self.output_size = self._lib.vt_output_size(self._handle)

    @property
    def unit_types(self):
        n = self._lib.vt_unit_count(self._handle)
        return [self._lib.vt_unit_type(self._handle, i).decode()
                for i in range(n)]

    def forward(self, x):
        x = numpy.ascontiguousarray(x, dtype=numpy.float32)
        batch = x.shape[0]
        if x.size != batch * self.input_size:
            raise Bug("input size mismatch: got %d elements/sample, "
                      "model wants %d" %
                      (x.size // batch, self.input_size))
        out = numpy.empty((batch, self.output_size),
                          dtype=numpy.float32)
        rc = self._lib.vt_forward(
            self._handle,
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), batch,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if rc != 0:
            raise Bug("native forward failed: %s" %
                      self._lib.vt_error().decode())
        return out

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.vt_free(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # lint-ok: VL302 interpreter teardown —
            pass           # logging itself may already be gone
