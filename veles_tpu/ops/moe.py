"""Mixture-of-Experts dispatch (GShard-style top-1 routing with
capacity) — the expert-parallel building block.

Not in the 2013-15 reference (SURVEY §5); part of the TPU build's
first-class scaling matrix (dp/tp/sp/ep).  The formulation is the
standard einsum dispatch: a (tokens, experts, capacity) one-hot
dispatch tensor gathers each expert's tokens, the expert FFNs run as
one batched einsum over the expert dimension, and a combine einsum
scatters outputs back weighted by the router gate.  Under a mesh with
an ``expert`` axis the expert dimension of the parameters and of the
dispatched activations shards there — XLA lowers the dispatch/combine
einsums to all-to-alls over ICI, exactly the manual A2A of expert-
parallel frameworks, without hand-written collectives.
"""

import jax
import jax.numpy as jnp


def top1_routing(logits, capacity):
    """Top-1 router (GShard): per-token expert choice with a
    per-expert capacity limit.

    Args:
      logits: (T, E) router scores.
      capacity: int — max tokens an expert accepts; overflow tokens
        are DROPPED (their combine weights are zero → residual path
        carries them, the standard top-1 behavior).

    Returns:
      dispatch: (T, E, C) 0/1 — token t occupies slot c of expert e;
      combine:  (T, E, C) float — dispatch · gate probability;
      aux_loss: load-balance auxiliary (mean_e f_e · p_e · E, the
        Switch/GShard formulation);
      expert_load: (E,) tokens routed per expert (pre-capacity).
    """
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate = probs.max(axis=-1)
    expert = probs.argmax(axis=-1)
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)
    # Position of each token within its expert's queue.
    position = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot
    keep = (position < capacity) * onehot          # (T, E)
    slot = position.sum(axis=-1).astype(jnp.int32)  # queue index
    dispatch = keep[:, :, None] * jax.nn.one_hot(
        slot, capacity, dtype=jnp.float32)[:, None, :]
    combine = dispatch * gate[:, None, None]
    # Load-balance aux: fraction routed × mean prob, summed over
    # experts, scaled by E (Switch Transformer eq. 4).
    f = onehot.mean(axis=0)
    p = probs.mean(axis=0)
    aux_loss = (f * p).sum() * E
    return dispatch, combine, aux_loss, onehot.sum(axis=0)


def moe_ffn(x, router_w, w1, b1, w2, b2, capacity_factor=1.25):
    """Top-1 MoE feed-forward over tokens.

    Args:
      x: (T, D) tokens; router_w: (D, E);
      w1: (E, D, H); b1: (E, H); w2: (E, H, D); b2: (E, D).

    Returns (y (T, D), aux_loss, expert_load (E,)).
    """
    T, D = x.shape
    E = router_w.shape[1]
    # lint-ok: VL101 static shape math — T/E are Python ints, the
    # capacity is a compile-time constant, never a traced value.
    capacity = max(1, int(capacity_factor * T / E))
    logits = x.astype(jnp.float32) @ router_w
    dispatch, combine, aux, load = top1_routing(logits, capacity)
    # Gather each expert's tokens: (E, C, D).
    expert_in = jnp.einsum("tec,td->ecd", dispatch,
                           x.astype(jnp.float32),
                           preferred_element_type=jnp.float32)
    h = jnp.maximum(jnp.einsum(
        "ecd,edh->ech", expert_in, w1,
        preferred_element_type=jnp.float32) + b1[:, None, :], 0.0)
    expert_out = jnp.einsum(
        "ech,ehd->ecd", h, w2,
        preferred_element_type=jnp.float32) + b2[:, None, :]
    # Scatter back with gate weighting: dropped tokens get zeros.
    y = jnp.einsum("tec,ecd->td", combine, expert_out,
                   preferred_element_type=jnp.float32)
    return y, aux, load
