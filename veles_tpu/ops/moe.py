"""Mixture-of-Experts dispatch (GShard/Switch-style top-k routing
with capacity) — the expert-parallel building block.

Not in the 2013-15 reference (SURVEY §5); part of the TPU build's
first-class scaling matrix (dp/tp/sp/ep).  The formulation is the
standard einsum dispatch: a (tokens, experts, capacity) one-hot
dispatch tensor gathers each expert's tokens, the expert FFNs run as
one batched einsum over the expert dimension, and a combine einsum
scatters outputs back weighted by the router gate.  Under a mesh with
an ``expert`` axis the expert dimension of the parameters and of the
dispatched activations shards there — XLA lowers the dispatch/combine
einsums to all-to-alls over ICI, exactly the manual A2A of expert-
parallel frameworks, without hand-written collectives.

Routing (ISSUE 12): :func:`top1_routing` is the historical GShard
top-1 path, kept verbatim — seeded trajectories depend on its exact
bits; :func:`topk_routing` generalizes it to k ≥ 2 choices per token
with rank-major capacity priority (all first choices queue before
any second choice), renormalized gates, the Switch load-balance
auxiliary (eq. 4) and the ST-MoE router z-loss.  Capacity scales
with k: ``C = capacity_factor · k · T / E``.
"""

import jax
import jax.numpy as jnp


def init_parser(parser):
    """MoE routing flags, aggregated into the velescli parser
    (handed to ``root.common.engine`` by
    ``__main__.apply_subsystem_flags``)."""
    parser.add_argument(
        "--moe-topk", type=int, default=None, metavar="K",
        help="Mixture-of-Experts router: experts per token (default "
             "1 = the Switch/GShard top-1 path; k>=2 dispatches each "
             "token to its k best experts with rank-major capacity "
             "priority and renormalized gates) (docs/moe.md)")
    parser.add_argument(
        "--moe-router-z", type=float, default=None, metavar="W",
        help="router z-loss weight (ST-MoE): penalizes "
             "mean(logsumexp(router logits)^2) to keep router "
             "logits small/stable; 0 (default) disables the term")


def top1_routing(logits, capacity):
    """Top-1 router (GShard): per-token expert choice with a
    per-expert capacity limit.

    Args:
      logits: (T, E) router scores.
      capacity: int — max tokens an expert accepts; overflow tokens
        are DROPPED (their combine weights are zero → residual path
        carries them, the standard top-1 behavior).

    Returns:
      dispatch: (T, E, C) 0/1 — token t occupies slot c of expert e;
      combine:  (T, E, C) float — dispatch · gate probability;
      aux_loss: load-balance auxiliary (mean_e f_e · p_e · E, the
        Switch/GShard formulation);
      expert_load: (E,) tokens routed per expert (pre-capacity).
    """
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate = probs.max(axis=-1)
    expert = probs.argmax(axis=-1)
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)
    # Position of each token within its expert's queue.
    position = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot
    keep = (position < capacity) * onehot          # (T, E)
    slot = position.sum(axis=-1).astype(jnp.int32)  # queue index
    dispatch = keep[:, :, None] * jax.nn.one_hot(
        slot, capacity, dtype=jnp.float32)[:, None, :]
    combine = dispatch * gate[:, None, None]
    # Load-balance aux: fraction routed × mean prob, summed over
    # experts, scaled by E (Switch Transformer eq. 4).
    f = onehot.mean(axis=0)
    p = probs.mean(axis=0)
    aux_loss = (f * p).sum() * E
    return dispatch, combine, aux_loss, onehot.sum(axis=0)


def topk_routing(logits, k, capacity):
    """Top-k router (GShard/Switch): per-token k expert choices with
    a per-expert capacity limit and rank-major queue priority —
    every token's FIRST choice queues before any token's second.

    Args:
      logits: (T, E) router scores; k: choices per token (k <= E);
      capacity: int — max tokens an expert accepts per rank-merged
        queue; overflow assignments are DROPPED (combine weight zero
        → the residual path carries them).

    Returns:
      dispatch: (T, E, C) 0/1 — token t occupies slot c of expert e
        through any of its k choices;
      combine:  (T, E, C) float — dispatch · renormalized gate
        (k = 1 keeps the raw top probability, matching
        :func:`top1_routing`'s Switch convention);
      aux_loss: Switch load-balance auxiliary (eq. 4) over the
        rank-0 choices: mean_e f_e · p_e · E;
      z_loss:   ST-MoE router z-loss, mean(logsumexp(logits)²);
      expert_load: (E,) assignments per expert over all k ranks,
        pre-capacity.
    """
    T, E = logits.shape
    if not 1 <= k <= E:
        raise ValueError("top_k=%d must satisfy 1 <= k <= %d experts"
                         % (k, E))
    logits = logits.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    gate, expert = jax.lax.top_k(probs, k)          # (T, k)
    if k > 1:
        # Renormalize the selected gates (GShard top-2 convention);
        # k = 1 keeps the raw probability so the top-1 path's bits
        # are reproducible through this function too.
        gate = gate / jnp.maximum(gate.sum(axis=-1, keepdims=True),
                                  1e-9)
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)  # (T,k,E)
    # Queue positions over the RANK-MAJOR flattening: all rank-0
    # choices first, so capacity overflow drops low-rank assignments
    # before anyone's primary expert.
    flat = onehot.transpose(1, 0, 2).reshape(k * T, E)
    position = (jnp.cumsum(flat, axis=0) - 1.0) * flat
    keep = (position < capacity) * flat             # (k·T, E)
    slot = position.sum(axis=-1).astype(jnp.int32)
    disp = (keep[:, :, None] * jax.nn.one_hot(
        slot, capacity, dtype=jnp.float32)[:, None, :]).reshape(
        k, T, E, capacity)
    dispatch = disp.sum(axis=0)
    combine = (disp * gate.T[:, :, None, None]).sum(axis=0)
    # Switch load-balance aux (eq. 4): fraction of rank-0 choices
    # per expert × mean router probability, scaled by E.
    f = onehot[:, 0, :].mean(axis=0)
    p = probs.mean(axis=0)
    aux_loss = (f * p).sum() * E
    z_loss = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
    return dispatch, combine, aux_loss, z_loss, onehot.sum(
        axis=(0, 1))


def moe_capacity(capacity_factor, n_tokens, n_experts, top_k=1):
    """The per-expert slot count: ``capacity_factor · k · T / E``,
    floored at 1 — a compile-time Python int (shapes depend on it)."""
    # lint-ok: VL101 static shape math — T/E/k are Python ints, the
    # capacity is a compile-time constant, never a traced value.
    return max(1, int(capacity_factor * top_k * n_tokens /
                      n_experts))


def moe_ffn_topk(x, router_w, w1, b1, w2, b2, capacity_factor=1.25,
                 top_k=1):
    """Top-k MoE feed-forward over tokens.

    Args:
      x: (T, D) tokens; router_w: (D, E);
      w1: (E, D, H); b1: (E, H); w2: (E, H, D); b2: (E, D);
      top_k: experts per token (1 = the historical top-1 path,
        bit-identical to the pre-top-k :func:`moe_ffn`).

    Returns (y (T, D), aux_loss, z_loss, expert_load (E,)) — the
    load-balance aux and the router z-loss ride back SEPARATELY so
    the caller weights them independently.
    """
    T, D = x.shape
    E = router_w.shape[1]
    capacity = moe_capacity(capacity_factor, T, E, top_k)
    logits = x.astype(jnp.float32) @ router_w
    if top_k == 1:
        # The pre-top-k code path, bit-for-bit (seeded MoE
        # trajectories are pinned on it); z computed on the side.
        dispatch, combine, aux, load = top1_routing(logits, capacity)
        z = jnp.mean(jax.nn.logsumexp(logits.astype(jnp.float32),
                                      axis=-1) ** 2)
    else:
        dispatch, combine, aux, z, load = topk_routing(
            logits, top_k, capacity)
    # Gather each expert's tokens: (E, C, D).
    expert_in = jnp.einsum("tec,td->ecd", dispatch,
                           x.astype(jnp.float32),
                           preferred_element_type=jnp.float32)
    h = jnp.maximum(jnp.einsum(
        "ecd,edh->ech", expert_in, w1,
        preferred_element_type=jnp.float32) + b1[:, None, :], 0.0)
    expert_out = jnp.einsum(
        "ech,ehd->ecd", h, w2,
        preferred_element_type=jnp.float32) + b2[:, None, :]
    # Scatter back with gate weighting: dropped tokens get zeros.
    y = jnp.einsum("tec,ecd->td", combine, expert_out,
                   preferred_element_type=jnp.float32)
    return y, aux, z, load


def moe_ffn(x, router_w, w1, b1, w2, b2, capacity_factor=1.25,
            top_k=1, router_z_weight=0.0):
    """Compatibility wrapper over :func:`moe_ffn_topk`: returns
    (y, aux, load) with ``router_z_weight·z_loss`` folded into the
    auxiliary (0 keeps the historical top-1 bits exactly)."""
    y, aux, z, load = moe_ffn_topk(
        x, router_w, w1, b1, w2, b2,
        capacity_factor=capacity_factor, top_k=top_k)
    if router_z_weight:
        aux = aux + router_z_weight * z
    return y, aux, load
