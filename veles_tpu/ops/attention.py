"""Attention ops: full, blockwise (flash-style), and ring
(sequence-parallel) formulations.

The reference framework predates attention entirely (2013-15, SURVEY
§5 "long-context: ABSENT in reference"), but long-context support is
a first-class obligation of the TPU build: sequences too long for one
chip's HBM shard along a mesh ``seq`` axis, and attention streams the
key/value shards around the ring over ICI (``lax.ppermute``) with an
online-softmax accumulator, so no device ever materializes the full
S×S score matrix or the full K/V.

Design notes (the "How to Scale Your Model" recipe):
  * all three formulations share one streaming-softmax block update —
    parity between them is structural, not coincidental;
  * the running max/normalizer (m, l) are ALWAYS float32 (bf16 loses
    the softmax tail); the materialized score/probability tensors and
    the output accumulator — the attention fast path's HBM traffic —
    drop to bf16 under ``root.common.engine.attention_dtype="bf16"``
    (per-block accumulation still happens in f32 and is rounded once
    per block), gated by parity tests with documented tolerances;
  * everything is ``lax.scan``/``ppermute`` — differentiable, so the
    backward pass is the same ring reversed, inserted by autodiff;
  * causal masking works on GLOBAL positions: each ring step offsets
    its key block by the sending device's shard start.

Attention fast path (BENCHNOTES round 6): three independently-gated
stages attack the LM bench's attention gap (7.8 ms fwd+bwd measured
vs ~1.5 ms of FLOP time at B=8/S=1024/H=16/D=128):

  * ``root.common.engine.fused_qkv`` — one (E, 3E) projection matmul
    per block instead of three (znicz/attention.py);
  * ``root.common.engine.attention_dtype`` — "f32" (default) or
    "bf16" score/accumulator intermediates (this module);
  * ``root.common.engine.attention_kernel`` — "auto" (default since
    the ISSUE 13 flip), "pallas", or "xla": route :func:`attention` /
    :func:`blockwise_attention` through the geometry-tuned Pallas
    flash kernel (ops/pallas_attention.py) when the platform
    supports it;
  * ``root.common.engine.sp_ring_kernel`` — "auto" (default),
    "pallas", or "xla": run each ring-attention step through the
    flash kernel on the ppermuted k/v shard with global causal
    offsets, merging partials by lse (ring-flash — the multi-chip
    composition of the kernel; docs/attention.md "Long context");
  * ``root.common.engine.decode_kernel`` — "off" (default: serving
    keeps its f32/xla pin), "pallas"/"auto"/"interpret": the
    flash-decode kernel behind export.py's cached/paged decode
    chain (token-identity gated).

Each knob has a ``--attn-*``/``--sp-*`` CLI flag (init_parser below)
and an A/B hook in ``bench.py --lm`` so the win is attributed per
stage.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..config import root, get as config_get

NEG_INF = -1e30

#: Valid sequence-parallel strategies (single source of truth for
#: sequence_parallel_attention and the unit-level validation).
SP_MODES = ("ring", "ulysses")

#: Valid attention-kernel dispatch modes.
KERNEL_MODES = ("xla", "pallas", "auto")

#: Default attention-kernel mode — "auto" since ISSUE 13 (the r6
#: roofline puts the flash kernel AT the bandwidth corner vs the XLA
#: formulation's ~7.4× traffic, and dispatch degrades silently
#: off-TPU/off-geometry, so auto is free where it cannot win).
#: Serving surfaces pin kernel="xla" explicitly and never read this.
DEFAULT_KERNEL_MODE = "auto"

#: Default ring-kernel mode for sequence-parallel attention — the
#: ring-flash body (per-shard Pallas flash + lse merge) engages
#: wherever the platform/geometry supports it, with the lax scan as
#: the silent fallback.
DEFAULT_RING_KERNEL_MODE = "auto"


def init_parser(parser):
    """Attention fast-path flags, aggregated into the velescli parser
    (handed to ``root.common.engine`` by
    ``__main__.apply_subsystem_flags``)."""
    parser.add_argument(
        "--attn-fused-qkv", default=None, choices=("on", "off"),
        help="attention fast path: compute q/k/v with ONE (E, 3E) "
             "projection matmul per transformer block instead of "
             "three (E, E) matmuls (docs/attention.md)")
    parser.add_argument(
        "--attn-dtype", default=None, choices=("f32", "bf16"),
        help="attention fast path: dtype of the materialized score/"
             "probability tensors and output accumulator; bf16 "
             "halves the attention block's HBM traffic at a "
             "documented parity tolerance (serving stays f32)")
    parser.add_argument(
        "--attn-kernel", default=None, choices=KERNEL_MODES,
        help="attention fast path: 'pallas' routes attention through "
             "the geometry-tuned flash kernel "
             "(ops/pallas_attention.py) where the platform supports "
             "it, 'auto' (default since the r9 flip) probes and "
             "degrades silently, 'xla' keeps the fused XLA "
             "formulation")
    parser.add_argument(
        "--sp-ring-kernel", default=None, choices=KERNEL_MODES,
        help="sequence-parallel long-context path: 'pallas'/'auto' "
             "(default) run each ring step through the flash kernel "
             "on the ppermuted k/v shard with global causal offsets, "
             "merging partials by lse (ring-flash, "
             "docs/attention.md); 'xla' keeps the lax streaming scan")
    parser.add_argument(
        "--attn-decode-kernel", default=None,
        choices=("off", "pallas", "auto", "interpret"),
        help="serving decode kernel: 'pallas'/'auto' route the "
             "cached/paged one-token decode steps through the "
             "flash-decode kernel (k/v-split grid + lse merge) where "
             "supported; 'interpret' forces the interpret-mode "
             "kernel (tests/CI); 'off' (default — serving keeps its "
             "f32/xla pin until the token-identity gate flips it)")


def attention_compute_dtype(precision=None):
    """Resolves the score/accumulator dtype: the explicit
    ``precision`` argument wins, else ``root.common.engine.
    attention_dtype`` ("f32" default).  Unknown strings RAISE — a
    typo'd config override must not silently run the f32 baseline
    while the operator believes the bf16 stage is being measured."""
    if precision is None:
        precision = config_get(root.common.engine.attention_dtype,
                               "f32")
    if hasattr(precision, "dtype") or not isinstance(precision, str):
        return jnp.dtype(precision).type
    if precision == "bf16":
        return jnp.bfloat16
    if precision == "f32":
        return jnp.float32
    raise ValueError("unknown attention dtype %r — valid: 'f32', "
                     "'bf16' (or a jnp dtype)" % (precision,))


def _kernel_mode():
    mode = str(config_get(root.common.engine.attention_kernel,
                          DEFAULT_KERNEL_MODE))
    if mode not in KERNEL_MODES:
        raise ValueError("unknown attention kernel mode %r — valid: "
                         "%s" % (mode, list(KERNEL_MODES)))
    return mode


def _ring_kernel_mode():
    mode = str(config_get(root.common.engine.sp_ring_kernel,
                          DEFAULT_RING_KERNEL_MODE))
    if mode not in KERNEL_MODES:
        raise ValueError("unknown ring kernel mode %r — valid: %s" %
                         (mode, list(KERNEL_MODES)))
    return mode


def _try_pallas(q, k, v, causal, kv_len=None, mode=None,
                precision=None):
    """Routes through the Pallas flash kernel when the knob (or the
    explicit ``mode`` override) asks for it AND the platform/geometry
    supports it; returns None (→ caller falls through to the jnp
    formulation) otherwise.  "pallas" and "auto" behave identically —
    both degrade silently, so a CPU test run with the flag on still
    exercises the reference path.  The matmul operand dtype follows
    the ``attention_dtype`` knob (or the explicit ``precision``)
    exactly like every other formulation — f32 by default, bf16
    under the bf16 stage.  With the kernel now engaging by DEFAULT
    ("auto" since the r9 flip) this matters: the pre-flip behavior
    of defaulting the operands to the kernel's bf16 MXU contract
    would silently downgrade a default-config (or explicit
    --attn-dtype f32) run the moment the platform supports the
    kernel — the dtype stage must stay an explicit opt-in, as the
    flip table documents."""
    if (mode or _kernel_mode()) == "xla":
        return None
    from . import pallas_attention as PA
    if not PA.supports(q.shape, k.shape, kv_len):
        return None
    if not PA.pallas_attention_available():
        return None
    return PA.pallas_attention(
        q, k, v, causal=causal, kv_len=kv_len,
        operand_dtype=attention_compute_dtype(precision))


def _block_update(acc, m, l, q, k, v, *, scale, mask=None):
    """One streaming-softmax update: fold the (q·kᵀ) scores of a
    key/value block into the running (acc, m, l) accumulator.

    Shapes: q (B, Sq, H, D); k/v (B, Sk, H, D); acc (B, Sq, H, D) in
    the caller-chosen compute dtype (``acc.dtype`` — f32 default,
    bf16 under the fast-path knob); m/l (B, Sq, H) ALWAYS f32.
    ``mask`` (Sq, Sk) True = attend.

    In bf16 mode the materialized tensors (scores, probabilities,
    the carried accumulator) are bf16 — the HBM traffic — while the
    running statistics and each block's accumulation happen in f32
    and are rounded ONCE per block, so the error is per-block
    rounding, not compounding summation drift.
    """
    dt = acc.dtype
    # preferred_element_type stays f32: the q·kᵀ dot is a D-term sum
    # whose ACCUMULATION must not round at bf16 (the materialized
    # tensor — the HBM traffic — is still dt after the cast).
    scores = (jnp.einsum("bqhd,bkhd->bqhk", q, k,
                         preferred_element_type=jnp.float32) *
              scale).astype(dt)
    if mask is not None:
        scores = jnp.where(mask[None, :, None, :], scores,
                           jnp.asarray(NEG_INF, dt))
    block_max = scores.max(axis=-1).astype(jnp.float32)
    new_m = jnp.maximum(m, block_max)
    correction = jnp.exp(m - new_m)
    p = jnp.exp(scores - new_m[..., None].astype(dt))
    if mask is not None:
        # exp(NEG_INF - m) underflows to 0 already; this guards the
        # fully-masked-row case where new_m itself is NEG_INF.
        p = jnp.where(mask[None, :, None, :], p, jnp.asarray(0.0, dt))
    new_l = l * correction + p.sum(axis=-1, dtype=jnp.float32)
    pv = jnp.einsum("bqhk,bkhd->bqhd", p, v.astype(dt),
                    preferred_element_type=jnp.float32)
    new_acc = (acc.astype(jnp.float32) * correction[..., None] +
               pv).astype(dt)
    return new_acc, new_m, new_l


def _finish(acc, l, dtype):
    return (acc.astype(jnp.float32) /
            jnp.maximum(l, 1e-30)[..., None]).astype(dtype)


def _causal_mask(sq, sk, q_offset, k_offset):
    qpos = q_offset + jnp.arange(sq)[:, None]
    kpos = k_offset + jnp.arange(sk)[None, :]
    return qpos >= kpos


def attention(q, k, v, causal=False, precision=None, kernel=None):
    """Full O(S²)-memory attention (B, S, H, D) — the reference
    formulation the streaming variants are tested against.

    ``precision``: None → the ``attention_dtype`` knob; "f32"/"bf16"
    forces.  ``kernel``: None → the ``attention_kernel`` knob;
    "xla" forces the jnp formulation — what the serving surfaces pin
    so a training-process knob never changes deployed bits.  Under
    "pallas"/"auto" the call routes through the Pallas flash kernel
    when the platform supports the geometry (the kernel never
    materializes the S×S scores, so the precision knob is moot
    there beyond the matmul operand dtype)."""
    out = _try_pallas(q, k, v, causal, mode=kernel,
                      precision=precision)
    if out is not None:
        return out
    dt = attention_compute_dtype(precision)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    mask = _causal_mask(q.shape[1], k.shape[1], 0, 0) if causal \
        else None
    B, Sq, H, D = q.shape
    acc = jnp.zeros((B, Sq, H, D), dt)
    m = jnp.full((B, Sq, H), NEG_INF, jnp.float32)
    l = jnp.zeros((B, Sq, H), jnp.float32)
    acc, m, l = _block_update(acc, m, l, q, k, v, scale=scale,
                              mask=mask)
    return _finish(acc, l, q.dtype)


def blockwise_attention(q, k, v, block_size=128, causal=False,
                        kv_len=None, precision=None, kernel=None):
    """Flash-style attention: scan over key/value blocks with the
    streaming accumulator — O(S·block) memory on one device.

    ``kv_len``: when set, keys at global positions >= kv_len are
    masked out — the padding contract for callers that padded k/v up
    to a block multiple (non-causal attention would otherwise attend
    the zero padding).

    ``precision``/``kernel``: None → the ``attention_dtype`` /
    ``attention_kernel`` knobs (explicit values force, as in
    :func:`attention`).  Under "pallas"/"auto" the scan is replaced
    wholesale by the Pallas flash kernel when the platform supports
    the geometry."""
    out = _try_pallas(q, k, v, causal, kv_len=kv_len, mode=kernel,
                      precision=precision)
    if out is not None:
        return out
    dt = attention_compute_dtype(precision)
    B, S, H, D = q.shape
    if S % block_size:
        raise ValueError("sequence %d not divisible by block %d" %
                         (S, block_size))
    nblocks = S // block_size
    scale = 1.0 / (D ** 0.5)
    kb = k.reshape(B, nblocks, block_size, H, D)
    vb = v.reshape(B, nblocks, block_size, H, D)

    def body(carry, xs):
        acc, m, l = carry
        kblk, vblk, idx = xs
        k_off = idx * block_size
        mask = _causal_mask(S, block_size, 0, k_off) \
            if causal else None
        if kv_len is not None:
            kvalid = jnp.broadcast_to(
                (k_off + jnp.arange(block_size))[None, :] < kv_len,
                (S, block_size))
            mask = kvalid if mask is None else \
                jnp.logical_and(mask, kvalid)
        acc, m, l = _block_update(acc, m, l, q, kblk, vblk,
                                  scale=scale, mask=mask)
        return (acc, m, l), None

    init = (jnp.zeros((B, S, H, D), dt),
            jnp.full((B, S, H), NEG_INF, jnp.float32),
            jnp.zeros((B, S, H), jnp.float32))
    (acc, m, l), _ = lax.scan(
        body, init,
        (kb.swapaxes(0, 1), vb.swapaxes(0, 1), jnp.arange(nblocks)))
    return _finish(acc, l, q.dtype)


def _try_ring_flash(q, k, mode, interpret):
    """Whether this ring call should run the Pallas flash body:
    the knob (or explicit ``kernel`` override) asks for it AND the
    per-shard geometry fits AND the kernel actually runs here
    (compiled probe on TPU; ``interpret=True`` — the test/dryrun
    path — runs the interpret kernel anywhere).  False falls through
    to the lax streaming scan, the same silent-degrade contract as
    ``_try_pallas``."""
    if mode == "xla":
        return False
    from . import pallas_attention as PA
    if not PA.supports_ring(q.shape, k.shape, interpret=interpret):
        return False
    return interpret or PA.pallas_attention_available()


def _ring_flash(q, k, v, axis_name, causal, od, interpret):
    """The ring-flash body: every ring step invokes the Pallas flash
    kernel on the currently-held (ppermuted) k/v shard with GLOBAL
    causal offsets — the source rank's shard start, a traced scalar
    the kernel masks by — and the per-step partials merge by lse
    (``pallas_attention.merge_partials``).  The steps unroll in
    Python (the axis size is static inside shard_map), and the
    backward stays autodiff-derived: each chunk's custom VJP
    recomputes its probabilities from the saved lse, the merge and
    the reversed ppermutes differentiate as plain jax — recompute-
    from-lse per ring step, exactly the single-chip kernel's
    contract stretched across the ring."""
    from . import pallas_attention as PA
    n = lax.psum(1, axis_name)
    rank = lax.axis_index(axis_name)
    B, Sq, H, D = q.shape
    q_offset = (rank * Sq).astype(jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    carry = None
    kr, vr = k, v
    for step in range(n):
        # The k/v shard currently held arrived from `rank - step`.
        # flash_resume holds the carried partial f32 across every
        # merge (one rounding at the final cast, like the lax ring's
        # single f32 accumulator).
        src = (rank - step) % n
        carry = PA.flash_resume(
            carry, q, kr, vr, causal=causal, q_offset=q_offset,
            k_offset=(src * Sq).astype(jnp.float32),
            operand_dtype=od, interpret=interpret)
        if step != n - 1:
            kr = lax.ppermute(kr, axis_name, perm)
            vr = lax.ppermute(vr, axis_name, perm)
    out, _lse = carry
    return out.astype(q.dtype)


def ring_attention(q, k, v, axis_name, causal=False, kernel=None,
                   precision=None, interpret=None):
    """Sequence-parallel attention INSIDE ``shard_map``: each device
    holds its (B, S/N, H, D) shard; N ring steps ppermute the k/v
    shard to the next device while folding the arriving block into
    the local queries' accumulator.  Communication rides ICI and
    overlaps the einsums; peak memory per device is O(S/N) — the
    long-context enabler.

    ``kernel``: None → the ``sp_ring_kernel`` knob ("auto" default);
    "pallas"/"auto" run each step through the Pallas flash kernel on
    the held shard (the ring-flash body, :func:`_ring_flash`) where
    the platform/geometry supports it, "xla" forces the lax scan.
    ``precision`` follows the ``attention_dtype`` knob as everywhere
    (in the flash body it becomes the matmul operand dtype);
    ``interpret`` forces the interpret-mode kernel — the CPU parity/
    dryrun path.
    """
    mode = kernel if kernel is not None else _ring_kernel_mode()
    if mode not in KERNEL_MODES:
        raise ValueError("unknown ring kernel mode %r — valid: %s" %
                         (mode, list(KERNEL_MODES)))
    itp = bool(interpret)
    if _try_ring_flash(q, k, mode, itp):
        return _ring_flash(q, k, v, axis_name, causal,
                           attention_compute_dtype(precision), itp)
    n = lax.psum(1, axis_name)
    rank = lax.axis_index(axis_name)
    B, Sq, H, D = q.shape
    dt = attention_compute_dtype(precision)
    scale = 1.0 / (D ** 0.5)
    q_offset = rank * Sq
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(carry, step):
        acc, m, l, kr, vr = carry
        # The k/v block currently held arrived from `rank - step`.
        src = (rank - step) % n
        if causal:
            mask = _causal_mask(Sq, kr.shape[1], q_offset, src * Sq)
        else:
            mask = None
        acc, m, l = _block_update(acc, m, l, q, kr, vr, scale=scale,
                                  mask=mask)
        kr = lax.ppermute(kr, axis_name, perm)
        vr = lax.ppermute(vr, axis_name, perm)
        return (acc, m, l, kr, vr), None

    init = (jnp.zeros((B, Sq, H, D), dt),
            jnp.full((B, Sq, H), NEG_INF, jnp.float32),
            jnp.zeros((B, Sq, H), jnp.float32), k, v)
    (acc, m, l, _, _), _ = lax.scan(body, init, jnp.arange(n))
    return _finish(acc, l, q.dtype)


def ulysses_attention(q, k, v, axis_name, causal=False):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style),
    INSIDE ``shard_map``: each device holds a (B, S/N, H, D) sequence
    shard; one all-to-all re-shards to (B, S, H/N, D) — full sequence,
    a subset of heads — so plain full attention runs locally, then the
    reverse all-to-all restores sequence sharding.  Two collectives
    total per call (vs N ppermute steps for the ring); requires
    H % N == 0.  Complements the ring: Ulysses moves activations
    twice and computes dense attention, the ring streams k/v blocks —
    which wins depends on S, H, and the interconnect.
    """
    n = lax.psum(1, axis_name)
    B, Sq, H, D = q.shape
    if H % n:
        raise ValueError("ulysses needs heads (%d) divisible by the "
                         "sequence-axis size (%d)" % (H, n))

    def to_heads(x):
        # (B, S/N, H, D) → (B, S, H/N, D): head-chunk i goes to
        # device i, which receives every device's sequence shard.
        return lax.all_to_all(x, axis_name, split_axis=2,
                              concat_axis=1, tiled=True)

    def to_seq(x):
        # Exact inverse: sequence chunks scatter back, head chunks
        # reassemble in device order.
        return lax.all_to_all(x, axis_name, split_axis=1,
                              concat_axis=2, tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    out = _gathered_attention(qh, kh, vh, causal)
    return to_seq(out)


#: Above this gathered length the local attention MUST stream
#: blockwise — a dense S×S score tensor is exactly the blow-up
#: sequence parallelism exists to avoid.
ULYSSES_DENSE_MAX = 1024


def _gathered_attention(q, k, v, causal):
    """Local attention over the Ulysses-gathered (full-S, head-shard)
    activations.  S <= ULYSSES_DENSE_MAX runs dense; anything longer
    streams blockwise at the largest dividing block size, PADDING up
    to a block multiple when nothing divides — never silently dense
    (the pre-round-5 behavior fell back to O(S²) scores for
    S = 1025..1535 and any non-multiple of 512)."""
    S = q.shape[1]
    if S <= ULYSSES_DENSE_MAX:
        return attention(q, k, v, causal=causal)
    for bs in (512, 384, 256, 128, 64):
        if S % bs == 0:
            return blockwise_attention(q, k, v, block_size=bs,
                                       causal=causal)
    bs = 512
    pad = (-S) % bs
    padded = [jnp.pad(x, ((0, 0), (0, pad), (0, 0), (0, 0)))
              for x in (q, k, v)]
    # kv_len masks the padded keys (a causal mask alone would let
    # NON-causal attention read the zero padding); padded query rows
    # are garbage and sliced away.
    out = blockwise_attention(*padded, block_size=bs, causal=causal,
                              kv_len=S)
    return out[:, :S]


def sequence_parallel_attention(q, k, v, mesh, seq_axis,
                                causal=False, batch_axis=None,
                                mode="ring", head_axis=None,
                                kernel=None, interpret=None):
    """Wraps a sequence-parallel attention (``mode``: "ring" →
    :func:`ring_attention`, "ulysses" → :func:`ulysses_attention`) in
    ``shard_map`` over the mesh's sequence axis (activations
    (B, S, H, D) sharded on dim 1), usable from inside an outer jit:
    GSPMD reshards the operands to the in_specs, the collectives run
    over ICI, and the result comes back sequence-sharded.
    ``batch_axis`` keeps the batch dim data-parallel inside the
    shard_map (dp × sp composes: the collectives involve only
    ``seq_axis``); ``head_axis`` keeps the head dim TENSOR-parallel
    (dp × tp × sp composes: attention is per-head, so a Megatron
    head shard rotates only its own heads' k/v around the ring —
    no model-axis collective is ever needed inside, and the
    ring-flash body sees only the local heads' (B, S/N, H/ntp, D)
    shard).  ``kernel``/``interpret`` reach the ring body only
    (:func:`ring_attention`'s ring-flash dispatch); Ulysses keeps
    its knob-driven local attention."""
    import inspect
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    sig = inspect.signature(shard_map).parameters
    # Disable replication/varying-axis checking: the ring's carried
    # k/v blocks change their varying-axis type across ppermute steps.
    _kw = {"check_vma": False} if "check_vma" in sig \
        else {"check_rep": False}
    from jax.sharding import PartitionSpec as P
    if batch_axis is not None and batch_axis not in mesh.axis_names:
        batch_axis = None
    if head_axis is not None and head_axis not in mesh.axis_names:
        head_axis = None
    spec = P(batch_axis, seq_axis, head_axis, None)
    modes = {"ring": ring_attention, "ulysses": ulysses_attention}
    assert set(modes) == set(SP_MODES)
    if mode not in modes:
        raise ValueError("unknown sequence-parallel mode %r — "
                         "valid: %s" % (mode, sorted(modes)))
    inner = modes[mode]
    inner_kw = {"axis_name": seq_axis, "causal": causal}
    if mode == "ring":
        inner_kw["kernel"] = kernel
        inner_kw["interpret"] = interpret
    fn = shard_map(
        functools.partial(inner, **inner_kw),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        **_kw)
    return fn(q, k, v)
