"""Pallas flash attention tuned to THIS repo's LM geometry.

Round-5 measured the two off-the-shelf Pallas kernels (flash, splash)
LOSING to XLA's fused full attention at the bench geometry
(B=8/S=1024/H=16/D=128: XLA 7.8 ms, flash 10.5, splash 10.9 fwd+bwd)
— their block shapes are tuned for large-batch GPU-style launches,
not a 128-lane head dim at batch 8.  This kernel makes the opposite
choices, for exactly one geometry family:

  * D is the FULL lane width (D % 128 == 0) — one q/k/v row is one
    (or a few) native (8, 128) tiles, no head-dim blocking ever;
  * k/v for a (batch·head) slice live WHOLE in VMEM (S ≤ 2048 ×
    D=128 × 4 B = 1 MB each — a fraction of 16 MB), so the only
    streaming dimension is the query block: grid (B·H, S/block_q),
    with the key loop a ``fori_loop`` over VMEM, never HBM;
  * matmul operands are bf16 (MXU-native), accumulation f32
    (``preferred_element_type``), the online-softmax statistics f32 —
    the same contract as ops/attention's bf16 mode;
  * the backward recomputes probabilities from the saved logsumexp
    (FLOPs are free at this arithmetic intensity; HBM traffic is
    not): one kernel produces dq gridded over query blocks, one
    produces dk/dv gridded over key blocks — no atomics, no
    cross-block races.

Since ISSUE 13 the kernel is RESUMABLE and MULTI-CHIP-composable:

  * the public contract carries the running softmax statistics — the
    forward returns ``(out, lse)`` and the custom VJP accepts an lse
    cotangent (dL/ds gains a ``+ g_lse·p`` term, folded into the
    existing delta row for free: ``delta' = Σ dO·O − g_lse``), so a
    caller may hold partial results open across kernel invocations;
  * :func:`flash_chunk` runs one partial over a k/v CHUNK with
    GLOBAL causal offsets (the ring streams shards whose true
    positions the kernel must mask by — offsets arrive as traced
    scalars, (1, 1) i32 operands read inside the kernel, because a
    ring step's source rank is data-dependent under ``shard_map``);
  * :func:`merge_partials` folds two partials by lse —
    ``lse = logaddexp(lse₁, lse₂); out = Σᵢ exp(lseᵢ − lse)·outᵢ``
    — the exact streaming-softmax combine, every exponent ≤ 0 so the
    merge is unconditionally stable; :func:`flash_resume` is the
    carry-shaped wrapper (``(out, lse)`` IS the ``(acc, m, l)``
    triple in collapsed form: ``out = acc/l``, ``lse = m + log l``);
  * :func:`pallas_decode_attention` is the decode-shaped variant
    (S_q ∈ 1..``DECODE_MAX_Q``): a k/v-SPLIT grid over the gathered
    paged table — each program owns one key block, emits its partial
    ``(out, lse)``, and a cross-block lse merge combines them — so
    serving's one-token steps ride the kernel without a VMEM-whole
    sequence bound (forward-only; decode has no backward).

Like pallas_lrn.py, the module ships three layers: the kernel, a
reference-parity fallback (ops/attention.blockwise_attention — the
parity oracle the tests pin), and availability probes so dispatch
(ops/attention._try_pallas, the ring body, export's decode gate)
degrades silently off-TPU.

HBM-traffic budget at the bench geometry (B=8, S=1024, H=16, D=128):
q/k/v/o are 64 MB each in f32; the fwd reads q/k/v once and writes
o + lse ≈ 0.26 GB, the bwd reads them + do and writes dq/dk/dv ≈
0.45 GB — ~0.9 ms at 819 GB/s vs the 6.4 GB (7.8 ms) the XLA
formulation moves through its materialized f32 score/probability
tensors.  That 8× traffic cut is the whole thesis; BENCHNOTES r6/r9
carry the A/B protocol (``bench.py --lm --attn-stages=...``).
"""

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30

#: Default query block: 512 rows × 128 lanes × 4 B = 256 KB of q per
#: grid step; the (block_q, S) score tile peaks at 512×2048×4 = 4 MB
#: f32 — comfortable VMEM at both target sequence lengths.
DEFAULT_BLOCK_Q = 512
#: Key loop step inside the kernel (VMEM-resident, so this only sets
#: the score-tile width): S=1024 runs the loop once, S=2048 twice.
DEFAULT_BLOCK_K = 1024

#: Geometry contract: lane-native head dim, tile-aligned sequence.
LANE = 128

#: Upper sequence bound: the kernel keeps a (batch·head) slice's
#: whole k/v in VMEM (S × D × 4 B each) plus a (block_q, S) f32
#: score tile — at S=2048/D=128 that is 2 × 1 MB + 4 MB, comfortable
#: in 16 MB; past it the tiles stop fitting and dispatch must fall
#: back to the streaming scan instead of dying in the compiler.
MAX_SEQ = 2048

#: Decode-kernel query bound: past this many query rows the chunk is
#: a prefill, which the full flash kernel (or the dense cached path)
#: serves better than a split-k/v decode launch.
DECODE_MAX_Q = 16
#: Decode key-block default: the split-k/v grid step over the
#: gathered paged table.
DEFAULT_DECODE_BLOCK_K = 512


def _pick_block(n, want):
    """Largest power-of-two divisor of ``n`` that is <= ``want``
    (n is a multiple of LANE by the ``supports`` contract)."""
    b = 1
    while b * 2 <= want and n % (b * 2) == 0:
        b *= 2
    return b


def supports(q_shape, k_shape, kv_len=None):
    """Whether the kernel's geometry contract holds: self-attention
    ((B, S, H, D) with equal q/k sequence), D lane-native, S
    tile-aligned.  ``kv_len`` (the blockwise padding contract) is
    supported as a static mask bound."""
    if len(q_shape) != 4 or len(k_shape) != 4:
        return False
    B, S, H, D = q_shape
    if k_shape[1] != S:
        return False
    if D % LANE or D > 4 * LANE:
        return False
    if S % LANE or S < LANE or S > MAX_SEQ:
        return False
    if kv_len is not None and not isinstance(kv_len, int):
        return False
    return True


def supports_ring(q_shape, k_shape, interpret=False):
    """The :func:`flash_chunk` geometry contract — one ring step's
    local queries against one streamed k/v shard.  Unlike
    :func:`supports` the q and k lengths may differ (a ring over an
    uneven composition could stream shards of another extent), but
    batch/heads/head-dim must agree.  ``interpret`` relaxes the
    lane/tile alignment: the interpret kernel is plain jax ops, so
    the tiny tier-1 geometries (D=4, S=8 shards) are parity-testable
    on CPU while compiled dispatch keeps the real-TPU tile contract.
    """
    if len(q_shape) != 4 or len(k_shape) != 4:
        return False
    B, Sq, H, D = q_shape
    Bk, Sk, Hk, Dk = k_shape
    if (B, H, D) != (Bk, Hk, Dk):
        return False
    if Sq < 1 or Sk < 1:
        return False
    if interpret:
        return True
    if D % LANE or D > 4 * LANE:
        return False
    for S in (Sq, Sk):
        if S % LANE or S < LANE or S > MAX_SEQ:
            return False
    return True


def supports_decode(q_shape, k_shape, interpret=False):
    """The :func:`pallas_decode_attention` contract: a small query
    chunk (S_q ≤ ``DECODE_MAX_Q`` — decode steps, not prefills)
    against a long gathered key table.  The table has NO ``MAX_SEQ``
    bound — the split-k/v grid streams it block by block instead of
    holding it whole in VMEM.  ``interpret`` relaxes tile alignment
    exactly as in :func:`supports_ring`."""
    if len(q_shape) != 4 or len(k_shape) != 4:
        return False
    B, Sq, H, D = q_shape
    Bk, L, Hk, Dk = k_shape
    if (B, H, D) != (Bk, Hk, Dk):
        return False
    if not 1 <= Sq <= DECODE_MAX_Q:
        return False
    if L < 1:
        return False
    if interpret:
        return True
    if D % LANE or D > 4 * LANE:
        return False
    if L % LANE:
        return False
    return True


# -- kernels -------------------------------------------------------------


def _mask_tile(grows0, gcols0, lcols0, bq, bk, causal, kv_len):
    """(bq, bk) boolean attend-mask for one score tile, or None when
    nothing masks.  Causality is judged on GLOBAL positions (row/col
    origins ``grows0``/``gcols0`` — possibly traced scalars: the ring
    offsets are data-dependent), while the ``kv_len`` padding bound
    applies to the chunk's LOCAL columns (origin ``lcols0``) — it is
    the caller's own padding, wherever the chunk sits globally."""
    mask = None
    if causal:
        rows = grows0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = gcols0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = rows >= cols
    if kv_len is not None:
        cols = lcols0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        kvm = cols < kv_len
        mask = kvm if mask is None else jnp.logical_and(mask, kvm)
    return mask


def _dot(a, b, od, trans_b=False):
    """MXU matmul: ``od`` operands (bf16 in production, f32 for the
    exact-parity tests), f32 accumulation."""
    dims = (((1,), (1,) if trans_b else (0,)), ((), ()))
    return jax.lax.dot_general(a.astype(od), b.astype(od), dims,
                               preferred_element_type=jnp.float32)


def _fwd_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, o_ref,
                lse_ref, *, scale, causal, kv_len, block_k,
                kv_seq_len, od):
    from jax.experimental import pallas as pl
    bq = q_ref.shape[1]
    D = q_ref.shape[2]
    i = pl.program_id(1)
    q = q_ref[0]
    grows0 = qoff_ref[0, 0] + i * bq
    koff = koff_ref[0, 0]
    nk = kv_seq_len // block_k

    def body(j, carry):
        acc, m, l = carry
        kb = k_ref[0, pl.ds(j * block_k, block_k), :]
        vb = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = _dot(q, kb, od, trans_b=True) * scale
        mask = _mask_tile(grows0, koff + j * block_k, j * block_k,
                          bq, block_k, causal, kv_len)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        bm = s.max(axis=1, keepdims=True)
        new_m = jnp.maximum(m, bm)
        corr = jnp.exp(m - new_m)
        p = jnp.exp(s - new_m)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        new_l = l * corr + p.sum(axis=1, keepdims=True)
        acc = acc * corr + _dot(p, vb, od)
        return acc, new_m, new_l

    acc, m, l = jax.lax.fori_loop(
        0, nk, body,
        (jnp.zeros((bq, D), jnp.float32),
         jnp.full((bq, 1), NEG_INF, jnp.float32),
         jnp.zeros((bq, 1), jnp.float32)))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    # Fully-masked rows keep m = NEG_INF so lse ≈ -1e30 (finite, not
    # -inf); the bwd kernels do NOT rely on exp(s - lse) underflowing
    # for such rows — they re-mask p with jnp.where before use.
    # Finite lse is also what makes the cross-chunk merge total: a
    # chunk a row attends nothing in contributes weight exp(-1e30 -
    # lse_total) = 0, never NaN.
    lse_ref[0, :] = (m + jnp.log(l_safe))[:, 0]


def _dq_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, do_ref,
               lse_ref, delta_ref, dq_ref, *, scale, causal, kv_len,
               block_k, kv_seq_len, od):
    from jax.experimental import pallas as pl
    bq = q_ref.shape[1]
    D = q_ref.shape[2]
    i = pl.program_id(1)
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, :][:, None]
    delta = delta_ref[0, :][:, None]
    grows0 = qoff_ref[0, 0] + i * bq
    koff = koff_ref[0, 0]
    nk = kv_seq_len // block_k

    def body(j, dq):
        kb = k_ref[0, pl.ds(j * block_k, block_k), :]
        vb = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = _dot(q, kb, od, trans_b=True) * scale
        mask = _mask_tile(grows0, koff + j * block_k, j * block_k,
                          bq, block_k, causal, kv_len)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        dp = _dot(do, vb, od, trans_b=True)
        ds = p * (dp - delta) * scale
        return dq + _dot(ds, kb, od)

    dq_ref[0] = jax.lax.fori_loop(
        0, nk, body,
        jnp.zeros((bq, D), jnp.float32)).astype(dq_ref.dtype)


def _dkv_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, do_ref,
                lse_ref, delta_ref, dk_ref, dv_ref, *, scale, causal,
                kv_len, block_q, q_seq_len, od):
    from jax.experimental import pallas as pl
    bk = k_ref.shape[1]
    D = k_ref.shape[2]
    j = pl.program_id(1)
    k = k_ref[0]
    v = v_ref[0]
    qoff = qoff_ref[0, 0]
    gcols0 = koff_ref[0, 0] + j * bk
    lcols0 = j * bk
    nq = q_seq_len // block_q

    def body(i, carry):
        dk, dv = carry
        qb = q_ref[0, pl.ds(i * block_q, block_q), :]
        dob = do_ref[0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(i * block_q, block_q)][:, None]
        delta = delta_ref[0, pl.ds(i * block_q, block_q)][:, None]
        s = _dot(qb, k, od, trans_b=True) * scale
        mask = _mask_tile(qoff + i * block_q, gcols0, lcols0,
                          block_q, bk, causal, kv_len)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        dv = dv + _dot(p.T, dob, od)
        dp = _dot(dob, v, od, trans_b=True)
        ds = p * (dp - delta) * scale
        dk = dk + _dot(ds.T, qb, od)
        return dk, dv

    dk, dv = jax.lax.fori_loop(
        0, nq, body,
        (jnp.zeros((bk, D), jnp.float32),
         jnp.zeros((bk, D), jnp.float32)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# -- pallas_call plumbing ------------------------------------------------


def _row_spec(block, D, which):
    """BlockSpec over (BH, S, D) arrays: ``which`` "blocked" walks
    grid dim 1 in ``block``-row steps, "whole" keeps the full
    sequence resident per (batch·head)."""
    from jax.experimental import pallas as pl
    if which == "blocked":
        return pl.BlockSpec((1, block, D), lambda b, i: (b, i, 0))
    return pl.BlockSpec((1, block, D), lambda b, i: (b, 0, 0))


def _vec_spec(block, which):
    """BlockSpec over (BH, S) row vectors (lse/delta)."""
    from jax.experimental import pallas as pl
    if which == "blocked":
        return pl.BlockSpec((1, block), lambda b, i: (b, i))
    return pl.BlockSpec((1, block), lambda b, i: (b, 0))


def _off_spec():
    """BlockSpec for the (1, 1) i32 global-offset operands: every
    program reads the same scalar (the ring's shard origin is
    data-dependent, so it cannot be a static kernel parameter)."""
    from jax.experimental import pallas as pl
    return pl.BlockSpec((1, 1), lambda b, i: (0, 0))


def _off_operand(off):
    """Traced-or-static offset → the (1, 1) i32 kernel operand.
    Offsets cross the custom-VJP boundary as (1, 1) f32 — rank ≥ 1
    because shard_map's autodiff cannot carry a device-varying
    RANK-0 residual (the ring's offsets depend on axis_index), and
    f32 so the cotangent contract stays float (exact for any
    realistic sequence position)."""
    return jnp.asarray(off, jnp.int32).reshape(1, 1)


def _flash_fwd_flat(qf, kf, vf, qoff, koff, causal, kv_len, bq, bk,
                    od, interpret):
    """(BH, Sq, D) × (BH, Sk, D) forward: returns (out, lse)."""
    from jax.experimental import pallas as pl
    BH, Sq, D = qf.shape
    Sk = kf.shape[1]
    scale = 1.0 / (D ** 0.5)
    kern = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                             kv_len=kv_len, block_k=bk,
                             kv_seq_len=Sk, od=od)
    return pl.pallas_call(
        kern,
        grid=(BH, Sq // bq),
        in_specs=[_off_spec(), _off_spec(),
                  _row_spec(bq, D, "blocked"),
                  _row_spec(Sk, D, "whole"),
                  _row_spec(Sk, D, "whole")],
        out_specs=(_row_spec(bq, D, "blocked"),
                   _vec_spec(bq, "blocked")),
        out_shape=(jax.ShapeDtypeStruct((BH, Sq, D), qf.dtype),
                   jax.ShapeDtypeStruct((BH, Sq), jnp.float32)),
        interpret=interpret,
    )(_off_operand(qoff), _off_operand(koff), qf, kf, vf)


def _flash_bwd_flat(qf, kf, vf, of, dof, lse, dlse, qoff, koff,
                    causal, kv_len, bq, bk, od, interpret):
    from jax.experimental import pallas as pl
    BH, Sq, D = qf.shape
    Sk = kf.shape[1]
    scale = 1.0 / (D ** 0.5)
    # delta_i = Σ_d dO·O − g_lse: the lse cotangent rides the same
    # per-row correction term (dL/ds_j = p_j·(dp_j − delta + g_lse)),
    # so lifting lse into the public contract costs the kernels
    # NOTHING — tiny elementwise pass, left to XLA.
    delta = (dof.astype(jnp.float32) *
             of.astype(jnp.float32)).sum(axis=-1) - \
        dlse.astype(jnp.float32)
    offs = (_off_operand(qoff), _off_operand(koff))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          kv_len=kv_len, block_k=bk, kv_seq_len=Sk,
                          od=od),
        grid=(BH, Sq // bq),
        in_specs=[_off_spec(), _off_spec(),
                  _row_spec(bq, D, "blocked"),
                  _row_spec(Sk, D, "whole"),
                  _row_spec(Sk, D, "whole"),
                  _row_spec(bq, D, "blocked"),
                  _vec_spec(bq, "blocked"),
                  _vec_spec(bq, "blocked")],
        out_specs=_row_spec(bq, D, "blocked"),
        out_shape=jax.ShapeDtypeStruct((BH, Sq, D), qf.dtype),
        interpret=interpret,
    )(*offs, qf, kf, vf, dof, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          kv_len=kv_len, block_q=bq, q_seq_len=Sq,
                          od=od),
        grid=(BH, Sk // bk),
        in_specs=[_off_spec(), _off_spec(),
                  _row_spec(Sq, D, "whole"),
                  _row_spec(bk, D, "blocked"),
                  _row_spec(bk, D, "blocked"),
                  _row_spec(Sq, D, "whole"),
                  _vec_spec(Sq, "whole"),
                  _vec_spec(Sq, "whole")],
        out_specs=(_row_spec(bk, D, "blocked"),
                   _row_spec(bk, D, "blocked")),
        out_shape=(jax.ShapeDtypeStruct((BH, Sk, D), qf.dtype),
                   jax.ShapeDtypeStruct((BH, Sk, D), qf.dtype)),
        interpret=interpret,
    )(*offs, qf, kf, vf, dof, lse, delta)
    return dq, dk, dv


# -- differentiable (B, S, H, D) entry points ----------------------------


def _to_flat(x):
    B, S, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)


def _from_flat(xf, B, H):
    BH, S, D = xf.shape
    return xf.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def _lse_from_flat(lf, B, H):
    BH, S = lf.shape
    return lf.reshape(B, H, S).transpose(0, 2, 1)


def _lse_to_flat(l):
    B, S, H = l.shape
    return l.transpose(0, 2, 1).reshape(B * H, S)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9,
                                                    10))
def _flash_lse(q, k, v, qoff, koff, causal, kv_len, bq, bk, od,
               interpret):
    """The lse-carrying flash core: (out, lse) with a backward that
    recomputes probabilities from the saved lse.  ``qoff``/``koff``
    are (1, 1) f32 arrays (global causal origins, possibly traced —
    see :func:`_off_operand` for the shape/dtype contract)."""
    out, lse = _flash_lse_fwd(q, k, v, qoff, koff, causal, kv_len,
                              bq, bk, od, interpret)[0]
    return out, lse


def _flash_lse_fwd(q, k, v, qoff, koff, causal, kv_len, bq, bk, od,
                   interpret):
    B, Sq, H, D = q.shape
    of, lsef = _flash_fwd_flat(_to_flat(q), _to_flat(k), _to_flat(v),
                               qoff, koff, causal, kv_len, bq, bk,
                               od, interpret)
    out = _from_flat(of, B, H)
    lse = _lse_from_flat(lsef, B, H)
    return (out, lse), (q, k, v, out, lsef, qoff, koff)


def _flash_lse_bwd(causal, kv_len, bq, bk, od, interpret, res, ct):
    q, k, v, out, lsef, qoff, koff = res
    do, dlse = ct
    B, Sq, H, D = q.shape
    dqf, dkf, dvf = _flash_bwd_flat(
        _to_flat(q), _to_flat(k), _to_flat(v), _to_flat(out),
        _to_flat(do), lsef, _lse_to_flat(dlse), qoff, koff, causal,
        kv_len, bq, bk, od, interpret)
    return (_from_flat(dqf, B, H), _from_flat(dkf, B, H),
            _from_flat(dvf, B, H), jnp.zeros((1, 1), jnp.float32),
            jnp.zeros((1, 1), jnp.float32))


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def pallas_attention(q, k, v, causal=False, kv_len=None, block_q=None,
                     block_k=None, operand_dtype=None,
                     interpret=False):
    """Flash attention over (B, S, H, D), differentiable (custom
    VJP).  Block shapes default to the geometry-tuned constants,
    shrunk to the largest power-of-two divisor of S — callers outside
    the ``supports`` contract must not reach here.

    ``operand_dtype``: matmul operand dtype — bf16 (default, the MXU
    contract) or f32 (the exact-parity test mode)."""
    B, S, H, D = q.shape
    if not supports(q.shape, k.shape, kv_len):
        raise ValueError(
            "geometry (%s, kv_len=%r) outside the pallas_attention "
            "contract — use ops.attention.blockwise_attention" %
            (q.shape, kv_len))
    bq = _pick_block(S, block_q or DEFAULT_BLOCK_Q)
    bk = _pick_block(S, block_k or DEFAULT_BLOCK_K)
    od = jnp.dtype(operand_dtype or jnp.bfloat16).type
    if kv_len is not None:
        # Static by the supports() contract (isinstance(int) gate).
        kv_len = int(kv_len)  # lint-ok: VL101 static config int
    zero = jnp.zeros((1, 1), jnp.float32)
    out, _lse = _flash_lse(q, k, v, zero, zero, bool(causal),
                           kv_len, bq, bk, od, bool(interpret))
    return out


# -- the resumable (ring) contract ---------------------------------------


def flash_chunk(q, k, v, causal=False, q_offset=0, k_offset=0,
                kv_len=None, block_q=None, block_k=None,
                operand_dtype=None, interpret=False):
    """ONE flash partial: local queries (B, Sq, H, D) against one
    k/v chunk (B, Sk, H, D) whose global positions start at
    ``k_offset`` (queries at ``q_offset``) — the ring-attention step
    body.  Returns ``(out, lse)`` with ``out`` the chunk-normalized
    partial and ``lse`` (B, Sq, H) f32 its log-normalizer; fold
    partials with :func:`merge_partials`.  Offsets may be TRACED
    scalars (a ring step's source rank is data-dependent inside
    ``shard_map``).  Differentiable: the backward recomputes
    probabilities from lse per chunk (dq/dkv kernels), and the lse
    output's own cotangent folds into the delta row — so autodiff
    through a chunk+merge composition is exact, no custom ring VJP
    needed."""
    if not supports_ring(q.shape, k.shape, interpret=interpret):
        raise ValueError(
            "geometry (%s × %s) outside the flash_chunk contract — "
            "use ops.attention's streaming formulations" %
            (q.shape, k.shape))
    Sq, Sk = q.shape[1], k.shape[1]
    bq = _pick_block(Sq, block_q or DEFAULT_BLOCK_Q)
    bk = _pick_block(Sk, block_k or DEFAULT_BLOCK_K)
    od = jnp.dtype(operand_dtype or jnp.bfloat16).type
    if kv_len is not None:
        # Static padding bound, never traced (supports_ring path).
        kv_len = int(kv_len)  # lint-ok: VL101 static config int
    qoff = jnp.asarray(q_offset, jnp.float32).reshape(1, 1)
    koff = jnp.asarray(k_offset, jnp.float32).reshape(1, 1)
    return _flash_lse(q, k, v, qoff, koff, bool(causal), kv_len, bq,
                      bk, od, bool(interpret))


def merge_partials(o1, lse1, o2, lse2):
    """Folds two flash partials by lse:
    ``lse = logaddexp(lse₁, lse₂)``;
    ``out = exp(lse₁ − lse)·o₁ + exp(lse₂ − lse)·o₂``.
    Every exponent is ≤ 0, so the merge is unconditionally stable,
    and a void partial (lse ≈ −1e30 from a fully-masked chunk)
    contributes weight exp(−1e30 − lse) = 0 — finite, never NaN.
    Associative and commutative: any merge tree over the ring steps
    produces the same softmax."""
    lse1 = lse1.astype(jnp.float32)
    lse2 = lse2.astype(jnp.float32)
    lse = jnp.logaddexp(lse1, lse2)
    w1 = jnp.exp(lse1 - lse)[..., None]
    w2 = jnp.exp(lse2 - lse)[..., None]
    out = (o1.astype(jnp.float32) * w1 +
           o2.astype(jnp.float32) * w2).astype(o1.dtype)
    return out, lse


def flash_resume(carry, q, k, v, **kwargs):
    """The carry-shaped resumable entry: folds one more k/v chunk
    into a running ``(out, lse)`` carry (None starts one).  The
    carry IS the streaming-softmax ``(acc, m, l)`` state in
    collapsed form — ``out = acc/l``, ``lse = m + log l`` — which is
    the only shape the cross-chunk combine needs.  The carried
    partial is HELD f32 whatever the chunk dtype (the merge's output
    dtype follows its first operand): a bf16 activation stream must
    round once when the caller finishes, not once per folded chunk —
    the single-accumulator discipline the lax streaming scan keeps.
    kwargs are :func:`flash_chunk`'s."""
    o_i, lse_i = flash_chunk(q, k, v, **kwargs)
    if carry is None:
        return o_i.astype(jnp.float32), lse_i
    return merge_partials(carry[0], carry[1], o_i, lse_i)


# -- the decode-shaped kernel --------------------------------------------


def _decode_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref, *,
                   scale, od):
    """One key block's flash partial for a tiny query chunk: the
    grid splits the KEY axis (each program owns one block of the
    gathered paged table), and the cross-block combine happens
    outside by lse merge — no carried state between programs, so the
    launch parallelizes over (B, H, key blocks) instead of
    serializing a fori_loop nobody amortizes at S_q = 1."""
    q = q_ref[0, 0]
    kb = k_ref[0, 0]
    vb = v_ref[0, 0]
    s = _dot(q, kb, od, trans_b=True) * scale
    mask = mask_ref[0] != 0
    s = jnp.where(mask, s, NEG_INF)
    m = s.max(axis=1, keepdims=True)
    p = jnp.exp(s - m)
    p = jnp.where(mask, p, 0.0)
    l = p.sum(axis=1, keepdims=True)
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0, 0, 0] = (_dot(p, vb, od) / l_safe).astype(o_ref.dtype)
    lse_ref[0, 0, 0] = (m + jnp.log(l_safe))[:, 0]


def _decode_kernel_quant(q_ref, k_ref, v_ref, ks_ref, vs_ref,
                         mask_ref, o_ref, lse_ref, *, scale, od):
    """The quantized-pool twin of :func:`_decode_kernel`: the k/v
    block arrives as stored codes (int8/fp8) plus a per-position
    scale row, and the DEQUANT HAPPENS HERE in the gather — the
    memory traffic is the quantized bytes, never a materialized f32
    cache (the whole point of the quantized KV plane: decode is
    bandwidth-bound, bytes are throughput)."""
    q = q_ref[0, 0]
    kb = k_ref[0, 0].astype(jnp.float32) * ks_ref[0, 0][:, None]
    vb = v_ref[0, 0].astype(jnp.float32) * vs_ref[0, 0][:, None]
    s = _dot(q, kb, od, trans_b=True) * scale
    mask = mask_ref[0] != 0
    s = jnp.where(mask, s, NEG_INF)
    m = s.max(axis=1, keepdims=True)
    p = jnp.exp(s - m)
    p = jnp.where(mask, p, 0.0)
    l = p.sum(axis=1, keepdims=True)
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0, 0, 0] = (_dot(p, vb, od) / l_safe).astype(o_ref.dtype)
    lse_ref[0, 0, 0] = (m + jnp.log(l_safe))[:, 0]


def pallas_decode_attention(q, k, v, key_mask, block_k=None,
                            operand_dtype=None, interpret=False,
                            k_scale=None, v_scale=None):
    """Flash-decode over a gathered key table: q (B, Sq, H, D) with
    Sq ≤ ``DECODE_MAX_Q``, k/v (B, L, H, D), ``key_mask`` (B, Sq, L)
    True = attend (the serving paths' per-row valid-slot masks —
    causality, pad slots, and table trash all arrive through it).
    Grid (B, H, L/block_k): every program emits its block's partial
    (out, lse) and a cross-block lse merge combines them.  Forward
    only — decode never backpropagates.  Masked slots are exact
    zeros after the merge and real keys keep their relative order,
    the same exactness argument as the dense paged path.

    ``k_scale``/``v_scale`` (B, L, H) engage the quantized-pool
    variant: k/v are stored codes (int8/fp8) and each program
    dequantizes its own block inside the kernel — ``codes · scale``
    per position/head — so the HBM reads stay quantized-width."""
    if not supports_decode(q.shape, k.shape, interpret=interpret):
        raise ValueError(
            "geometry (%s × %s) outside the decode-kernel contract "
            "— serve through the dense cached path" %
            (q.shape, k.shape))
    from jax.experimental import pallas as pl
    B, Sq, H, D = q.shape
    L = k.shape[1]
    bk = _pick_block(L, block_k or DEFAULT_DECODE_BLOCK_K)
    nk = L // bk
    od = jnp.dtype(operand_dtype or jnp.bfloat16).type
    scale = 1.0 / (D ** 0.5)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    mask = key_mask.astype(jnp.int32)
    quantized = k_scale is not None
    in_specs = [
        pl.BlockSpec((1, 1, Sq, D), lambda b, h, j: (b, h, 0, 0)),
        pl.BlockSpec((1, 1, bk, D), lambda b, h, j: (b, h, j, 0)),
        pl.BlockSpec((1, 1, bk, D), lambda b, h, j: (b, h, j, 0)),
    ]
    operands = [qt, kt, vt]
    if quantized:
        # (B, L, H) → (B, H, L): each program reads its block's
        # per-position scale row next to the codes.
        in_specs += [
            pl.BlockSpec((1, 1, bk), lambda b, h, j: (b, h, j)),
            pl.BlockSpec((1, 1, bk), lambda b, h, j: (b, h, j)),
        ]
        operands += [k_scale.transpose(0, 2, 1),
                     v_scale.transpose(0, 2, 1)]
        kernel = functools.partial(_decode_kernel_quant,
                                   scale=scale, od=od)
    else:
        kernel = functools.partial(_decode_kernel, scale=scale,
                                   od=od)
    in_specs.append(
        pl.BlockSpec((1, Sq, bk), lambda b, h, j: (b, 0, j)))
    operands.append(mask)
    o_part, lse_part = pl.pallas_call(
        kernel,
        grid=(B, H, nk),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((1, 1, 1, Sq, D),
                         lambda b, h, j: (b, h, j, 0, 0)),
            pl.BlockSpec((1, 1, 1, Sq),
                         lambda b, h, j: (b, h, j, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B, H, nk, Sq, D), jnp.float32),
            jax.ShapeDtypeStruct((B, H, nk, Sq), jnp.float32),
        ),
        interpret=interpret,
    )(*operands)
    # Cross-block lse merge (the flash-decode combine): weights are
    # exp(lse_i − lse_total) ≤ 1, void blocks weigh 0.
    lse = jax.nn.logsumexp(lse_part, axis=2)
    w = jnp.exp(lse_part - lse[:, :, None, :])
    out = (o_part * w[..., None]).sum(axis=2)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


# -- availability --------------------------------------------------------

_available = [None]
_decode_available = [None]


def pallas_attention_available():
    """True when the live backend compiles and runs the kernel (cached
    probe, same contract as pallas_lrn.tpu_available but end-to-end:
    a toolchain that lowers LRN but chokes on this kernel's fori_loop
    must read as unavailable, not crash the training step)."""
    if _available[0] is None:
        from .pallas_lrn import tpu_available
        if not tpu_available():
            _available[0] = False
        else:
            try:
                x = jnp.zeros((1, LANE, 1, LANE), jnp.float32)
                jax.block_until_ready(
                    pallas_attention(x, x, x, causal=True))
                _available[0] = True
            except Exception as e:
                # The silent-fallback contract stands, but WHY the
                # kernel is off must be discoverable.
                import logging
                logging.getLogger("pallas_attention").info(
                    "flash kernel probe failed (%s) — xla fallback",
                    e)
                _available[0] = False
    return _available[0]


def pallas_decode_available():
    """End-to-end probe for the decode-shaped kernel (its split-k/v
    grid and 5-d output tiling are a different lowering than the
    training kernel's, so it gets its own cached verdict)."""
    if _decode_available[0] is None:
        from .pallas_lrn import tpu_available
        if not tpu_available():
            _decode_available[0] = False
        else:
            try:
                q = jnp.zeros((1, 1, 1, LANE), jnp.float32)
                kv = jnp.zeros((1, LANE, 1, LANE), jnp.float32)
                mask = jnp.ones((1, 1, LANE), bool)
                # f32 operands: the probe must gate the LOWERING the
                # serving path actually runs (export._decode_attend
                # pins operand_dtype=f32), not the bf16 default.
                jax.block_until_ready(
                    pallas_decode_attention(
                        q, kv, kv, mask,
                        operand_dtype=jnp.float32))
                _decode_available[0] = True
            except Exception as e:
                import logging
                logging.getLogger("pallas_attention").info(
                    "decode kernel probe failed (%s) — dense "
                    "fallback", e)
                _decode_available[0] = False
    return _decode_available[0]


def reset_probe():
    """Clears the cached availability probes (tests, backend swaps)."""
    _available[0] = None
    _decode_available[0] = None
