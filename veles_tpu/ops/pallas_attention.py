"""Pallas flash attention tuned to THIS repo's LM geometry.

Round-5 measured the two off-the-shelf Pallas kernels (flash, splash)
LOSING to XLA's fused full attention at the bench geometry
(B=8/S=1024/H=16/D=128: XLA 7.8 ms, flash 10.5, splash 10.9 fwd+bwd)
— their block shapes are tuned for large-batch GPU-style launches,
not a 128-lane head dim at batch 8.  This kernel makes the opposite
choices, for exactly one geometry family:

  * D is the FULL lane width (D % 128 == 0) — one q/k/v row is one
    (or a few) native (8, 128) tiles, no head-dim blocking ever;
  * k/v for a (batch·head) slice live WHOLE in VMEM (S ≤ 2048 ×
    D=128 × 4 B = 1 MB each — a fraction of 16 MB), so the only
    streaming dimension is the query block: grid (B·H, S/block_q),
    with the key loop a ``fori_loop`` over VMEM, never HBM;
  * matmul operands are bf16 (MXU-native), accumulation f32
    (``preferred_element_type``), the online-softmax statistics f32 —
    the same contract as ops/attention's bf16 mode;
  * the backward recomputes probabilities from the saved logsumexp
    (FLOPs are free at this arithmetic intensity; HBM traffic is
    not): one kernel produces dq gridded over query blocks, one
    produces dk/dv gridded over key blocks — no atomics, no
    cross-block races.

Like pallas_lrn.py, the module ships three layers: the kernel, a
reference-parity fallback (ops/attention.blockwise_attention — the
parity oracle the tests pin), and an availability probe so dispatch
(ops/attention._try_pallas) degrades silently off-TPU.

HBM-traffic budget at the bench geometry (B=8, S=1024, H=16, D=128):
q/k/v/o are 64 MB each in f32; the fwd reads q/k/v once and writes
o + lse ≈ 0.26 GB, the bwd reads them + do and writes dq/dk/dv ≈
0.45 GB — ~0.9 ms at 819 GB/s vs the 6.4 GB (7.8 ms) the XLA
formulation moves through its materialized f32 score/probability
tensors.  That 8× traffic cut is the whole thesis; BENCHNOTES r6
carries the A/B protocol (``bench.py --lm --attn-stages=...``).
"""

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30

#: Default query block: 512 rows × 128 lanes × 4 B = 256 KB of q per
#: grid step; the (block_q, S) score tile peaks at 512×2048×4 = 4 MB
#: f32 — comfortable VMEM at both target sequence lengths.
DEFAULT_BLOCK_Q = 512
#: Key loop step inside the kernel (VMEM-resident, so this only sets
#: the score-tile width): S=1024 runs the loop once, S=2048 twice.
DEFAULT_BLOCK_K = 1024

#: Geometry contract: lane-native head dim, tile-aligned sequence.
LANE = 128

#: Upper sequence bound: the kernel keeps a (batch·head) slice's
#: whole k/v in VMEM (S × D × 4 B each) plus a (block_q, S) f32
#: score tile — at S=2048/D=128 that is 2 × 1 MB + 4 MB, comfortable
#: in 16 MB; past it the tiles stop fitting and dispatch must fall
#: back to the streaming scan instead of dying in the compiler.
MAX_SEQ = 2048


def _pick_block(n, want):
    """Largest power-of-two divisor of ``n`` that is <= ``want``
    (n is a multiple of LANE by the ``supports`` contract)."""
    b = 1
    while b * 2 <= want and n % (b * 2) == 0:
        b *= 2
    return b


def supports(q_shape, k_shape, kv_len=None):
    """Whether the kernel's geometry contract holds: self-attention
    ((B, S, H, D) with equal q/k sequence), D lane-native, S
    tile-aligned.  ``kv_len`` (the blockwise padding contract) is
    supported as a static mask bound."""
    if len(q_shape) != 4 or len(k_shape) != 4:
        return False
    B, S, H, D = q_shape
    if k_shape[1] != S:
        return False
    if D % LANE or D > 4 * LANE:
        return False
    if S % LANE or S < LANE or S > MAX_SEQ:
        return False
    if kv_len is not None and not isinstance(kv_len, int):
        return False
    return True


# -- kernels -------------------------------------------------------------


def _mask_tile(rows0, cols0, bq, bk, causal, kv_len):
    """(bq, bk) boolean attend-mask for the tile whose global row/col
    origins are rows0/cols0, or None when nothing masks."""
    mask = None
    if causal:
        rows = rows0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = cols0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = rows >= cols
    if kv_len is not None:
        cols = cols0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        kvm = cols < kv_len
        mask = kvm if mask is None else jnp.logical_and(mask, kvm)
    return mask


def _dot(a, b, od, trans_b=False):
    """MXU matmul: ``od`` operands (bf16 in production, f32 for the
    exact-parity tests), f32 accumulation."""
    dims = (((1,), (1,) if trans_b else (0,)), ((), ()))
    return jax.lax.dot_general(a.astype(od), b.astype(od), dims,
                               preferred_element_type=jnp.float32)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale,
                causal, kv_len, block_k, seq_len, od):
    from jax.experimental import pallas as pl
    bq = q_ref.shape[1]
    D = q_ref.shape[2]
    i = pl.program_id(1)
    q = q_ref[0]
    q_off = i * bq
    nk = seq_len // block_k

    def body(j, carry):
        acc, m, l = carry
        kb = k_ref[0, pl.ds(j * block_k, block_k), :]
        vb = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = _dot(q, kb, od, trans_b=True) * scale
        mask = _mask_tile(q_off, j * block_k, bq, block_k, causal,
                          kv_len)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        bm = s.max(axis=1, keepdims=True)
        new_m = jnp.maximum(m, bm)
        corr = jnp.exp(m - new_m)
        p = jnp.exp(s - new_m)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        new_l = l * corr + p.sum(axis=1, keepdims=True)
        acc = acc * corr + _dot(p, vb, od)
        return acc, new_m, new_l

    acc, m, l = jax.lax.fori_loop(
        0, nk, body,
        (jnp.zeros((bq, D), jnp.float32),
         jnp.full((bq, 1), NEG_INF, jnp.float32),
         jnp.zeros((bq, 1), jnp.float32)))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    # Fully-masked rows keep m = NEG_INF so lse ≈ -1e30 (finite, not
    # -inf); the bwd kernels do NOT rely on exp(s - lse) underflowing
    # for such rows — they re-mask p with jnp.where before use.
    lse_ref[0, :] = (m + jnp.log(l_safe))[:, 0]


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, *, scale, causal, kv_len, block_k, seq_len,
               od):
    from jax.experimental import pallas as pl
    bq = q_ref.shape[1]
    D = q_ref.shape[2]
    i = pl.program_id(1)
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, :][:, None]
    delta = delta_ref[0, :][:, None]
    q_off = i * bq
    nk = seq_len // block_k

    def body(j, dq):
        kb = k_ref[0, pl.ds(j * block_k, block_k), :]
        vb = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = _dot(q, kb, od, trans_b=True) * scale
        mask = _mask_tile(q_off, j * block_k, bq, block_k, causal,
                          kv_len)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        dp = _dot(do, vb, od, trans_b=True)
        ds = p * (dp - delta) * scale
        return dq + _dot(ds, kb, od)

    dq_ref[0] = jax.lax.fori_loop(
        0, nk, body,
        jnp.zeros((bq, D), jnp.float32)).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, scale, causal, kv_len, block_q,
                seq_len, od):
    from jax.experimental import pallas as pl
    bk = k_ref.shape[1]
    D = k_ref.shape[2]
    j = pl.program_id(1)
    k = k_ref[0]
    v = v_ref[0]
    k_off = j * bk
    nq = seq_len // block_q

    def body(i, carry):
        dk, dv = carry
        qb = q_ref[0, pl.ds(i * block_q, block_q), :]
        dob = do_ref[0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(i * block_q, block_q)][:, None]
        delta = delta_ref[0, pl.ds(i * block_q, block_q)][:, None]
        s = _dot(qb, k, od, trans_b=True) * scale
        mask = _mask_tile(i * block_q, k_off, block_q, bk, causal,
                          kv_len)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        dv = dv + _dot(p.T, dob, od)
        dp = _dot(dob, v, od, trans_b=True)
        ds = p * (dp - delta) * scale
        dk = dk + _dot(ds.T, qb, od)
        return dk, dv

    dk, dv = jax.lax.fori_loop(
        0, nq, body,
        (jnp.zeros((bk, D), jnp.float32),
         jnp.zeros((bk, D), jnp.float32)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# -- pallas_call plumbing ------------------------------------------------


def _row_spec(block, D, which):
    """BlockSpec over (BH, S, D) arrays: ``which`` "blocked" walks
    grid dim 1 in ``block``-row steps, "whole" keeps the full
    sequence resident per (batch·head)."""
    from jax.experimental import pallas as pl
    if which == "blocked":
        return pl.BlockSpec((1, block, D), lambda b, i: (b, i, 0))
    return pl.BlockSpec((1, block, D), lambda b, i: (b, 0, 0))


def _vec_spec(block, which):
    """BlockSpec over (BH, S) row vectors (lse/delta)."""
    from jax.experimental import pallas as pl
    if which == "blocked":
        return pl.BlockSpec((1, block), lambda b, i: (b, i))
    return pl.BlockSpec((1, block), lambda b, i: (b, 0))


def _flash_fwd_flat(qf, kf, vf, causal, kv_len, bq, bk, od,
                    interpret):
    """(BH, S, D) forward: returns (out, lse)."""
    from jax.experimental import pallas as pl
    BH, S, D = qf.shape
    scale = 1.0 / (D ** 0.5)
    kern = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                             kv_len=kv_len, block_k=bk, seq_len=S,
                             od=od)
    return pl.pallas_call(
        kern,
        grid=(BH, S // bq),
        in_specs=[_row_spec(bq, D, "blocked"),
                  _row_spec(S, D, "whole"),
                  _row_spec(S, D, "whole")],
        out_specs=(_row_spec(bq, D, "blocked"),
                   _vec_spec(bq, "blocked")),
        out_shape=(jax.ShapeDtypeStruct((BH, S, D), qf.dtype),
                   jax.ShapeDtypeStruct((BH, S), jnp.float32)),
        interpret=interpret,
    )(qf, kf, vf)


def _flash_bwd_flat(qf, kf, vf, of, dof, lse, causal, kv_len, bq, bk,
                    od, interpret):
    from jax.experimental import pallas as pl
    BH, S, D = qf.shape
    scale = 1.0 / (D ** 0.5)
    # delta_i = Σ_d dO·O — tiny elementwise pass, left to XLA.
    delta = (dof.astype(jnp.float32) *
             of.astype(jnp.float32)).sum(axis=-1)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          kv_len=kv_len, block_k=bk, seq_len=S,
                          od=od),
        grid=(BH, S // bq),
        in_specs=[_row_spec(bq, D, "blocked"),
                  _row_spec(S, D, "whole"),
                  _row_spec(S, D, "whole"),
                  _row_spec(bq, D, "blocked"),
                  _vec_spec(bq, "blocked"),
                  _vec_spec(bq, "blocked")],
        out_specs=_row_spec(bq, D, "blocked"),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), qf.dtype),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          kv_len=kv_len, block_q=bq, seq_len=S,
                          od=od),
        grid=(BH, S // bk),
        in_specs=[_row_spec(S, D, "whole"),
                  _row_spec(bk, D, "blocked"),
                  _row_spec(bk, D, "blocked"),
                  _row_spec(S, D, "whole"),
                  _vec_spec(S, "whole"),
                  _vec_spec(S, "whole")],
        out_specs=(_row_spec(bk, D, "blocked"),
                   _row_spec(bk, D, "blocked")),
        out_shape=(jax.ShapeDtypeStruct((BH, S, D), qf.dtype),
                   jax.ShapeDtypeStruct((BH, S, D), qf.dtype)),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)
    return dq, dk, dv


# -- differentiable (B, S, H, D) entry point -----------------------------


def _to_flat(x):
    B, S, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)


def _from_flat(xf, B, H):
    BH, S, D = xf.shape
    return xf.reshape(B, H, S, D).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, kv_len, bq, bk, od, interpret):
    out, _ = _flash_fwd(q, k, v, causal, kv_len, bq, bk, od,
                        interpret)
    return out


def _flash_fwd(q, k, v, causal, kv_len, bq, bk, od, interpret):
    B, S, H, D = q.shape
    of, lse = _flash_fwd_flat(_to_flat(q), _to_flat(k), _to_flat(v),
                              causal, kv_len, bq, bk, od, interpret)
    return _from_flat(of, B, H), (q, k, v, _from_flat(of, B, H), lse)


def _flash_bwd(causal, kv_len, bq, bk, od, interpret, res, do):
    q, k, v, out, lse = res
    B, S, H, D = q.shape
    dqf, dkf, dvf = _flash_bwd_flat(
        _to_flat(q), _to_flat(k), _to_flat(v), _to_flat(out),
        _to_flat(do), lse, causal, kv_len, bq, bk, od, interpret)
    return (_from_flat(dqf, B, H), _from_flat(dkf, B, H),
            _from_flat(dvf, B, H))


_flash.defvjp(_flash_fwd, _flash_bwd)


def pallas_attention(q, k, v, causal=False, kv_len=None, block_q=None,
                     block_k=None, operand_dtype=None,
                     interpret=False):
    """Flash attention over (B, S, H, D), differentiable (custom
    VJP).  Block shapes default to the geometry-tuned constants,
    shrunk to the largest power-of-two divisor of S — callers outside
    the ``supports`` contract must not reach here.

    ``operand_dtype``: matmul operand dtype — bf16 (default, the MXU
    contract) or f32 (the exact-parity test mode)."""
    B, S, H, D = q.shape
    if not supports(q.shape, k.shape, kv_len):
        raise ValueError(
            "geometry (%s, kv_len=%r) outside the pallas_attention "
            "contract — use ops.attention.blockwise_attention" %
            (q.shape, kv_len))
    bq = _pick_block(S, block_q or DEFAULT_BLOCK_Q)
    bk = _pick_block(S, block_k or DEFAULT_BLOCK_K)
    od = jnp.dtype(operand_dtype or jnp.bfloat16).type
    if kv_len is not None:
        kv_len = int(kv_len)
    return _flash(q, k, v, bool(causal), kv_len, bq, bk, od,
                  bool(interpret))


# -- availability --------------------------------------------------------

_available = [None]


def pallas_attention_available():
    """True when the live backend compiles and runs the kernel (cached
    probe, same contract as pallas_lrn.tpu_available but end-to-end:
    a toolchain that lowers LRN but chokes on this kernel's fori_loop
    must read as unavailable, not crash the training step)."""
    if _available[0] is None:
        from .pallas_lrn import tpu_available
        if not tpu_available():
            _available[0] = False
        else:
            try:
                x = jnp.zeros((1, LANE, 1, LANE), jnp.float32)
                jax.block_until_ready(
                    pallas_attention(x, x, x, causal=True))
                _available[0] = True
            except Exception as e:
                # The silent-fallback contract stands, but WHY the
                # kernel is off must be discoverable.
                import logging
                logging.getLogger("pallas_attention").info(
                    "flash kernel probe failed (%s) — xla fallback",
                    e)
                _available[0] = False
    return _available[0]


def reset_probe():
    """Clears the cached availability probe (tests, backend swaps)."""
    _available[0] = None
