"""GPipe-style pipeline parallelism over a mesh ``stage`` axis.

Not in the 2013-15 reference (its only parallelism was master–slave
DP, SURVEY §2.3); completes the TPU build's scaling matrix
(dp/tp/sp/ep/pp).  The formulation is the standard collective-permute
pipeline: a stack of IDENTICALLY-SHAPED layer applications is laid
out one stage per device (stacked parameters shard on their leading
stage dimension), the batch splits into M microbatches, and for
S + M − 1 steps each device applies its stage to the microbatch it
holds while ``lax.ppermute`` hands activations to the next stage —
the classic bubble of S − 1 idle slots per ramp.  Everything is
``lax.scan`` + ``ppermute`` inside ``shard_map``, so autodiff derives
the backward pipeline (reverse ring) automatically.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _pipeline_body(fn, params, x_mb, axis_name):
    """The per-device pipeline loop.  ``params``: this stage's layer
    parameters (stage dim already sliced away by shard_map);
    ``x_mb``: (M, mb, ...) microbatched input, replicated."""
    n_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    M = x_mb.shape[0]
    steps = M + n_stages - 1
    perm = [(i, i + 1) for i in range(n_stages - 1)]
    mb_shape = x_mb.shape[1:]
    out_acc = jnp.zeros((M,) + mb_shape, jnp.float32)

    def body(carry, t):
        recv, acc = carry
        # Stage 0 injects microbatch t (zeros once the ramp ends);
        # later stages consume what arrived from stage-1.
        feed_idx = jnp.clip(t, 0, M - 1)
        fresh = jnp.where(t < M, x_mb[feed_idx],
                          jnp.zeros(mb_shape, x_mb.dtype))
        inp = jnp.where(stage == 0, fresh.astype(jnp.float32), recv)
        out = fn(params, inp)
        # The LAST stage finishes microbatch t−(S−1) at step t.
        mb_done = t - (n_stages - 1)
        is_last = stage == n_stages - 1
        valid = jnp.logical_and(is_last, mb_done >= 0)
        slot = jnp.clip(mb_done, 0, M - 1)
        acc = jnp.where(
            valid,
            acc.at[slot].set(out.astype(jnp.float32)),
            acc)
        # Hand the activation to the next stage (last stage sends
        # nothing anyone reads).
        recv = lax.ppermute(out, axis_name, perm)
        return (recv, acc), None

    init = (jnp.zeros(mb_shape, jnp.float32), out_acc)
    (_, acc), _ = lax.scan(body, init, jnp.arange(steps))
    # Only the last stage holds real outputs; psum replicates them
    # (every other stage contributes zeros).
    return lax.psum(acc, axis_name)


def gpipe(fn, stacked_params, x, mesh, stage_axis, n_microbatches):
    """Runs ``y = fn(p[S-1], …fn(p[1], fn(p[0], x))…)`` microbatch-
    pipelined over the mesh's ``stage_axis``.

    Args:
      fn: (layer_params, activation (mb, ...)) → activation, same
        shape class in and out (stages must be homogeneous).
      stacked_params: pytree whose leaves carry a leading S dim.
      x: (B, ...) input; B must divide into ``n_microbatches``.
      mesh / stage_axis: where the stages live.
      n_microbatches: M; the bubble fraction is (S−1)/(M+S−1).

    Returns y (B, ...) float32, replicated over the stage axis.
    """
    try:
        from jax import shard_map
        import inspect
        _kw = {"check_vma": False} if "check_vma" in \
            inspect.signature(shard_map).parameters else {}
    except ImportError:
        from jax.experimental.shard_map import shard_map
        _kw = {"check_rep": False}
    from jax.sharding import PartitionSpec as P
    B = x.shape[0]
    if B % n_microbatches:
        raise ValueError("batch %d not divisible into %d microbatches"
                         % (B, n_microbatches))
    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    n_stages = mesh.shape[stage_axis]
    if n_layers % n_stages:
        raise ValueError(
            "%d stacked layers do not divide over %d pipeline "
            "stages" % (n_layers, n_stages))
    mb = B // n_microbatches
    x_mb = x.reshape((n_microbatches, mb) + x.shape[1:])

    def stage_fn(params, x_all):
        # shard_map leaves each device a (n_layers/n_stages, ...)
        # local sub-stack; a stage applies its local layers in
        # sequence (scan), so n_layers may be any multiple of the
        # stage count.
        return _pipeline_body(
            lambda p, h: sequential_stack(fn, p, h),
            params, x_all, stage_axis)

    pspec = jax.tree_util.tree_map(
        lambda p: P(stage_axis, *([None] * (p.ndim - 1))),
        stacked_params)
    out = shard_map(
        stage_fn, mesh=mesh,
        in_specs=(pspec, P()), out_specs=P(), **_kw)(
            stacked_params, x_mb)
    return out.reshape((B,) + out.shape[2:])


def sequential_stack(fn, stacked_params, x):
    """The no-mesh reference path: the same stacked layers applied by
    a plain scan — pipelined and sequential must agree exactly."""
    def body(h, params):
        return fn(params, h), None
    y, _ = lax.scan(body, x.astype(jnp.float32), stacked_params)
    return y
