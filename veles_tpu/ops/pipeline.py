"""Pipeline parallelism over a mesh ``stage`` axis: GPipe, 1F1B
(PipeDream-flush) and interleaved (Megatron) schedules.

Not in the 2013-15 reference (its only parallelism was master–slave
DP, SURVEY §2.3); completes the TPU build's scaling matrix
(dp/tp/sp/ep/pp).  The formulation is the standard collective-permute
pipeline: a stack of IDENTICALLY-SHAPED layer applications is laid
out one stage per device (stacked parameters shard on their leading
stage dimension), the batch splits into M microbatches, and each
device applies its stage to the microbatch it holds while
``lax.ppermute`` hands activations to the next stage.  Everything is
``lax.scan`` + ``ppermute`` inside ``shard_map``, so autodiff derives
the backward pipeline (reverse ring) automatically — for EVERY
schedule; :func:`sequential_stack` stays the exact-parity oracle.

Schedules (the ``schedule`` knob of :func:`pipeline`):

* ``gpipe`` — the classic fill-and-drain ramp: T = M + S − 1 scan
  steps, each device applying its whole local sub-stack per step.
  Bubble fraction (S − 1)/(M + S − 1); live activation residuals
  scale with M (every step's inputs are saved for the backward).
* ``1f1b`` — PipeDream-flush.  The forward ramp is timing-identical
  to GPipe's (T = M + S − 1 — as in the paper, the schedules differ
  in what is held live, not in forward step count), but each scan
  step REMATERIALIZES its stage application (``jax.checkpoint``), so
  the backward re-runs the stage forward per step and the live
  residuals drop from every layer's internals (attention scores, MLP
  hiddens — the dominant term) to one chunk-input activation per
  step.  NOTE the honest bound: the scan's carry chain is still
  O(M) activations — an SPMD scan whose backward autodiff derives
  cannot express the hand-scheduled O(S) in-flight interleave — so
  this is the remat memory class that makes large M affordable, not
  a strict ≤ S cap.  At a memory-constrained operating point GPipe
  flushes every ~S microbatches (bubble (S − 1)/(2S − 1) ≈ 43% at
  S=4) while 1F1B runs the full M unflushed (bubble
  (S − 1)/(M + S − 1) ≈ 27% at M=8) — the dispatch-count reduction
  measured in BENCHNOTES.
* ``interleaved`` — Megatron interleaved stages: each device hosts
  V = ``n_chunks`` non-contiguous layer chunks (global chunk j lives
  on device j mod S), microbatches circulate the ring V times in
  groups of S.  Per-step compute drops to 1/V of a stage, the table
  below packs groups back-to-back, and T = M·V + S − 1 chunk-steps
  (M ≥ S), so the bubble shrinks to (S − 1)/(M·V + S − 1) in
  chunk-step units — the Megatron 1/V bubble reduction, visible on
  CPU as both shorter weighted scan length and wall time.

Every schedule's step table comes from :func:`schedule_steps` — a
pure-python simulation the bubble-accounting tests assert on — and
:func:`bubble_fraction` derives the idle fraction from the table, so
the claimed formulas and the executed scan cannot drift apart.
"""

import functools

import numpy

import jax
import jax.numpy as jnp
from jax import lax

#: Valid pipeline schedules (single source of truth for the unit
#: knob, the CLI flag and the bench A/B hook).
SCHEDULES = ("gpipe", "1f1b", "interleaved")


def init_parser(parser):
    """Pipeline-schedule flags, aggregated into the velescli parser
    (handed to ``root.common.engine`` by
    ``__main__.apply_subsystem_flags``)."""
    parser.add_argument(
        "--pp-schedule", default=None, choices=SCHEDULES,
        help="pipeline-parallel schedule for stage-stacked "
             "transformer stacks: 'gpipe' (fill-and-drain, default), "
             "'1f1b' (PipeDream-flush: per-step rematerialization "
             "shrinks live residuals from per-layer internals to one "
             "activation per step, making large microbatch counts "
             "affordable), or 'interleaved' (Megatron V-chunk stages "
             "— bubble shrinks ~1/V; see --pp-chunks) "
             "(docs/pipeline.md)")
    parser.add_argument(
        "--pp-chunks", type=int, default=None, metavar="V",
        help="interleaved schedule: virtual chunks per pipeline "
             "stage (default: one chunk per local block; the block "
             "count must divide into stages x chunks)")


def _shard_map():
    """Version-portable shard_map + its replication-check kwarg."""
    try:
        from jax import shard_map
        import inspect
        kw = {"check_vma": False} if "check_vma" in \
            inspect.signature(shard_map).parameters else {}
    except ImportError:
        from jax.experimental.shard_map import shard_map
        kw = {"check_rep": False}
    return shard_map, kw


def _validate(x, n_microbatches, n_layers, n_stages):
    """Shared argument validation — actionable errors instead of
    silent reshape/astype surprises (ISSUE 12 satellite)."""
    if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
        raise TypeError(
            "pipeline input dtype %s is not a float dtype — the "
            "pipelined stack carries a float activation stream "
            "(embed integer tokens before the stack instead of "
            "relying on a silent astype)" % jnp.asarray(x).dtype)
    B = x.shape[0]
    if n_microbatches < 1:
        raise ValueError(
            "n_microbatches must be >= 1, got %d" % n_microbatches)
    if n_microbatches > B:
        raise ValueError(
            "n_microbatches=%d exceeds the batch size %d — every "
            "microbatch needs at least one sample (lower "
            "n_microbatches or raise the minibatch size)"
            % (n_microbatches, B))
    if B % n_microbatches:
        raise ValueError("batch %d not divisible into %d microbatches"
                         % (B, n_microbatches))
    if n_layers % n_stages:
        raise ValueError(
            "%d stacked layers do not divide over %d pipeline "
            "stages" % (n_layers, n_stages))


def _pipeline_body(fn, params, x_mb, axis_name):
    """The per-device GPipe loop.  ``params``: this stage's layer
    parameters (stage dim already sliced away by shard_map);
    ``x_mb``: (M, mb, ...) microbatched input, replicated."""
    n_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    M = x_mb.shape[0]
    steps = M + n_stages - 1
    perm = [(i, i + 1) for i in range(n_stages - 1)]
    mb_shape = x_mb.shape[1:]
    out_acc = jnp.zeros((M,) + mb_shape, jnp.float32)

    def body(carry, t):
        recv, acc = carry
        # Stage 0 injects microbatch t (zeros once the ramp ends);
        # later stages consume what arrived from stage-1.
        feed_idx = jnp.clip(t, 0, M - 1)
        fresh = jnp.where(t < M, x_mb[feed_idx],
                          jnp.zeros(mb_shape, x_mb.dtype))
        inp = jnp.where(stage == 0, fresh.astype(jnp.float32), recv)
        out = fn(params, inp)
        # The LAST stage finishes microbatch t−(S−1) at step t.
        mb_done = t - (n_stages - 1)
        is_last = stage == n_stages - 1
        valid = jnp.logical_and(is_last, mb_done >= 0)
        slot = jnp.clip(mb_done, 0, M - 1)
        acc = jnp.where(
            valid,
            acc.at[slot].set(out.astype(jnp.float32)),
            acc)
        # Hand the activation to the next stage (last stage sends
        # nothing anyone reads).
        recv = lax.ppermute(out, axis_name, perm)
        return (recv, acc), None

    init = (jnp.zeros(mb_shape, jnp.float32), out_acc)
    (_, acc), _ = lax.scan(body, init, jnp.arange(steps))
    # Only the last stage holds real outputs; psum replicates them
    # (every other stage contributes zeros).
    return lax.psum(acc, axis_name)


def gpipe(fn, stacked_params, x, mesh, stage_axis, n_microbatches):
    """Runs ``y = fn(p[S-1], …fn(p[1], fn(p[0], x))…)`` microbatch-
    pipelined over the mesh's ``stage_axis`` (GPipe schedule).

    Args:
      fn: (layer_params, activation (mb, ...)) → activation, same
        shape class in and out (stages must be homogeneous).
      stacked_params: pytree whose leaves carry a leading S dim.
      x: (B, ...) float input; B must divide into ``n_microbatches``.
      mesh / stage_axis: where the stages live.
      n_microbatches: M; the bubble fraction is (S−1)/(M+S−1).

    Returns y (B, ...) float32, replicated over the stage axis.
    """
    shard_map, _kw = _shard_map()
    from jax.sharding import PartitionSpec as P
    B = x.shape[0]
    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    n_stages = mesh.shape[stage_axis]
    _validate(x, n_microbatches, n_layers, n_stages)
    mb = B // n_microbatches
    x_mb = x.reshape((n_microbatches, mb) + x.shape[1:])

    def stage_fn(params, x_all):
        # shard_map leaves each device a (n_layers/n_stages, ...)
        # local sub-stack; a stage applies its local layers in
        # sequence (scan), so n_layers may be any multiple of the
        # stage count.
        return _pipeline_body(
            lambda p, h: sequential_stack(fn, p, h),
            params, x_all, stage_axis)

    pspec = jax.tree_util.tree_map(
        lambda p: P(stage_axis, *([None] * (p.ndim - 1))),
        stacked_params)
    out = shard_map(
        stage_fn, mesh=mesh,
        in_specs=(pspec, P()), out_specs=P(), **_kw)(
            stacked_params, x_mb)
    return out.reshape((B,) + out.shape[2:])


def sequential_stack(fn, stacked_params, x):
    """The no-mesh reference path: the same stacked layers applied by
    a plain scan — every pipelined schedule and sequential must agree
    exactly (the parity oracle)."""
    def body(h, params):
        return fn(params, h), None
    y, _ = lax.scan(body, x.astype(jnp.float32), stacked_params)
    return y


# -- schedule tables -------------------------------------------------------

def schedule_steps(schedule, n_stages, n_microbatches, n_chunks=1):
    """The static schedule table — the single source of truth the
    scan loops consume and the bubble-accounting tests assert on.

    Returns a list of T steps; ``step[t]`` is a list of ``n_stages``
    entries, one per device: None (idle bubble slot) or a dict with

      * ``chunk``: local chunk index on that device (< n_chunks);
      * ``mb``: global microbatch id;
      * ``fresh``: the input is ``x_mb[mb]`` (pipeline entry);
      * ``final``: the output is the finished microbatch.

    GPipe and 1F1B are stage-granular (n_chunks must be 1) with
    T = M + S − 1: stage s is active exactly during steps
    [s, s + M) on microbatch t − s — the staggered ramp whose
    scan-reverse is the staggered backward.  Interleaved packs
    groups of min(S, M) microbatches back-to-back through V chunks
    per device (global chunk j on device j mod S): conflict-free by
    construction, one ring hop per chunk-step, T = M·V + S − 1 for
    M ≥ S (M + V·S − 1 for a single partial group).
    """
    S, M, V = n_stages, n_microbatches, n_chunks
    if schedule not in SCHEDULES:
        raise ValueError("unknown pipeline schedule %r — valid: %s"
                         % (schedule, list(SCHEDULES)))
    if schedule in ("gpipe", "1f1b"):
        if V != 1:
            raise ValueError(
                "schedule %r is stage-granular — n_chunks must be 1 "
                "(got %d); virtual chunks belong to 'interleaved'"
                % (schedule, V))
        steps = []
        for t in range(M + S - 1):
            row = []
            for s in range(S):
                m = t - s
                row.append(None if not 0 <= m < M else dict(
                    chunk=0, mb=m, fresh=(s == 0),
                    final=(s == S - 1)))
            steps.append(row)
        return steps
    # interleaved: groups of g microbatches, group k offset by k·V·S
    # chunk-steps; in-group microbatch m runs global chunk j at step
    # k·V·S + m + j.  Conflict-freedom (one op per device per step)
    # is asserted below, not assumed.
    g = min(S, M)
    if M % g:
        raise ValueError(
            "interleaved schedule needs n_microbatches (%d) "
            "divisible by the group size min(stages, microbatches) "
            "= %d — pad the microbatch count or use gpipe/1f1b"
            % (M, g))
    n_steps = (M // g - 1) * V * S + (g - 1) + (V * S - 1) + 1
    steps = [[None] * S for _ in range(n_steps)]
    for k in range(M // g):
        for m in range(g):
            for j in range(V * S):
                t = k * V * S + m + j
                d = j % S
                if steps[t][d] is not None:  # pragma: no cover
                    raise AssertionError(
                        "interleaved schedule conflict at step %d "
                        "device %d" % (t, d))
                steps[t][d] = dict(chunk=j // S, mb=k * g + m,
                                   fresh=(j == 0),
                                   final=(j == V * S - 1))
    return steps


def bubble_fraction(schedule, n_stages, n_microbatches, n_chunks=1):
    """Idle fraction of the fleet, derived FROM the schedule table
    (so formula and execution cannot drift): idle device-steps over
    total device-steps.  gpipe/1f1b: (S−1)/(M+S−1); interleaved:
    (S−1)/(M·V+S−1) in chunk-step units for M ≥ S."""
    table = schedule_steps(schedule, n_stages, n_microbatches,
                           n_chunks)
    total = len(table) * n_stages
    active = sum(1 for row in table for e in row if e is not None)
    return (total - active) / float(total)


def _table_arrays(table, n_stages):
    """Packs a schedule table into the (T, S) numpy arrays the scan
    consumes: chunk index, fresh flag, feed microbatch, final flag,
    output slot."""
    T = len(table)
    chunk = numpy.zeros((T, n_stages), numpy.int32)
    fresh = numpy.zeros((T, n_stages), numpy.float32)
    feed = numpy.zeros((T, n_stages), numpy.int32)
    final = numpy.zeros((T, n_stages), numpy.float32)
    slot = numpy.zeros((T, n_stages), numpy.int32)
    for t, row in enumerate(table):
        for d, e in enumerate(row):
            if e is None:
                continue
            chunk[t, d] = e["chunk"]
            if e["fresh"]:
                fresh[t, d] = 1.0
                feed[t, d] = e["mb"]
            if e["final"]:
                final[t, d] = 1.0
                slot[t, d] = e["mb"]
    return chunk, fresh, feed, final, slot


def _scheduled_body(fn, params, x_mb, tables, axis_name, n_chunks,
                    remat_step):
    """The per-device table-driven loop shared by 1F1B and
    interleaved: a closed ppermute ring, one chunk application per
    step, inputs selected fresh-vs-received and outputs accumulated
    per the schedule table.  ``params``: this device's local layer
    stack (stage dim sliced away, chunk-major order — see the
    reorder in :func:`pipeline`)."""
    n_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    M = x_mb.shape[0]
    mb_shape = x_mb.shape[1:]
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    # Local stack (V·Lc, ...) → (V, Lc, ...): chunk a = local[a].
    local = jax.tree_util.tree_map(
        lambda p: p.reshape((n_chunks, p.shape[0] // n_chunks) +
                            p.shape[1:]), params)

    def apply_chunk(cparams, h):
        return sequential_stack(fn, cparams, h)
    if remat_step:
        # The 1F1B memory lever: the backward re-runs each chunk's
        # forward from its saved input instead of keeping every
        # layer's internals live — per-step residuals shrink to one
        # activation (the scan's O(M) carry chain remains; see the
        # module docstring for the honest bound).
        apply_chunk = jax.checkpoint(apply_chunk)

    def body(carry, xs):
        recv, acc = carry
        chunk_row, fresh_row, feed_row, final_row, slot_row = xs
        c = jnp.take(chunk_row, stage)
        is_fresh = jnp.take(fresh_row, stage)
        f_idx = jnp.take(feed_row, stage)
        is_final = jnp.take(final_row, stage)
        o_slot = jnp.take(slot_row, stage)
        fresh = x_mb[f_idx].astype(jnp.float32)
        inp = jnp.where(is_fresh > 0, fresh, recv)
        cparams = jax.tree_util.tree_map(
            lambda p: lax.dynamic_index_in_dim(p, c, 0,
                                               keepdims=False),
            local)
        out = apply_chunk(cparams, inp)
        acc = jnp.where(
            is_final > 0,
            acc.at[o_slot].set(out.astype(jnp.float32)),
            acc)
        recv = lax.ppermute(out, axis_name, perm)
        return (recv, acc), None

    init = (jnp.zeros(mb_shape, jnp.float32),
            jnp.zeros((M,) + mb_shape, jnp.float32))
    (_, acc), _ = lax.scan(body, init, tables)
    # Only final-chunk outputs landed in acc (on the last device);
    # psum replicates them (other stages contribute zeros).
    return lax.psum(acc, axis_name)


def pipeline(fn, stacked_params, x, mesh, stage_axis, n_microbatches,
             schedule="gpipe", n_chunks=None, remat_step=None):
    """Schedule-dispatching pipeline: ``schedule`` picks gpipe
    (exactly :func:`gpipe`), 1f1b, or interleaved; every schedule
    computes the same function as :func:`sequential_stack` (the
    parity oracle) over a mesh ``stage_axis``.

    Args beyond :func:`gpipe`:
      schedule: one of :data:`SCHEDULES`.
      n_chunks: interleaved only — virtual chunks per stage (default
        one chunk per local layer); layers must divide into
        stages × chunks.
      remat_step: per-step rematerialization; None → on for 1f1b
        (its defining memory lever), off otherwise.
    """
    if schedule not in SCHEDULES:
        raise ValueError("unknown pipeline schedule %r — valid: %s"
                         % (schedule, list(SCHEDULES)))
    if schedule in ("gpipe", "1f1b") and n_chunks not in (None, 1):
        # Refuse, don't silently ignore: --pp-chunks with a
        # stage-granular schedule means the operator expected
        # interleaving that would never happen.
        raise ValueError(
            "schedule %r is stage-granular — n_chunks must be 1 "
            "(got %r); virtual chunks belong to 'interleaved'"
            % (schedule, n_chunks))
    if schedule == "gpipe":
        return gpipe(fn, stacked_params, x, mesh, stage_axis,
                     n_microbatches)
    shard_map, _kw = _shard_map()
    from jax.sharding import PartitionSpec as P
    B = x.shape[0]
    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    n_stages = mesh.shape[stage_axis]
    _validate(x, n_microbatches, n_layers, n_stages)
    local_layers = n_layers // n_stages
    if schedule == "1f1b":
        V = 1
    else:
        V = local_layers if n_chunks is None else n_chunks
        if V < 1 or local_layers % V:
            raise ValueError(
                "interleaved schedule: %d layers per stage do not "
                "divide into %r chunks" % (local_layers, V))
    if remat_step is None:
        remat_step = schedule == "1f1b"
    table = schedule_steps(schedule, n_stages, n_microbatches,
                           n_chunks=V)
    arrays = tuple(jnp.asarray(a) for a in _table_arrays(table,
                                                         n_stages))
    mb = B // n_microbatches
    x_mb = x.reshape((n_microbatches, mb) + x.shape[1:])

    params = stacked_params
    if V > 1:
        # Interleaved layer placement: global chunk j lives on device
        # j mod S, so the stacked layers must be reordered CHUNK-
        # MAJOR PER DEVICE before shard_map's contiguous leading-dim
        # split (device d then holds chunks d, d+S, …, d+(V−1)S).
        # A gather is differentiable; the stage-axis sharding spec is
        # unchanged.
        lc = n_layers // (n_stages * V)
        order = numpy.zeros(n_layers, numpy.int32)
        pos = 0
        for d in range(n_stages):
            for a in range(V):
                j = a * n_stages + d
                for l in range(lc):
                    order[pos] = j * lc + l
                    pos += 1
        order = jnp.asarray(order)
        params = jax.tree_util.tree_map(
            lambda p: jnp.take(p, order, axis=0), stacked_params)

    def stage_fn(p, x_all, *tbl):
        return _scheduled_body(fn, p, x_all, tbl, stage_axis, V,
                               remat_step)

    pspec = jax.tree_util.tree_map(
        lambda p: P(stage_axis, *([None] * (p.ndim - 1))), params)
    out = shard_map(
        stage_fn, mesh=mesh,
        in_specs=(pspec, P()) + (P(),) * len(arrays),
        out_specs=P(), **_kw)(params, x_mb, *arrays)
    return out.reshape((B,) + out.shape[2:])
