"""Hand-written TPU kernels (Pallas) for ops where XLA's default
lowering leaves bandwidth on the table.  Each module exposes an
``*_reference`` pure-jnp twin used for CPU execution and parity
tests."""
