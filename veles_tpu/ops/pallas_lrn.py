"""Pallas fused cross-channel LRN (forward + custom VJP).

Why a hand kernel (the reference's LRN lived in znicz's OpenCL/CUDA
normalization kernels; SURVEY §7 milestone 2 names the Pallas homes):
measured inside the AlexNet fused step on v5e, the banded-matmul
formulation (znicz/lrn.py) costs ~9 ms of a ~40 ms tick — ~3× the
minimal HBM traffic — because XLA materializes the square and the
f32 window-sum as full-size intermediates between the matmul and the
surrounding elementwise math.  This kernel does the whole chain

    y = x · (k + α/n · Σ_{j∈window} x_j²)^(−β)

in ONE pass per direction: a (rows × C) tile is read into VMEM, the
windowed channel sum rides the MXU as a tiny banded matmul against a
resident C×C 0/1 band, and only the result returns to HBM.  The
backward pass recomputes the denominator in-VMEM (FLOPs are free
here; traffic is not) so its only HBM traffic is x, dy in → dx out.

dx math: with d = k + (α/n)·S, S_j = Σ_i B[i,j] x_i²,

    dx_i = dy_i·d_i^{−β} − (2αβ/n)·x_i·Σ_j B[i,j]·dy_j·x_j·d_j^{−β−1}

(the window membership matrix B is the same band as forward; the
second term is one more in-VMEM banded matmul).
"""

import functools

import jax
import jax.numpy as jnp

#: Rows per grid step.  f32 working set ≈ 5 tiles × BP × 128 lanes
#: × 4 B ≈ 5 MB at 2048 — comfortably inside 16 MB VMEM.
_BLOCK_ROWS = 2048


def band_matrix(c, n, dtype=jnp.float32):
    """0/1 window-membership matrix B[i, j] = 1 iff input channel i
    falls in output channel j's window (asymmetric for even n,
    matching znicz's padded slice-add semantics)."""
    half = n // 2
    i = jnp.arange(c)
    d = i[:, None] - i[None, :]
    return ((d >= -half) & (d <= n - 1 - half)).astype(dtype)


def lrn_reference(x, n, alpha, beta, k):
    """Pure-jnp twin (CPU path + parity oracle): the banded-matmul
    formulation from znicz/lrn.py."""
    band = band_matrix(x.shape[-1], n, x.dtype)
    sq = x * x
    ssum = jnp.einsum("...c,cd->...d", sq, band,
                      preferred_element_type=jnp.float32)
    denom = (k + (alpha / n) * ssum) ** beta
    return (x.astype(jnp.float32) / denom).astype(x.dtype)


def _neg_pow(d, beta):
    """d^(−β) without exp/log where β allows: AlexNet's β = 0.75
    becomes rsqrt·sqrt(rsqrt) (hardware sqrt units), the generic case
    falls back to pow."""
    if abs(beta - 0.75) < 1e-12:
        inv = jax.lax.rsqrt(d)
        return inv * jnp.sqrt(inv)
    if abs(beta - 0.5) < 1e-12:
        return jax.lax.rsqrt(d)
    if abs(beta - 1.0) < 1e-12:
        return 1.0 / d
    return d ** -beta


def _window_sum(x, band_ref):
    """Σ_{j∈window} x_j² as a banded matmul on the MXU: bf16 operands
    (the band is exact 0/1 and the squares round to bf16 on the MXU
    regardless), f32 accumulation."""
    xb = x.astype(jnp.bfloat16)
    return jax.lax.dot(xb * xb, band_ref[:].astype(jnp.bfloat16),
                       preferred_element_type=jnp.float32)


def _fwd_kernel(x_ref, band_ref, y_ref, *, k, coef, beta):
    x = x_ref[:].astype(jnp.float32)
    d = k + coef * _window_sum(x, band_ref)
    y_ref[:] = (x * _neg_pow(d, beta)).astype(y_ref.dtype)


def _bwd_kernel(x_ref, dy_ref, band_ref, dx_ref, *, k, coef, beta):
    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    d = k + coef * _window_sum(x, band_ref)
    dpow = _neg_pow(d, beta)
    t = dy * x * dpow / d
    u = jax.lax.dot(t.astype(jnp.bfloat16),
                    band_ref[:].astype(jnp.bfloat16).T,
                    preferred_element_type=jnp.float32)
    dx = dy * dpow - (2.0 * coef * beta) * x * u
    dx_ref[:] = dx.astype(dx_ref.dtype)


def _call(kernel, args, c, out_dtype, interpret):
    """Runs a row-blocked (P, C) pallas kernel; the band rides along
    whole (it is C×C, tiny)."""
    from jax.experimental import pallas as pl
    p = args[0].shape[0]
    bp = min(_BLOCK_ROWS, p)
    grid = (-(-p // bp),)
    row_spec = pl.BlockSpec((bp, c), lambda i: (i, 0))
    band_spec = pl.BlockSpec((c, c), lambda i: (0, 0))
    specs = [row_spec] * (len(args) - 1) + [band_spec]
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((p, c), out_dtype),
        grid=grid,
        in_specs=specs,
        out_specs=row_spec,
        interpret=interpret,
    )(*args)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def lrn_pallas(x, n, alpha, beta, k, interpret=False):
    y, _ = _lrn_fwd(x, n, alpha, beta, k, interpret)
    return y


def _lrn_fwd(x, n, alpha, beta, k, interpret):
    c = x.shape[-1]
    flat = x.reshape(-1, c)
    # Static nondiff config scalars (custom_vjp nondiff_argnums),
    # baked into the kernel — never traced values.
    kern = functools.partial(
        _fwd_kernel, k=float(k),                    # lint-ok: VL101
        coef=float(alpha) / n, beta=float(beta))    # lint-ok: VL101
    y = _call(kern, (flat, band_matrix(c, n, jnp.float32)), c,
              x.dtype, interpret)
    return y.reshape(x.shape), x


def _lrn_bwd(n, alpha, beta, k, interpret, res, dy):
    x = res
    c = x.shape[-1]
    kern = functools.partial(_bwd_kernel, k=float(k),
                             coef=float(alpha) / n, beta=float(beta))
    dx = _call(kern, (x.reshape(-1, c), dy.reshape(-1, c),
                      band_matrix(c, n, jnp.float32)), c,
               x.dtype, interpret)
    return (dx.reshape(x.shape),)


lrn_pallas.defvjp(_lrn_fwd, _lrn_bwd)


def tpu_available():
    try:
        dev = jax.devices()[0]
    except Exception as e:
        import logging
        logging.getLogger("pallas_lrn").debug(
            "no jax backend available: %s", e)
        return False
    return "tpu" in dev.device_kind.lower() or \
        dev.platform in ("tpu", "axon")


def lrn(x, n, alpha, beta, k):
    """Backend-dispatching LRN: the Pallas kernel on TPU, the banded
    reference elsewhere (Pallas TPU kernels do not run on the CPU
    backend outside interpret mode)."""
    if tpu_available():
        return lrn_pallas(x, n, alpha, beta, k)
    return lrn_reference(x, n, alpha, beta, k)
