"""Confluence wiki publishing backend.

Capability parity with the reference Confluence backend (reference:
veles/publishing/confluence.py:45 — ``Confluence`` client with
get_page/store_page_content/attach_file against the wiki, used by the
publisher to push the end-of-run report under a space + parent page).
The reference spoke the old XML-RPC API; this client targets the
Confluence REST API (``/rest/api/content``): pages are created or
version-bumped in the *storage* representation, plot PNGs ride as
attachments referenced with ``<ac:image>`` markup.

Config (``backend_config={"confluence": {...}}`` on the Publisher, or
``root.common.publishing.confluence``): ``server`` (base URL),
``username``/``password`` (basic auth; an API token works as the
password), ``space`` (the space KEY), ``parent`` (optional parent
page title), ``page`` (title, default = workflow name).
"""

import base64
import html
import json
import urllib.error
import urllib.request
import uuid

from .error import BadFormatError
from .logger import Logger
from .publishing import Backend


class ConfluenceClient(Logger):
    """Minimal REST client (reference role: confluence.py:45)."""

    def __init__(self, server, username, password, timeout=60):
        super(ConfluenceClient, self).__init__()
        self.base = server.rstrip("/")
        self.timeout = timeout
        token = base64.b64encode(
            ("%s:%s" % (username, password)).encode()).decode()
        self._auth = "Basic " + token

    def _request(self, method, path, payload=None, content_type=None,
                 body=None):
        headers = {"Authorization": self._auth}
        data = body
        if payload is not None:
            data = json.dumps(payload).encode()
            headers["Content-Type"] = "application/json"
        elif content_type:
            headers["Content-Type"] = content_type
            # Confluence requires this header on attachment POSTs.
            headers["X-Atlassian-Token"] = "no-check"
        req = urllib.request.Request(
            self.base + path, data=data, headers=headers,
            method=method)
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:500]
            raise BadFormatError(
                "confluence %s %s -> HTTP %d: %s"
                % (method, path, e.code, detail))
        except (urllib.error.URLError, OSError) as e:
            raise BadFormatError(
                "confluence %s %s failed: %s" % (method, path, e))

    def get_page(self, space, title):
        """Returns {id, version} for a page, or None."""
        from urllib.parse import quote
        reply = self._request(
            "GET", "/rest/api/content?spaceKey=%s&title=%s"
            "&expand=version" % (quote(space), quote(title)))
        results = reply.get("results") or []
        if not results:
            return None
        page = results[0]
        return {"id": page["id"],
                "version": page["version"]["number"]}

    def store_page(self, space, title, storage_body, parent=None):
        """Creates the page or bumps its version with a new body
        (reference: store_page_content:227); returns the page id."""
        existing = self.get_page(space, title)
        payload = {
            "type": "page",
            "title": title,
            "space": {"key": space},
            "body": {"storage": {"value": storage_body,
                                 "representation": "storage"}},
        }
        if existing is None:
            if parent:
                parent_page = self.get_page(space, parent)
                if parent_page is None:
                    raise BadFormatError(
                        "confluence parent page %r not found in "
                        "space %s" % (parent, space))
                payload["ancestors"] = [{"id": parent_page["id"]}]
            reply = self._request("POST", "/rest/api/content",
                                  payload)
            return reply["id"]
        payload["version"] = {"number": existing["version"] + 1}
        self._request("PUT", "/rest/api/content/%s" % existing["id"],
                      payload)
        return existing["id"]

    def _find_attachment(self, page_id, filename):
        from urllib.parse import quote
        reply = self._request(
            "GET", "/rest/api/content/%s/child/attachment"
            "?filename=%s" % (page_id, quote(filename)))
        results = reply.get("results") or []
        return results[0]["id"] if results else None

    def attach(self, page_id, filename, blob, mime="image/png"):
        """Uploads or REPLACES one attachment (reference:
        attach_file:125-156, which also branched on existing
        attachments): a POST with an already-used filename is a 400
        on real Confluence, so updates go through the attachment's
        ``/data`` endpoint."""
        boundary = uuid.uuid4().hex
        body = b"".join([
            b"--", boundary.encode(), b"\r\n",
            b'Content-Disposition: form-data; name="file"; '
            b'filename="', filename.encode(), b'"\r\n',
            b"Content-Type: ", mime.encode(), b"\r\n\r\n",
            blob, b"\r\n--", boundary.encode(), b"--\r\n"])
        existing = self._find_attachment(page_id, filename)
        path = "/rest/api/content/%s/child/attachment" % page_id
        if existing is not None:
            path += "/%s/data" % existing
        self._request(
            "POST", path,
            content_type="multipart/form-data; boundary=%s"
            % boundary, body=body)


class ConfluenceBackend(Backend):
    """Publishes the report as a wiki page + attached plots
    (reference: veles/publishing/confluence.py)."""

    MAPPING = "confluence"

    def __init__(self, **kwargs):
        from .config import root, get as config_get
        cfg = root.common.publishing.confluence
        self.server = kwargs.get("server", config_get(cfg.server, ""))
        self.username = kwargs.get("username",
                                   config_get(cfg.username, ""))
        self.password = kwargs.get("password",
                                   config_get(cfg.password, ""))
        self.space = kwargs.get("space", config_get(cfg.space, ""))
        self.parent = kwargs.get("parent",
                                 config_get(cfg.parent, None))
        self.page = kwargs.get("page", config_get(cfg.page, None))
        if not (self.server and self.space):
            raise BadFormatError(
                "confluence backend needs server + space "
                "(root.common.publishing.confluence.*)")

    def storage_body(self, report):
        """The page body in Confluence *storage* markup; plots are
        referenced as attachments (data: URIs are not supported
        there)."""
        esc = lambda v: html.escape(str(v), quote=True)  # noqa: E731
        parts = ["<p><em>Generated %s</em></p>"
                 % esc(report["generated"]),
                 "<h2>Results</h2><ul>"]
        for key, value in sorted(report["results"].items()):
            parts.append("<li><strong>%s</strong>: %s</li>"
                         % (esc(key), esc(value)))
        parts.append(
            "</ul><h2>Run</h2><p>mode %s, %.1f s, %d units, "
            "checksum <code>%s</code></p>"
            % (esc(report["mode"]), report["runtime"],
               report["units"], esc(report["checksum"])))
        for i, plot in enumerate(report["plots"]):
            parts.append(
                '<h3>%s</h3><ac:image><ri:attachment '
                'ri:filename="plot_%d.png"/></ac:image>'
                % (esc(plot["name"]), i))
        return "".join(parts)

    def render(self, report, output_dir):
        client = ConfluenceClient(self.server, self.username,
                                  self.password)
        title = self.page or report["workflow"]
        page_id = client.store_page(self.space, title,
                                    self.storage_body(report),
                                    parent=self.parent)
        for i, plot in enumerate(report["plots"]):
            client.attach(page_id, "plot_%d.png" % i,
                          self._png_of(plot))
        url = "%s/spaces/%s/pages/%s" % (self.server.rstrip("/"),
                                         self.space, page_id)
        return url
