"""Workflow snapshotting (checkpoint/resume).

Capability parity with the reference snapshotter (reference:
veles/snapshotter.py — ``SnapshotterBase:84``, ``SnapshotterToFile:358``,
compression codecs, interval + time throttling ``:159-174``,
``_current`` symlink ``:395-407``, size warning ``:203-225``; resume
via ``-s file`` ``__main__.py:532-582``): a unit linked after the
Decision that pickles the ENTIRE workflow — graph, unit state, Vectors
(device arrays are mapped back to host first, memory.py pickling) —
whenever the decision reports improvement, subject to throttles.

Integrity + retention (docs/resilience.md "Training health &
checkpoint integrity"): every export writes a sidecar **manifest**
(``<blob>.manifest.json`` — SHA-256 of the blob, size, epoch,
validation error, codec, timestamp) with the same atomic
temp+``os.replace`` discipline as the blob itself; ``import_``
verifies the checksum before unpickling and the resume path
(``Launcher.resume_latest`` → ``resilience.iter_snapshots``) walks
back to the previous good **generation** when the newest snapshot is
corrupt, missing, or unloadable.  The last ``keep`` generations per
prefix are retained (``--snapshot-keep``, default 3); older ones are
pruned after each successful export.  Both backends participate: the
DB backend stores the checksum in a ``sha256`` column, prunes rows
beyond the retention count, and walks back over rows the same way.

TPU note: Vectors pickle via their host mirror (memory.py maps
device→host on ``__getstate__``), so a snapshot taken on an N-chip
mesh restores onto ANY topology — shardings are re-applied at
``initialize`` time, which is exactly the reference's "resume onto a
different cluster" capability.
"""

import bz2
import gzip
import hashlib
import json
import lzma
import os
import pickle
import time

import numpy

from . import resilience
from .config import root, get as config_get
from .registry import MappedUnitRegistry
from .resilience import RetryPolicy
from .units import Unit

def init_parser(parser):
    """Snapshotter flags for the aggregated velescli parser."""
    parser.add_argument(
        "--snapshot-dir", default=None, metavar="DIR",
        help="snapshot destination directory "
             "(sets root.common.dirs.snapshots)")
    parser.add_argument(
        "--snapshot-compression", default=None,
        choices=("", "gz", "bz2", "xz"),
        help="snapshot codec (sets root.common.snapshotter."
             "compression)")
    parser.add_argument(
        "--snapshot-keep", type=int, default=None, metavar="K",
        help="retain the last K snapshot generations per prefix "
             "(default 3; 0 = unlimited; sets "
             "root.common.snapshotter.keep)")
    parser.add_argument(
        "--no-snapshots", action="store_true",
        help="disable snapshotting for this run")
    parser.add_argument(
        "--auto-resume", action="store_true",
        help="coordinator crash-resume: if the snapshot directory "
             "holds a *_current.lnk pointer, resume from the newest "
             "VERIFIED snapshot generation instead of starting fresh "
             "(no-op when -s is given or no snapshot exists)")
    parser.add_argument(
        "--snapshot-artifact", action="store_true",
        help="continuous deployment: alongside every snapshot, "
             "export the workflow's forward chain as a serving "
             "artifact (<blob>.veles.tgz + sha256 manifest sidecar) "
             "— a serving replica watching <prefix>_current.lnk "
             "(--serve-reload-watch) hot-deploys each verified "
             "generation (sets root.common.snapshotter.artifact)")


CODECS = {
    "": (lambda p: open(p, "wb"), lambda p: open(p, "rb"), ""),
    "gz": (lambda p: gzip.open(p, "wb"),
           lambda p: gzip.open(p, "rb"), ".gz"),
    "bz2": (lambda p: bz2.open(p, "wb"),
            lambda p: bz2.open(p, "rb"), ".bz2"),
    "xz": (lambda p: lzma.open(p, "wb"),
           lambda p: lzma.open(p, "rb"), ".xz"),
}

#: Manifest sidecar suffix (``<blob>.manifest.json``).
MANIFEST_SUFFIX = ".manifest.json"

#: Manifest schema version.
MANIFEST_FORMAT = 1


class SnapshotIntegrityError(resilience.ResilienceError):
    """A snapshot blob does not match its manifest checksum (bit rot,
    torn write, tampering).  Resume paths catch this and walk back to
    the previous generation instead of loading garbage."""


class SnapshotUnhealthyError(resilience.ResilienceError):
    """The manifest records that the snapshot was written with
    NON-FINITE trainables (a NaN epoch under the guardian's rollback
    policy): the blob is intact but resuming from it is useless, so
    the generation walk skips it like a corrupt one.  Load explicitly
    with ``verify=False`` to inspect the poisoned state."""


class SnapshotPointerError(FileNotFoundError):
    """A ``_current.lnk`` pointer that cannot be resolved (missing,
    empty, or naming a deleted snapshot).  Carries an actionable
    message naming the pointer file — the raw FileNotFoundError from
    deep inside pickle loading named only the target."""


def workflow_is_finite(workflow):
    """True when every trainable Vector of the workflow holds only
    finite values on its host mirror (pickling just mapped them
    host-side, so this reads memory already paid for)."""
    from .memory import Vector
    for unit in getattr(workflow, "units", ()):
        vecs = getattr(unit, "trainables", None)
        if not isinstance(vecs, dict):
            continue
        for vec in vecs.values():
            if isinstance(vec, Vector) and vec and \
                    vec.mem is not None and \
                    not numpy.isfinite(vec.mem).all():
                return False
    return True


def manifest_path(path):
    """The sidecar manifest path for a snapshot blob."""
    return path + MANIFEST_SUFFIX


def write_manifest_sidecar(path, manifest):
    """Writes ``path``'s sidecar manifest atomically (temp +
    ``os.replace``) — shared by the snapshot and serving-artifact
    writers: resume and the deploy gate trust the checksum, so a
    torn manifest must never exist."""
    mpath = manifest_path(path)
    tmp = mpath + ".part"
    try:
        with open(tmp, "w") as fout:
            json.dump(manifest, fout, indent=1, sort_keys=True)
        os.replace(tmp, mpath)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return manifest


def read_manifest(path):
    """The parsed manifest for a snapshot blob, or None when the blob
    has no (readable) sidecar — legacy snapshots predate manifests."""
    try:
        with open(manifest_path(path)) as fin:
            manifest = json.load(fin)
    except (OSError, ValueError):
        return None
    return manifest if isinstance(manifest, dict) else None


def sha256_file(path, chunk=1 << 20):
    """Streaming SHA-256 of a file (snapshots can be GBs — never read
    them whole)."""
    digest = hashlib.sha256()
    with open(path, "rb") as fin:
        while True:
            block = fin.read(chunk)
            if not block:
                break
            digest.update(block)
    return digest.hexdigest()


def _declared_families(directory):
    """Family names the directory itself declares — via
    ``*_current.lnk`` pointers AND via manifest ``prefix`` fields
    (so a family stays protected from a shorter family's
    retention/resume walks even after an operator deletes its
    pointer)."""
    import glob
    families = {os.path.basename(link)[:-len("_current.lnk")]
                for link in glob.glob(
                    os.path.join(directory, "*_current.lnk"))}
    for mpath in glob.glob(os.path.join(
            directory, "*" + MANIFEST_SUFFIX)):
        try:
            with open(mpath) as fin:
                prefix = json.load(fin).get("prefix")
        except (OSError, ValueError, AttributeError):
            continue
        if isinstance(prefix, str) and prefix:
            families.add(prefix)
    return families


def iter_generations(directory, prefix):
    """Snapshot blob paths of one family in ``directory``, newest
    first.  Ordering prefers the manifest ``created`` stamp (mtime as
    the legacy fallback); blobs of a DIFFERENT family that merely
    share the glob (``mnist_big_*`` under prefix ``mnist``) are
    excluded — by their manifest's recorded prefix, or, for legacy
    manifest-less blobs, by belonging to a longer family the
    directory's pointers declare (retention pruning must never eat
    another training's checkpoints)."""
    import glob
    if not directory or not os.path.isdir(directory):
        return []
    longer_families = {f for f in _declared_families(directory)
                       if f != prefix and f.startswith(prefix)}
    out = []
    seen = set()
    for pattern in (prefix + ".pickle*", prefix + "_*.pickle*"):
        for path in glob.glob(os.path.join(directory, pattern)):
            # .veles.tgz: a snapshot's SIBLING SERVING ARTIFACT
            # (--snapshot-artifact) shares the blob's name stem — it
            # is a deploy artifact, never a resumable generation.
            if path.endswith((MANIFEST_SUFFIX, ".part", ".lnk",
                              ".veles.tgz")) or path in seen:
                continue
            seen.add(path)
            manifest = read_manifest(path)
            if manifest is not None and \
                    manifest.get("prefix") not in (None, prefix):
                continue
            if manifest is None and any(
                    os.path.basename(path).startswith(f + "_") or
                    os.path.basename(path).startswith(f + ".")
                    for f in longer_families):
                continue
            stamp = None
            if manifest is not None:
                try:
                    stamp = float(manifest["created"])
                except (KeyError, TypeError, ValueError):
                    stamp = None
            if stamp is None:
                try:
                    stamp = os.path.getmtime(path)
                except OSError:
                    continue  # pruned between glob and stat
            out.append((stamp, path))
    out.sort(reverse=True)
    return [path for _, path in out]


class SnapshotterRegistry(MappedUnitRegistry):
    """String → snapshotter class (reference mapping: "file", "odbc",
    …)."""
    registry = {}


class SnapshotterBase(Unit, metaclass=SnapshotterRegistry):
    """Common throttling/trigger logic (reference:
    snapshotter.py:84).

    kwargs: ``prefix`` — snapshot name stem; ``compression`` —
    ""/gz/bz2/xz; ``interval`` — snapshot every Nth trigger;
    ``time_interval`` — min seconds between snapshots; ``skip`` —
    disable; ``keep`` — generations retained per prefix (default
    ``root.common.snapshotter.keep`` or 3; 0 = unlimited).  Link
    ``suffix`` from the Decision (``snapshot_suffix``) and gate the
    unit on decision.improved.
    """

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        self.prefix = kwargs.get("prefix", "snapshot")
        self.compression = kwargs.get(
            "compression",
            root.common.snapshotter.get("compression", "gz"))
        self.interval = kwargs.get(
            "interval", root.common.snapshotter.get("interval", 1))
        self.time_interval = kwargs.get(
            "time_interval",
            root.common.snapshotter.get("time_interval", 1.0))
        self.skip = kwargs.get("skip", False)
        self.keep = int(kwargs.get(
            "keep", root.common.snapshotter.get("keep", 3)))
        #: Continuous deployment (``--snapshot-artifact``): export a
        #: verified serving artifact next to every snapshot blob.
        self.export_artifact = bool(kwargs.get(
            "artifact", root.common.snapshotter.get("artifact",
                                                    False)))
        super(SnapshotterBase, self).__init__(workflow, **kwargs)
        # After super().__init__ — it runs init_unpickled, which
        # clears the transient injector slot.
        #: Transient write failures (NFS hiccup, injected
        #: ``snapshot.fail``) are retried with backoff; exhaustion
        #: propagates — a training run silently losing its
        #: checkpoints is worse than a loud stop.
        self.retry_policy = kwargs.get("retry_policy") or RetryPolicy(
            max_attempts=int(kwargs.get("write_retries", 3)),
            base_delay=0.05)
        #: Fault injector consulted at ``snapshot.write`` /
        #: ``snapshot.corrupt``; None = the process-wide one.
        #: Trailing underscore: transient — injectors hold locks and
        #: never ride a snapshot.
        self.injector_ = kwargs.get("injector")
        self.view_group = "SERVICE"
        self.suffix = ""
        self.destination = None
        self._counter = 0
        self._last_time = 0.0
        self._deferred = False

    def init_unpickled(self):
        super(SnapshotterBase, self).init_unpickled()
        self.injector_ = None

    def initialize(self, **kwargs):
        super(SnapshotterBase, self).initialize(**kwargs)
        self._last_time = time.time()

    def run(self):
        self._counter += 1
        if self.skip or config_get(root.common.snapshot_disabled,
                                   False):
            return
        if self._counter % self.interval:
            return
        if time.time() - self._last_time < self.time_interval:
            return
        self._last_time = time.time()
        # Coordinated distributed snapshot (reference:
        # snapshotter.py:181-195,227-234 — the master waited for all
        # slaves' acks): with worker jobs outstanding, the pickled
        # state would disagree with updates already in flight, so
        # defer until the workflow reports the queue drained
        # (on_jobs_drained) or the jobs are requeued by a drop.
        inflight = getattr(self.workflow, "total_inflight_jobs",
                           None)
        if inflight is not None and inflight():
            self._deferred = True
            self.info("deferring snapshot: %d worker job(s) in "
                      "flight", inflight())
            return
        self._deferred = False  # self-heal a stale deferral
        self.export()

    def on_jobs_drained(self):
        """Master-side callback once every outstanding worker job has
        been answered or requeued — performs a deferred snapshot."""
        if self._deferred:
            self._deferred = False
            # Re-stamp: the throttle window starts at the actual
            # export, not at the (earlier) deferred request.
            self._last_time = time.time()
            self.export()

    def describe(self):
        """Training-progress fields recorded in the manifest: the
        decision's epoch counter and best validation error, when the
        workflow has them (duck-typed — non-training workflows
        snapshot too)."""
        decision = getattr(self.workflow, "decision", None)
        out = {}
        try:
            epoch = getattr(decision, "epoch_number", None)
            if epoch is not None:
                out["epoch"] = int(epoch)
        except (TypeError, ValueError):
            pass
        try:
            verr = getattr(decision, "min_validation_err", None)
            if verr is not None and float(verr) < 1e29:
                out["validation_error"] = float(verr)
        except (TypeError, ValueError):
            pass
        # Optimizer kind(s): resuming under a DIFFERENT optimizer
        # fails at initialize with a slot-mismatch error — recording
        # the kind here lets operators (and tooling) see what a
        # checkpoint needs before loading multi-GB state.
        kinds = sorted({
            kind for kind in (
                getattr(unit, "optimizer", None)
                for unit in getattr(self.workflow, "units", ()))
            if isinstance(kind, str)})
        if kinds:
            out["optimizer"] = "+".join(kinds)
        return out

    def export(self):
        raise NotImplementedError()


class _HashingWriter(object):
    """File-object tee that SHA-256s (and counts) every byte on its
    way to the underlying raw file — the manifest checksum comes for
    free with the write instead of re-reading the blob."""

    def __init__(self, raw):
        self._raw = raw
        self.digest = hashlib.sha256()
        self.size = 0

    def write(self, data):
        self.digest.update(data)
        # pickle protocol 5 hands PickleBuffer objects (no len());
        # the raw write reports the byte count either way.
        written = self._raw.write(data)
        self.size += written
        return written

    def flush(self):
        self._raw.flush()

    # gzip's GzipFile probes these on its fileobj.
    def seekable(self):
        return False

    @property
    def mode(self):
        return "wb"

    def fileno(self):
        return self._raw.fileno()


class SnapshotterToFile(SnapshotterBase):
    """Pickle-to-file backend (reference: snapshotter.py:358)."""

    MAPPING = "file"

    def __init__(self, workflow, **kwargs):
        super(SnapshotterToFile, self).__init__(workflow, **kwargs)
        self.directory = kwargs.get(
            "directory",
            config_get(root.common.dirs.snapshots, "snapshots"))

    def export(self):
        os.makedirs(self.directory, exist_ok=True)
        _, _, ext = CODECS[self.compression]
        name = self.prefix
        if self.suffix:
            name += "_" + self.suffix
        path = os.path.join(self.directory, name + ".pickle" + ext)
        digest, size = self.retry_policy.call(
            lambda: self._write_atomic(path),
            retry_on=(OSError,), stat="snapshot.retry",
            on_retry=lambda attempt, e: self.warning(
                "snapshot write failed (%s) — retrying", e))
        # Same retry umbrella as the blob: a transient error here
        # would otherwise leave a healthy blob with no sidecar —
        # loadable, but unverifiable.
        self.retry_policy.call(
            lambda: self._write_manifest(path, digest, size),
            retry_on=(OSError,), stat="snapshot.retry",
            on_retry=lambda attempt, e: self.warning(
                "manifest write failed (%s) — retrying", e))
        # Chaos: bit-rot the blob AFTER the manifest recorded the
        # good checksum — resume must now reject this generation and
        # walk back to the previous one.
        try:
            resilience.effective(self.injector_).check(
                "snapshot.corrupt")
        except resilience.InjectedSnapshotCorruption:
            corrupt_file(path)
            self.warning("chaos: flipped one byte of %s", path)
        if self.export_artifact:
            # BEFORE the pointer moves: a serving replica watching
            # _current.lnk must never resolve a pointer whose
            # artifact is still being written.
            self._export_serving_artifact(path)
        self.destination = path
        self._update_current_link(path)
        resilience.stats.incr("snapshot.write")
        size = os.path.getsize(path)
        self.info("snapshot -> %s (%.1f MB)", path, size / 1e6)
        self.prune()
        if size > (1 << 30):
            self.warning("snapshot exceeds 1 GB — consider trimming "
                         "unit state (reference kept a per-unit size "
                         "breakdown for this)")

    def _write_atomic(self, path):
        """Pickles into a temp file in the same directory, then
        ``os.replace``s it over the target: a crash mid-pickle can
        never clobber the previous good snapshot at the same path —
        the invariant coordinator crash-resume rests on.  The
        on-disk bytes are SHA-256'd as they stream through (no
        second multi-GB read for the manifest); returns
        ``(hexdigest, size)``."""
        resilience.effective(self.injector_).check("snapshot.write")
        tmp = path + ".part"
        try:
            with open(tmp, "wb") as raw:
                tee = _HashingWriter(raw)
                # gzip/bz2/lzma .open all accept a file object; ""
                # writes straight through the tee.
                codec = {"": lambda f: f,
                         "gz": lambda f: gzip.open(f, "wb"),
                         "bz2": lambda f: bz2.open(f, "wb"),
                         "xz": lambda f: lzma.open(f, "wb")}[
                    self.compression]
                fout = codec(tee)
                try:
                    pickle.dump(self.workflow, fout,
                                protocol=pickle.HIGHEST_PROTOCOL)
                finally:
                    if fout is not tee:
                        fout.close()  # flush the codec trailer
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return tee.digest.hexdigest(), tee.size

    def _write_manifest(self, path, digest, size):
        """Sidecar integrity manifest, atomic like the blob: resume
        trusts the checksum, so a torn manifest must never exist."""
        manifest = {
            "format": MANIFEST_FORMAT,
            "sha256": digest,
            "size": size,
            "prefix": self.prefix,
            "suffix": self.suffix,
            "codec": self.compression,
            "created": time.time(),
            "finite": workflow_is_finite(self.workflow),
        }
        manifest.update(self.describe())
        return write_manifest_sidecar(path, manifest)

    def _export_serving_artifact(self, path):
        """The train→serve hot-deploy hook: exports the workflow's
        forward chain as a serving artifact next to the snapshot blob
        (``<blob>.veles.tgz`` + sha256 sidecar manifest, atomic
        temp+replace like everything else here).  A serving replica
        following this family's ``_current.lnk`` verifies the
        manifest and hot-swaps the weights in (docs/serving.md
        "Operations").  Workflows without an exportable forward
        chain — or transient export failures — log and skip: losing
        one deploy generation must never fail the training snapshot
        that carries it."""
        from .export import export_workflow
        from .serving.reload import ARTIFACT_SUFFIX
        apath = path + ARTIFACT_SUFFIX
        tmp = apath + ".part"
        try:
            export_workflow(self.workflow, tmp)
            digest = sha256_file(tmp)
            size = os.path.getsize(tmp)
            os.replace(tmp, apath)
            manifest = {
                "format": MANIFEST_FORMAT,
                "kind": "serving-artifact",
                "sha256": digest,
                "size": size,
                "prefix": self.prefix,
                "created": time.time(),
            }
            manifest.update(self.describe())
            write_manifest_sidecar(apath, manifest)
            resilience.stats.incr("snapshot.artifact")
            self.info("serving artifact -> %s", apath)
        except Exception as e:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            self.warning("serving-artifact export skipped: %s", e)

    def prune(self):
        """Deletes generations beyond ``keep`` (oldest first), with
        their manifests and sibling serving artifacts.  The newest
        generation — the one ``_current.lnk`` names — always
        survives; ``keep <= 0`` disables pruning."""
        if self.keep <= 0:
            return
        from .serving.reload import ARTIFACT_SUFFIX
        for path in iter_generations(self.directory,
                                     self.prefix)[self.keep:]:
            try:
                os.unlink(path)
            except OSError as e:
                # Keep the manifest too: a surviving blob without
                # its sidecar would degrade to an unverifiable
                # legacy snapshot.
                self.warning("cannot prune %s (%s) — kept with its "
                             "manifest", path, e)
                continue
            for extra in (manifest_path(path),
                          path + ARTIFACT_SUFFIX,
                          manifest_path(path + ARTIFACT_SUFFIX)):
                try:
                    os.unlink(extra)
                except OSError:
                    pass
            resilience.stats.incr("snapshot.prune")
            self.info("pruned snapshot generation %s", path)

    def _update_current_link(self, path):
        """Maintains ``<prefix>_current.lnk`` with the newest snapshot
        path (reference: snapshotter.py:395-407).  Atomic for the
        same reason as the snapshot itself: the pointer is what a
        restarted coordinator trusts."""
        link = os.path.join(self.directory,
                            self.prefix + "_current.lnk")
        tmp = link + ".part"
        with open(tmp, "w") as fout:
            # Absolute: a coordinator restarted from a different cwd
            # (supervisors rarely preserve it) must still find the
            # snapshot the pointer names.
            fout.write(os.path.abspath(path))
        os.replace(tmp, link)

    @staticmethod
    def resolve(path):
        """Resolves a ``_current.lnk`` pointer to its snapshot path
        (non-pointer paths pass through).  Raises
        :class:`SnapshotPointerError` naming the POINTER file when it
        is missing, empty, or dangling — the generation-walk resume
        fallback (``resilience.iter_snapshots``) then takes over for
        ``--auto-resume``; an explicit ``-s`` gets the actionable
        message instead of a raw FileNotFoundError from pickle."""
        if not path.endswith(".lnk"):
            return path
        try:
            with open(path) as fin:
                target = fin.read().strip()
        except OSError as e:
            raise SnapshotPointerError(
                "snapshot pointer %s cannot be read (%s) — pass the "
                "snapshot file itself, or use --auto-resume to walk "
                "the surviving generations" % (path, e)) from e
        if not target:
            raise SnapshotPointerError(
                "snapshot pointer %s is empty — the snapshot "
                "directory may have been partially cleaned; use "
                "--auto-resume to walk the surviving generations"
                % path)
        if not os.path.isfile(target):
            # Legacy cwd-relative pointer: pointer and snapshot share
            # a directory.
            sibling = os.path.join(os.path.dirname(path),
                                   os.path.basename(target))
            if os.path.isfile(sibling):
                return sibling
            raise SnapshotPointerError(
                "snapshot pointer %s names %s, which does not exist "
                "— the snapshot was deleted or the volume is "
                "incomplete; use --auto-resume to fall back to an "
                "older generation" % (path, target))
        return target

    @staticmethod
    def verify(path):
        """Checks ``path`` against its sidecar manifest.  Returns the
        manifest dict (or None for a legacy blob without one); raises
        :class:`SnapshotIntegrityError` — and counts
        ``snapshot.verify_fail`` — on checksum or size mismatch."""
        manifest = read_manifest(path)
        if manifest is None:
            return None
        expected = manifest.get("sha256")
        size = manifest.get("size")
        try:
            if size is not None and os.path.getsize(path) != size:
                raise SnapshotIntegrityError(
                    "snapshot %s is %d bytes, manifest says %s"
                    % (path, os.path.getsize(path), size))
            if expected and sha256_file(path) != expected:
                raise SnapshotIntegrityError(
                    "snapshot %s fails its manifest checksum "
                    "(expected sha256 %s…) — refusing to load a "
                    "corrupt checkpoint" % (path, expected[:12]))
        except SnapshotIntegrityError:
            resilience.stats.incr("snapshot.verify_fail")
            raise
        if manifest.get("finite") is False:
            resilience.stats.incr("snapshot.unhealthy")
            raise SnapshotUnhealthyError(
                "snapshot %s was written with non-finite trainables "
                "(a poisoned epoch) — the generation walk skips it; "
                "load with verify=False to inspect it" % path)
        return manifest

    @staticmethod
    def import_(path, verify=True):
        """Loads a snapshot (resume path; reference:
        snapshotter.py:410 + __main__.py:532-582).  ``path`` may be
        the ``_current.lnk`` pointer file.  With ``verify`` (the
        default) the blob is checked against its manifest first;
        legacy blobs without a manifest load unchecked."""
        path = SnapshotterToFile.resolve(path)
        if verify:
            SnapshotterToFile.verify(path)
        for _, reader, ext in CODECS.values():
            if ext and path.endswith(ext):
                with reader(path) as fin:
                    return pickle.load(fin)
        with open(path, "rb") as fin:
            return pickle.load(fin)


def corrupt_file(path):
    """Flips one mid-file byte in place (chaos `snapshot.corrupt` and
    integrity tests)."""
    size = os.path.getsize(path)
    offset = size // 2
    with open(path, "r+b") as fout:
        fout.seek(offset)
        byte = fout.read(1)
        fout.seek(offset)
        fout.write(bytes([byte[0] ^ 0xFF]))


class SnapshotterToDB(SnapshotterBase):
    """Database snapshot backend (reference: snapshotter.py:425
    ``SnapshotterToDB`` over pyodbc; here stdlib sqlite3 — same
    capability, no driver dependency.  ``database`` accepts a file
    path or an ``odbc://``-style spec whose tail is treated as the
    file path).

    Snapshots land in a ``snapshots`` table (prefix, suffix, created,
    codec, sha256, epoch, validation_error, blob); writes ride the
    same ``retry_policy`` + ``snapshot.write`` injection point as the
    file backend, rows beyond ``keep`` are pruned per prefix, and
    resume with ``SnapshotterToDB.import_(database, prefix=...)``
    walks rows newest-first, skipping any whose blob fails its
    ``sha256`` — the DB-side equivalent of the file backend's
    generation walk.
    """

    MAPPING = "db"

    TABLE_DDL = ("CREATE TABLE IF NOT EXISTS snapshots ("
                 "id INTEGER PRIMARY KEY AUTOINCREMENT, "
                 "prefix TEXT NOT NULL, suffix TEXT, "
                 "created REAL NOT NULL, codec TEXT, "
                 "sha256 TEXT, epoch INTEGER, "
                 "validation_error REAL, finite INTEGER, "
                 "blob BLOB NOT NULL)")

    #: Columns added since the first schema revision — applied with
    #: ALTER TABLE when an existing database predates them.
    MIGRATIONS = ("sha256 TEXT", "epoch INTEGER",
                  "validation_error REAL", "finite INTEGER")

    def __init__(self, workflow, **kwargs):
        super(SnapshotterToDB, self).__init__(workflow, **kwargs)
        self.database = self._db_path(kwargs["database"])

    @staticmethod
    def _db_path(spec):
        for scheme in ("odbc://", "sqlite://", "db://"):
            if spec.startswith(scheme):
                return spec[len(scheme):]
        return spec

    @classmethod
    def _ensure_schema(cls, conn):
        conn.execute(cls.TABLE_DDL)
        import sqlite3
        for column in cls.MIGRATIONS:
            try:
                conn.execute(
                    "ALTER TABLE snapshots ADD COLUMN " + column)
            except sqlite3.OperationalError:
                pass  # already present

    def export(self):
        import sqlite3
        blob = pickle.dumps(self.workflow,
                            protocol=pickle.HIGHEST_PROTOCOL)
        if self.compression == "gz":
            blob = gzip.compress(blob)
        elif self.compression == "bz2":
            blob = bz2.compress(blob)
        elif self.compression == "xz":
            blob = lzma.compress(blob)
        # Chaos: the manifest checksum is of the GOOD blob; the
        # corrupted bytes are what lands in the row.
        stored = blob
        try:
            resilience.effective(self.injector_).check(
                "snapshot.corrupt")
        except resilience.InjectedSnapshotCorruption:
            mid = len(blob) // 2
            stored = blob[:mid] + bytes([blob[mid] ^ 0xFF]) + \
                blob[mid + 1:]
            self.warning("chaos: flipped one byte of the %s row",
                         self.prefix)
        digest = hashlib.sha256(blob).hexdigest()
        os.makedirs(os.path.dirname(os.path.abspath(self.database)),
                    exist_ok=True)
        described = self.describe()
        described["finite"] = int(workflow_is_finite(self.workflow))
        self.retry_policy.call(
            lambda: self._insert_row(stored, digest, described),
            retry_on=(OSError, sqlite3.OperationalError),
            stat="snapshot.retry",
            on_retry=lambda attempt, e: self.warning(
                "snapshot row insert failed (%s) — retrying", e))
        resilience.stats.incr("snapshot.write")
        self.destination = "%s#%s" % (self.database, self.prefix)
        self.info("snapshot -> %s (%.1f MB)", self.destination,
                  len(stored) / 1e6)

    def _insert_row(self, blob, digest, described):
        import sqlite3
        resilience.effective(self.injector_).check("snapshot.write")
        with sqlite3.connect(self.database) as conn:
            self._ensure_schema(conn)
            conn.execute(
                "INSERT INTO snapshots (prefix, suffix, created, "
                "codec, sha256, epoch, validation_error, finite, "
                "blob) VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?)",
                (self.prefix, self.suffix, time.time(),
                 self.compression, digest, described.get("epoch"),
                 described.get("validation_error"),
                 described.get("finite"), sqlite3.Binary(blob)))
            if self.keep > 0:
                pruned = conn.execute(
                    "DELETE FROM snapshots WHERE prefix = ? AND id "
                    "NOT IN (SELECT id FROM snapshots WHERE "
                    "prefix = ? ORDER BY id DESC LIMIT ?)",
                    (self.prefix, self.prefix, self.keep)).rowcount
                if pruned:
                    resilience.stats.incr("snapshot.prune", pruned)

    @staticmethod
    def import_(database, prefix=None, verify=True):
        """Loads the newest VERIFIED snapshot (optionally filtered by
        prefix) from the database, walking back over rows whose blob
        fails its stored checksum — the row-store generation walk."""
        import sqlite3
        path = SnapshotterToDB._db_path(database)
        with sqlite3.connect(path) as conn:
            SnapshotterToDB._ensure_schema(conn)
            # Metadata first, blobs lazily per candidate: the walk
            # usually stops at row one, and fetching every
            # generation's (multi-GB) blob up front would balloon
            # the coordinator's memory for nothing.
            if prefix is None:
                rows = conn.execute(
                    "SELECT id, codec, sha256, finite FROM "
                    "snapshots ORDER BY id DESC").fetchall()
            else:
                rows = conn.execute(
                    "SELECT id, codec, sha256, finite FROM "
                    "snapshots WHERE prefix = ? ORDER BY id DESC",
                    (prefix,)).fetchall()
            if not rows:
                raise FileNotFoundError(
                    "no snapshot rows in %s (prefix=%r)"
                    % (path, prefix))
            last_error = None
            for row_id, codec, digest, finite in rows:
                if verify and finite == 0:
                    resilience.stats.incr("snapshot.unhealthy")
                    last_error = SnapshotUnhealthyError(
                        "snapshot row %d in %s holds non-finite "
                        "trainables — walking back" % (row_id, path))
                    continue
                blob = bytes(conn.execute(
                    "SELECT blob FROM snapshots WHERE id = ?",
                    (row_id,)).fetchone()[0])
                if verify and digest and \
                        hashlib.sha256(blob).hexdigest() != digest:
                    resilience.stats.incr("snapshot.verify_fail")
                    last_error = SnapshotIntegrityError(
                        "snapshot row %d in %s fails its checksum — "
                        "walking back to the previous generation"
                        % (row_id, path))
                    continue
                if codec == "gz":
                    blob = gzip.decompress(blob)
                elif codec == "bz2":
                    blob = bz2.decompress(blob)
                elif codec == "xz":
                    blob = lzma.decompress(blob)
                return pickle.loads(blob)
        raise last_error or FileNotFoundError(
            "no loadable snapshot rows in %s (prefix=%r)"
            % (path, prefix))
