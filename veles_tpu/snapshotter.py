"""Workflow snapshotting (checkpoint/resume).

Capability parity with the reference snapshotter (reference:
veles/snapshotter.py — ``SnapshotterBase:84``, ``SnapshotterToFile:358``,
compression codecs, interval + time throttling ``:159-174``,
``_current`` symlink ``:395-407``, size warning ``:203-225``; resume
via ``-s file`` ``__main__.py:532-582``): a unit linked after the
Decision that pickles the ENTIRE workflow — graph, unit state, Vectors
(device arrays are mapped back to host first, memory.py pickling) —
whenever the decision reports improvement, subject to throttles.

TPU note: Vectors pickle via their host mirror (memory.py maps
device→host on ``__getstate__``), so a snapshot taken on an N-chip
mesh restores onto ANY topology — shardings are re-applied at
``initialize`` time, which is exactly the reference's "resume onto a
different cluster" capability.
"""

import bz2
import gzip
import lzma
import os
import pickle
import time

from . import resilience
from .config import root, get as config_get
from .registry import MappedUnitRegistry
from .resilience import RetryPolicy
from .units import Unit

def init_parser(parser):
    """Snapshotter flags for the aggregated velescli parser."""
    parser.add_argument(
        "--snapshot-dir", default=None, metavar="DIR",
        help="snapshot destination directory "
             "(sets root.common.dirs.snapshots)")
    parser.add_argument(
        "--snapshot-compression", default=None,
        choices=("", "gz", "bz2", "xz"),
        help="snapshot codec (sets root.common.snapshotter."
             "compression)")
    parser.add_argument(
        "--no-snapshots", action="store_true",
        help="disable snapshotting for this run")
    parser.add_argument(
        "--auto-resume", action="store_true",
        help="coordinator crash-resume: if the snapshot directory "
             "holds a *_current.lnk pointer, resume from the newest "
             "snapshot instead of starting fresh (no-op when -s is "
             "given or no snapshot exists)")


CODECS = {
    "": (lambda p: open(p, "wb"), lambda p: open(p, "rb"), ""),
    "gz": (lambda p: gzip.open(p, "wb"),
           lambda p: gzip.open(p, "rb"), ".gz"),
    "bz2": (lambda p: bz2.open(p, "wb"),
            lambda p: bz2.open(p, "rb"), ".bz2"),
    "xz": (lambda p: lzma.open(p, "wb"),
           lambda p: lzma.open(p, "rb"), ".xz"),
}


class SnapshotterRegistry(MappedUnitRegistry):
    """String → snapshotter class (reference mapping: "file", "odbc",
    …)."""
    registry = {}


class SnapshotterBase(Unit, metaclass=SnapshotterRegistry):
    """Common throttling/trigger logic (reference:
    snapshotter.py:84).

    kwargs: ``prefix`` — snapshot name stem; ``compression`` —
    ""/gz/bz2/xz; ``interval`` — snapshot every Nth trigger;
    ``time_interval`` — min seconds between snapshots; ``skip`` —
    disable.  Link ``suffix`` from the Decision
    (``snapshot_suffix``) and gate the unit on decision.improved.
    """

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        self.prefix = kwargs.get("prefix", "snapshot")
        self.compression = kwargs.get(
            "compression",
            root.common.snapshotter.get("compression", "gz"))
        self.interval = kwargs.get(
            "interval", root.common.snapshotter.get("interval", 1))
        self.time_interval = kwargs.get(
            "time_interval",
            root.common.snapshotter.get("time_interval", 1.0))
        self.skip = kwargs.get("skip", False)
        super(SnapshotterBase, self).__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.suffix = ""
        self.destination = None
        self._counter = 0
        self._last_time = 0.0
        self._deferred = False

    def initialize(self, **kwargs):
        super(SnapshotterBase, self).initialize(**kwargs)
        self._last_time = time.time()

    def run(self):
        self._counter += 1
        if self.skip or config_get(root.common.snapshot_disabled,
                                   False):
            return
        if self._counter % self.interval:
            return
        if time.time() - self._last_time < self.time_interval:
            return
        self._last_time = time.time()
        # Coordinated distributed snapshot (reference:
        # snapshotter.py:181-195,227-234 — the master waited for all
        # slaves' acks): with worker jobs outstanding, the pickled
        # state would disagree with updates already in flight, so
        # defer until the workflow reports the queue drained
        # (on_jobs_drained) or the jobs are requeued by a drop.
        inflight = getattr(self.workflow, "total_inflight_jobs",
                           None)
        if inflight is not None and inflight():
            self._deferred = True
            self.info("deferring snapshot: %d worker job(s) in "
                      "flight", inflight())
            return
        self._deferred = False  # self-heal a stale deferral
        self.export()

    def on_jobs_drained(self):
        """Master-side callback once every outstanding worker job has
        been answered or requeued — performs a deferred snapshot."""
        if self._deferred:
            self._deferred = False
            # Re-stamp: the throttle window starts at the actual
            # export, not at the (earlier) deferred request.
            self._last_time = time.time()
            self.export()

    def export(self):
        raise NotImplementedError()


class SnapshotterToFile(SnapshotterBase):
    """Pickle-to-file backend (reference: snapshotter.py:358)."""

    MAPPING = "file"

    def __init__(self, workflow, **kwargs):
        super(SnapshotterToFile, self).__init__(workflow, **kwargs)
        self.directory = kwargs.get(
            "directory",
            config_get(root.common.dirs.snapshots, "snapshots"))
        #: Transient write failures (NFS hiccup, injected
        #: ``snapshot.fail``) are retried with backoff; exhaustion
        #: propagates — a training run silently losing its
        #: checkpoints is worse than a loud stop.
        self.retry_policy = kwargs.get("retry_policy") or RetryPolicy(
            max_attempts=int(kwargs.get("write_retries", 3)),
            base_delay=0.05)
        #: Fault injector consulted at ``snapshot.write``; None =
        #: the process-wide one.  Trailing underscore: transient —
        #: injectors hold locks and never ride a snapshot.
        self.injector_ = kwargs.get("injector")

    def init_unpickled(self):
        super(SnapshotterToFile, self).init_unpickled()
        self.injector_ = None

    def export(self):
        os.makedirs(self.directory, exist_ok=True)
        opener, _, ext = CODECS[self.compression]
        name = self.prefix
        if self.suffix:
            name += "_" + self.suffix
        path = os.path.join(self.directory, name + ".pickle" + ext)
        self.retry_policy.call(
            lambda: self._write_atomic(opener, path),
            retry_on=(OSError,), stat="snapshot.retry",
            on_retry=lambda attempt, e: self.warning(
                "snapshot write failed (%s) — retrying", e))
        self.destination = path
        self._update_current_link(path)
        resilience.stats.incr("snapshot.write")
        size = os.path.getsize(path)
        self.info("snapshot -> %s (%.1f MB)", path, size / 1e6)
        if size > (1 << 30):
            self.warning("snapshot exceeds 1 GB — consider trimming "
                         "unit state (reference kept a per-unit size "
                         "breakdown for this)")

    def _write_atomic(self, opener, path):
        """Pickles into a temp file in the same directory, then
        ``os.replace``s it over the target: a crash mid-pickle can
        never clobber the previous good snapshot at the same path —
        the invariant coordinator crash-resume rests on."""
        resilience.effective(self.injector_).check("snapshot.write")
        tmp = path + ".part"
        try:
            with opener(tmp) as fout:
                pickle.dump(self.workflow, fout,
                            protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def _update_current_link(self, path):
        """Maintains ``<prefix>_current.lnk`` with the newest snapshot
        path (reference: snapshotter.py:395-407).  Atomic for the
        same reason as the snapshot itself: the pointer is what a
        restarted coordinator trusts."""
        link = os.path.join(self.directory,
                            self.prefix + "_current.lnk")
        tmp = link + ".part"
        with open(tmp, "w") as fout:
            # Absolute: a coordinator restarted from a different cwd
            # (supervisors rarely preserve it) must still find the
            # snapshot the pointer names.
            fout.write(os.path.abspath(path))
        os.replace(tmp, link)

    @staticmethod
    def import_(path):
        """Loads a snapshot (resume path; reference:
        snapshotter.py:410 + __main__.py:532-582).  ``path`` may be
        the ``_current.lnk`` pointer file."""
        if path.endswith(".lnk"):
            with open(path) as fin:
                path = fin.read().strip()
        for _, reader, ext in CODECS.values():
            if ext and path.endswith(ext):
                with reader(path) as fin:
                    return pickle.load(fin)
        with open(path, "rb") as fin:
            return pickle.load(fin)


class SnapshotterToDB(SnapshotterBase):
    """Database snapshot backend (reference: snapshotter.py:425
    ``SnapshotterToDB`` over pyodbc; here stdlib sqlite3 — same
    capability, no driver dependency.  ``database`` accepts a file
    path or an ``odbc://``-style spec whose tail is treated as the
    file path).

    Snapshots land in a ``snapshots`` table (prefix, suffix, created,
    codec, blob); resume with
    ``SnapshotterToDB.import_(database, prefix=...)`` which loads the
    newest matching row — the reference's ``-s odbc://...`` flow.
    """

    MAPPING = "db"

    TABLE_DDL = ("CREATE TABLE IF NOT EXISTS snapshots ("
                 "id INTEGER PRIMARY KEY AUTOINCREMENT, "
                 "prefix TEXT NOT NULL, suffix TEXT, "
                 "created REAL NOT NULL, codec TEXT, "
                 "blob BLOB NOT NULL)")

    def __init__(self, workflow, **kwargs):
        super(SnapshotterToDB, self).__init__(workflow, **kwargs)
        self.database = self._db_path(kwargs["database"])

    @staticmethod
    def _db_path(spec):
        for scheme in ("odbc://", "sqlite://", "db://"):
            if spec.startswith(scheme):
                return spec[len(scheme):]
        return spec

    def export(self):
        import sqlite3
        blob = pickle.dumps(self.workflow,
                            protocol=pickle.HIGHEST_PROTOCOL)
        if self.compression == "gz":
            blob = gzip.compress(blob)
        elif self.compression == "bz2":
            blob = bz2.compress(blob)
        elif self.compression == "xz":
            blob = lzma.compress(blob)
        os.makedirs(os.path.dirname(os.path.abspath(self.database)),
                    exist_ok=True)
        with sqlite3.connect(self.database) as conn:
            conn.execute(self.TABLE_DDL)
            conn.execute(
                "INSERT INTO snapshots (prefix, suffix, created, "
                "codec, blob) VALUES (?, ?, ?, ?, ?)",
                (self.prefix, self.suffix, time.time(),
                 self.compression, sqlite3.Binary(blob)))
        self.destination = "%s#%s" % (self.database, self.prefix)
        self.info("snapshot -> %s (%.1f MB)", self.destination,
                  len(blob) / 1e6)

    @staticmethod
    def import_(database, prefix=None):
        """Loads the newest snapshot (optionally filtered by prefix)
        from the database."""
        import sqlite3
        path = SnapshotterToDB._db_path(database)
        with sqlite3.connect(path) as conn:
            if prefix is None:
                row = conn.execute(
                    "SELECT codec, blob FROM snapshots "
                    "ORDER BY id DESC LIMIT 1").fetchone()
            else:
                row = conn.execute(
                    "SELECT codec, blob FROM snapshots WHERE "
                    "prefix = ? ORDER BY id DESC LIMIT 1",
                    (prefix,)).fetchone()
        if row is None:
            raise FileNotFoundError(
                "no snapshot rows in %s (prefix=%r)"
                % (path, prefix))
        codec, blob = row
        blob = bytes(blob)
        if codec == "gz":
            blob = gzip.decompress(blob)
        elif codec == "bz2":
            blob = bz2.decompress(blob)
        elif codec == "xz":
            blob = lzma.decompress(blob)
        return pickle.loads(blob)
