"""Coordinator (master) side of the distributed job protocol.

Capability parity with the reference master (reference: veles/server.py
— ``VelesProtocol:194`` with its WAIT→WORK FSM ``:230-254``, handshake
with workflow-checksum verification ``:478-529``, job generation
deferred off the IO loop ``:596-611``, update application + ack
``:401-430``, hang detection/blacklist ``:369-395``, adaptive job
timeout mean+3σ ``:619-635``, slave drop → ``workflow.drop_slave``
``:315-338``, pause/resume ``:734-745``).

TPU-era scope: SPMD over a mesh is the fast path for on-pod data
parallelism (parallel/); this protocol is the *control-plane* engine —
elastic workers joining/leaving over plain TCP, minibatch indices out,
updates back — the role the reference's Twisted+ZMQ master played.
Threads replace the reactor: one acceptor + one handler thread per
worker, with a single lock serializing workflow access.

Aggregation semantics: each job ships the trainables' current values
(ForwardBase.generate_data_for_slave); the worker runs its ticks
locally and returns its updated values; the master applies the DIFF
against what it shipped that worker (delayed/async SGD — the
reference's per-unit apply_data_from_slave aggregation point,
workflow.py:518-535).
"""

import os
import socket
import statistics
import threading
import time

from . import resilience
from .config import root, get as config_get
from .distributable import SniffedLock
from .fleet import FleetScheduler
from .logger import Logger
from .network_common import (Channel, machine_id, normalize_secret,
                             parse_address)
from .resilience import MasterCrash


def negotiate_protocol(hello, cfg=None):
    """Computes the effective wire protocol for one worker from its
    handshake capabilities and this coordinator's ``root.common.net``
    configuration.

    Returns ``(proto, error)``: ``proto`` is the negotiated dict
    ({} = legacy pickle-compat), ``error`` a rejection string when the
    peer cannot be served at all (``--net-require`` against an
    old-format peer).  Every capability degrades gracefully by
    default — a new master serves an old worker in pickle-compat
    mode, and vice versa an old master simply ignores the ``proto``
    key in the hello."""
    if cfg is None:
        cfg = {
            "mode": config_get(root.common.net.mode, "delta"),
            "codec": config_get(root.common.net.codec, "gzip"),
            "codec_level": config_get(root.common.net.codec_level, 1),
            "codec_threshold": config_get(
                root.common.net.codec_threshold, 1 << 16),
            "dtype": config_get(root.common.net.dtype, "fp32"),
            "job_ticks": config_get(root.common.net.job_ticks, 1),
            "zero": config_get(root.common.net.zero, 0),
            "require": config_get(root.common.net.require, False),
            # None = derive from the live tracing state (--trace-out
            # flips it on); an explicit config value wins.
            "trace": config_get(root.common.observability.trace,
                                None),
        }
    theirs = hello.get("proto") or {}
    if not theirs.get("tensor") or cfg.get("mode") == "legacy":
        if cfg.get("require") and cfg.get("mode") != "legacy":
            return None, (
                "this coordinator requires the tensor-framed delta "
                "wire protocol (--net-require) but the worker's "
                "handshake advertises no such capability — upgrade "
                "the worker to a tensor-framing build, or restart "
                "the coordinator without --net-require to serve it "
                "in pickle-compat mode")
        return {}, None  # legacy pickle-compat session
    codec = cfg.get("codec", "gzip")
    if codec not in (theirs.get("codecs") or ("none",)):
        codec = "none"
    dtype = cfg.get("dtype", "fp32")
    if dtype not in (theirs.get("dtypes") or ("fp32",)):
        dtype = "fp32"
    ticks = int(cfg.get("job_ticks") or 1)
    if not theirs.get("block"):
        ticks = 1
    proto = {
        "tensor": True,
        "delta": bool(theirs.get("delta")),
        "codec": codec,
        "codec_level": cfg.get("codec_level"),
        "codec_threshold": cfg.get("codec_threshold"),
        "dtype": dtype,
        "ticks": max(1, ticks),
    }
    # ZeRO slot-shard sync (--net-zero K; docs/distributed.md):
    # optimizer slots join the delta data plane, sharded K ways —
    # each worker syncs only its 1/K flat slice.  Needs the delta
    # dialect AND the worker's "slots" capability; old peers never
    # see the key (protocol version bump by capability, not by
    # breaking the frame format).
    zero = int(cfg.get("zero") or 0)
    if zero > 0 and proto["delta"] and theirs.get("slots"):
        proto["zero"] = zero
    # Span tracing (docs/observability.md): when the master traces
    # and the worker advertises the capability, job frames carry
    # clock-sync timestamps + trace context and updates carry the
    # worker's spans.  Old peers never see the fields (the key is
    # simply absent — pickle-compat fallback).
    want_trace = cfg.get("trace")
    if want_trace is None:
        from .observability import tracing
        want_trace = tracing.enabled()
    if want_trace and theirs.get("trace"):
        proto["trace"] = True
    return proto, None


class SlaveDescription(object):
    """Per-worker bookkeeping (reference: server.py:172)."""

    def __init__(self, sid, mid, power, address):
        self.id = sid
        self.mid = mid
        self.power = power
        self.address = address
        self.state = "WAIT"
        self.jobs_done = 0
        self.job_times = []
        self.job_started = None
        self.joined = time.time()
        self.last_update = None
        self.blacklisted = False
        self.paused = False
        #: Slot-shard rank this session owns (--net-zero sessions
        #: only) — consulted when assigning ranks to later joiners.
        self.zero_rank = None
        #: Membership epoch at which this session was admitted
        #: (FleetScheduler.join) — joins and leaves are numbered
        #: events, so "which fleet did this worker belong to?" has a
        #: stable answer in logs and heartbeats.
        self.epoch = None
        #: Parole: this session belongs to a previously-blacklisted
        #: machine — it gets ONE job at a time until one completes
        #: clean (then the machine's blacklist entry is erased).
        self.probation = False

    @property
    def jobs_per_second(self):
        """Per-worker job throughput over WALL CLOCK (join to last
        applied update), not inverse busy-time — idle gaps (no_job
        backoff, a paused master) must drag the number down, or the
        comms row reads healthy exactly when the operator is
        diagnosing a starved worker."""
        if not self.jobs_done or self.last_update is None:
            return 0.0
        span = self.last_update - self.joined
        return self.jobs_done / span if span > 0 else 0.0


class Server(Logger):
    """Listens for workers and drives the job/update cycle over the
    master workflow (reference: server.py:659 ``Server``)."""

    def __init__(self, address, workflow, **kwargs):
        super(Server, self).__init__()
        self.workflow = workflow
        self.host, self.port = parse_address(address)
        self._sock = socket.socket(socket.AF_INET,
                                   socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR,
                              1)
        self._sock.bind((self.host, self.port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(16)
        # Serializes workflow access across handler threads; sniffs
        # and reports acquisitions stuck past DEADLOCK_TIME.
        self._lock = SniffedLock(name="master.workflow_lock")
        self._slaves = {}  # guarded-by: _lock
        #: Departed workers' final descriptors (jobs_done/jobs_per_
        #: second), kept for the exit throughput report — EVERY
        #: disconnect (graceful bye included) removes the live entry,
        #: so without this the report would always be empty.  Bounded
        #: (oldest evicted): every reconnect mints a fresh sid, so an
        #: elastic master under worker churn would otherwise leak one
        #: descriptor per departed session.
        self._retired_slaves = {}  # guarded-by: _lock
        self._max_retired = int(kwargs.get("max_retired", 64))
        self._slave_seq = 0  # guarded-by: _lock
        #: Round-robin shard-rank assignment for --net-zero sessions.
        self._zero_seq = 0  # guarded-by: _lock
        self._stop = threading.Event()
        self.on_stopped = kwargs.get("on_stopped")
        #: Frames are HMAC-authenticated before unpickling.  Key
        #: precedence: explicit kwarg > VELES_NETWORK_SECRET env >
        #: workflow checksum.  The checksum default stops stray/
        #: accidental peers and version mismatches, but it is derived
        #: from the workflow source — anyone who has the source can
        #: compute it, so set a real secret on untrusted networks.
        self._secret = normalize_secret(
            kwargs.get("secret") or
            os.environ.get("VELES_NETWORK_SECRET") or
            workflow.checksum)
        #: jobs handed out but not yet answered, per slave id
        self._outstanding = {}  # guarded-by: _lock
        #: Fault injector (resilience.FaultInjector) consulted at the
        #: ``master.crash`` point; None falls back to the process-wide
        #: one (``--chaos`` plan).
        self.injector = kwargs.get("injector")
        self._crashed = False  # guarded-by: _chan_lock
        #: First master-side exception raised while serving a worker
        #: (None = clean).  Launcher.run re-raises it so the process
        #: exits NONZERO — a degraded coordinator must never write a
        #: results file and read as success.
        self.failure = None
        #: live worker channels — a simulated crash must sever them
        #: abruptly, exactly like a process death would.  Guarded by
        #: ``_chan_lock``: crash() must also catch a channel whose
        #: handler registered it concurrently.
        self._channels = set()  # guarded-by: _chan_lock
        self._chan_lock = threading.Lock()
        #: Respawn hook: ``respawn(desc)`` relaunches a dropped
        #: worker (reference: server.py:637-655).
        self.respawn = kwargs.get("respawn")
        self.max_respawns = int(kwargs.get("max_respawns", 10))
        self._respawn_counts = {}  # guarded-by: _lock
        self._watchdog_interval = kwargs.get("watchdog_interval", 1.0)
        #: Floor for the adaptive timeout (reference: server.py:624
        #: floors it at a job_timeout defaulting to 2 minutes).  With
        #: uniform job times σ≈0 and a bare mean+3σ would blacklist a
        #: healthy worker on any transient stall.
        self.job_timeout = float(kwargs.get("job_timeout", 120.0))
        #: Blacklist parole (``--blacklist-cooldown``): machines the
        #: watchdog blacklisted are re-admitted on probation after
        #: this many seconds — a straggler that recovered (GC pause,
        #: thermal throttle, network blip) rejoins the fleet instead
        #: of being ejected for good.
        self.blacklist_cooldown = float(kwargs.get(
            "blacklist_cooldown",
            config_get(root.common.server.blacklist_cooldown, 60.0)))
        #: machine id -> wall time of its latest blacklisting.
        self._blacklist = {}  # guarded-by: _lock
        #: Membership registry + shared placement policy: every join
        #: and leave bumps an epoch-numbered event here, surfaced as
        #: the ``membership.epoch`` gauge and the launcher-heartbeat
        #: "fleet" section.  Injectable for tests / shared fleets.
        self.fleet = kwargs.get("fleet") or FleetScheduler()
        #: Optional global in-flight-job ceiling.  ``max_inflight=1``
        #: serializes dispatch: every delta fold then lands on a
        #: fully-current base, making the weight trajectory
        #: bit-identical to a standalone run regardless of fleet size
        #: or membership churn — the property the elastic-soak parity
        #: gate asserts.  None (default) = unbounded, the normal
        #: delayed-SGD regime.
        self.max_inflight = kwargs.get("max_inflight")
        # Threads LAST, accept included: the socket is bound above,
        # so a worker hammering reconnects (the chaos restart loop)
        # can dial the instant the port exists — its handler must
        # never observe a half-constructed server (a pre-ISSUE-13
        # flake: _serve_slave read self._blacklist before __init__
        # assigned it and the AttributeError read as a master-side
        # failure, stopping the coordinator mid-chaos-plan).
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="veles-server-accept")
        self._accept_thread.start()
        self._watchdog_thread = threading.Thread(
            target=self._watchdog_loop, daemon=True,
            name="veles-server-watchdog")
        self._watchdog_thread.start()
        self.info("coordinator listening on %s:%d", self.host,
                  self.port)

    # -- lifecycle ---------------------------------------------------------

    @property
    def is_running(self):
        return not self._stop.is_set()

    def stop(self):
        if self._stop.is_set():
            return
        # Close the listen socket BEFORE signaling waiters: a
        # supervisor that rebinds the same port the moment wait()
        # returns must never race our own still-bound fd.
        try:
            self._sock.close()
        except OSError:
            pass
        self._stop.set()
        if self.on_stopped is not None:
            self.on_stopped()

    def wait(self, timeout=None):
        """Blocks until training completes (decision.complete on the
        master workflow stops the server)."""
        self._stop.wait(timeout)

    def _injector_(self):
        return resilience.effective(self.injector)

    @property
    def crashed(self):
        return self._crashed

    def crash(self):
        """Simulated coordinator process death: every socket dies
        abruptly, nothing is requeued, no goodbye frames — the ONLY
        recovery path is a restarted master resuming the newest
        atomic snapshot (Launcher.resume_latest).  Driven by the
        ``master.crash`` injection point; also callable directly by
        chaos tests."""
        with self._chan_lock:
            if self._crashed:
                return
            self._crashed = True
            chans = list(self._channels)
        self.warning("injected coordinator crash — dying abruptly")
        resilience.stats.incr("master.crash")
        # Socket first, stop-event second — see stop(): wait()
        # returning is the restart supervisor's cue to rebind.
        try:
            self._sock.close()
        except OSError:
            pass
        self._stop.set()
        for chan in chans:
            chan.close()

    # -- worker management (reference pause/resume/blacklist) --------------

    @property
    def slaves(self):
        return dict(self._slaves)

    @property
    def all_slaves(self):
        """Live AND departed workers (live wins on id collision) —
        the exit throughput report runs after every worker has said
        bye, when :attr:`slaves` is already empty."""
        merged = dict(self._retired_slaves)
        merged.update(self._slaves)
        return merged

    def pause_slave(self, sid):
        if sid in self._slaves:
            self._slaves[sid].paused = True

    def resume_slave(self, sid):
        if sid in self._slaves:
            self._slaves[sid].paused = False

    def _blacklist_check(self, desc):
        """Adaptive job timeout: mean+3σ of this worker's history,
        floored at ``job_timeout`` (reference: server.py:619-635 with
        the 2-minute floor at :624).  ``job_started`` is read once
        — a handler thread may null it concurrently."""
        started = desc.job_started
        times = list(desc.job_times)
        if started is None:
            return False
        if len(times) < 4:
            threshold = self.job_timeout
        else:
            mean = statistics.mean(times)
            sigma = statistics.pstdev(times)
            threshold = max(mean + 3 * sigma + 1.0, self.job_timeout)
        if time.time() - started > threshold:
            desc.blacklisted = True
            return True
        return False

    def _watchdog_loop(self):
        """Periodic sweep firing the adaptive timeout: a hung worker
        is blacklisted and its in-flight work requeued — the
        reference's job-timeout dropper (server.py:619-635) made
        periodic instead of waiting for the TCP connection to die.
        The whole sweep runs under the workflow lock so it cannot
        interleave with an update being applied for the same job."""
        while not self._stop.wait(self._watchdog_interval):
            with self._lock:
                for desc in list(self._slaves.values()):
                    if desc.blacklisted or desc.state != "WORK":
                        continue
                    if self._blacklist_check(desc):
                        self.warning(
                            "worker %s exceeded adaptive job timeout "
                            "— blacklisted, requeueing its work "
                            "(parole in %.0f s)",
                            desc.id, self.blacklist_cooldown)
                        resilience.stats.incr("server.blacklist")
                        if desc.mid:
                            self._blacklist[desc.mid] = time.time()
                        if self._outstanding.pop(desc.id, None):
                            resilience.stats.incr("server.requeue")
                        self.workflow.drop_slave(desc.id)

    # -- protocol ----------------------------------------------------------

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, addr = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve_slave,
                             args=(conn, addr), daemon=True,
                             name="veles-server-worker").start()

    def _recv_or_none(self, chan):
        """A frame that cannot be received OR deserialized reads as a
        dead peer (drop + requeue), never as a master-side failure:
        the bytes are peer-supplied, so a worker running skewed code
        (pickle naming a class this master lacks) must only cost
        itself, not the coordinator."""
        try:
            return chan.recv()
        except (ConnectionError, TimeoutError):
            return None
        except Exception as e:
            self.warning("dropping worker: undeserializable frame "
                         "(%s)", e)
            return None

    def _serve_slave(self, conn, addr):
        desc = None
        clean = False
        chan = Channel(conn, self._secret, injector=self.injector)
        with self._chan_lock:
            self._channels.add(chan)
            crashed = self._crashed
        if crashed:
            # Raced past crash(): a dead master serves nobody.
            chan.close()
            return
        try:
            hello = self._recv_or_none(chan)
            if not hello or hello.get("cmd") != "handshake":
                return
            # Checksum verification (reference: server.py:484-493).
            theirs = hello.get("checksum")
            ours = self.workflow.checksum
            if theirs != ours:
                chan.send({"cmd": "error",
                           "error": "checksum mismatch",
                           "expected": ours})
                return
            proto, proto_error = negotiate_protocol(hello)
            if proto_error:
                chan.send({"cmd": "error", "error": proto_error})
                resilience.stats.incr("server.proto_reject")
                return
            # The admission seam: a ``fleet.join`` chaos rule kills
            # the joiner here — after checksum/protocol vetting,
            # before any registration — so tests can prove a join
            # that dies mid-handshake leaves no membership residue
            # (no epoch bump, no slave entry, no requeue).  The
            # raised fault is a ConnectionError: the dead-peer path
            # below handles it, and the worker redials.
            self._injector_().check("fleet.join")
            with self._lock:
                self._slave_seq += 1
                sid = "%s/%d" % (hello.get("mid", machine_id()),
                                 self._slave_seq)
                if proto.get("zero"):
                    # Slot-shard ownership: the lowest shard rank no
                    # LIVE session holds, so churn re-fills orphaned
                    # shards instead of blindly round-robining past
                    # them (a replacement for a dead rank-1 worker
                    # must own shard 1, not double up on 0).  With
                    # more workers than shards, overlap resolves
                    # last-writer-wins at the fold — degraded
                    # freshness, never corruption.
                    proto = dict(proto)
                    k = int(proto["zero"])
                    held = {s.zero_rank for s in
                            self._slaves.values()
                            if s.zero_rank is not None}
                    rank = FleetScheduler.lowest_free_rank(k, held)
                    proto["zero_rank"] = rank if rank is not None \
                        else self._zero_seq % k
                    self._zero_seq += 1
                desc = SlaveDescription(
                    sid, hello.get("mid"), hello.get("power", 1.0),
                    addr)
                desc.zero_rank = proto.get("zero_rank") \
                    if proto else None
                if desc.mid in self._blacklist:
                    # Parole: the machine was blacklisted — it may
                    # rejoin, but on probation (no jobs until the
                    # cooldown elapses, then one at a time until one
                    # completes clean).
                    desc.probation = True
                self._slaves[sid] = desc
                desc.epoch = self.fleet.join(sid, desc.mid,
                                             desc.power)
                note = getattr(self.workflow, "note_slave_protocol",
                               None)
                if note is not None:
                    note(sid, proto)
                initial = self.workflow.\
                    generate_initial_data_for_slave(sid)
            # Fresh session nonce: all post-handshake frames (both
            # directions) are MAC-bound to it + a sequence number, so
            # captured frames cannot be replayed into this or any
            # other session (ADVICE r2).  The ack itself still rides
            # the legacy framing (the peer switches formats only
            # after reading the negotiation result).
            nonce = os.urandom(16)
            chan.send({"cmd": "handshake_ack", "id": sid,
                       "nonce": nonce, "initial": initial,
                       "proto": proto})
            chan.rekey(nonce)
            chan.set_proto(proto)
            self.info("worker %s joined at membership epoch %d "
                      "(power %.1f%s)", sid, desc.epoch,
                      desc.power,
                      ", proto: delta=%s codec=%s ticks=%s" % (
                          proto.get("delta"), proto.get("codec"),
                          proto.get("ticks")) if proto else
                      ", pickle-compat")
            if desc.probation:
                self.info("worker %s joined on PROBATION (machine "
                          "%s was blacklisted)", sid, desc.mid)
            clean = bool(self._message_loop(chan, desc))
        except MasterCrash:
            self.crash()
        except (ConnectionError, TimeoutError):
            # Dead peer mid-protocol (broken pipe on a send, a
            # keepalive timeout, or an injected net fault): identical
            # to a recv()→None close — the finally below drops and
            # requeues.
            pass
        except Exception:
            # NOT a peer problem: a master-side failure raised while
            # applying this worker's traffic (exhausted snapshot-write
            # retries, loader I/O error, ...).  Swallowing it as a
            # dead peer would silently requeue forever; the contract
            # is a LOUD stop.  (During shutdown/crash the racing
            # EBADF from our own close is expected noise, not a
            # failure.)
            if not self._stop.is_set():
                import sys
                self.failure = sys.exc_info()[1]
                self.exception(
                    "master-side error while serving worker %s — "
                    "stopping coordinator", desc.id if desc else addr)
                self.stop()
        finally:
            with self._chan_lock:
                self._channels.discard(chan)
            chan.close()
            # A crashed master does NOT requeue or respawn — it is
            # dead; cleanup is the restarted master's job.
            if desc is not None and not self._crashed:
                if not clean and self._stop.is_set() and \
                        self.failure is None:
                    with self._lock:
                        finished = self._finished_locked()
                else:
                    finished = False
                if finished:
                    # Orderly-completion race: ONE handler observes
                    # the finished run, sends its peer the bye and
                    # stops the coordinator; every OTHER live session
                    # (and this one, when its own bye send raced the
                    # teardown) then unwinds through a closed socket
                    # or the _stop flag.  Training completed and the
                    # master is healthy, so this is a retirement, not
                    # a drop — _drop still demotes it to drop+requeue
                    # if the worker holds in-flight work, keeping
                    # ``server.drop`` a pure error signal both ways.
                    clean = True
                self._drop(desc, clean=clean)

    def _message_loop(self, chan, desc):
        """Returns True on an ORDERLY end of session (the worker's
        explicit goodbye, or this master's own "bye" after training
        completed) — the caller then retires the worker without the
        drop+requeue error path; False/None means the peer vanished
        (crash, timeout, blacklist disconnect) and ``server.drop``
        stays a pure error signal."""
        from .observability import tracing
        # Trace dialect for this session (handshake-negotiated):
        # replies carry clock-sync timestamps, jobs carry trace
        # context, and updates bring the worker's spans home.  Open
        # dispatch spans are FIFO — a pipelined worker can hold more
        # than one job in flight.
        trace_on = bool(chan.proto.get("trace"))
        open_dispatches = []
        while not self._stop.is_set():
            msg = self._recv_or_none(chan)
            if msg is None:
                for sp in open_dispatches:
                    sp.set(dropped=True)
                    sp.finish()
                return False
            recv_wall = time.time()
            cmd = msg.get("cmd")
            if cmd == "job_request":
                if desc.blacklisted:
                    # A blacklisted worker is disconnected rather than
                    # left spinning on no_job retries; its dead job was
                    # already requeued by the watchdog.  The connection
                    # is dropped WITHOUT a "bye" (which would read as
                    # orderly completion and retire the worker):
                    # recv()→None makes the client reconnect with a
                    # fresh id and a clean slate (the reference dropped
                    # the connection outright, server.py:630-635).
                    return False
                if desc.paused or self._probation_hold(desc):
                    chan.send(self._stamp({"cmd": "no_job",
                                           "retry": True}, trace_on,
                                          recv_wall))
                    continue
                # The dispatch window: opens BEFORE job generation
                # (the master-side share of the job's latency belongs
                # inside it), closes when the worker's update has
                # been folded — on one aligned timeline it strictly
                # encloses the worker.step span.  Detached: pipelined
                # workers hold overlapping windows on this thread,
                # and stack nesting would chain siblings into
                # parent/child; children attach explicitly below.
                sp = tracing.begin("server.dispatch", detached=True,
                                   worker=desc.id) \
                    if trace_on and tracing.enabled() else None
                job = self._generate_job(desc)
                if job is None:
                    if sp is not None:
                        sp.cancel()
                    if self._maybe_finished():
                        chan.send({"cmd": "bye"})
                        return True
                    chan.send(self._stamp({"cmd": "no_job",
                                           "retry": True}, trace_on,
                                          recv_wall))
                else:
                    desc.state = "WORK"
                    desc.job_started = time.time()
                    if sp is None:
                        self._send_job(chan, job, None)
                    else:
                        open_dispatches.append(sp)
                        extra = self._stamp(
                            {"trace": {"trace_id": sp.trace_id,
                                       "parent": sp.id}},
                            True, recv_wall)
                        # net.serialize/net.send of THIS job nest
                        # under THIS dispatch window.
                        with tracing.attach(sp.trace_id, sp.id):
                            self._send_job(chan, job, extra)
            elif cmd == "update":
                if trace_on:
                    spans = msg.get("spans")
                    if spans:
                        tracing.ingest(spans,
                                       proc="worker:%s" % desc.id)
                # Replies arrive in dispatch order (one TCP stream,
                # serial handler): this update answers the OLDEST
                # open window — fold under it, then close it.
                owner = open_dispatches.pop(0) if open_dispatches \
                    else None
                if owner is not None:
                    with tracing.attach(owner.trace_id, owner.id):
                        with tracing.span("net.fold",
                                          worker=desc.id):
                            self._apply_update(desc, msg["data"])
                    owner.finish()
                else:
                    with tracing.span("net.fold", worker=desc.id):
                        self._apply_update(desc, msg["data"])
                chan.send(self._stamp({"cmd": "update_ack"},
                                      trace_on, recv_wall))
                if self._maybe_finished():
                    chan.send({"cmd": "bye"})
                    return True
            elif cmd == "power":
                # Periodic re-measurement from the worker (reference:
                # server.py:531) keeps load balancing honest.
                desc.power = float(msg.get("power", desc.power))
            elif cmd == "bye":
                # The worker's explicit end-of-session frame: a clean
                # exit, NOT a crash — the two must be distinguishable
                # (the satellite the reference's _drop conflated).
                return True
        return False

    def _probation_hold(self, desc):
        """True when a paroled worker must keep polling no_job: its
        machine's blacklist cooldown has not elapsed yet, or its one
        probation job is still in flight (probation = ONE job at a
        time until one completes clean)."""
        if not desc.probation:
            return False
        listed = self._blacklist.get(desc.mid)
        if listed is not None and \
                time.time() - listed < self.blacklist_cooldown:
            return True
        return bool(self._outstanding.get(desc.id))

    # -- workflow bridging -------------------------------------------------

    @staticmethod
    def _stamp(msg, trace_on, recv_wall):
        """Adds the clock-sync timestamp to a reply (trace sessions
        only): the worker pairs it with its local send/recv times
        for the NTP-style offset estimate aligning its spans to the
        master timeline.  The stamp is the MIDPOINT of request
        receipt and reply build — NTP's (t2+t3)/2 — so server-side
        processing (job generation can take a while) does not bias
        the estimate."""
        if trace_on:
            msg["ts"] = (recv_wall + time.time()) / 2.0
        return msg

    def _send_job(self, chan, job, extra=None):
        """Serializes AND sends one job — called with the workflow
        lock NOT held.  The lock split matters: serializing a
        params-sized job for a slow worker must never stall
        ``_apply_update`` from the others (``_generate_job`` holds
        the lock only for the bookkeeping + host-side array
        snapshot).  ``extra`` carries the negotiated trace fields
        (context + timestamp) at the message level."""
        if extra:
            msg = {"cmd": "job", "data": job}
            msg.update(extra)
            chan.send_parts(*chan.encode(msg))
        else:
            chan.send_parts(*self._serialize_job(chan, job))

    def _serialize_job(self, chan, job):
        """The expensive half (pickle/framing/compression), exposed
        as a seam so tests can pin that it runs outside the lock."""
        return chan.encode({"cmd": "job", "data": job})

    def _generate_job(self, desc):
        """Generates one job under the workflow lock
        (reference: server.py:596-611 deferred generation).  The
        ``job`` chaos counter ticks per job actually GENERATED —
        never on no_job polls, whose count is wall-clock-dependent —
        so a plan like ``master.crash@job:7`` crashes the coordinator
        at the exact same ledger position every run.  The crash fires
        before the job is recorded as outstanding or dispatched; the
        consumed workflow state rolls back through the snapshot on
        resume."""
        inj = self._injector_()
        with self._lock:
            if self._finished_locked():
                return None
            if self.max_inflight is not None and \
                    sum(self._outstanding.values()) >= \
                    self.max_inflight:
                # Serialized dispatch (see __init__): hold this
                # worker on no_job until an outstanding fold lands.
                return None
            data = self.workflow.generate_data_for_slave(desc.id)
            if data is None:
                # Workflow has nothing to hand out right now (e.g. a
                # GA generation fully in flight elsewhere) — the
                # caller sends no_job; counting it as outstanding
                # would block _maybe_finished forever.
                return None
            inj.tick("job")
            inj.check("master.crash")
            self._outstanding[desc.id] = \
                self._outstanding.get(desc.id, 0) + 1
            return data

    def _apply_update(self, desc, data):
        """Returns False when the update was discarded.  The
        blacklist re-check happens UNDER the lock: the watchdog may
        have blacklisted this worker (and requeued its job) between
        the handler reading the frame and getting here — applying
        the late result then would double-count the batch."""
        inj = self._injector_()
        inj.tick("update")
        inj.check("master.crash")
        with self._lock:
            if desc.blacklisted:
                return False
            self.workflow.apply_data_from_slave(data, desc.id)
            desc.state = "WAIT"
            desc.jobs_done += 1
            desc.last_update = time.time()
            if desc.probation:
                # The probation job completed clean: parole granted —
                # the machine rejoins the fleet at full rate.
                desc.probation = False
                self._blacklist.pop(desc.mid, None)
                resilience.stats.incr("server.parole")
                self.info("worker %s completed its probation job — "
                          "parole granted", desc.id)
            if desc.job_started is not None:
                desc.job_times.append(time.time() - desc.job_started)
                desc.job_started = None
            n = self._outstanding.get(desc.id, 0)
            if n <= 1:
                self._outstanding.pop(desc.id, None)
            else:
                self._outstanding[desc.id] = n - 1
            return True

    def _finished_locked(self):
        stop = getattr(self.workflow, "should_stop_serving", None)
        if stop is not None:
            return bool(stop())
        return bool(self.workflow.stopped)

    def _maybe_finished(self):
        with self._lock:
            done = self._finished_locked() and not self._outstanding
        if done:
            self.info("all jobs done — stopping coordinator")
            self.stop()
        return done

    def _drop(self, desc, clean=False):
        """End of a worker session.  ``clean=True`` (an explicit
        goodbye frame, or this master's own bye) DEREGISTERS the
        worker — no requeue, no respawn, and ``server.drop`` stays a
        pure error signal (previously a clean exit and a crash were
        indistinguishable here).  Otherwise: connection lost →
        requeue in-flight work (reference: server.py:315-338), then
        optionally respawn the worker."""
        with self._lock:
            if self._slaves.pop(desc.id, None) is not None:
                self._retired_slaves[desc.id] = desc
                while len(self._retired_slaves) > self._max_retired:
                    self._retired_slaves.pop(
                        next(iter(self._retired_slaves)))
            if self._outstanding.pop(desc.id, None):
                # A "goodbye" with work still in flight is NOT clean
                # — the job must be requeued like any other loss.
                resilience.stats.incr("server.requeue")
                clean = False
            if clean and desc.probation:
                # A probation session that drains and says bye with
                # nothing outstanding counts as a clean completion
                # for parole purposes — an orderly departure (spot
                # preemption, scale-down) must not keep the machine's
                # cooldown armed as if it had failed again.
                desc.probation = False
                if self._blacklist.pop(desc.mid, None) is not None:
                    resilience.stats.incr("server.parole")
                    self.info("worker %s said a clean goodbye during "
                              "probation — parole granted", desc.id)
            self.workflow.drop_slave(desc.id)
        self.fleet.leave(desc.id, clean=clean)
        if clean:
            resilience.stats.incr("server.goodbye")
            self.info("worker %s retired (clean goodbye) — "
                      "membership epoch %d", desc.id,
                      self.fleet.epoch)
            return
        resilience.stats.incr("server.drop")
        self.info("worker %s dropped", desc.id)
        self._maybe_respawn(desc)

    def _maybe_respawn(self, desc):
        """Relaunches a dropped worker with exponential backoff
        (reference: server.py:637-655 respawned over SSH; here the
        hook is a callable — local subprocess, SSH, k8s, whatever the
        deployment uses — so policy stays out of the protocol)."""
        if self.respawn is None or self._stop.is_set():
            return
        mid = desc.mid or "unknown"
        # Concurrent drops (one handler thread per worker) race this
        # counter — claim the respawn slot under the lock.
        with self._lock:
            count = self._respawn_counts.get(mid, 0)
            if count >= self.max_respawns:
                give_up = True
            else:
                give_up = False
                self._respawn_counts[mid] = count + 1
        if give_up:
            self.warning("worker machine %s exceeded %d respawns — "
                         "giving up on it", mid, self.max_respawns)
            return
        delay = min(2.0 ** count * 0.5, 30.0)

        def relaunch():
            if self._stop.wait(delay):
                return
            self.info("respawning worker for %s (attempt %d)", mid,
                      count + 1)
            resilience.stats.incr("server.respawn")
            try:
                self.respawn(desc)
            except Exception:
                self.exception("respawn hook failed for %s", mid)

        threading.Thread(target=relaunch, daemon=True,
                         name="veles-respawn").start()
