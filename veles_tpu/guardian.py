"""Training health guardian: NaN/loss-spike detection and recovery.

A single NaN tick silently poisons every later epoch — by the time a
human looks at the loss curve, hours of compute are gone.  The
:class:`HealthGuardian` unit closes the loop ON the box:

* **Detection** is on-device and free of extra host syncs: the fused
  step accumulates an ``isfinite(loss) & isfinite(grad_norm)`` flag
  and the grad-norm scalar into the evaluator's ``health_acc`` row
  (see ``StepCompiler``), which the Decision fetches together with
  the ordinary epoch accumulator (``DecisionGD._fetch_class_metrics``).
  The guardian additionally keeps a rolling median of recent train
  losses and flags a ``> spike_factor × median`` epoch as a spike.
* **Recovery** executes one of three policies:

  - ``skip`` (default) — non-finite updates are dropped *inside the
    compiled step* (the device gate in ``StepCompiler``): the poison
    batch trains nothing and weights stay clean;
  - ``lr_backoff`` — additionally multiplies every GD unit's learning
    rate by ``lr_backoff_factor`` on a spike/NaN epoch (the step is
    re-traced via ``StepCompiler.invalidate``);
  - ``rollback`` — restores every trainable/state Vector in-process
    from the last VERIFIED snapshot generation (no restart; see
    :func:`restore_vectors`) and reshuffles the loader's train order
    so the poison batch order is not replayed.

Every event increments ``resilience.stats`` counters
(``guardian.nan_ticks``, ``guardian.skipped``, ``guardian.lr_backoff``,
``guardian.rollbacks``) surfaced through launcher heartbeats, the
``web_status`` dashboard, and ``Workflow.print_stats``; the
deterministic ``step.nan`` chaos point (``--chaos "step.nan@7"``)
makes every recovery path testable and replayable
(docs/resilience.md).
"""

import collections
import statistics

import numpy

from . import resilience
from .config import root, get as config_get
from .loader.base import TRAIN, VALID, CLASS_NAME
from .result_provider import IResultProvider
from .units import Unit

#: Recognized recovery policies ("off" observes and counts only).
POLICIES = ("off", "skip", "lr_backoff", "rollback")


def init_parser(parser):
    """Guardian flags for the aggregated velescli parser."""
    parser.add_argument(
        "--guardian-policy", default=None, choices=POLICIES,
        help="training health policy on NaN/loss-spike epochs: skip "
             "the poison updates on-device (default), back off the "
             "learning rate, roll back to the last good snapshot, or "
             "off (sets root.common.guardian.policy)")
    parser.add_argument(
        "--guardian-spike", type=float, default=None, metavar="K",
        help="flag a train epoch whose loss exceeds K x the rolling "
             "median as a spike (default 4.0; sets "
             "root.common.guardian.spike_factor)")
    parser.add_argument(
        "--guardian-window", type=int, default=None, metavar="N",
        help="rolling-median window in epochs for spike detection "
             "(default 5; sets root.common.guardian.window)")


def restore_vectors(dst_workflow, src_workflow):
    """Copies every matching trainable/optimizer-state Vector from
    ``src_workflow`` (an unpickled snapshot) into the LIVE
    ``dst_workflow`` — in-process weight rollback, no restart.  Units
    pair by name, tensors by attribute; shape mismatches are skipped
    (a resumed-then-grown model keeps its new tensors).  Returns the
    number of tensors restored.  The copies land on the host mirror
    (``Vector.mem``), so the next fused dispatch re-uploads under
    whatever sharding the live run uses."""
    from .memory import Vector
    from .znicz.optimizers import param_of_slot
    src_units = {u.name: u for u in src_workflow.units}
    restored = 0
    orphan_slots = []
    for unit in dst_workflow.units:
        src = src_units.get(unit.name)
        if src is None:
            continue
        for which in ("trainables", "tstate"):
            dst_vecs = getattr(unit, which, None)
            src_vecs = getattr(src, which, None)
            if not isinstance(dst_vecs, dict) or \
                    not isinstance(src_vecs, dict):
                continue
            if which == "tstate":
                # Optimizer slots pair by attr like everything else
                # (velocity_*/adam_*/lion_* all ride tstate), but a
                # snapshot trained under a DIFFERENT optimizer has no
                # matching names — that must be loud, not a silent
                # partial restore.
                orphan_slots.extend(
                    "%s/%s" % (unit.name, attr)
                    for attr in src_vecs
                    if param_of_slot(attr) and attr not in dst_vecs)
            for attr, dvec in dst_vecs.items():
                svec = src_vecs.get(attr)
                if not isinstance(dvec, Vector) or \
                        not isinstance(svec, Vector):
                    continue
                if not svec or not dvec or svec.shape != dvec.shape:
                    continue
                svec.map_read()
                dvec.mem = numpy.array(svec.mem)
                restored += 1
    if orphan_slots:
        dst_workflow.warning(
            "rollback source holds optimizer slots the live run has "
            "no home for (%s, ...) — it was trained under a "
            "different optimizer; its weights restored but the live "
            "optimizer state was NOT reset", orphan_slots[0])
    return restored


class HealthGuardian(Unit, IResultProvider):
    """Watches the health rows the fused step accumulates and
    executes the configured recovery policy at class-epoch
    boundaries.  Link it AFTER the decision (it reads the metrics the
    decision just fetched) and give it the snapshotter when the
    rollback policy should be available::

        guardian = HealthGuardian(wf, policy="rollback",
                                  snapshotter=snap)
        guardian.link_from(wf.decision)
        guardian.link_attrs(wf.loader, "minibatch_class",
                            "last_minibatch", "epoch_number")
        wf.gds[0].link_from(guardian)   # instead of the decision

    kwargs: ``policy`` — one of :data:`POLICIES`; ``spike_factor`` —
    spike threshold over the rolling loss median; ``window`` — median
    window (epochs); ``lr_backoff_factor`` / ``min_learning_rate`` —
    LR policy knobs; ``snapshotter`` — the workflow's
    SnapshotterToFile (rollback source); ``decision`` — defaults to
    ``workflow.decision``.
    """

    def __init__(self, workflow, **kwargs):
        self.policy = kwargs.get("policy", config_get(
            root.common.guardian.policy, "skip"))
        if self.policy not in POLICIES:
            raise ValueError(
                "unknown guardian policy %r (known: %s)"
                % (self.policy, ", ".join(POLICIES)))
        self.spike_factor = float(kwargs.get("spike_factor", config_get(
            root.common.guardian.spike_factor, 4.0)))
        self.window = int(kwargs.get("window", config_get(
            root.common.guardian.window, 5)))
        self.lr_backoff_factor = float(
            kwargs.get("lr_backoff_factor", 0.5))
        self.min_learning_rate = float(
            kwargs.get("min_learning_rate", 1e-6))
        self.snapshotter = kwargs.get("snapshotter")
        self.decision = kwargs.get("decision")
        super(HealthGuardian, self).__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.events = []
        self.rollbacks = 0
        self.lr_backoffs = 0
        self._loss_history = collections.deque(maxlen=self.window)
        self.demand("minibatch_class", "last_minibatch",
                    "epoch_number")

    def initialize(self, **kwargs):
        super(HealthGuardian, self).initialize(**kwargs)
        if self.decision is None:
            self.decision = getattr(self.workflow, "decision", None)
        if self.snapshotter is None:
            self.snapshotter = self._find_snapshotter()
        # The device gate drops non-finite updates inside the
        # compiled step for skip/lr_backoff; the rollback policy
        # deliberately lets the poison land so the restore repairs a
        # REAL corruption (and so chaos tests prove it does).
        self.workflow.health_device_skip = self.policy != "rollback"
        if self.policy == "rollback" and self.snapshotter is None:
            # No restore source means the disabled device gate would
            # make this policy strictly WORSE than skip.
            self.warning(
                "rollback policy but no snapshotter in the workflow "
                "— falling back to the skip policy (add a "
                "SnapshotterToFile, or pass snapshotter=)")
            self.policy = "skip"
            self.workflow.health_device_skip = True

    def _find_snapshotter(self):
        """The workflow's file snapshotter, when one was linked in
        (duck-typed on directory+prefix so DB backends are passed
        over — rollback restores from file generations)."""
        for unit in self.workflow.units:
            if unit is not self and \
                    getattr(unit, "directory", None) is not None and \
                    getattr(unit, "prefix", None) is not None and \
                    callable(getattr(unit, "export", None)):
                return unit
        return None

    @property
    def last_event(self):
        return self.events[-1] if self.events else None

    def loss_median(self):
        if not self._loss_history:
            return None
        return statistics.median(self._loss_history)

    def run(self):
        if not self.last_minibatch or self.decision is None:
            return
        self.check_class(self.minibatch_class)

    def check_class(self, cls):
        """Evaluates one class-epoch's health numbers (just fetched
        by the decision — via the on-device accumulator standalone,
        via worker update metrics in master mode) and reacts."""
        nonfinite = float(getattr(self.decision, "epoch_nonfinite",
                                  (0.0, 0.0, 0.0))[cls])
        loss = float(self.decision.epoch_loss[cls])
        if nonfinite:
            resilience.stats.incr("guardian.nan_ticks",
                                  int(nonfinite))
            # Recovery acts on TRAIN events only: eval ticks never
            # update weights, so a persistently-corrupt validation
            # record must not roll real training progress back every
            # epoch (poisoned WEIGHTS always surface at the train
            # boundary too — which, in the test/valid/train class
            # order, is checked before the next eval pass).
            self.on_event("nan", cls,
                          "%d non-finite tick(s)" % int(nonfinite),
                          act=cls == TRAIN)
            return
        if cls != TRAIN:
            return
        if not numpy.isfinite(loss):
            # The accumulator itself went non-finite without the
            # sentinel tripping (shouldn't happen; belt-and-braces).
            self.on_event("nan", cls, "non-finite epoch loss")
            return
        median = self.loss_median()
        if median is not None and median > 0 and \
                loss > self.spike_factor * median:
            self.on_event(
                "spike", cls, "loss %.4g > %.3g x median %.4g"
                % (loss, self.spike_factor, median))
            return
        self._loss_history.append(loss)

    # -- policy execution --------------------------------------------------

    def on_event(self, kind, cls, detail, act=True):
        """Records a health event; executes the policy when ``act``
        (recovery is reserved for train-class events — eval NaNs are
        observed and counted only)."""
        self.warning("health event at epoch %d (%s %s): %s — "
                     "policy %s%s", self.epoch_number,
                     CLASS_NAME[cls], kind, detail, self.policy,
                     "" if act else " (eval class: observed only)")
        action = "observed"
        if not act:
            pass
        elif self.policy == "skip":
            # The device gate already dropped the poison updates;
            # nothing to repair, just account for it.
            action = "skipped"
            resilience.stats.incr("guardian.skipped")
        elif self.policy == "lr_backoff":
            action = "lr_backoff" if self.backoff_learning_rate() \
                else "skipped"
        elif self.policy == "rollback":
            action = "rollback" if self.rollback() else "skipped"
        event = {"epoch": int(self.epoch_number), "class": cls,
                 "kind": kind, "detail": detail, "action": action}
        self.events.append(event)
        return event

    def backoff_learning_rate(self):
        """Multiplies every GD unit's learning rate by
        ``lr_backoff_factor`` (floored at ``min_learning_rate``) and
        re-traces the step — the hyperparameters are baked into the
        compiled program as constants."""
        from .znicz.nn_units import GradientDescentBase
        changed = False
        for unit in self.workflow.units:
            if not isinstance(unit, GradientDescentBase):
                continue
            for attr in ("learning_rate", "learning_rate_bias"):
                lr = getattr(unit, attr, None)
                if lr:
                    setattr(unit, attr,
                            max(lr * self.lr_backoff_factor,
                                self.min_learning_rate))
                    changed = True
        if not changed:
            resilience.stats.incr("guardian.skipped")
            return False
        compiler = getattr(self.workflow, "_compiler_", None)
        if compiler is not None:
            compiler.invalidate()
        self.lr_backoffs += 1
        resilience.stats.incr("guardian.lr_backoff")
        self.info("learning rates backed off by %.2f",
                  self.lr_backoff_factor)
        return True

    def rollback(self):
        """In-process weight rollback: restores Vectors from the
        newest snapshot generation that verifies and loads, reseeds
        the train data order, and resets the in-epoch accumulators.
        Returns False (and falls back to skip accounting) when no
        usable snapshot exists — e.g. the poison hit before the first
        improvement ever snapshotted."""
        from .snapshotter import (SnapshotterToFile, iter_generations,
                                  workflow_is_finite)
        snap = self.snapshotter
        directory = getattr(snap, "directory", None)
        candidates = list(iter_generations(
            directory, snap.prefix)) if directory else []
        for path in candidates:
            try:
                source = SnapshotterToFile.import_(path)
            except Exception as e:
                self.warning("rollback: cannot use %s (%s) — trying "
                             "the previous generation", path, e)
                continue
            if not workflow_is_finite(source):
                # Legacy blob without a manifest "finite" record: the
                # poison may have been snapshotted before detection.
                self.warning("rollback: %s holds non-finite weights "
                             "— trying the previous generation", path)
                continue
            restored = restore_vectors(self.workflow, source)
            loader = getattr(self.workflow, "loader", None)
            if loader is not None and hasattr(loader, "shuffle"):
                # Reseed the data order: replaying the exact batch
                # order that produced the poison would just poison
                # the restored weights again.
                loader.shuffle()
            evaluator = getattr(self.decision, "evaluator", None)
            if evaluator is not None:
                for cls in range(3):
                    evaluator.reset_epoch_acc(cls)
                    evaluator.reset_health_acc(cls)
            self.rollbacks += 1
            resilience.stats.incr("guardian.rollbacks")
            self.info("rolled back %d tensors from %s and reshuffled "
                      "the train order", restored, path)
            return True
        # Nothing to restore from: weights may hold the poison (the
        # rollback policy keeps the device gate OFF so restores can
        # be proven real).  Re-arm the gate and re-trace so no
        # FURTHER poison lands while the run limps on.
        self.warning("rollback requested but no usable snapshot "
                     "exists%s — weights may be poisoned; re-arming "
                     "the on-device skip gate",
                     "" if candidates else " (no generations found)")
        self.workflow.health_device_skip = True
        compiler = getattr(self.workflow, "_compiler_", None)
        if compiler is not None:
            compiler.invalidate()
        resilience.stats.incr("guardian.skipped")
        return False

    # -- reporting ---------------------------------------------------------

    def health_status(self):
        """Dashboard payload (rides launcher heartbeats)."""
        return {"policy": self.policy,
                "events": len(self.events),
                "last_event": self.last_event,
                "rollbacks": self.rollbacks,
                "lr_backoffs": self.lr_backoffs,
                "loss_median": self.loss_median()}

    def get_metric_names(self):
        return ["guardian_events", "guardian_rollbacks"]

    def get_metric_values(self):
        return {"guardian_events": len(self.events),
                "guardian_rollbacks": self.rollbacks}
