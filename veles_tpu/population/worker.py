"""Worker side of the population engine: member contexts over ONE
built model workflow.

A worker serves many members of a population, but builds the module
workflow ONCE: a population job establishes its member's state from
the wire (weights full/delta, slot shards, loader indices, the
member's step key and traced hypers), so the only per-member state
the worker must keep is each member's delta-session sync bases — the
``(_base_, version)`` snapshots ``ForwardBase``/``GradientDescentBase``
expose through ``export_sync_state``/``import_sync_state``.  Those
contexts are swapped around every job by member id, so lineages
interleaved on one worker never cross-apply a delta.

An ``exploit`` marker on a job (PBT exploit-as-delta,
docs/population.md) re-bases the member's context on the LEADER's
context this worker already holds, mirroring the master's synced-base
adoption — the wire then carries only the xor delta between the
member's new (copied) weights and the leader state already here.
"""

from .. import resilience
from ..error import Bug
from ..workflow import Workflow
from .lineage import build_member_workflow
from .master import population_checksum


class PopulationWorker(Workflow):
    """Executes member-tagged population jobs on a single built model
    workflow (Client-drivable: the Server's counterpart is
    :class:`veles_tpu.population.master.PopulationMaster`)."""

    def __init__(self, launcher, module, seed=1234, **kwargs):
        super(PopulationWorker, self).__init__(launcher, **kwargs)
        self.module = module
        self.build_seed = int(seed)
        self.negotiates_on_connect = False
        self._inner = None
        self._contexts = {}   # member id -> {unit name: sync state}
        self.jobs_done = 0

    @property
    def inner(self):
        """The model workflow, built lazily with the module's default
        config (member genes ride as traced hypers; weights and slots
        come from the wire, so the build seed only shapes tensors)."""
        if self._inner is None:
            self._inner, _launcher = build_member_workflow(
                self.module, self.build_seed)
        return self._inner

    @property
    def checksum(self):
        return population_checksum(self.module)

    def note_net_proto(self, proto):
        super(PopulationWorker, self).note_net_proto(proto)
        self.inner.note_net_proto(proto)

    # -- member contexts ---------------------------------------------------

    def _sync_units(self):
        for unit in self.inner.units:
            if hasattr(unit, "export_sync_state"):
                yield unit

    def _export_context(self):
        return {unit.name: unit.export_sync_state()
                for unit in self._sync_units()}

    def _install_context(self, ctx):
        for unit in self._sync_units():
            unit.import_sync_state(
                ctx.get(unit.name) if ctx else None)

    @staticmethod
    def _copy_context(ctx):
        """A member context copy for exploit re-basing: the base
        dicts are copied (their arrays are rebound, never mutated in
        place, so sharing them is safe)."""
        out = {}
        for name, state in ctx.items():
            base, version, residual = (
                tuple(state) + (None,) * (3 - len(state))
            ) if state else (None, None, None)
            out[name] = (dict(base) if base is not None else None,
                         version,
                         dict(residual) if residual is not None
                         else None)
        return out

    def _adopt_exploit(self, member, leader):
        """Re-bases ``member``'s context on ``leader``'s (the marker
        only rides jobs whose master adopted the leader's synced base
        for THIS worker, so a missing leader context means the
        session desynchronized — the ordinary ProtocolError →
        reconnect → full-rebase recovery handles it)."""
        ctx = self._contexts.get(leader)
        if ctx is None:
            self.warning(
                "exploit marker names member %r but this worker "
                "holds no context for it — the delta will rebase "
                "through the protocol-error reconnect path", leader)
            resilience.stats.incr("population.exploit_miss")
            return
        self._contexts[member] = self._copy_context(ctx)
        resilience.stats.incr("population.exploit_adopt")

    # -- job execution -----------------------------------------------------

    def do_job(self, data, update, callback):
        member = (data or {}).get("m")
        if member is None:
            raise Bug("population job carries no member id — "
                      "coordinator/worker build mismatch")
        # Retire markers: the master announces recorded GA
        # chromosomes so their sync contexts free here too (a long
        # GA run must not hold one context per evaluated chromosome).
        for retired in data.get("retire") or ():
            self.drop_member(retired)
        leader = data.get("exploit")
        if leader is not None:
            self._adopt_exploit(member, leader)
        self._install_context(self._contexts.get(member))
        try:
            replies = []
            self.inner.do_job(data["data"], None, replies.append)
        finally:
            self._contexts[member] = self._export_context()
        self.jobs_done += 1
        callback({"m": member, "data": replies[0]})

    def drop_member(self, member):
        """Forgets a member's context (a retired GA chromosome)."""
        self._contexts.pop(member, None)
