"""On-chip sub-population backend: one device job evaluates a whole
generation.

Small members don't need the fleet: when every GA tune is a traced
GD/optimizer hyperparameter, the existing vmapped population path
(:mod:`veles_tpu.genetics.vmap_eval`) trains EVERY chromosome of a
generation in one compiled program on one device.  This module
promotes it to a population-engine scheduler backend: the engine
hands a generation's gene matrix to :meth:`evaluate` and gets the
fitness vector back — one "device job" per sub-population instead of
one lineage per member.

The evaluate loop is ``strict_step``-clean after the first
generation: block uploads are explicit ``device_put``, the traced
training flag is cached on device, and the only host syncs are the
explicit epoch-boundary accumulator fetches
(:mod:`veles_tpu.analysis.runtime` enforces this in the tier-1
suite).
"""

from ..config import root, get as config_get
from ..error import Bug


class VmapSubPopulation(object):
    """Generation evaluator backend over ``PopulationEvaluator``.

    ``applicable(tunes)`` gates construction the same way the
    genetics standalone path gates its vmap evaluator; the population
    engine falls back to fleet lineages when it returns False.
    """

    def __init__(self, module, tunes, seed, epochs=None):
        from ..genetics.vmap_eval import PopulationEvaluator
        self._evaluator = PopulationEvaluator(module, tunes, seed,
                                              epochs=epochs)
        self.generations_evaluated = 0

    @staticmethod
    def applicable(module, tunes):
        """True when the vmapped path can carry these tunes (every
        leaf a uniquely-named GD/optimizer hyper) AND the config
        enables it (``root.common.population.vmap``, default on —
        mirroring ``root.common.genetics.vmap``)."""
        from ..genetics.vmap_eval import hyper_names
        if not bool(config_get(root.common.population.vmap, True)):
            return False
        return hyper_names(tunes) is not None

    def evaluate(self, genes_matrix):
        """Fitness vector for one generation's gene matrix — a single
        vmapped device job over the whole sub-population."""
        fitnesses = self._evaluator.evaluate(genes_matrix)
        self.generations_evaluated += 1
        from .. import resilience
        resilience.stats.incr("population.vmap_generations")
        return fitnesses

    def run_population(self, population, log=None):
        """Drives a genetics Population to completion, one vmapped
        device job per generation (the population engine's GA mode
        when the backend applies)."""
        while not population.complete:
            batch = []
            while True:
                got = population.acquire(owner="vmap")
                if got is None:
                    break
                batch.append(got)
            if not batch:
                raise Bug("population stalled: nothing pending yet "
                          "generation incomplete")
            fitnesses = self.evaluate(
                [genes for _, genes in batch])
            for (index, _), fitness in zip(batch, fitnesses):
                if log is not None:
                    log("chromosome %d -> fitness %.6f", index,
                        float(fitness))
                population.record(index, float(fitness))
        return population.best
