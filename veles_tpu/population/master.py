"""The population master: lineages scheduled as first-class fleet jobs.

GA individuals, PBT members, and ensemble instances are *lineages*
(:mod:`veles_tpu.population.lineage`) the master schedules across the
worker fleet over the ordinary Server job protocol: a population job
wraps one member's multi-tick job (the member id tags it), worker
deltas fold into that member's lineage only, a dropped worker's
member ticks requeue with their original step keys, and per-lineage
guardian policy rolls a poisoned member back from its OWN last-good
generation — never a sibling's.

Scheduling modes (docs/population.md):

* ``train`` — fixed seed-varied members (ensemble training on the
  fleet), each running to its decision's completion;
* ``ga`` — generation-synchronous: chromosomes from
  :class:`veles_tpu.genetics.Population` become fresh lineages (genes
  applied per-lineage through ``config.override_scope``, and shipped
  to workers as traced hypers), evaluate → select → mutate;
* ``pbt`` — asynchronous Population Based Training: when a member's
  fitness lags the population quantile at its ``--pbt-interval``
  check, exploit copies the leader's weights as a DELTA ship (the
  member's synced base is re-pointed at the leader's, so the wire
  carries an xor delta against state the worker already holds — no
  full-weight transfer) plus ``--pbt-perturb``-perturbed hypers.
"""

import time
import weakref

import numpy

from .. import resilience
from ..config import root, get as config_get
from ..distributable import SniffedLock
from ..error import Bug
from ..loader.base import TRAIN, VALID
from ..workflow import Workflow
from .lineage import Lineage

#: Live masters in this process, feeding the launcher-heartbeat
#: "population" section and the web_status per-member fitness row.
_LIVE_MASTERS = weakref.WeakSet()


def live_population_summary():
    """Aggregate across this process's live population masters for
    the heartbeat ``population`` section, or None when none runs."""
    masters = [m for m in list(_LIVE_MASTERS) if m.members]
    if not masters:
        return None
    out = {"masters": len(masters)}
    members = 0
    active = 0
    exploits = 0
    requeues = 0
    rollbacks = 0
    fitness = {}
    generation = {}
    best = None
    for master in masters:
        snap = master.population_summary()
        members += snap["members"]
        active += snap["active"]
        exploits += snap["exploits"]
        requeues += snap["requeues"]
        rollbacks += snap["rollbacks"]
        fitness.update(snap.get("fitness") or {})
        generation.update(snap.get("generation") or {})
        b = snap.get("best_fitness")
        if b is not None and (best is None or b > best):
            best = b
    out.update(members=members, active=active, exploits=exploits,
               requeues=requeues, rollbacks=rollbacks)
    if best is not None:
        out["best_fitness"] = best
    if fitness:
        out["fitness"] = fitness
    if generation:
        out["generation"] = generation
    return out


def population_checksum(module):
    """Coordinator and workers must run the same population protocol
    over the same model module — the checksum covers both (the base
    ``Workflow.checksum`` would differ between the master and worker
    classes, which live in different source files)."""
    import hashlib
    import os
    parts = []
    for fname in ("master.py", "worker.py", "lineage.py"):
        path = os.path.join(os.path.dirname(
            os.path.abspath(__file__)), fname)
        try:
            with open(path, "rb") as fin:
                parts.append(fin.read())
        except OSError:
            parts.append(fname.encode())
    digest = hashlib.sha1(b"".join(parts)).hexdigest()
    name = "none" if module is None else os.path.basename(
        getattr(module, "__file__", None) or
        getattr(module, "__name__", "module"))
    return "%s_population_%s" % (digest, name)


class PopulationMaster(Workflow):
    """Master-side population engine riding the Server job protocol.

    The Server serializes the job hooks under its workflow lock; the
    member-table lock below additionally guards the table against the
    heartbeat/summary thread.  Lock order: server lock (if held) →
    member-table lock; the summary path takes only the table lock.
    """

    MODES = ("train", "ga", "pbt")

    def __init__(self, launcher, module, **kwargs):
        super(PopulationMaster, self).__init__(launcher, **kwargs)
        self.module = module
        self.mode = kwargs.get("mode", "train")
        if self.mode not in self.MODES:
            raise Bug("unknown population mode %r (known: %s)"
                      % (self.mode, ", ".join(self.MODES)))
        self.negotiates_on_connect = False
        self.size = int(kwargs.get("size", 2))
        self.seed = int(kwargs.get("seed", 1234))
        #: Seed stride between members (ensemble convention).
        self.seed_stride = int(kwargs.get("seed_stride", 1000003))
        self.pbt_interval = int(kwargs.get("pbt_interval", config_get(
            root.common.population.pbt_interval, 1)))
        self.pbt_quantile = float(kwargs.get(
            "pbt_quantile",
            config_get(root.common.population.pbt_quantile, 0.25)))
        self.pbt_perturb = float(kwargs.get(
            "pbt_perturb",
            config_get(root.common.population.pbt_perturb, 1.2)))
        self.guardian_policy = kwargs.get("guardian_policy") or \
            config_get(root.common.guardian.policy, "skip")
        #: Extra per-member config overrides {path: value} applied to
        #: EVERY lineage build (ensemble train_ratio etc.), on top of
        #: per-member genes.
        self.base_overrides = dict(kwargs.get("base_overrides") or {})
        #: Guards the member table + scheduling state below against
        #: the heartbeat/summary thread.
        self._lock = SniffedLock(name="population.members")
        self._members = {}        # guarded-by: _lock
        self._order = []          # guarded-by: _lock
        self._slave_protos = {}   # guarded-by: _lock
        #: Retired member ids not yet announced to each worker: the
        #: ids ride the next job to that worker as a ``retire``
        #: marker, and the worker frees those members' sync
        #: contexts — without it a long GA run would accumulate one
        #: full weight+slot context per evaluated chromosome on
        #: every worker.
        self._retire_pending = {}  # guarded-by: _lock
        self._version_seq = 1     # guarded-by: _lock
        self._done = False        # guarded-by: _lock
        self.exploits = 0         # guarded-by: _lock
        self.requeues = 0         # guarded-by: _lock
        self.rollbacks = 0        # guarded-by: _lock
        self.best = None          # (member_id, fitness, hypers)
        self.last_exploit_ms = None
        # GA state (mode == "ga"): the genetics engine drives
        # generations; chromosomes become lineages on demand.
        self._ga_pop = None
        self._ga_tunes = None
        self._ga_live = {}        # chromosome index -> Lineage
        #: PBT's own rng (hyper init + perturbation draws) — NEVER a
        #: lineage stream, which must replay exactly like standalone.
        self._pbt_rng = numpy.random.RandomState(self.seed ^ 0x9B7)
        with self._lock:
            if self.mode == "ga":
                self._init_ga_locked(kwargs)
            else:
                self._init_members_locked(kwargs)
        _LIVE_MASTERS.add(self)
        self._publish_gauges()

    # -- member construction -----------------------------------------------

    def _hyper_leaves(self, tunes):
        """Validates that every tune is a traced-hyper leaf the fleet
        path can ship (the vmap path's applicability rule): genes
        reach workers as traced step inputs, so topology tunes cannot
        ride fleet lineages."""
        from ..genetics.vmap_eval import hyper_names
        names = hyper_names(tunes)
        if names is None:
            raise Bug(
                "population fleet scheduling requires every Tune leaf "
                "to be a uniquely-named GD/optimizer hyperparameter "
                "(genes ship to workers as traced step inputs); "
                "topology tunes need the standalone --optimize "
                "subprocess path")
        return names

    def _init_ga_locked(self, kwargs):
        from ..genetics.core import Population, collect_tunes
        self._ga_tunes = collect_tunes(root)
        self._hyper_leaves(self._ga_tunes)
        self._ga_pop = Population(
            self._ga_tunes, self.size,
            kwargs.get("generations"), seed=self.seed,
            **{k: v for k, v in kwargs.items()
               if k in ("elite_ratio", "mutation_rate",
                        "blend_alpha", "stagnation")})

    def _init_members_locked(self, kwargs):
        from ..genetics.core import collect_tunes, _concrete
        tunes = collect_tunes(root)
        hyper_leaves = ()
        if tunes and self.mode == "pbt":
            hyper_leaves = self._hyper_leaves(tunes)
        for i in range(self.size):
            overrides = dict(self.base_overrides)
            hypers = {}
            if self.mode == "pbt" and tunes:
                # Initial hyper spread: uniform over each tune's
                # range (member 0 keeps the defaults so one lineage
                # always matches the hand-tuned baseline).
                for (path, tune), leaf in zip(tunes, hyper_leaves):
                    if i == 0:
                        value = _concrete(tune, float(tune.default))
                    else:
                        value = _concrete(tune, self._pbt_rng.uniform(
                            tune.min, tune.max))
                    overrides[path] = value
                    hypers[leaf] = float(value)
            member = Lineage(
                "m%d" % i, self.module,
                self.seed + i * self.seed_stride,
                overrides=overrides, hypers=hypers or None,
                origin=self.mode)
            self._register_locked(member)

    def _register_locked(self, member):
        member.build()
        member.wf._weights_version_ = self._version_seq
        self._version_seq += 1
        for slave, proto in self._slave_protos.items():
            member.wf.note_slave_protocol(slave, proto)
        self._members[member.member_id] = member
        self._order.append(member.member_id)
        return member

    @property
    def members(self):
        return [self._members[mid] for mid in self._order]

    # -- protocol plumbing (Server-facing) ---------------------------------

    @property
    def checksum(self):
        return population_checksum(self.module)

    def note_slave_protocol(self, slave, proto):
        with self._lock:
            self._slave_protos[slave] = dict(proto or {})
            for member in self.members:
                member.wf.note_slave_protocol(slave, proto)

    def slave_protocol(self, slave):
        return self._slave_protos.get(slave) or {}

    def generate_initial_data_for_slave(self, slave=None):
        return None

    def should_stop_serving(self):
        with self._lock:
            return self._finished_locked()

    def _finished_locked(self):
        if self._done:
            return True
        if self.mode == "ga":
            done = self._ga_pop.complete
        else:
            done = all(m.complete for m in self.members)
        if done:
            self._done = True
            self._record_best_locked()
        return done

    def _record_best_locked(self):
        candidates = [(m.fitness, m) for m in self.members
                      if m.fitness is not None]
        if self.mode == "ga" and self._ga_pop.best is not None:
            best = self._ga_pop.best
            self.best = ("ga", float(best.fitness),
                         dict(best.overrides(self._ga_tunes)))
        elif candidates:
            fit, m = max(candidates, key=lambda p: p[0])
            self.best = (m.member_id, float(fit), dict(m.hypers))

    # -- job generation ----------------------------------------------------

    def generate_data_for_slave(self, slave=None):
        with self._lock:
            if self._finished_locked():
                return None
            member = self._pick_member_locked(slave)
            if member is None:
                return None
            with member.scope():
                inner = member.wf.generate_data_for_slave(slave)
            key = member.draw_job_key()
            meta = inner.get("__job__")
            if meta is None:
                meta = inner["__job__"] = {}
            meta["rng"] = key
            if member.hypers:
                meta["hypers"] = dict(member.hypers)
            ticks = 1
            for piece in inner.values():
                if isinstance(piece, dict) and "block" in piece:
                    ticks = len(piece["block"]["classes"])
                    break
            member.outstanding = (slave, key, ticks)
            member.affinity = slave
            member.last_served = time.time()
            member.jobs_done += 1
            resilience.stats.incr("population.jobs")
            job = {"m": member.member_id, "data": inner}
            leader = member.exploit_rebase.pop(slave, None)
            if leader is not None:
                job["exploit"] = leader
            retired = self._retire_pending.pop(slave, None)
            if retired:
                job["retire"] = retired
            return job

    def _pick_member_locked(self, slave):
        """One member, one job in flight: folds stay serialized per
        lineage (the delta fold then reconstructs the worker's exact
        values).  The pick itself is the fleet-wide affinity policy
        (:meth:`FleetScheduler.pick_affine`): affinity first — a
        member stays on the worker that holds its synced base, so
        steady state ships deltas, not full weights — then a fresh
        member, then steal the least recently served (its next job to
        this worker is a one-time full ship, then deltas again)."""
        if self.mode == "ga":
            self._refill_ga_locked()
        candidates = [m for m in self.members
                      if m.built and m.outstanding is None and
                      not m.complete]
        if self.mode == "ga":
            live = set(self._ga_live.values())
            candidates = [m for m in candidates if m in live]
        from ..fleet import FleetScheduler
        return FleetScheduler.pick_affine(
            candidates, slave,
            affinity_of=lambda m: m.affinity,
            age_of=lambda m: m.last_served)

    def _refill_ga_locked(self):
        """Builds lineages for pending chromosomes of the current GA
        generation (chromosomes applied PER-LINEAGE through the
        override scope — the global tree never mutates)."""
        from ..genetics.core import _concrete
        while True:
            got = self._ga_pop.acquire(owner="population")
            if got is None:
                return
            index, genes = got
            overrides = dict(self.base_overrides)
            hypers = {}
            for (path, tune), gene in zip(self._ga_tunes, genes):
                value = _concrete(tune, gene)
                overrides[path] = value
                hypers[path.rsplit(".", 1)[-1]] = float(value)
            member = Lineage(
                "g%dc%d" % (self._ga_pop.generation, index),
                self.module, self.seed, overrides=overrides,
                hypers=hypers, origin="ga")
            member.ga_index = index
            self._register_locked(member)
            self._ga_live[index] = member

    # -- folds -------------------------------------------------------------

    def apply_data_from_slave(self, data, slave=None):
        with self._lock:
            mid = (data or {}).get("m")
            member = self._members.get(mid)
            if member is None or member.outstanding is None or \
                    member.outstanding[0] != slave:
                # Stale reply (the member's job was requeued after a
                # watchdog blacklist, or the member retired) — the
                # batch re-trains elsewhere, so this fold must drop.
                resilience.stats.incr("population.stale_updates")
                return
            inner = data.get("data") or {}
            meta = dict(inner.get("__job__") or {})
            ticks = member.outstanding[2]
            with member.scope():
                member.wf.apply_data_from_slave(inner, slave)
            member.outstanding = None
            member.ticks_done += ticks
            resilience.stats.incr("population.ticks", ticks)
            member.wf._weights_version_ = self._version_seq
            self._version_seq += 1
            if meta.get("last_minibatch"):
                self._on_class_epoch_locked(
                    member, meta.get("minibatch_class"))
            if member.complete:
                self._on_member_complete_locked(member)
            self._publish_gauges()

    def _on_class_epoch_locked(self, member, cls):
        if cls == VALID:
            member.val_epochs += 1
            member.refresh_fitness()
            if self.mode == "pbt":
                self._maybe_exploit_locked(member)
        elif cls == TRAIN:
            self._guardian_check_locked(member)

    def _guardian_check_locked(self, member):
        """Per-lineage guardian: a poisoned train epoch rolls the
        member back from its OWN last-good generation; a healthy one
        becomes the new last-good."""
        d = member.decision
        if d is None:
            return
        nonfinite = float(getattr(
            d, "epoch_nonfinite", (0.0, 0.0, 0.0))[TRAIN])
        loss = d.epoch_loss[TRAIN]
        healthy = nonfinite == 0.0 and (
            loss is None or numpy.isfinite(float(loss)))
        if healthy:
            member.record_good()
            return
        resilience.stats.incr("population.nan_epochs")
        if self.guardian_policy == "rollback" and \
                member.rollback_last_good():
            self.rollbacks += 1
            resilience.stats.incr("population.rollbacks")

    def _on_member_complete_locked(self, member):
        fitness = member.final_fitness()
        if fitness is not None:
            member.fitness = fitness
            if member.best_fitness is None or \
                    fitness > member.best_fitness:
                member.best_fitness = fitness
        self.info("member %s complete: fitness %s after %d jobs",
                  member.member_id, fitness, member.jobs_done)
        if self.mode == "ga":
            index = getattr(member, "ga_index", None)
            if index in self._ga_live:
                del self._ga_live[index]
                self._ga_pop.record(index, float(fitness or 0.0))
                # A recorded chromosome's model is dead weight: a GA
                # run evaluates size×generations lineages and must
                # not hold one workflow per chromosome forever —
                # master side (retire frees the workflow + guardian
                # snapshot) AND worker side (the retire marker on
                # each worker's next job frees its sync context).
                member.retire()
                for slave in self._slave_protos:
                    self._retire_pending.setdefault(slave, []) \
                        .append(member.member_id)

    # -- PBT exploit (exploit-as-delta) ------------------------------------

    def _maybe_exploit_locked(self, member):
        if member.val_epochs - member.last_pbt_check < \
                self.pbt_interval:
            return
        member.last_pbt_check = member.val_epochs
        scored = [(m.fitness, m) for m in self.members
                  if m.fitness is not None]
        if len(scored) < 2 or member.fitness is None:
            return
        fits = numpy.array([f for f, _ in scored])
        cut = float(numpy.quantile(fits, self.pbt_quantile))
        if member.fitness > cut:
            return
        leader = max((p for p in scored if p[1] is not member),
                     key=lambda p: p[0], default=(None, None))[1]
        if leader is None or leader.fitness <= member.fitness:
            return
        self.exploit(member, leader)

    def exploit(self, member, leader):
        """Copies the leader's weights+slots into the member's
        lineage and re-points the member's per-worker synced bases at
        the leader's, so the next job ships an xor delta against
        state that worker ALREADY holds for the leader — an exploit
        costs delta bytes, never a full weight ship
        (docs/population.md, "exploit as delta").

        The copied generation is the leader's last-SHIPPED state at
        its affinity worker (its synced base there), bit-identical to
        what that worker holds — the follow-up delta then collapses
        to unchanged-None markers.  Async PBT tolerates the ≤1-job
        staleness by design; when the leader has no shipped state
        (never served), the copy falls back to its live weights and
        the next job full-ships."""
        t0 = time.time()
        l_units = {u.name: u for u in leader.wf.units}
        src_worker = leader.affinity \
            if leader.affinity in self._slave_protos else None
        copied = False
        if src_worker is not None and int(
                self._slave_protos[src_worker].get("zero") or 0) == 1:
            copied = self._adopt_shipped_locked(
                member, l_units, src_worker)
        if not copied:
            from ..guardian import restore_vectors
            restore_vectors(member.wf, leader.wf)
        for slave in self._slave_protos:
            adopted = copied and slave == src_worker and \
                self._adopt_synced_locked(member, l_units, slave)
            if adopted:
                member.exploit_rebase[slave] = leader.member_id
            else:
                # No base this worker already holds can carry the
                # exploit: drop the member's stale bases so the next
                # job to it full-ships.
                for unit in member.wf.units:
                    for attr in ("_synced_", "_slot_synced_"):
                        synced = getattr(unit, attr, None)
                        if isinstance(synced, dict):
                            synced.pop(slave, None)
                member.exploit_rebase.pop(slave, None)
        self._post_exploit_locked(member, leader, t0)

    def _adopt_shipped_locked(self, member, l_units, slave):
        """Overwrites the member's weights/slots with the leader's
        last-shipped generation at ``slave``; all-or-nothing (a
        partial copy would mix two generations)."""
        results = []
        for unit in member.wf.units:
            src = l_units.get(unit.name)
            adopt = getattr(unit, "adopt_shipped_values", None)
            if adopt is None or src is None:
                continue
            results.append(adopt(src, slave))
        results = [r for r in results if r is not None]
        return bool(results) and all(results)

    def _adopt_synced_locked(self, member, l_units, slave):
        results = []
        for unit in member.wf.units:
            src = l_units.get(unit.name)
            adopt = getattr(unit, "adopt_synced_from", None)
            if adopt is None or src is None:
                continue
            results.append(adopt(src, slave))
        results = [r for r in results if r is not None]
        return bool(results) and all(results)

    def _post_exploit_locked(self, member, leader, t0):
        if member.hypers:
            # Explore: perturb the copied leader's hypers (clipped to
            # the tune ranges when known).
            base = dict(leader.hypers or member.hypers)
            from ..genetics.core import collect_tunes
            spans = {path.rsplit(".", 1)[-1]: tune
                     for path, tune in collect_tunes(root)}
            for name, value in base.items():
                factor = self.pbt_perturb if self._pbt_rng.rand() < \
                    0.5 else 1.0 / self.pbt_perturb
                new = float(value) * factor
                tune = spans.get(name)
                if tune is not None:
                    new = float(numpy.clip(new, tune.min, tune.max))
                member.hypers[name] = new
        member.generation += 1
        member.last_good = None  # pre-exploit snapshots are obsolete
        self.exploits += 1
        resilience.stats.incr("population.exploits")
        exploit_ms = (time.time() - t0) * 1000.0
        self.last_exploit_ms = exploit_ms
        self.info(
            "PBT exploit: %s (fitness %.4f) adopted leader %s "
            "(%.4f), hypers %s, %.1f ms",
            member.member_id, member.fitness or 0.0,
            leader.member_id, leader.fitness or 0.0, member.hypers,
            exploit_ms)

    # -- drops -------------------------------------------------------------

    def drop_slave(self, slave=None):
        with self._lock:
            for member in self.members:
                if not member.built:
                    continue
                if member.outstanding is not None and \
                        member.outstanding[0] == slave:
                    member.requeue_outstanding()
                    self.requeues += 1
                    resilience.stats.incr("population.requeues")
                if member.affinity == slave:
                    member.affinity = None
                with member.scope():
                    member.wf.drop_slave(slave)
            self._slave_protos.pop(slave, None)
            self._retire_pending.pop(slave, None)
            self._publish_gauges()

    # -- observability -----------------------------------------------------

    def population_summary(self):
        """The heartbeat "population" section / web_status row
        payload: member fitness and lineage generation live, exploit
        and requeue counts aggregated."""
        with self._lock:
            members = self.members
            out = {"members": len(members),
                   "mode": self.mode,
                   "active": sum(1 for m in members
                                 if m.built and not m.complete),
                   "exploits": self.exploits,
                   "requeues": self.requeues,
                   "rollbacks": self.rollbacks,
                   "jobs": sum(m.jobs_done for m in members)}
            fitness = {m.member_id: round(m.fitness, 6)
                       for m in members if m.fitness is not None}
            if fitness:
                out["fitness"] = fitness
                out["best_fitness"] = max(fitness.values())
                out["mean_fitness"] = round(
                    sum(fitness.values()) / len(fitness), 6)
            generation = {m.member_id: m.generation for m in members}
            if generation:
                out["generation"] = generation
            if self.mode == "ga" and self._ga_pop is not None:
                out["ga_generation"] = self._ga_pop.generation
            return out

    def _publish_gauges(self):
        """population.* gauges in the process metrics registry
        (scraped on /metrics; docs/observability.md)."""
        from ..observability import metrics
        reg = metrics.registry
        members = self.members
        reg.gauge("population.members").set(len(members))
        reg.gauge("population.active").set(
            sum(1 for m in members if m.built and not m.complete))
        for m in members:
            labels = {"member": m.member_id}
            if m.fitness is not None:
                reg.gauge("population.member_fitness",
                          labels).set(m.fitness)
            reg.gauge("population.member_generation",
                      labels).set(m.generation)
