"""Population engine: GA / PBT / ensemble training as first-class
fleet jobs on the delta data plane (docs/population.md).

Members are long-lived weight *lineages* the master schedules across
the worker fleet: jobs are member-tagged multi-tick blocks, worker
deltas fold into that member's lineage only, dropped workers' member
ticks requeue with their original step keys, PBT exploits ship as
deltas against synced state workers already hold, and small GA
members pack on-chip through the vmapped sub-population backend.
"""

from .engine import PopulationEngine, loopback_proto  # noqa: F401
from .lineage import Lineage, build_member_workflow  # noqa: F401
from .master import (PopulationMaster,  # noqa: F401
                     live_population_summary, population_checksum)
from .vmap_backend import VmapSubPopulation  # noqa: F401
from .worker import PopulationWorker  # noqa: F401


def init_parser(parser):
    """Population flags for the aggregated velescli parser
    (docs/population.md, docs/cli.md)."""
    parser.add_argument(
        "--population", default="", metavar="N[:GENERATIONS]",
        help="train N population members as fleet-scheduled lineages "
             "(GA mode when the config carries Tune() leaves — "
             "GENERATIONS caps the GA; PBT with --pbt; plain "
             "seed-varied member training otherwise)")
    parser.add_argument(
        "--pbt", action="store_true",
        help="population scheduling runs asynchronous Population "
             "Based Training: lagging members exploit a leader's "
             "weights (shipped as a delta) with perturbed hypers")
    parser.add_argument(
        "--pbt-interval", type=int, default=None, metavar="EPOCHS",
        help="validation epochs between a member's PBT fitness "
             "checks (default 1; sets "
             "root.common.population.pbt_interval)")
    parser.add_argument(
        "--pbt-quantile", type=float, default=None, metavar="Q",
        help="a member at or below this population fitness quantile "
             "exploits a leader (default 0.25; sets "
             "root.common.population.pbt_quantile)")
    parser.add_argument(
        "--pbt-perturb", type=float, default=None, metavar="F",
        help="explore step: exploited hypers multiply by F or 1/F "
             "(default 1.2; sets root.common.population.pbt_perturb)")
    parser.add_argument(
        "--population-vmap", default=None, choices=("on", "off"),
        help="GA generations evaluate as ONE vmapped device job when "
             "every tune is a GD hyperparameter (default on; sets "
             "root.common.population.vmap)")
    parser.add_argument(
        "--ensemble-population", action="store_true",
        help="route --ensemble-train instances through the "
             "population scheduler (fleet-trained ensemble members "
             "instead of sequential in-process runs)")
